"""Collective communication operators — ICI-native.

Reference parity: `paddle/fluid/operators/collective/` — c_allreduce_{sum,
max,min,prod}, c_broadcast, c_allgather, c_reducescatter, c_comm_init,
c_gen_nccl_id, c_sync_calc_stream, c_sync_comm_stream (kernels call
ncclAllReduce etc., `c_allreduce_op.h:58-105`).

TPU-native design: there is no NCCL communicator object. A `ring_id` maps to
a *mesh axis name* (registry in `paddle_tpu.parallel.env`); when the program
is lowered under `shard_map` over a `jax.sharding.Mesh`, these ops emit XLA
collectives (`lax.psum` / `all_gather` / `psum_scatter`) which XLA schedules
over ICI. Outside any mesh (single chip) they are identities, matching
single-process semantics. Stream-sync ops are no-ops: XLA's dataflow
schedule replaces explicit stream synchronisation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


def _axis_for(attrs):
    from ..parallel import env

    ring_id = attrs.get("ring_id", 0)
    return env.axis_name_for_ring(ring_id)


def _seq_reduce(fn, x, axis):
    """psum/pmax/pmin over `axis`; a TUPLE axis — ring 0 on a hybrid
    (dcn, ici) mesh spans the pair — reduces HIERARCHICALLY, minor
    (intra-pod ici) axis first then cross-pod dcn: two collectives
    whose replica_groups and fp association match the sharded-update
    lowering (parallel/README.md "Hierarchical collectives"), so
    replicated and ZeRO runs stay bit-identical on hybrid meshes and
    only the pod-partial bytes cross the DCN link."""
    if isinstance(axis, tuple):
        for a in reversed(axis):
            x = fn(x, a)
        return x
    return fn(x, axis)


def _linear_axis_index(axis):
    """Replica's linear index over a single axis or a (major, minor)
    axis tuple (row-major, matching the hybrid mesh device order)."""
    if isinstance(axis, tuple):
        from ..parallel import env

        axes = env.active_axes() or {}
        idx = lax.axis_index(axis[0])
        for a in axis[1:]:
            idx = idx * axes.get(a, 1) + lax.axis_index(a)
        return idx
    return lax.axis_index(axis)


def _register_allreduce(suffix, monoid):
    @register_op("c_allreduce_" + suffix)
    def _c_allreduce(ins, attrs, _monoid=monoid):
        x = ins["X"][0]
        axis = _axis_for(attrs)
        if axis is None:
            return {"Out": x}
        return {"Out": _monoid(x, axis)}


_register_allreduce("sum", lambda x, ax: _seq_reduce(lax.psum, x, ax))
_register_allreduce("max", lambda x, ax: _seq_reduce(lax.pmax, x, ax))
_register_allreduce("min", lambda x, ax: _seq_reduce(lax.pmin, x, ax))
# prod: all_gather + product over the gathered axis. The previous
# exp(psum(log(x))) NaN'd for any zero/negative element; the reference
# kRedProd (c_allreduce_op.h:58-105, ncclProd) handles all reals. The
# extra ICI bytes (N x data vs 1 x) are acceptable for this rarely-hot
# op in exchange for exact all-reals semantics.
_register_allreduce("prod", lambda x, ax: jnp.prod(
    lax.all_gather(x, ax), axis=0))


@register_op("c_broadcast")
def _c_broadcast(ins, attrs):
    x = ins["X"][0]
    axis = _axis_for(attrs)
    if axis is None:
        return {"Out": x}
    root = attrs.get("root", 0)
    idx = _linear_axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return {"Out": _seq_reduce(lax.psum, masked, axis)}


@register_op("c_allgather")
def _c_allgather(ins, attrs):
    x = ins["X"][0]
    axis = _axis_for(attrs)
    if axis is None:
        return {"Out": x}
    return {"Out": lax.all_gather(x, axis, tiled=True)}


@register_op("c_reducescatter")
def _c_reducescatter(ins, attrs):
    x = ins["X"][0]
    axis = _axis_for(attrs)
    if axis is None:
        return {"Out": x}
    return {"Out": lax.psum_scatter(x, axis, tiled=True)}


@register_op("c_reduce_sum")
def _c_reduce_sum(ins, attrs):
    x = ins["X"][0]
    axis = _axis_for(attrs)
    if axis is None:
        return {"Out": x}
    # reduce-to-root: root keeps the sum, others keep their input (the
    # reference only defines the root's output).
    total = _seq_reduce(lax.psum, x, axis)
    idx = _linear_axis_index(axis)
    return {"Out": jnp.where(idx == attrs.get("root_id", 0), total, x)}


@register_op("alltoall")
def _alltoall(ins, attrs):
    x = ins["X"][0]
    axis = _axis_for(attrs)
    if axis is None:
        return {"Out": x}
    from ..parallel import env

    n = env.axis_size_for_ring(attrs.get("ring_id", 0))
    xs = x.reshape((n, x.shape[0] // n) + x.shape[1:])
    out = lax.all_to_all(xs, axis, split_axis=0, concat_axis=0, tiled=False)
    return {"Out": out.reshape(x.shape)}


@register_op("c_concat")
def _c_concat(ins, attrs):
    x = ins["X"][0]
    axis = _axis_for(attrs)
    if axis is None:
        return {"Out": x}
    return {"Out": lax.all_gather(x, axis, axis=x.ndim - 1, tiled=True)}


@register_op("c_split")
def _c_split(ins, attrs):
    x = ins["X"][0]
    axis = _axis_for(attrs)
    if axis is None:
        return {"Out": x}
    from ..parallel import env

    n = env.axis_size_for_ring(attrs.get("ring_id", 0))
    idx = _linear_axis_index(axis)
    piece = x.shape[-1] // n
    return {"Out": lax.dynamic_slice_in_dim(x, idx * piece, piece, x.ndim - 1)}


@register_op("c_embedding")
def _c_embedding(ins, attrs):
    # vocab-sharded embedding lookup: local partial lookup + psum
    w, ids = ins["W"][0], ins["Ids"][0]
    axis = _axis_for(attrs)
    start = attrs.get("start_index", 0)
    local_ids = ids.astype(jnp.int32) - start
    valid = (local_ids >= 0) & (local_ids < w.shape[0])
    out = jnp.take(w, jnp.clip(local_ids, 0, w.shape[0] - 1), axis=0)
    out = jnp.where(valid[..., None], out, jnp.zeros_like(out))
    if axis is not None:
        out = _seq_reduce(lax.psum, out, axis)
    return {"Out": out}


@register_op("c_identity")
def _c_identity(ins, attrs):
    return {"Out": ins["X"][0]}


@register_op("c_sync_calc_stream")
def _c_sync_calc(ins, attrs):
    # XLA's dataflow schedule subsumes stream sync — identity.
    return {"Out": ins["X"][0]}


@register_op("c_sync_comm_stream")
def _c_sync_comm(ins, attrs):
    return {"Out": [x for x in ins["X"]]}


@register_op("allreduce")
def _legacy_allreduce(ins, attrs):
    # legacy operators/distributed_ops/allreduce_op.cc
    x = ins["X"][0]
    axis = _axis_for({"ring_id": 0})
    red = attrs.get("reduce_type", 0)
    if axis is None:
        return {"Out": x}
    fns = {0: lax.psum, 1: lax.pmax, 2: lax.pmin}
    if red in fns:
        return {"Out": _seq_reduce(fns[red], x, axis)}
    return {"Out": jnp.exp(_seq_reduce(lax.psum, jnp.log(x), axis))}


@register_op("broadcast")
def _legacy_broadcast(ins, attrs):
    return _c_broadcast({"X": ins["X"]},
                        {"ring_id": 0, "root": attrs.get("root", 0)})


@register_op("dgc")
def _dgc(ins, attrs):
    """Deep Gradient Compression (reference: `operators/dgc_op.cc` +
    `dgc_momentum_op.cc`): momentum-corrected top-k sparsification.

      u = m * u + g                (momentum correction)
      v = v + u                    (local accumulation)
      keep the top-(1-sparsity) |v| entries -> EncodeGrad; clear u, v at
      the sent positions (unsent residuals keep accumulating locally).

    Before `rampup_begin_step` every entry is sent (dense warmup). The
    'sparse' transfer is a masked dense tensor: on TPU the allreduce
    rides ICI either way, so sparsity saves *cross-host DCN* bytes (the
    reference's PCIe/ethernet concern) while staying one fused XLA op.
    Outputs: UOut, VOut, EncodeGrad, StepOut.
    """
    import jax

    g = ins["Grad"][0]
    u = ins["U"][0]
    v = ins["V"][0]
    step = ins["Step"][0]
    m = float(attrs.get("momentum", 0.9))
    sparsity = float(attrs.get("sparsity", 0.75))
    rampup_begin = float(attrs.get("rampup_begin_step", 0.0))

    u_new = m * u + g
    v_new = v + u_new
    flat = jnp.abs(v_new.reshape(-1))
    numel = flat.shape[0]
    k = max(1, int(numel * (1.0 - sparsity)))
    topk_vals = jax.lax.top_k(flat, k)[0]
    thresh = topk_vals[-1]
    mask = (jnp.abs(v_new) >= thresh)
    # dense warmup while step < rampup_begin
    dense = step.reshape(())[()] < rampup_begin
    mask = jnp.logical_or(mask, jnp.broadcast_to(dense, mask.shape))
    maskf = mask.astype(v_new.dtype)
    encode = v_new * maskf
    return {"UOut": u_new * (1.0 - maskf),
            "VOut": v_new * (1.0 - maskf),
            "EncodeGrad": encode,
            "StepOut": step + 1.0}
