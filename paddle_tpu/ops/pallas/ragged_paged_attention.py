"""Ragged paged attention for TPU serving (Pallas kernel + reference).

The serving runtime (paddle_tpu/serving) keeps the KV cache in
fixed-size HBM *pages* shared by every live request; each sequence owns
a block table naming its pages in order. One batch then mixes
sequences of wildly different lengths — long prefills next to
single-token decodes — and a dense [B, S, S] attention would burn both
HBM and MXU time on padding. This kernel is the TPU-native answer
(after "Ragged Paged Attention: A High-Performance and Flexible LLM
Inference Kernel for TPU", arXiv 2604.15464): ONE kernel walks each
sequence's block table with scalar prefetch, computes online-softmax
attention page by page in VMEM (the flash_attention.py recipe), and
masks by per-sequence query/context lengths — so a mixed
prefill+decode batch is a single fixed-shape dispatch regardless of
how ragged the real lengths are.

Semantics (shared by kernel and reference, golden-tested against the
dense `reference_attention`):

- ``q``             [S, Q, Hq, D] — Q is the padded per-sequence query
                    length (1 for pure decode buckets);
- ``k_pages``/``v_pages`` [P, page_size, Hkv, D] — the paged KV cache;
                    Hq must be a multiple of Hkv (GQA: query head h
                    reads kv head h // (Hq // Hkv));
- ``block_tables``  [S, pages_per_seq] int32 — page ids per sequence,
                    in order; entries past the live context must still
                    be valid page indices (pad with 0);
- ``context_lens``  [S] int32 — total tokens of the sequence ALREADY
                    WRITTEN to the cache, including this call's query
                    tokens (the serving step writes K/V first, then
                    attends);
- ``q_lens``        [S] int32 — valid query rows per sequence (None =
                    all Q rows valid). A row i < q_lens[s] has absolute
                    position ``context_lens[s] - q_lens[s] + i`` and
                    attends every cached position <= its own (causal).
                    Rows >= q_lens[s] (and whole sequences with
                    q_lens == 0 — inactive batch slots) return zeros.

On non-TPU backends the kernel runs under the Pallas interpreter, but
it is grid-sequential there — the serving engine's CPU tier-1 path
uses the jittable pure-JAX ``ragged_paged_attention_reference``
instead (``impl="auto"``), which implements the identical contract.
Inference-only by design: no VJP (the serving path never
differentiates through the cache).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .flash_attention import (_HAS_PLTPU, _LANES, _NEG_INF,
                              _compiler_params, _interpret_default,
                              _vmem, pltpu)

__all__ = ["ragged_paged_attention", "ragged_paged_attention_reference"]


def _check_args(q, k_pages, v_pages, block_tables, context_lens, q_lens,
                k_scale=None, v_scale=None):
    S, Q, Hq, D = q.shape
    P, page_size, Hkv, Dk = k_pages.shape
    if v_pages.shape != k_pages.shape:
        raise ValueError("k_pages %s != v_pages %s"
                         % (k_pages.shape, v_pages.shape))
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if k_scale is not None:
        if k_scale.shape != (P, page_size) or \
                v_scale.shape != (P, page_size):
            raise ValueError(
                "k_scale/v_scale must be [num_pages, page_size] = %s, "
                "got %s / %s" % ((P, page_size), k_scale.shape,
                                 v_scale.shape))
    if Dk != D:
        raise ValueError("head_dim mismatch: q %d vs pages %d" % (D, Dk))
    if Hq % Hkv != 0:
        raise ValueError("q heads %d not a multiple of kv heads %d"
                         % (Hq, Hkv))
    if block_tables.ndim != 2 or block_tables.shape[0] != S:
        raise ValueError("block_tables must be [S, pages_per_seq], got %s"
                         % (block_tables.shape,))
    if context_lens.shape != (S,):
        raise ValueError("context_lens must be [S], got %s"
                         % (context_lens.shape,))
    if q_lens is not None and q_lens.shape != (S,):
        raise ValueError("q_lens must be [S], got %s" % (q_lens.shape,))


# ---------------------------------------------------------------------------
# Pure-JAX reference (jittable; the serving engine's CPU path)
# ---------------------------------------------------------------------------

def ragged_paged_attention_reference(q, k_pages, v_pages, block_tables,
                                     context_lens, q_lens=None, *,
                                     sm_scale=None, k_scale=None,
                                     v_scale=None):
    """Gather-then-mask reference with the exact kernel semantics.

    Fixed shapes throughout (the gather spans the FULL block table, not
    the batch's max context), so per-row results are independent of how
    the batch was packed — the property the serving engine's
    bit-identical continuous-batching contract rests on.

    `k_scale`/`v_scale` ([num_pages, page_size] fp32, both or neither)
    dequantize int8 pages in-flight: the gathered slot values are
    multiplied by their per-slot abs-max scale before the attention
    math, so quantized pages never materialize densely outside f32
    registers. With scales absent the computation is byte-identical to
    the pre-quantization reference."""
    q = jnp.asarray(q)
    k_pages = jnp.asarray(k_pages)
    v_pages = jnp.asarray(v_pages)
    block_tables = jnp.asarray(block_tables, jnp.int32)
    context_lens = jnp.asarray(context_lens, jnp.int32)
    if q_lens is not None:
        q_lens = jnp.asarray(q_lens, jnp.int32)
    if k_scale is not None:
        k_scale = jnp.asarray(k_scale, jnp.float32)
    if v_scale is not None:
        v_scale = jnp.asarray(v_scale, jnp.float32)
    _check_args(q, k_pages, v_pages, block_tables, context_lens, q_lens,
                k_scale, v_scale)
    S, Q, Hq, D = q.shape
    P, page_size, Hkv, _ = k_pages.shape
    G = Hq // Hkv
    npages = block_tables.shape[1]
    kvmax = npages * page_size
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    if q_lens is None:
        q_lens = jnp.full((S,), Q, jnp.int32)

    # [S, kvmax, Hkv, D] — every sequence's pages, in table order
    k = k_pages[block_tables].reshape(S, kvmax, Hkv, D)
    v = v_pages[block_tables].reshape(S, kvmax, Hkv, D)
    if k_scale is not None:
        ks = k_scale[block_tables].reshape(S, kvmax)[:, :, None, None]
        vs = v_scale[block_tables].reshape(S, kvmax)[:, :, None, None]
        k = k.astype(jnp.float32) * ks
        v = v.astype(jnp.float32) * vs

    qf = q.astype(jnp.float32).reshape(S, Q, Hkv, G, D)
    s = jnp.einsum("sqhgd,skhd->shgqk", qf, k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * sm_scale

    kpos = lax.broadcasted_iota(jnp.int32, (1, 1, 1, 1, kvmax), 4)
    qrow = lax.broadcasted_iota(jnp.int32, (1, 1, 1, Q, 1), 3)
    qpos = (context_lens - q_lens)[:, None, None, None, None] + qrow
    valid = (kpos <= qpos) & (qrow < q_lens[:, None, None, None, None])
    s = jnp.where(valid, s, _NEG_INF)

    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(valid, p, 0.0)  # fully-masked rows: exp(0)=1 otherwise
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("shgqk,skhd->shgqd", p, v.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    o = o / jnp.where(l == 0.0, 1.0, l)
    # [S, Hkv, G, Q, D] -> [S, Q, Hq, D]
    return o.transpose(0, 3, 1, 2, 4).reshape(S, Q, Hq, D).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _rpa_kernel(tbl_ref, ctx_ref, qlen_ref, q_ref, k_ref, v_ref, o_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, page_size, q_rows,
                gq_rows, ks_ref=None, vs_ref=None):
    """Grid (S, Hkv, pages_per_seq); innermost page dim is sequential
    and carries the online-softmax (m, l, acc) state in VMEM scratch.
    The q block is the GQA-packed [G*Q, D] row block for (seq, kv
    head); row r maps to query group g = r // Q, row i = r % Q.
    `ks_ref`/`vs_ref` (quantized pool only) hold the page's per-slot
    fp32 scales; int8 K/V dequantize in VMEM right after the load."""
    s_idx = pl.program_id(0)
    j = pl.program_id(2)
    npages = pl.num_programs(2)
    ctx = ctx_ref[s_idx]
    qlen = qlen_ref[s_idx]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # dead page: nothing of this sequence's context lives at j
    @pl.when(j * page_size < ctx)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)          # [GQ, D]
        k = k_ref[0, 0].astype(jnp.float32)          # [page, D]
        if ks_ref is not None:
            k = k * ks_ref[0][:, None]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        s = s * sm_scale                             # [GQ, page]
        rows = lax.broadcasted_iota(jnp.int32, (gq_rows, page_size), 0)
        qi = rows - (rows // q_rows) * q_rows        # row i within Q
        kpos = j * page_size + lax.broadcasted_iota(
            jnp.int32, (gq_rows, page_size), 1)
        qpos = ctx - qlen + qi
        s = jnp.where((kpos <= qpos) & (qi < qlen), s, _NEG_INF)

        m_prev = m_scr[:]                            # [GQ, LANES]
        l_prev = l_scr[:]
        m_curr = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_curr)
        p = jnp.exp(s - m_next[:, :1])
        # a fully-masked row keeps m == -inf: exp(-inf - -inf) = nan —
        # zero it so l stays 0 and the final write outputs zeros
        p = jnp.where(m_next[:, :1] == _NEG_INF, 0.0, p)
        alpha = jnp.exp(m_prev - m_next)
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:] = m_next
        v = v_ref[0, 0].astype(jnp.float32)
        if vs_ref is not None:
            v = v * vs_ref[0][:, None]
        pv = lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha[:, :1] + pv

    @pl.when(j == npages - 1)
    def _final():
        l_row = jnp.max(l_scr[:], axis=-1, keepdims=True)
        l_safe = jnp.where(l_row == 0.0, 1.0, l_row)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)


def _rpa_kernel_quant(tbl_ref, ctx_ref, qlen_ref, q_ref, k_ref, v_ref,
                      ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr,
                      **kw):
    """Operand-order adapter for the quantized pool: pallas passes the
    two scale blocks positionally after v; the body is `_rpa_kernel`."""
    _rpa_kernel(tbl_ref, ctx_ref, qlen_ref, q_ref, k_ref, v_ref, o_ref,
                m_scr, l_scr, acc_scr, ks_ref=ks_ref, vs_ref=vs_ref,
                **kw)


def _rpa_call_impl(q_packed, k_heads, v_heads, block_tables,
                   context_lens, q_lens, *, sm_scale, q_rows, interpret,
                   k_scale=None, v_scale=None):
    """q_packed: [S, Hkv, G*Q, D]; k_heads/v_heads: [Hkv, P, page, D];
    k_scale/v_scale (optional): [P, page] fp32 per-slot dequant scales.
    Returns [S, Hkv, G*Q, D]."""
    S, Hkv, GQ, D = q_packed.shape
    _, P, page_size, _ = k_heads.shape
    npages = block_tables.shape[1]
    quant = k_scale is not None

    kernel = functools.partial(
        _rpa_kernel_quant if quant else _rpa_kernel,
        sm_scale=sm_scale, page_size=page_size, q_rows=q_rows,
        gq_rows=GQ)

    in_specs = [
        pl.BlockSpec((1, 1, GQ, D),
                     lambda s, h, j, tbl, ctx, ql: (s, h, 0, 0)),
        pl.BlockSpec((1, 1, page_size, D),
                     lambda s, h, j, tbl, ctx, ql:
                     (h, tbl[s, j], 0, 0)),
        pl.BlockSpec((1, 1, page_size, D),
                     lambda s, h, j, tbl, ctx, ql:
                     (h, tbl[s, j], 0, 0)),
    ]
    operands = [block_tables, context_lens, q_lens, q_packed, k_heads,
                v_heads]
    if quant:
        in_specs += [
            pl.BlockSpec((1, page_size),
                         lambda s, h, j, tbl, ctx, ql: (tbl[s, j], 0)),
            pl.BlockSpec((1, page_size),
                         lambda s, h, j, tbl, ctx, ql: (tbl[s, j], 0)),
        ]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, Hkv, npages),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, GQ, D), lambda s, h, j, tbl, ctx, ql: (s, h, 0, 0)),
        scratch_shapes=[
            _vmem((GQ, _LANES), jnp.float32),
            _vmem((GQ, _LANES), jnp.float32),
            _vmem((GQ, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, Hkv, GQ, D), q_packed.dtype),
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(*operands)


def ragged_paged_attention(q, k_pages, v_pages, block_tables,
                           context_lens, q_lens=None, *, sm_scale=None,
                           impl="auto", interpret=None, k_scale=None,
                           v_scale=None):
    """Paged attention over mixed-length sequences through a block
    table (see module docstring for the argument contract).

    impl: "kernel" = the Pallas kernel (Mosaic on TPU, interpreter
    elsewhere), "reference" = the jittable pure-JAX gather reference,
    "auto" = kernel on TPU, reference on CPU/GPU — the interpreter is
    grid-sequential and only meant for kernel parity tests.

    k_scale/v_scale ([num_pages, page_size] fp32, both or neither):
    per-slot dequantization scales for int8 pages — kernel and
    reference multiply each slot's K/V by its scale in f32 before the
    attention math. Omitting them keeps the float paths byte-identical
    to the pre-quantization op."""
    q = jnp.asarray(q)
    k_pages = jnp.asarray(k_pages)
    v_pages = jnp.asarray(v_pages)
    block_tables = jnp.asarray(block_tables, jnp.int32)
    context_lens = jnp.asarray(context_lens, jnp.int32)
    if q_lens is not None:
        q_lens = jnp.asarray(q_lens, jnp.int32)
    if k_scale is not None:
        k_scale = jnp.asarray(k_scale, jnp.float32)
    if v_scale is not None:
        v_scale = jnp.asarray(v_scale, jnp.float32)
    _check_args(q, k_pages, v_pages, block_tables, context_lens, q_lens,
                k_scale, v_scale)
    if impl not in ("auto", "kernel", "reference"):
        raise ValueError("impl must be auto|kernel|reference, got %r"
                         % (impl,))
    if impl == "kernel" and not _HAS_PLTPU:
        raise ImportError(
            "impl='kernel' needs jax.experimental.pallas.tpu "
            "(PrefetchScalarGridSpec) — this install lacks it; use "
            "impl='reference'")
    use_kernel = _HAS_PLTPU and (
        impl == "kernel"
        or (impl == "auto" and not _interpret_default()))
    if not use_kernel:
        return ragged_paged_attention_reference(
            q, k_pages, v_pages, block_tables, context_lens, q_lens,
            sm_scale=sm_scale, k_scale=k_scale, v_scale=v_scale)

    S, Q, Hq, D = q.shape
    P, page_size, Hkv, _ = k_pages.shape
    G = Hq // Hkv
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    if q_lens is None:
        q_lens = jnp.full((S,), Q, jnp.int32)
    if interpret is None:
        interpret = _interpret_default()

    # GQA packing: [S, Q, Hq, D] -> [S, Hkv, G*Q, D]; query head
    # h = kv*G + g shares kv head kv, so group-major rows r = g*Q + i
    q_packed = q.reshape(S, Q, Hkv, G, D).transpose(0, 2, 3, 1, 4) \
        .reshape(S, Hkv, G * Q, D)
    k_heads = k_pages.transpose(2, 0, 1, 3)   # [Hkv, P, page, D]
    v_heads = v_pages.transpose(2, 0, 1, 3)
    o = _rpa_call_impl(q_packed, k_heads, v_heads, block_tables,
                       context_lens, q_lens, sm_scale=float(sm_scale),
                       q_rows=Q, interpret=bool(interpret),
                       k_scale=k_scale, v_scale=v_scale)
    return o.reshape(S, Hkv, G, Q, D).transpose(0, 3, 1, 2, 4) \
        .reshape(S, Q, Hq, D)
