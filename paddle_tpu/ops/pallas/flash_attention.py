"""Flash attention for TPU as a Pallas kernel (forward + backward).

Reference parity: the reference fuses inference attention by hand in CUDA
(`paddle/fluid/operators/math/bert_encoder_functor.cu`,
`operators/fused/multihead_matmul_op.cu`); training attention is unfused
matmul/softmax ops (`python/paddle/fluid/layers/nn.py` stacks). TPU-native
design: ONE blockwise online-softmax kernel (Dao et al. FlashAttention
recipe) that keeps the [S, S] score matrix out of HBM entirely — scores
live tile-by-tile in VMEM, the MXU does the two matmuls per tile, and the
running (m, l, acc) statistics are carried in VMEM scratch across the
sequential innermost grid dimension. Backward recomputes tiles the same
way (no O(S^2) residuals; only the per-row logsumexp is saved).

Layout: q, k, v are [B, H, S, D]; internally flattened to [B*H, S, D].
`key_bias` is an additive [B, S_k] bias on the keys (the BERT padding
mask); it is treated as non-differentiable (its cotangent is zero), which
matches how masks are used everywhere in the reference.

On non-TPU backends the same kernels run under the Pallas interpreter so
CPU CI exercises the identical code path.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU-specific memory spaces; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG_INF = -1e30
_LANES = 128  # VREG lane count: scratch stats are replicated across lanes


def _dropout_mask(seed, bh, row0, col0, block_q, block_k, p_drop):
    """Per-element keep/scale mask for attention-prob dropout, from a
    counter-based hash (murmur3 finalizer over the GLOBAL (row, col,
    batch*head, seed) coordinates). Deterministic per coordinate, so the
    backward kernels regenerate the identical mask regardless of grid
    iteration order, with no O(S^2) HBM mask buffer — the whole point of
    the flash recipe. Plain uint32 vector ops: lowers under Mosaic and
    the interpreter alike (pltpu.prng_* has no CPU interpret rule
    here)."""
    # every operand must be uint32 BEFORE arithmetic: row0/col0/bh are
    # traced int32 (program_id), and int32+uint32 promotion would make
    # the multiplies signed and the shifts arithmetic
    row0 = jnp.asarray(row0).astype(jnp.uint32)
    col0 = jnp.asarray(col0).astype(jnp.uint32)
    rows = row0 + lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 0)
    cols = col0 + lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 1)
    x = (rows * jnp.uint32(0x9E3779B1)) ^ (cols * jnp.uint32(0x85EBCA77))
    x = x ^ (jnp.asarray(bh).astype(jnp.uint32)
             * jnp.uint32(0xC2B2AE3D)) ^ seed.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    thresh = jnp.uint32(min(int(p_drop * 4294967296.0), 0xFFFFFFFF))
    return jnp.where(x >= thresh, 1.0 / (1.0 - p_drop),
                     0.0).astype(jnp.float32)


def _seed_spec():
    # scalar dropout seed rides in SMEM (full-array spec; one int32)
    if _HAS_PLTPU:
        return pl.BlockSpec(memory_space=pltpu.SMEM)
    return pl.BlockSpec(memory_space=pl.MemorySpace.ANY)  # pragma: no cover


def _interpret_default() -> bool:
    # Real Mosaic kernels only lower for TPU; interpret everywhere else
    # (CPU tests, GPU installs).
    return jax.default_backend() != "tpu"


def _compiler_params():
    # Outer two grid dims are embarrassingly parallel; only the innermost
    # (the online-softmax / accumulation dim) is sequential.
    if _HAS_PLTPU:
        try:
            return pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"))
        except Exception:  # older jax: TPUCompilerParams
            return pltpu.TPUCompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"))
    return None


def _vmem(shape, dtype):
    if _HAS_PLTPU:
        return pltpu.VMEM(shape, dtype)
    return pl.MemorySpace.ANY(shape, dtype)  # pragma: no cover


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, seed_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, causal,
                block_q, block_k, p_drop):
    bh = pl.program_id(0)
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # A causal block is live unless every (row, col) pair has col > row.
    live = (iq * block_q + block_q - 1 >= ik * block_k) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        s = s * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)   # (1, bk) broadcast
        if causal:
            rows = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)

        m_prev = m_scr[:]                       # [bq, LANES] lane-replicated
        l_prev = l_scr[:]
        m_curr = jnp.max(s, axis=-1, keepdims=True)      # [bq, 1]
        m_next = jnp.maximum(m_prev, m_curr)             # [bq, LANES]
        p = jnp.exp(s - m_next[:, :1])                   # [bq, bk]
        alpha = jnp.exp(m_prev - m_next)                 # [bq, LANES]
        # l accumulates the PRE-dropout sums: the softmax denominator is
        # over the full probs; dropout only zeroes/rescales the numerator
        # (out = dropout(softmax(s)) @ v)
        l_scr[:] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_scr[:] = m_next
        if p_drop > 0.0:
            p = p * _dropout_mask(seed_ref[0].astype(jnp.uint32), bh,
                                  iq * block_q, ik * block_k,
                                  block_q, block_k, p_drop)
        pv = lax.dot_general(p, v_ref[0].astype(jnp.float32),
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * alpha[:, :1] + pv

    @pl.when(ik == nk - 1)
    def _final():
        # All lanes of m/l are equal; a lane-reduce reads them cheaply.
        l_row = jnp.max(l_scr[:], axis=-1, keepdims=True)   # [bq, 1]
        m_row = jnp.max(m_scr[:], axis=-1, keepdims=True)   # [bq, 1]
        l_safe = jnp.where(l_row == 0.0, 1.0, l_row)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_row + jnp.log(l_safe)                # [bq, 1]


def _fwd_call(q, k, v, key_bias, seed, sm_scale, causal, block_q,
              block_k, p_drop, interpret):
    BH, S, D = q.shape
    Sk = k.shape[1]
    nq, nk = S // block_q, Sk // block_k
    grid = (BH, nq, nk)

    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
    ]
    args = [q, k, v]
    has_bias = key_bias is not None
    has_drop = p_drop > 0.0
    if has_bias:
        # [BH, 1, Sk]: lane-layout so (1, bk) broadcasts over score rows
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)))
        args.append(key_bias)
    if has_drop:
        in_specs.append(_seed_spec())
        args.append(seed)

    def kernel(*refs):
        ins = refs[:len(args)]
        bias_ref = ins[3] if has_bias else None
        seed_ref = ins[3 + int(has_bias)] if has_drop else None
        o_ref, lse_ref, m_scr, l_scr, acc_scr = refs[len(args):]
        return _fwd_kernel(ins[0], ins[1], ins[2], bias_ref, seed_ref,
                           o_ref, lse_ref, m_scr, l_scr, acc_scr,
                           sm_scale=sm_scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           p_drop=p_drop)

    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            # [BH, S, 1]: sublane-layout so lse reads back as (bq, 1)
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((block_q, _LANES), jnp.float32),
            _vmem((block_q, _LANES), jnp.float32),
            _vmem((block_q, D), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                    bias_ref, seed_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                    *, sm_scale, causal, block_q, block_k, p_drop):
    bh = pl.program_id(0)
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    live = (iq * block_q + block_q - 1 >= ik * block_k) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)      # (1, bk)
        if causal:
            rows = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])                      # [bq, bk]
        # with dropout: O = (P∘M) @ V, so dV = (P∘M)^T @ dO and
        # dP = (dO @ V^T)∘M; delta = rowsum(dO∘O) is unchanged because
        # rowsum((P∘M)∘dZ) = rowsum(dO∘O) still holds with Z = P∘M
        if p_drop > 0.0:
            mask = _dropout_mask(seed_ref[0].astype(jnp.uint32), bh,
                                 iq * block_q, ik * block_k,
                                 block_q, block_k, p_drop)
            z = p * mask
        else:
            z = p
        # dv += (p∘M)^T @ do
        dv_scr[:] = dv_scr[:] + lax.dot_general(
            z, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do @ v^T ; ds = p * (dp∘M - delta)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        if p_drop > 0.0:
            dp = dp * mask
        ds = p * (dp - delta_ref[0]) * sm_scale
        # dk += ds^T @ q
        dk_scr[:] = dk_scr[:] + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _final():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                   bias_ref, seed_ref, dq_ref, dq_scr, *,
                   sm_scale, causal, block_q, block_k, p_drop):
    bh = pl.program_id(0)
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    live = (iq * block_q + block_q - 1 >= ik * block_k) if causal else True

    @pl.when(live)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)      # (1, bk)
        if causal:
            rows = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ik * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        if p_drop > 0.0:
            dp = dp * _dropout_mask(
                seed_ref[0].astype(jnp.uint32), bh, iq * block_q,
                ik * block_k, block_q, block_k, p_drop)
        ds = p * (dp - delta_ref[0]) * sm_scale
        dq_scr[:] = dq_scr[:] + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _final():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_call(q, k, v, key_bias, seed, o, lse, do, sm_scale, causal,
              block_q, block_k, p_drop, interpret):
    BH, S, D = q.shape
    Sk = k.shape[1]
    nq, nk = S // block_q, Sk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                   # [BH, S, 1]

    has_bias = key_bias is not None
    has_drop = p_drop > 0.0

    def dkv_kernel(*refs):
        n_in = 6 + int(has_bias) + int(has_drop)
        ins = refs[:n_in]
        bias_ref = ins[6] if has_bias else None
        seed_ref = ins[6 + int(has_bias)] if has_drop else None
        dk_ref, dv_ref, dk_scr, dv_scr = refs[n_in:]
        _bwd_dkv_kernel(ins[0], ins[1], ins[2], ins[3], ins[4], ins[5],
                        bias_ref, seed_ref, dk_ref, dv_ref, dk_scr,
                        dv_scr, sm_scale=sm_scale, causal=causal,
                        block_q=block_q, block_k=block_k, p_drop=p_drop)

    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),  # q
        pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),  # do
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),  # lse
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),  # delta
        pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),  # k
        pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),  # v
    ]
    args = [q, do, lse, delta, k, v]
    if has_bias:
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b, 0, j)))
        args.append(key_bias)
    if has_drop:
        in_specs.append(_seed_spec())
        args.append(seed)

    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, nk, nq),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            _vmem((block_k, D), jnp.float32),
            _vmem((block_k, D), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(*args)

    def dq_kernel(*refs):
        n_in = 6 + int(has_bias) + int(has_drop)
        ins = refs[:n_in]
        bias_ref = ins[6] if has_bias else None
        seed_ref = ins[6 + int(has_bias)] if has_drop else None
        dq_ref, dq_scr = refs[n_in:]
        _bwd_dq_kernel(ins[0], ins[1], ins[2], ins[3], ins[4], ins[5],
                       bias_ref, seed_ref, dq_ref, dq_scr,
                       sm_scale=sm_scale, causal=causal,
                       block_q=block_q, block_k=block_k, p_drop=p_drop)

    in_specs_q = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),  # q
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),  # do
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),  # lse
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),  # delta
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),  # k
        pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),  # v
    ]
    if has_bias:
        in_specs_q.append(
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)))
    if has_drop:
        in_specs_q.append(_seed_spec())

    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, nq, nk),
        in_specs=in_specs_q,
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[_vmem((block_q, D), jnp.float32)],
        interpret=interpret,
        compiler_params=_compiler_params(),
    )(*args)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public entry: padding wrapper + custom VJP
# ---------------------------------------------------------------------------

def _pad_to(x, axis, mult, value=0.0):
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _flash_core(q, k, v, key_bias, seed, sm_scale, causal, block_q,
                block_k, p_drop):
    """custom_vjp wrapper. The int32 dropout `seed` is deliberately NOT
    a differentiable positional arg of the custom_vjp (integer tangents
    are float0 on current JAX, but relying on the bwd returning a None
    cotangent for it is exactly the structure detail that breaks across
    JAX upgrades — ADVICE r5): the vjp is built per-call with `seed`
    closed over, so only the genuinely differentiable q/k/v/bias appear
    in the vjp signature. Building it per call costs one python closure
    per trace — the pallas_call inside dominates by orders of
    magnitude, and under jit it traces exactly as often as before."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
    def core(q, k, v, key_bias, sm_scale, causal, block_q, block_k,
             p_drop):
        o, _ = _fwd_call(q, k, v, key_bias, seed, sm_scale, causal,
                         block_q, block_k, p_drop, _interpret_default())
        return o

    def core_fwd(q, k, v, key_bias, sm_scale, causal, block_q, block_k,
                 p_drop):
        o, lse = _fwd_call(q, k, v, key_bias, seed, sm_scale, causal,
                           block_q, block_k, p_drop,
                           _interpret_default())
        return o, (q, k, v, key_bias, o, lse)

    def core_bwd(sm_scale, causal, block_q, block_k, p_drop, res, do):
        q, k, v, key_bias, o, lse = res
        dq, dk, dv = _bwd_call(q, k, v, key_bias, seed, o, lse, do,
                               sm_scale, causal, block_q, block_k,
                               p_drop, _interpret_default())
        dbias = None if key_bias is None else jnp.zeros_like(key_bias)
        return dq, dk, dv, dbias

    core.defvjp(core_fwd, core_bwd)
    return core(q, k, v, key_bias, sm_scale, causal, block_q, block_k,
                p_drop)


def flash_attention(q, k, v, key_bias=None, causal=False, sm_scale=None,
                    block_q=128, block_k=128, dropout_p=0.0,
                    dropout_seed=None):
    """Blockwise (flash) attention.

    q: [B, H, Sq, D]; k, v: [B, H, Sk, D]; key_bias: optional [B, Sk]
    additive bias on keys (e.g. `(mask - 1) * 1e4` padding bias;
    non-differentiable). Returns [B, H, Sq, D] in q.dtype.

    dropout_p > 0 applies upscale-in-train dropout to the normalized
    attention probs INSIDE the kernel (mask regenerated from
    (dropout_seed, coordinates) in backward — no O(S^2) mask buffer),
    so dropout-active pretraining can run the flash path. dropout_seed:
    int32 scalar (traced is fine), required when dropout_p > 0.
    """
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    dropout_p = float(dropout_p)
    if not 0.0 <= dropout_p < 1.0:
        raise ValueError("dropout_p must be in [0, 1): %r" % dropout_p)
    seed = None
    if dropout_p > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_p > 0 requires dropout_seed")
        seed = jnp.reshape(dropout_seed, (1,)).astype(jnp.int32)

    block_q = min(block_q, -(-Sq // 8) * 8)
    block_k = min(block_k, -(-Sk // 8) * 8)

    qf = _pad_to(q.reshape(B * H, Sq, D), 1, block_q)
    kf = _pad_to(k.reshape(B * H, Sk, D), 1, block_k)
    vf = _pad_to(v.reshape(B * H, Sk, D), 1, block_k)

    pad_k = (-Sk) % block_k
    bias = key_bias
    if pad_k and bias is None:
        bias = jnp.zeros((B, Sk), jnp.float32)
    if bias is not None:
        bias = _pad_to(bias.astype(jnp.float32), 1, block_k,
                       value=_NEG_INF)
        # one bias row per (b, h) program, lane-layout [BH, 1, Sk]
        bias = jnp.repeat(bias, H, axis=0)[:, None, :]

    o = _flash_core(qf, kf, vf, bias, seed, float(sm_scale),
                    bool(causal), int(block_q), int(block_k), dropout_p)
    return o[:, :Sq, :].reshape(B, H, Sq, D)


def reference_attention(q, k, v, key_bias=None, causal=False,
                        sm_scale=None):
    """Naive XLA attention with identical semantics (golden reference)."""
    D = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if key_bias is not None:
        s = s + key_bias[:, None, None, :].astype(jnp.float32)
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        rows = lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        cols = lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
