"""Pallas TPU kernels for the hot ops (flash attention, ...).

These are the hand-scheduled kernels sitting below the XLA-lowered op
registry — the TPU-native counterpart of the reference's hand-written
CUDA in `paddle/fluid/operators/fused/` and `operators/math/`.
"""
from .flash_attention import flash_attention, reference_attention  # noqa: F401
from .ragged_paged_attention import (  # noqa: F401
    ragged_paged_attention, ragged_paged_attention_reference)
