"""Operator registry: the single compute layer shared by static graph
lowering, the eager (dygraph) engine, and OpTest golden tests.

Reference parity: `paddle/fluid/framework/op_registry.h:223-295` registers
each op type with CPU/CUDA kernels, and `OperatorWithKernel::RunImpl`
(`operator.cc:908-1030`) dispatches on (place, dtype, layout). TPU-native
design: every op is ONE pure jax function `compute(ins, attrs) -> outs`;
device dispatch, layout, fusion, and memory planning all belong to XLA.
Shape/dtype inference (reference: `shape_inference.h`) falls out for free
via `jax.eval_shape` over the same compute function — no per-op InferShape
code to keep in sync with kernels.

Autodiff: the reference hand-writes a GradOpMaker per op
(`grad_op_desc_maker.h`); here gradients come from jax.vjp over the traced
forward segment (see fluid/backward.py), so no per-op grad rules exist to
get wrong.
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, List, Optional

import numpy as np

_REGISTRY: Dict[str, "OpDef"] = {}

# Sentinel dimension used in place of -1 ("any batch") during compile-time
# shape inference; mapped back to -1 in inferred output shapes.
_DYN_SENTINEL = 97


class OpDef:
    __slots__ = ("type", "compute", "needs_rng", "infer_shape", "n_outputs",
                 "no_jit", "dynamic_shape")

    def __init__(self, type_: str, compute: Callable, needs_rng: bool = False,
                 infer_shape: Optional[Callable] = None,
                 no_jit: bool = False, dynamic_shape: bool = False):
        self.type = type_
        self.compute = compute
        self.needs_rng = needs_rng
        self.infer_shape = infer_shape
        # host-side op (numpy compute); lowers via pure_callback in jit
        self.no_jit = no_jit
        # output SHAPE depends on input VALUES (NMS-style): cannot run
        # under jit at all; the block executes unjitted instead
        self.dynamic_shape = dynamic_shape


def register_op(type_: str, needs_rng: bool = False,
                infer_shape: Optional[Callable] = None,
                no_jit: bool = False, dynamic_shape: bool = False):
    """Decorator: register `compute(ins, attrs) -> {slot: [array, ...]}`.

    `ins` maps input slot name -> list of jax arrays (possibly empty).
    Returned dict values may be a single array or a list of arrays.
    RNG ops receive a jax PRNG key in attrs['_rng_key'].
    """

    def deco(fn):
        _REGISTRY[type_] = OpDef(type_, fn, needs_rng=needs_rng,
                                 infer_shape=infer_shape, no_jit=no_jit,
                                 dynamic_shape=dynamic_shape)
        return fn

    return deco


def get_op(type_: str) -> OpDef:
    try:
        return _REGISTRY[type_]
    except KeyError:
        raise NotImplementedError(
            "op %r is not registered in paddle_tpu.ops (have %d ops)"
            % (type_, len(_REGISTRY)))


def has_op(type_: str) -> bool:
    return type_ in _REGISTRY


def registered_ops() -> List[str]:
    return sorted(_REGISTRY)


def normalize_outs(outs) -> Dict[str, list]:
    normed = {}
    for slot, v in outs.items():
        if isinstance(v, (list, tuple)):
            normed[slot] = list(v)
        else:
            normed[slot] = [v]
    return normed


def run_op(type_: str, ins: Dict[str, list], attrs: dict) -> Dict[str, list]:
    """Execute an op's compute function (inside or outside a trace)."""
    op = get_op(type_)
    attrs = dict(attrs)
    if op.needs_rng and "_rng_key" not in attrs:
        from ..core.rng import make_key

        attrs["_rng_key"] = make_key(np.random.randint(0, 2**31 - 1))
    return normalize_outs(op.compute(ins, attrs))


# ---------------------------------------------------------------------------
# Compile-time shape/dtype inference via jax.eval_shape.
# ---------------------------------------------------------------------------

def infer_outputs(type_: str, input_specs: Dict[str, list], attrs: dict):
    """input_specs: slot -> list of (shape_tuple_with_-1, dtype_str).

    Returns slot -> list of (shape_tuple_with_-1, dtype_str).
    """
    import jax

    op = get_op(type_)
    if op.infer_shape is not None:
        return op.infer_shape(input_specs, attrs)

    if op.no_jit:
        # host ops run numpy code that cannot be traced by eval_shape;
        # probe shapes by executing on zero-filled concrete inputs. Dims
        # that come from a dynamic (-1) input dim are found by probing
        # TWICE with different sentinel extents: only dims that track the
        # sentinel change are dynamic (an honest static dim of size 97
        # stays put).
        from ..core.types import to_numpy_dtype, normalize_dtype

        had_dynamic = any(
            d is None or d < 0
            for specs in input_specs.values() for shape, _ in specs
            for d in shape)

        def probe(sentinel):
            zeros = {
                slot: [np.zeros([d if (d is not None and d >= 0)
                                 else sentinel for d in shape],
                                to_numpy_dtype(dtype))
                       for shape, dtype in specs]
                for slot, specs in input_specs.items()
            }
            run_attrs = dict(attrs)
            if op.needs_rng:
                from ..core.rng import make_key

                run_attrs["_rng_key"] = make_key(0)
            return normalize_outs(op.compute(zeros, run_attrs))

        outs = probe(_DYN_SENTINEL)
        outs2 = probe(89) if had_dynamic else outs

        result = {}
        for slot, vs in outs.items():
            specs = []
            for v, v2 in zip(vs, outs2[slot]):
                s1 = np.asarray(v).shape
                s2 = np.asarray(v2).shape
                shape = tuple(
                    -1 if (len(s1) == len(s2) and a != b) else int(a)
                    for a, b in zip(s1, s2)) if had_dynamic else                     tuple(int(d) for d in s1)
                specs.append((shape,
                              normalize_dtype(np.asarray(v).dtype)))
            result[slot] = specs
        return result

    dyn_axes = set()

    def to_struct(spec):
        shape, dtype = spec
        concrete = []
        for d in shape:
            if d is None or d < 0:
                concrete.append(_DYN_SENTINEL)
                dyn_axes.add(_DYN_SENTINEL)
            else:
                concrete.append(int(d))
        from ..core.types import to_numpy_dtype
        return jax.ShapeDtypeStruct(tuple(concrete), to_numpy_dtype(dtype))

    struct_ins = {
        slot: [to_struct(s) for s in specs]
        for slot, specs in input_specs.items()
    }
    run_attrs = dict(attrs)

    def fn(tree_ins, key):
        a = dict(run_attrs)
        if op.needs_rng:
            a["_rng_key"] = key
        return normalize_outs(op.compute(tree_ins, a))

    # a typed key from the SAME impl runtime tracing uses — a raw
    # uint32[2] struct here only worked through JAX's legacy raw-key
    # acceptance and diverges from the rbg path
    from ..core.rng import make_key

    key_struct = jax.eval_shape(lambda: make_key(0))
    out_struct = jax.eval_shape(fn, struct_ins, key_struct)

    from ..core.types import normalize_dtype

    result = {}
    for slot, structs in out_struct.items():
        specs = []
        for s in structs:
            shape = tuple(
                -1 if (dyn_axes and d == _DYN_SENTINEL) else int(d)
                for d in s.shape)
            specs.append((shape, normalize_dtype(s.dtype)))
        result[slot] = specs
    return result


# ---------------------------------------------------------------------------
# Per-op jitted eager execution cache (dygraph fast path).  Reference parity:
# the generated `core.ops.*` fast entry points
# (`pybind/op_function_generator.cc:131-341`).
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=4096)
def _jitted(type_: str, attr_items: tuple, slot_layout: tuple, rng: bool):
    import jax

    op = get_op(type_)
    attrs = dict(attr_items)

    def fn(flat_args, key):
        ins, i = {}, 0
        for slot, n in slot_layout:
            ins[slot] = list(flat_args[i:i + n])
            i += n
        a = dict(attrs)
        if rng:
            a["_rng_key"] = key
        return normalize_outs(op.compute(ins, a))

    return jax.jit(fn)


def _hashable_attr(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable_attr(x) for x in v)
    if isinstance(v, np.ndarray):
        return (v.dtype.str, v.shape, v.tobytes())
    return v


def eager_run(type_: str, ins: Dict[str, list], attrs: dict, rng_key=None):
    """Run one op eagerly through a cached per-op jitted function."""
    import jax

    op = get_op(type_)
    slot_layout = tuple((slot, len(vals)) for slot, vals in sorted(ins.items()))
    flat = [v for _, vals in sorted(ins.items()) for v in vals]
    attr_items = tuple(sorted((k, _hashable_attr(v)) for k, v in attrs.items()
                              if not k.startswith("_")))
    if op.needs_rng and rng_key is None:
        from ..core.rng import make_key

        rng_key = make_key(np.random.randint(0, 2**31 - 1))
    if op.no_jit:
        ins_l = {slot: list(vals) for slot, vals in ins.items()}
        a = dict(attrs)
        if op.needs_rng:
            a["_rng_key"] = rng_key
        return normalize_outs(op.compute(ins_l, a))
    jfn = _jitted(type_, attr_items, slot_layout, op.needs_rng)
    return jfn(flat, rng_key)
