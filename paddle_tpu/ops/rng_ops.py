"""Random / initializer operators (stateless threefry PRNG).

Reference parity: `paddle/fluid/operators/uniform_random_op.cc`,
`gaussian_random_op.cc`, `truncated_gaussian_random_op.cc`,
`randperm_op.cc`, `randint_op.cc`, initializer kernels used by
`python/paddle/fluid/initializer.py`. TPU-native: counter-based stateless
PRNG keys are threaded by the lowering (deterministic given
program.random_seed + op index), instead of the reference's per-device
curand generator state.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op
from ..core.types import to_numpy_dtype


@register_op("uniform_random", needs_rng=True)
def _uniform_random(ins, attrs):
    shape = tuple(int(d) for d in attrs["shape"])
    dtype = to_numpy_dtype(attrs.get("dtype", "float32"))
    lo = attrs.get("min", -1.0)
    hi = attrs.get("max", 1.0)
    out = jax.random.uniform(attrs["_rng_key"], shape, jnp.float32, lo, hi)
    return {"Out": out.astype(dtype)}


@register_op("seed", no_jit=True)
def _seed(ins, attrs):
    """Emit a seed scalar: the fixed attr when nonzero, else a fresh
    random draw (reference: seed_op.h:23 CPUSeedKernel; always host-side
    there too — the output feeds dropout-style seed attrs)."""
    import numpy as np_

    user_seed = int(attrs.get("seed", 0))
    val = user_seed if user_seed != 0 \
        else int(np_.random.randint(0, 2**31 - 1))
    return {"Out": np_.asarray([val], np_.int32)}


@register_op("uniform_random_batch_size_like", needs_rng=True)
def _uniform_random_bsl(ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ref.shape[
        attrs.get("input_dim_idx", 0)]
    attrs = dict(attrs, shape=shape)
    return _uniform_random({}, attrs)


@register_op("gaussian_random", needs_rng=True)
def _gaussian_random(ins, attrs):
    shape = tuple(int(d) for d in attrs["shape"])
    dtype = to_numpy_dtype(attrs.get("dtype", "float32"))
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    out = jax.random.normal(attrs["_rng_key"], shape, jnp.float32) * std + mean
    return {"Out": out.astype(dtype)}


@register_op("truncated_gaussian_random", needs_rng=True)
def _truncated_gaussian(ins, attrs):
    shape = tuple(int(d) for d in attrs["shape"])
    dtype = to_numpy_dtype(attrs.get("dtype", "float32"))
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    out = jax.random.truncated_normal(
        attrs["_rng_key"], -2.0, 2.0, shape, jnp.float32) * std + mean
    return {"Out": out.astype(dtype)}


@register_op("randint", needs_rng=True)
def _randint(ins, attrs):
    shape = tuple(int(d) for d in attrs["shape"])
    dtype = to_numpy_dtype(attrs.get("dtype", "int64"))
    out = jax.random.randint(attrs["_rng_key"], shape,
                             attrs.get("low", 0), attrs.get("high", 100))
    return {"Out": out.astype(dtype)}


@register_op("randperm", needs_rng=True)
def _randperm(ins, attrs):
    n = attrs["n"]
    dtype = to_numpy_dtype(attrs.get("dtype", "int64"))
    return {"Out": jax.random.permutation(attrs["_rng_key"], n).astype(dtype)}


@register_op("bernoulli", needs_rng=True)
def _bernoulli(ins, attrs):
    x = ins["X"][0]
    out = jax.random.bernoulli(attrs["_rng_key"], x)
    return {"Out": out.astype(x.dtype)}


@register_op("multinomial", needs_rng=True)
def _multinomial(ins, attrs):
    x = ins["X"][0]
    num = attrs.get("num_samples", 1)
    logits = jnp.log(jnp.maximum(x, 1e-30))
    out = jax.random.categorical(attrs["_rng_key"], logits,
                                 shape=x.shape[:-1] + (num,), axis=-1)
    return {"Out": out.astype(jnp.int64)}


@register_op("sampling_id", needs_rng=True)
def _sampling_id(ins, attrs):
    x = ins["X"][0]
    logits = jnp.log(jnp.maximum(x, 1e-30))
    out = jax.random.categorical(attrs["_rng_key"], logits, axis=-1)
    return {"Out": out.astype(jnp.int64)}


@register_op("exponential", needs_rng=True)
def _exponential(ins, attrs):
    import jax as _jax

    x = ins["X"][0]
    lam = attrs.get("lambda", 1.0)
    u = _jax.random.uniform(attrs["_rng_key"], x.shape,
                            minval=1e-7, maxval=1.0)
    return {"Out": (-jnp.log(u) / lam).astype(x.dtype)}


@register_op("poisson", needs_rng=True)
def _poisson(ins, attrs):
    import jax as _jax

    x = ins["X"][0]
    return {"Out": _jax.random.poisson(
        attrs["_rng_key"], x.astype(jnp.float32)).astype(x.dtype)}


@register_op("gumbel_softmax", needs_rng=True)
def _gumbel_softmax(ins, attrs):
    import jax as _jax

    x = ins["X"][0]
    temperature = attrs.get("temperature", 1.0)
    hard = attrs.get("hard", False)
    axis = attrs.get("axis", -1)
    g = _jax.random.gumbel(attrs["_rng_key"], x.shape, x.dtype)
    y = _jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.where(
            jnp.arange(y.shape[axis]).reshape(
                [-1 if i == (axis % y.ndim) else 1
                 for i in range(y.ndim)]) == idx, 1.0, 0.0)
        y = onehot + y - _jax.lax.stop_gradient(y)
    return {"Out": y}
