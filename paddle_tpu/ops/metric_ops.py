"""Metric operators. Reference: `paddle/fluid/operators/metrics/`
(accuracy_op.cc, auc_op.cc, precision_recall_op.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("accuracy")
def _accuracy(ins, attrs):
    # reference: metrics/accuracy_op.cc — inputs Out (topk values),
    # Indices (topk indices [N,k]), Label [N,1]
    indices = ins["Indices"][0]
    label = ins["Label"][0].reshape((-1, 1)).astype(indices.dtype)
    correct_row = jnp.any(indices == label, axis=1)
    num_correct = jnp.sum(correct_row.astype(jnp.int32))
    total = indices.shape[0]
    acc = (num_correct.astype(jnp.float32) / total).reshape((1,))
    return {"Accuracy": acc,
            "Correct": num_correct.reshape((1,)),
            "Total": jnp.full((1,), total, jnp.int32)}


@register_op("auc")
def _auc(ins, attrs):
    # streaming AUC with histogram stat buffers (reference: metrics/auc_op.cc)
    predict = ins["Predict"][0]
    label = ins["Label"][0].reshape((-1,))
    stat_pos = ins["StatPos"][0]
    stat_neg = ins["StatNeg"][0]
    num_thresholds = attrs.get("num_thresholds", 4095)
    pos_prob = predict[:, -1] if predict.ndim == 2 else predict
    bucket = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32),
                      0, num_thresholds)
    is_pos = (label > 0)
    pos_hist = jnp.zeros_like(stat_pos).at[bucket].add(
        is_pos.astype(stat_pos.dtype))
    neg_hist = jnp.zeros_like(stat_neg).at[bucket].add(
        (~is_pos).astype(stat_neg.dtype))
    new_pos = stat_pos + pos_hist
    new_neg = stat_neg + neg_hist
    # integrate trapezoid over descending threshold
    tot_pos = jnp.cumsum(new_pos[::-1])
    tot_neg = jnp.cumsum(new_neg[::-1])
    area = jnp.sum((tot_neg - jnp.concatenate([jnp.zeros(1, tot_neg.dtype),
                                               tot_neg[:-1]]))
                   * (jnp.concatenate([jnp.zeros(1, tot_pos.dtype),
                                       tot_pos[:-1]]) + tot_pos) / 2.0)
    denom = tot_pos[-1] * tot_neg[-1]
    auc = jnp.where(denom > 0, area / jnp.maximum(denom, 1), 0.0)
    return {"AUC": auc.astype(jnp.float64).reshape((1,)),
            "StatPosOut": new_pos, "StatNegOut": new_neg}


@register_op("mean_iou")
def _mean_iou(ins, attrs):
    pred = ins["Predictions"][0].reshape((-1,)).astype(jnp.int32)
    label = ins["Labels"][0].reshape((-1,)).astype(jnp.int32)
    n = attrs["num_classes"]
    inter = jnp.zeros((n,), jnp.float32).at[
        jnp.where(pred == label, pred, n - 1)].add(
        (pred == label).astype(jnp.float32))
    pred_cnt = jnp.zeros((n,), jnp.float32).at[pred].add(1.0)
    label_cnt = jnp.zeros((n,), jnp.float32).at[label].add(1.0)
    union = pred_cnt + label_cnt - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1e-9), 0.0)
    valid = (union > 0).astype(jnp.float32)
    mean = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1.0)
    return {"OutMeanIou": mean.reshape((1,)), "OutWrong": pred_cnt - inter,
            "OutCorrect": inter}


@register_op("precision_recall")
def _precision_recall(ins, attrs):
    """Reference: operators/metrics/precision_recall_op.cc — per-class
    macro/micro precision/recall/F1 with streaming state accumulation."""
    cls_num = attrs["class_number"]
    preds = ins["MaxProbs"][1] if len(ins.get("MaxProbs", [])) > 1 else \
        ins["Indices"][0]
    labels = ins["Labels"][0]
    prev = ins["StatesInfo"][0] if ins.get("StatesInfo") else \
        jnp.zeros((cls_num, 4), jnp.float32)
    p = preds.reshape(-1).astype(jnp.int32)
    l = labels.reshape(-1).astype(jnp.int32)
    correct = (p == l)
    onehot_p = jax.nn.one_hot(p, cls_num, dtype=jnp.float32)
    onehot_l = jax.nn.one_hot(l, cls_num, dtype=jnp.float32)
    tp = jnp.sum(onehot_p * correct[:, None].astype(jnp.float32), 0)
    fp = jnp.sum(onehot_p, 0) - tp
    fn = jnp.sum(onehot_l, 0) - tp
    tn = p.shape[0] - tp - fp - fn
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)
    acc_states = prev + batch_states

    def metrics(states):
        tp_, fp_, tn_, fn_ = (states[:, 0], states[:, 1], states[:, 2],
                              states[:, 3])
        prec = jnp.where(tp_ + fp_ > 0, tp_ / (tp_ + fp_ + 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / (tp_ + fn_ + 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0,
                       2 * prec * rec / (prec + rec + 1e-12), 0.0)
        macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
        tps, fps, fns = jnp.sum(tp_), jnp.sum(fp_), jnp.sum(fn_)
        mp = jnp.where(tps + fps > 0, tps / (tps + fps + 1e-12), 0.0)
        mr = jnp.where(tps + fns > 0, tps / (tps + fns + 1e-12), 0.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / (mp + mr + 1e-12), 0.0)
        return jnp.concatenate([macro, jnp.stack([mp, mr, mf])])

    return {"BatchMetrics": metrics(batch_states),
            "AccumMetrics": metrics(acc_states),
            "AccumStatesInfo": acc_states}


@register_op("edit_distance", no_jit=True)
def _edit_distance(ins, attrs):
    """Levenshtein distance between hypothesis and reference token
    sequences (reference: operators/edit_distance_op.cc). Host-side:
    dynamic-programming over ragged rows."""
    import numpy as np

    hyp = np.asarray(ins["Hyps"][0])
    ref = np.asarray(ins["Refs"][0])
    hyp_len = np.asarray(ins["HypsLength"][0]).reshape(-1) \
        if ins.get("HypsLength") else np.full((hyp.shape[0],),
                                              hyp.shape[1])
    ref_len = np.asarray(ins["RefsLength"][0]).reshape(-1) \
        if ins.get("RefsLength") else np.full((ref.shape[0],),
                                              ref.shape[1])
    normalized = attrs.get("normalized", False)
    out = np.zeros((hyp.shape[0], 1), np.float32)
    for b in range(hyp.shape[0]):
        h = hyp[b, :int(hyp_len[b])]
        r = ref[b, :int(ref_len[b])]
        m, n = len(h), len(r)
        dp = np.arange(n + 1, dtype=np.int64)
        for i in range(1, m + 1):
            prev = dp.copy()
            dp[0] = i
            for j in range(1, n + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (h[i - 1] != r[j - 1]))
        d = float(dp[n])
        out[b, 0] = d / max(n, 1) if normalized else d
    return {"Out": out,
            "SequenceNum": np.asarray([hyp.shape[0]], np.int64)}


# (num_tag_types, tag_begin, tag_inside, tag_end, tag_single) per scheme
# — reference: chunk_eval_op.cc:119 InEnum + chunk_eval_op.h tag table
_CHUNK_SCHEMES = {
    # plain has NO begin tag (all -1, reference chunk_eval_op.h:142-147):
    # contiguous same-type tokens form ONE chunk (IO semantics); a begin
    # tag of 0 would make every token (label % 1 == 0) open its own chunk
    "plain": (1, -1, -1, -1, -1),
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
}


@register_op("chunk_eval", no_jit=True)
def _chunk_eval(ins, attrs):
    """Chunk-level precision/recall/F1 for sequence labeling
    (reference: operators/chunk_eval_op.h GetSegments/ChunkBegin/
    ChunkEnd state machine). Schemes: plain, IOB, IOE, IOBES."""
    import numpy as np

    inference = np.asarray(ins["Inference"][0])
    label = np.asarray(ins["Label"][0])
    num_chunk_types = attrs["num_chunk_types"]
    scheme = attrs.get("chunk_scheme", "IOB")
    excluded = set(attrs.get("excluded_chunk_types", []) or [])
    if scheme not in _CHUNK_SCHEMES:
        raise ValueError(
            "chunk_scheme %r invalid: must be one of %s (reference "
            "chunk_eval_op.cc:119)" % (scheme,
                                       sorted(_CHUNK_SCHEMES)))
    # batched [B, T] input: segment per sequence (SeqLength bounds each
    # row; without it, the full row). 1-D input = one sequence.
    if inference.ndim == 1:
        inference = inference[None, :]
        label = label[None, :]
    seq_len = np.asarray(ins["SeqLength"][0]).reshape(-1) \
        if ins.get("SeqLength") else np.full((inference.shape[0],),
                                             inference.shape[1])

    n_tag, t_begin, t_inside, t_end, t_single = _CHUNK_SCHEMES[scheme]
    other = num_chunk_types  # type id of the Outside label

    def _chunk_end(pt, pty, t, ty):
        # reference: chunk_eval_op.h:89 ChunkEnd
        if pty == other:
            return False
        if ty == other or ty != pty:
            return True
        if pt == t_begin or pt == t_inside:
            return t == t_begin or t == t_single
        return pt == t_end or pt == t_single

    def _chunk_begin(pt, pty, t, ty):
        # reference: chunk_eval_op.h:102 ChunkBegin
        if pty == other:
            return ty != other
        if ty == other:
            return False
        if ty != pty:
            return True
        if t == t_begin or t == t_single:
            return True
        if t == t_inside or t == t_end:
            return pt == t_end or pt == t_single
        return False

    def chunks(tags):
        # reference: chunk_eval_op.h:41 GetSegments — one pass with the
        # scheme-parameterized begin/end predicates
        out = []
        start, in_chunk = 0, False
        tag, ty = -1, other
        for i, lbl in enumerate(tags):
            pt, pty = tag, ty
            tag, ty = int(lbl) % n_tag, int(lbl) // n_tag
            if in_chunk and _chunk_end(pt, pty, tag, ty):
                out.append((start, i, pty))
                in_chunk = False
            if _chunk_begin(pt, pty, tag, ty):
                start, in_chunk = i, True
        if in_chunk:
            out.append((start, len(tags), ty))
        return set(out)

    pred, gold = set(), set()
    for b in range(inference.shape[0]):
        n = int(seq_len[b])
        pred |= {(b,) + c for c in chunks(inference[b, :n])
                 if c[2] not in excluded}
        gold |= {(b,) + c for c in chunks(label[b, :n])
                 if c[2] not in excluded}
    correct = len(pred & gold)
    prec = correct / len(pred) if pred else 0.0
    rec = correct / len(gold) if gold else 0.0
    f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
    return {"Precision": np.asarray([prec], np.float32),
            "Recall": np.asarray([rec], np.float32),
            "F1-Score": np.asarray([f1], np.float32),
            "NumInferChunks": np.asarray([len(pred)], np.int64),
            "NumLabelChunks": np.asarray([len(gold)], np.int64),
            "NumCorrectChunks": np.asarray([correct], np.int64)}


@register_op("positive_negative_pair", no_jit=True)
def _positive_negative_pair(ins, attrs):
    """Ranking pair statistics per query (reference:
    positive_negative_pair_op.h:25): for every same-query doc pair with
    different labels, weight w = mean of the two doc weights; concordant
    score/label ordering counts positive, discordant negative; equal
    scores count neutral AND negative (the reference's ternary runs
    after the neu += w — mirrored faithfully)."""
    import numpy as np

    score = np.asarray(ins["Score"][0], np.float64)
    label = np.asarray(ins["Label"][0], np.float64).reshape(-1)
    query = np.asarray(ins["QueryID"][0]).reshape(-1).astype(np.int64)
    weight = np.asarray(ins["Weight"][0], np.float64).reshape(-1) \
        if ins.get("Weight") else np.ones_like(label)
    column = int(attrs.get("column", 0))
    if score.ndim == 1:
        score = score[:, None]
    if column < 0:
        column += score.shape[1]
    s = score[:, column]
    pos = neg = neu = 0.0
    # reference requires ALL THREE accumulators together (&&); any
    # partial set starts from zero rather than crashing
    if (ins.get("AccumulatePositivePair")
            and ins.get("AccumulateNegativePair")
            and ins.get("AccumulateNeutralPair")):
        pos = float(np.asarray(
            ins["AccumulatePositivePair"][0]).reshape(-1)[0])
        neg = float(np.asarray(
            ins["AccumulateNegativePair"][0]).reshape(-1)[0])
        neu = float(np.asarray(
            ins["AccumulateNeutralPair"][0]).reshape(-1)[0])
    by_query = {}
    for i in range(len(label)):
        by_query.setdefault(int(query[i]), []).append(i)
    for idxs in by_query.values():
        for a_pos in range(len(idxs)):
            for b_pos in range(a_pos + 1, len(idxs)):
                i, j = idxs[a_pos], idxs[b_pos]
                if label[i] == label[j]:
                    continue
                w = (weight[i] + weight[j]) * 0.5
                if s[i] == s[j]:
                    neu += w
                if (s[i] - s[j]) * (label[i] - label[j]) > 0.0:
                    pos += w
                else:
                    neg += w
    odt = np.asarray(ins["Score"][0]).dtype  # outputs use Score's T
    return {"PositivePair": np.asarray([pos], odt),
            "NegativePair": np.asarray([neg], odt),
            "NeutralPair": np.asarray([neu], odt)}
