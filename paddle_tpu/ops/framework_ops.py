"""Framework-plumbing operators: checkpoint IO ops, debugging ops,
tensor-array aliases, control-flow routing, selected-rows utilities,
buffer coalescing, int8 (re)quantization.

Reference parity: `paddle/fluid/operators/save_op.cc`, `load_op.cc`,
`save_combine_op.cc`, `load_combine_op.cc`, `print_op.cc`,
`py_func_op.cc`, `tensor_array_read_write_op.cc` (write_to_array /
read_from_array), `multiplex_op.cc`, `controlflow/` select_input /
select_output, `split_lod_tensor_op.cc` / `merge_lod_tensor_op.cc`,
`coalesce_tensor_op.cc`, `shuffle_batch_op.cc`,
`get_tensor_from_selected_rows_op.cc`, `merge_selected_rows_op.cc`,
`split_selected_rows_op.cc`, `mkldnn/quantize_op.cc` family.

TPU-native design: IO/debug/routing ops are host-side (`no_jit`) — they
exist for program compatibility, not for the compiled hot path (XLA owns
buffer packing, so `coalesce_tensor` is a functional concat that keeps
the op contract without pretending to alias memory).
"""
from __future__ import annotations

import os
import struct
from typing import Callable, Dict

import numpy as np
import jax.numpy as jnp

from .registry import register_op

# -- save / load ------------------------------------------------------------

_MAGIC = b"PTPU0001"


def _save_arrays(path: str, named: Dict[str, np.ndarray]):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<I", len(named)))
        for name, arr in named.items():
            nb = name.encode()
            f.write(struct.pack("<I", len(nb)))
            f.write(nb)
            hdr = ("%s|%s" % (arr.dtype.str,
                              ",".join(map(str, arr.shape)))).encode()
            f.write(struct.pack("<I", len(hdr)))
            f.write(hdr)
            payload = np.ascontiguousarray(arr).tobytes()
            f.write(struct.pack("<Q", len(payload)))
            f.write(payload)


def _load_arrays(path: str) -> Dict[str, np.ndarray]:
    with open(path, "rb") as f:
        assert f.read(8) == _MAGIC, "not a paddle_tpu checkpoint: %s" % path
        (n,) = struct.unpack("<I", f.read(4))
        out = {}
        for _ in range(n):
            (ln,) = struct.unpack("<I", f.read(4))
            name = f.read(ln).decode()
            (hl,) = struct.unpack("<I", f.read(4))
            dtype_s, shape_s = f.read(hl).decode().split("|")
            shape = tuple(int(s) for s in shape_s.split(",") if s)
            (pl,) = struct.unpack("<Q", f.read(8))
            out[name] = np.frombuffer(
                f.read(pl), dtype=np.dtype(dtype_s)).reshape(shape).copy()
    return out


@register_op("save", no_jit=True)
def _save(ins, attrs):
    x = np.asarray(ins["X"][0])
    if attrs.get("save_as_fp16", False):
        x = x.astype("float16")
    _save_arrays(attrs["file_path"], {attrs.get("var_name", "X"): x})
    return {}


@register_op("load", no_jit=True)
def _load(ins, attrs):
    named = _load_arrays(attrs["file_path"])
    arr = next(iter(named.values()))
    if attrs.get("load_as_fp16", False):
        arr = arr.astype("float16")
    return {"Out": jnp.asarray(arr)}


@register_op("save_combine", no_jit=True)
def _save_combine(ins, attrs):
    names = attrs.get("var_names") or [
        "X_%d" % i for i in range(len(ins["X"]))]
    _save_arrays(attrs["file_path"],
                 {n: np.asarray(v) for n, v in zip(names, ins["X"])})
    return {}


@register_op("load_combine", no_jit=True)
def _load_combine(ins, attrs):
    named = _load_arrays(attrs["file_path"])
    return {"Out": [jnp.asarray(v) for v in named.values()]}


# -- debug ops --------------------------------------------------------------

def _print_infer(ins, attrs):
    return {"Out": list(ins.get("In") or ins["X"])}


@register_op("print", no_jit=True, infer_shape=_print_infer)
def _print(ins, attrs):
    x = ins["In"][0] if ins.get("In") else ins["X"][0]
    arr = np.asarray(x)
    msg = attrs.get("message", "")
    first_n = attrs.get("summarize", 20)
    flat = arr.reshape(-1)[:max(int(first_n), 0) or None]
    print("%s dtype=%s shape=%s data=%s"
          % (msg, arr.dtype, arr.shape, flat))
    return {"Out": x}


_PY_FUNCS: Dict[int, Callable] = {}


def register_py_func(fn: Callable) -> int:
    """Register a python callable; returns the id used by the py_func
    op's `func_id` attr (reference: py_func_op.cc registers callables in
    a python-side registry keyed by index)."""
    fid = len(_PY_FUNCS)
    _PY_FUNCS[fid] = fn
    return fid


@register_op("py_func", no_jit=True)
def _py_func(ins, attrs):
    fn = _PY_FUNCS[int(attrs["func_id"])]
    args = [np.asarray(v) for v in ins.get("X", [])]
    out = fn(*args)
    if out is None:
        return {"Out": []}
    if not isinstance(out, (list, tuple)):
        out = [out]
    return {"Out": [jnp.asarray(np.asarray(o)) for o in out]}


# -- tensor-array aliases ---------------------------------------------------

def _alias(new, old):
    from .registry import get_op
    target = get_op(old)
    register_op(new, needs_rng=target.needs_rng,
                no_jit=target.no_jit)(target.compute)


_alias("write_to_array", "array_write")
_alias("read_from_array", "array_read")


# -- routing ----------------------------------------------------------------

@register_op("multiplex")
def _multiplex(ins, attrs):
    ids = ins["Ids"][0].reshape((-1,)).astype(jnp.int32)
    stacked = jnp.stack(ins["X"], axis=0)        # [K, N, ...]
    return {"Out": stacked[ids, jnp.arange(stacked.shape[1])]}


@register_op("select_input", no_jit=True)
def _select_input(ins, attrs):
    mask = int(np.asarray(ins["Mask"][0]).reshape(()))
    return {"Out": ins["X"][mask]}


@register_op("select_output", no_jit=True)
def _select_output(ins, attrs):
    # routes X to output branch `mask`; other branches get empty
    # placeholders (reference: controlflow/select_output_op.cc)
    mask = int(np.asarray(ins["Mask"][0]).reshape(()))
    n = int(attrs.get("n_outputs", 1))
    x = ins["X"][0]
    outs = [jnp.zeros((0,), x.dtype)] * n
    outs[mask] = x
    return {"Out": outs}


@register_op("split_lod_tensor", no_jit=True)
def _split_lod_tensor(ins, attrs):
    x = np.asarray(ins["X"][0])
    mask = np.asarray(ins["Mask"][0]).reshape(-1).astype(bool)
    return {"OutTrue": jnp.asarray(x[mask]),
            "OutFalse": jnp.asarray(x[~mask])}


@register_op("merge_lod_tensor", no_jit=True)
def _merge_lod_tensor(ins, attrs):
    mask = np.asarray(ins["Mask"][0]).reshape(-1).astype(bool)
    in_true = np.asarray(ins["InTrue"][0])
    in_false = np.asarray(ins["InFalse"][0])
    width = in_true.shape[1:] if in_true.size else in_false.shape[1:]
    out = np.zeros((mask.shape[0],) + tuple(width), in_true.dtype
                   if in_true.size else in_false.dtype)
    out[mask] = in_true
    out[~mask] = in_false
    return {"Out": jnp.asarray(out)}


@register_op("coalesce_tensor")
def _coalesce_tensor(ins, attrs):
    """Functional stand-in for the grad-fusion buffer: FusedOutput is the
    concat of all inputs; Output passes the originals through. XLA owns
    real buffer packing, so no aliasing is pretended."""
    xs = ins["Input"]
    flat = jnp.concatenate([x.reshape(-1) for x in xs]) if xs else \
        jnp.zeros((0,), jnp.float32)
    return {"Output": list(xs), "FusedOutput": flat}


@register_op("shuffle_batch", needs_rng=True)
def _shuffle_batch(ins, attrs):
    import jax
    x = ins["X"][0]
    perm = jax.random.permutation(attrs["_rng_key"], x.shape[0])
    return {"Out": x[perm], "ShuffleIdx": perm.astype(jnp.int64),
            "SeedOut": jnp.zeros((1,), jnp.int64)}


# -- selected-rows utilities ------------------------------------------------

@register_op("get_tensor_from_selected_rows", no_jit=True)
def _get_tensor_from_selected_rows(ins, attrs):
    from ..core.selected_rows import SelectedRows
    x = ins["X"][0]
    if isinstance(x, SelectedRows):
        return {"Out": jnp.asarray(np.asarray(x.values))}
    return {"Out": x}


@register_op("merge_selected_rows", no_jit=True)
def _merge_selected_rows(ins, attrs):
    from ..core.selected_rows import SelectedRows
    x = ins["X"][0]
    if isinstance(x, SelectedRows):
        return {"Out": x.merge()}
    return {"Out": x}


@register_op("split_selected_rows", no_jit=True)
def _split_selected_rows(ins, attrs):
    """Shard a SelectedRows (or dense) by row ranges: height_sections
    attr gives per-shard dense extents (reference:
    split_selected_rows_op.cc, the PS param-send path)."""
    from ..core.selected_rows import SelectedRows
    sections = attrs["height_sections"]
    x = ins["X"][0]
    bounds = np.cumsum([0] + list(sections))
    outs = []
    if isinstance(x, SelectedRows):
        rows = np.asarray(x.rows)
        vals = np.asarray(x.values)
        for i in range(len(sections)):
            sel = (rows >= bounds[i]) & (rows < bounds[i + 1])
            outs.append(SelectedRows(rows[sel] - bounds[i], vals[sel],
                                     int(sections[i])))
    else:
        arr = np.asarray(x)
        for i in range(len(sections)):
            outs.append(jnp.asarray(arr[bounds[i]:bounds[i + 1]]))
    return {"Out": outs}


# -- int8 (re)quantization (reference: operators/mkldnn/quantize_op etc.) ---

@register_op("quantize")
def _quantize(ins, attrs):
    x = ins["Input"][0]
    scale = float(attrs.get("Scale", 1.0))
    if attrs.get("is_negative_input", True):
        q = jnp.clip(jnp.rint(x * scale), -128, 127).astype(jnp.int8)
    else:
        q = jnp.clip(jnp.rint(x * scale), 0, 255).astype(jnp.uint8)
    return {"Output": q}


@register_op("dequantize")
def _dequantize(ins, attrs):
    x = ins["Input"][0]
    scale = float(attrs.get("Scale", 1.0))
    return {"Output": x.astype(jnp.float32) / scale}


@register_op("requantize")
def _requantize(ins, attrs):
    x = ins["Input"][0]
    scale_in = float(attrs.get("Scale_in", 1.0))
    scale_out = float(attrs.get("Scale_out", 1.0))
    y = jnp.rint(x.astype(jnp.float32) * (scale_out / scale_in))
    info = jnp.iinfo(x.dtype)
    return {"Output": jnp.clip(y, info.min, info.max).astype(x.dtype)}


@register_op("run_program", no_jit=True)
def _run_program(ins, attrs):
    """Execute a captured Program (run_program_op.cc — the dygraph-to-
    static jit.save/load execution path). attrs: `program` (a
    framework.Program), `feed_names`, `fetch_names`."""
    from ..fluid.executor import Executor

    program = attrs["program"]
    feed_names = list(attrs.get("feed_names", []))
    fetch_names = list(attrs.get("fetch_names", []))
    feed = {n: np.asarray(v) for n, v in zip(feed_names, ins.get("X", []))}
    outs = Executor().run(program, feed=feed, fetch_list=fetch_names)
    return {"Out": [jnp.asarray(np.asarray(o)) for o in outs]}


@register_op("assert", no_jit=True, infer_shape=lambda ins, attrs: {})
def _assert(ins, attrs):
    """Runtime assertion (reference: operators/assert_op.cc): raises
    when the bool condition is not all-true; optional data tensors are
    included in the message. Host-side (no_jit) like the reference's
    CPU-only kernel."""
    cond = np.asarray(ins["Cond"][0])
    if not bool(cond.all()):
        datas = [np.asarray(d) for d in ins.get("Data", [])]
        summarize = int(attrs.get("summarize", -1))
        parts = []
        for d in datas:
            flat = d.reshape(-1)
            parts.append(str(flat[:summarize] if summarize > 0 else flat))
        raise AssertionError(
            attrs.get("message", "") or
            "Assert failed%s" % ((": " + "; ".join(parts))
                                 if parts else ""))
    return {}
