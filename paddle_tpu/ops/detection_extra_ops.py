"""Detection pipeline operators beyond the geometric core: matching,
target assignment, proposal generation/routing, SSD/RetinaNet decode,
perspective RoI transform, deformable PSRoI pooling, plus misc sequence
/vision helpers (hsigmoid, sampled softmax, random_crop,
similarity_focus, add_position_encoding).

Reference parity: `paddle/fluid/operators/detection/` —
`bipartite_match_op.cc`, `target_assign_op.cc`,
`rpn_target_assign_op.cc`, `generate_proposals_op.cc`,
`distribute_fpn_proposals_op.cc`, `collect_fpn_proposals_op.cc`,
`retinanet_detection_output_op.cc`, `polygon_box_transform_op.cc`,
`roi_perspective_transform_op.cc`, `deformable_psroi_pooling_op.cc`,
`generate_proposal_labels_op.cc`; plus `hierarchical_sigmoid_op.cc`,
`sample_logits_op.cc` (sampled softmax composition),
`random_crop_op.cc`, `similarity_focus_op.cc`,
`add_position_encoding_op.cc`, `detection_map_op.cc`.

TPU-native design: ops whose outputs are data-dependent-sized (proposal
generation, NMS-style decode, label sampling) run `no_jit` on host —
the reference keeps these on CPU in real pipelines too; the dense ops
(target_assign, perspective transform, deformable PSRoI) are jit-able
gather/scatter compositions.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op, get_op


def _np_iou_xyxy(a, b, normalized=True):
    """IoU matrix between [n,4] and [m,4] corner boxes (numpy).
    normalized=False adds the reference's +1 to extents (integer pixel
    coordinates, nms_util.h JaccardOverlap)."""
    off = 0.0 if normalized else 1.0
    area_a = np.maximum(a[:, 2] - a[:, 0] + off, 0) * \
        np.maximum(a[:, 3] - a[:, 1] + off, 0)
    area_b = np.maximum(b[:, 2] - b[:, 0] + off, 0) * \
        np.maximum(b[:, 3] - b[:, 1] + off, 0)
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter,
                              1e-10)


@register_op("bipartite_match", no_jit=True)
def _bipartite_match(ins, attrs):
    """Greedy bipartite matching of columns (priors) to rows (gt):
    repeatedly take the global max of the remaining similarity matrix
    (bipartite_match_op.cc BipartiteMatch); per-prediction argmax rows
    also matched when match_type='per_prediction' and sim > overlap."""
    dist = np.asarray(ins["DistMat"][0]).copy()       # [gt, priors]
    match_type = attrs.get("match_type", "bipartite")
    overlap = float(attrs.get("dist_threshold", 0.5))
    g, p = dist.shape
    match_idx = np.full((1, p), -1, "int32")
    match_dist = np.zeros((1, p), "float32")
    d = dist.copy()
    for _ in range(min(g, p)):
        flat = int(np.argmax(d))
        i, j = divmod(flat, p)
        if d[i, j] <= 0:
            break
        match_idx[0, j] = i
        match_dist[0, j] = d[i, j]
        d[i, :] = -1.0
        d[:, j] = -1.0
    if match_type == "per_prediction":
        for j in range(p):
            if match_idx[0, j] == -1:
                i = int(np.argmax(dist[:, j]))
                if dist[i, j] > overlap:
                    match_idx[0, j] = i
                    match_dist[0, j] = dist[i, j]
    return {"ColToRowMatchIndices": jnp.asarray(match_idx),
            "ColToRowMatchDist": jnp.asarray(match_dist)}


@register_op("target_assign")
def _target_assign(ins, attrs):
    """Assign per-prior targets from matched gt rows
    (target_assign_op.h): with X [gt, M, K] (per-(gt,prior) encodings,
    e.g. box_coder output) out[j] = X[match[j], j]; with X [gt, K]
    (per-gt rows, e.g. labels) out[j] = X[match[j]]. Unmatched priors
    get mismatch_value and weight 0."""
    x = ins["X"][0]
    match = ins["MatchIndices"][0].astype(jnp.int32)   # [1, priors]
    mismatch = attrs.get("mismatch_value", 0)
    mi = match[0]
    matched = mi >= 0
    safe = jnp.maximum(mi, 0)
    if x.ndim >= 3:
        gathered = x[safe, jnp.arange(mi.shape[0])]    # [priors, K]
    else:
        gathered = jnp.take(x, safe, axis=0)
    fill = jnp.full_like(gathered, mismatch)
    out = jnp.where(matched[:, None], gathered, fill)
    w = matched.astype(jnp.float32)[:, None]
    return {"Out": out[None], "OutWeight": w[None]}


@register_op("polygon_box_transform")
def _polygon_box_transform(ins, attrs):
    """(polygon_box_transform_op.cc) Input [N, 8, H, W] quad offsets →
    absolute coords: out = 4*cell_coord - offset (EAST-style geometry)."""
    x = ins["Input"][0]
    n, c, h, w = x.shape
    gx = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    is_x = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    base = jnp.where(is_x, 4.0 * gx, 4.0 * gy)
    return {"Output": base - x}


@register_op("rpn_target_assign", no_jit=True,
             dynamic_shape=True)
def _rpn_target_assign(ins, attrs):
    """Sample anchors into fg/bg for RPN training
    (rpn_target_assign_op.cc): fg = IoU >= pos_thresh or argmax per gt;
    bg = IoU < neg_thresh; subsample to batch_size*fg_fraction."""
    anchors = np.asarray(ins["Anchor"][0])             # [A, 4]
    gt = np.asarray(ins["GtBoxes"][0])                 # [G, 4]
    batch = int(attrs.get("rpn_batch_size_per_im", 256))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    pos_t = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_t = float(attrs.get("rpn_negative_overlap", 0.3))
    rng = np.random.RandomState(int(attrs.get("seed", 0)))
    a_n = anchors.shape[0]
    if len(gt) == 0:
        # gt-free image: everything is background
        iou = np.zeros((0, a_n))
        best = np.zeros(a_n)
        arg = np.zeros(a_n, int)
        labels = np.zeros(a_n, "int32")
    else:
        iou = _np_iou_xyxy(gt, anchors)                # [G, A]
        best = iou.max(0)
        arg = iou.argmax(0)
        labels = np.full(a_n, -1, "int32")
        labels[best >= pos_t] = 1
        labels[iou.argmax(1)] = 1                      # best per gt
        labels[best < neg_t] = np.where(
            labels[best < neg_t] == 1, 1, 0)
    fg_inds = np.nonzero(labels == 1)[0]
    n_fg = int(batch * fg_frac)
    if len(fg_inds) > n_fg:
        labels[rng.choice(fg_inds, len(fg_inds) - n_fg,
                          replace=False)] = -1
        fg_inds = np.nonzero(labels == 1)[0]
    bg_inds = np.nonzero(labels == 0)[0]
    n_bg = batch - len(fg_inds)
    if len(bg_inds) > n_bg:
        labels[rng.choice(bg_inds, len(bg_inds) - n_bg,
                          replace=False)] = -1
        bg_inds = np.nonzero(labels == 0)[0]
    loc_idx = fg_inds
    score_idx = np.concatenate([fg_inds, bg_inds])
    tgt_lbl = (labels[score_idx] == 1).astype("int32")[:, None]
    matched_gt = gt[arg[loc_idx]] if len(loc_idx) else \
        np.zeros((0, 4), "float32")
    return {"LocationIndex": jnp.asarray(loc_idx.astype("int32")),
            "ScoreIndex": jnp.asarray(score_idx.astype("int32")),
            "TargetLabel": jnp.asarray(tgt_lbl),
            "TargetBBox": jnp.asarray(matched_gt.astype("float32")),
            "BBoxInsideWeight": jnp.asarray(
                np.ones_like(matched_gt, "float32"))}


def _decode_center(anchors, deltas, variances=None):
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + aw * 0.5
    ay = anchors[:, 1] + ah * 0.5
    v = variances if variances is not None else np.ones((1, 4))
    cx = v[:, 0] * deltas[:, 0] * aw + ax
    cy = v[:, 1] * deltas[:, 1] * ah + ay
    w = np.exp(np.minimum(v[:, 2] * deltas[:, 2], 10.0)) * aw
    h = np.exp(np.minimum(v[:, 3] * deltas[:, 3], 10.0)) * ah
    return np.stack([cx - w * 0.5, cy - h * 0.5,
                     cx + w * 0.5 - 1.0, cy + h * 0.5 - 1.0], 1)


@register_op("generate_proposals", no_jit=True,
             dynamic_shape=True)
def _generate_proposals(ins, attrs):
    """RPN proposal generation (generate_proposals_op.cc): decode anchor
    deltas, clip, filter small, NMS, keep post_nms_topN."""
    scores = np.asarray(ins["Scores"][0])              # [N, A, H, W]
    deltas = np.asarray(ins["BboxDeltas"][0])          # [N, A*4, H, W]
    im_info = np.asarray(ins["ImInfo"][0])             # [N, 3]
    anchors = np.asarray(ins["Anchors"][0]).reshape(-1, 4)
    variances = np.asarray(ins["Variances"][0]).reshape(-1, 4) \
        if ins.get("Variances") else None
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_t = float(attrs.get("nms_thresh", 0.5))
    min_size = float(attrs.get("min_size", 0.1))
    n = scores.shape[0]
    all_rois, all_probs, nums = [], [], []
    for i in range(n):
        sc = scores[i].transpose(1, 2, 0).reshape(-1)
        dl = deltas[i].reshape(deltas.shape[1] // 4, 4, -1) \
            .transpose(2, 0, 1).reshape(-1, 4)
        order = np.argsort(-sc)[:pre_n]
        props = _decode_center(anchors[order], dl[order],
                               variances[order] if variances is not None
                               else None)
        h, w = im_info[i, 0], im_info[i, 1]
        props[:, 0::2] = np.clip(props[:, 0::2], 0, w - 1)
        props[:, 1::2] = np.clip(props[:, 1::2], 0, h - 1)
        keep = ((props[:, 2] - props[:, 0] >= min_size)
                & (props[:, 3] - props[:, 1] >= min_size))
        props, sc_k = props[keep], sc[order][keep]
        keep_idx = []
        while len(keep_idx) < post_n and sc_k.size:
            j = int(np.argmax(sc_k))
            keep_idx.append(j)
            iou = _np_iou_xyxy(props[j:j + 1], props)[0]
            sc_k = np.where(iou > nms_t, -1e30, sc_k)
            sc_k[j] = -1e30
            if np.all(sc_k <= -1e29):
                break
        props = props[keep_idx]
        all_rois.append(props)
        all_probs.append(np.asarray(ins["Scores"][0][i]).transpose(
            1, 2, 0).reshape(-1)[order][keep][keep_idx])
        nums.append(len(keep_idx))
    rois = np.concatenate(all_rois) if all_rois else np.zeros((0, 4))
    probs = np.concatenate(all_probs) if all_probs else np.zeros((0,))
    return {"RpnRois": jnp.asarray(rois.astype("float32")),
            "RpnRoiProbs": jnp.asarray(
                probs.astype("float32").reshape(-1, 1)),
            "RpnRoisNum": jnp.asarray(np.asarray(nums, "int32"))}


@register_op("distribute_fpn_proposals", no_jit=True,
             dynamic_shape=True)
def _distribute_fpn_proposals(ins, attrs):
    """Route RoIs to FPN levels by scale (distribute_fpn_proposals_op.cc):
    level = floor(log2(sqrt(area)/224) + refer_level), clipped."""
    rois = np.asarray(ins["FpnRois"][0])
    min_l = int(attrs.get("min_level", 2))
    max_l = int(attrs.get("max_level", 5))
    refer_l = int(attrs.get("refer_level", 4))
    refer_s = float(attrs.get("refer_scale", 224))
    scale = np.sqrt(np.maximum(
        (rois[:, 2] - rois[:, 0]) * (rois[:, 3] - rois[:, 1]), 1e-10))
    lvl = np.clip(np.floor(np.log2(scale / refer_s + 1e-6)) + refer_l,
                  min_l, max_l).astype(int)
    outs, restore = [], np.zeros(len(rois), "int32")
    pos = 0
    order = []
    for lev in range(min_l, max_l + 1):
        idx = np.nonzero(lvl == lev)[0]
        outs.append(jnp.asarray(rois[idx].astype("float32")))
        order.extend(idx.tolist())
    for new_i, old_i in enumerate(order):
        restore[old_i] = new_i
    del pos
    return {"MultiFpnRois": outs,
            "RestoreIndex": jnp.asarray(restore.reshape(-1, 1))}


@register_op("collect_fpn_proposals", no_jit=True,
             dynamic_shape=True)
def _collect_fpn_proposals(ins, attrs):
    """Merge per-level RoIs back, keep top post_nms_topN by score
    (collect_fpn_proposals_op.cc)."""
    rois = np.concatenate([np.asarray(r) for r in ins["MultiLevelRois"]])
    scores = np.concatenate(
        [np.asarray(s).reshape(-1) for s in ins["MultiLevelScores"]])
    keep = np.argsort(-scores)[:int(attrs.get("post_nms_topN", 1000))]
    return {"FpnRois": jnp.asarray(rois[keep].astype("float32"))}


@register_op("retinanet_detection_output", no_jit=True,
             dynamic_shape=True)
def _retinanet_detection_output(ins, attrs):
    """Multi-level sigmoid-score decode + class-wise NMS
    (retinanet_detection_output_op.cc)."""
    score_t = float(attrs.get("score_threshold", 0.05))
    nms_t = float(attrs.get("nms_threshold", 0.3))
    keep_k = int(attrs.get("keep_top_k", 100))
    nms_top = int(attrs.get("nms_top_k", 1000))
    boxes_l = [np.asarray(b) for b in ins["BBoxes"]]
    scores_l = [np.asarray(s) for s in ins["Scores"]]
    anchors_l = [np.asarray(a) for a in ins["Anchors"]]
    dets = []
    for boxes, scores, anchors in zip(boxes_l, scores_l, anchors_l):
        sc = 1.0 / (1.0 + np.exp(-scores.reshape(-1, scores.shape[-1])))
        dl = boxes.reshape(-1, 4)
        order = np.argsort(-sc.max(1))[:nms_top]
        dec = _decode_center(anchors.reshape(-1, 4)[order], dl[order])
        for c in range(sc.shape[1]):
            m = sc[order, c] > score_t
            for b, s in zip(dec[m], sc[order, c][m]):
                dets.append([c, s, *b])
    if not dets:
        return {"Out": jnp.zeros((1, 6), jnp.float32)}
    dets = np.asarray(dets, "float32")
    final = []
    for c in np.unique(dets[:, 0]):
        dc = dets[dets[:, 0] == c]
        dc = dc[np.argsort(-dc[:, 1])]
        while dc.size:
            final.append(dc[0])
            iou = _np_iou_xyxy(dc[0:1, 2:], dc[:, 2:])[0]
            dc = dc[iou <= nms_t]
    final = np.stack(sorted(final, key=lambda d: -d[1])[:keep_k])
    return {"Out": jnp.asarray(final)}


@register_op("retinanet_target_assign", no_jit=True,
             dynamic_shape=True)
def _retinanet_target_assign(ins, attrs):
    """Anchor→gt assignment for RetinaNet (retinanet_target_assign_op.cc):
    fg = IoU >= pos_thresh, bg = IoU < neg_thresh, rest ignored."""
    anchors = np.asarray(ins["Anchor"][0])
    gt = np.asarray(ins["GtBoxes"][0])
    gt_labels = np.asarray(ins["GtLabels"][0]).reshape(-1)
    pos_t = float(attrs.get("positive_overlap", 0.5))
    neg_t = float(attrs.get("negative_overlap", 0.4))
    iou = _np_iou_xyxy(gt, anchors)
    best = iou.max(0) if len(gt) else np.zeros(anchors.shape[0])
    arg = iou.argmax(0) if len(gt) else np.zeros(anchors.shape[0], int)
    labels = np.full(anchors.shape[0], -1, "int32")
    labels[best < neg_t] = 0
    labels[best >= pos_t] = 1
    if len(gt):
        labels[iou.argmax(1)] = 1
    fg = np.nonzero(labels == 1)[0]
    bg = np.nonzero(labels == 0)[0]
    score_idx = np.concatenate([fg, bg])
    if len(gt):
        tgt_lbl = np.where(labels[score_idx] == 1,
                           gt_labels[arg[score_idx]], 0)[:, None]
    else:
        tgt_lbl = np.zeros((len(score_idx), 1), gt_labels.dtype)
    return {"LocationIndex": jnp.asarray(fg.astype("int32")),
            "ScoreIndex": jnp.asarray(score_idx.astype("int32")),
            "TargetLabel": jnp.asarray(tgt_lbl.astype("int32")),
            "TargetBBox": jnp.asarray(gt[arg[fg]].astype("float32")
                                      if len(gt) else
                                      np.zeros((0, 4), "float32")),
            "BBoxInsideWeight": jnp.asarray(np.ones(
                (len(fg), 4), "float32")),
            "ForegroundNumber": jnp.asarray(
                np.asarray([max(len(fg), 1)], "int32"))}


@register_op("generate_proposal_labels", no_jit=True,
             dynamic_shape=True)
def _generate_proposal_labels(ins, attrs):
    """Sample RoIs into labelled fg/bg training rois
    (generate_proposal_labels_op.cc, simplified single-image)."""
    rois = np.asarray(ins["RpnRois"][0])
    gt_classes = np.asarray(ins["GtClasses"][0]).reshape(-1)
    gt_boxes = np.asarray(ins["GtBoxes"][0])
    batch = int(attrs.get("batch_size_per_im", 256))
    fg_frac = float(attrs.get("fg_fraction", 0.25))
    fg_t = float(attrs.get("fg_thresh", 0.5))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    class_num = int(attrs.get("class_nums", 81))
    rng = np.random.RandomState(int(attrs.get("seed", 0)))
    cand = np.concatenate([rois, gt_boxes]) if len(gt_boxes) else rois
    iou = _np_iou_xyxy(gt_boxes, cand) if len(gt_boxes) else \
        np.zeros((0, len(cand)))
    best = iou.max(0) if len(gt_boxes) else np.zeros(len(cand))
    arg = iou.argmax(0) if len(gt_boxes) else np.zeros(len(cand), int)
    fg = np.nonzero(best >= fg_t)[0]
    bg = np.nonzero((best < bg_hi) & (best >= bg_lo))[0]
    n_fg = min(int(batch * fg_frac), len(fg))
    fg = rng.choice(fg, n_fg, replace=False) if len(fg) > n_fg else fg
    n_bg = min(batch - len(fg), len(bg))
    bg = rng.choice(bg, n_bg, replace=False) if len(bg) > n_bg else bg
    keep = np.concatenate([fg, bg]).astype(int)
    out_rois = cand[keep]
    labels = np.concatenate([gt_classes[arg[fg]],
                             np.zeros(len(bg), gt_classes.dtype)])
    tgt = np.zeros((len(keep), 4), "float32")
    if len(gt_boxes):
        tgt[:len(fg)] = gt_boxes[arg[fg]]
    bbox_targets = np.zeros((len(keep), 4 * class_num), "float32")
    w_in = np.zeros_like(bbox_targets)
    for i in range(len(fg)):
        c = int(labels[i])
        bbox_targets[i, 4 * c:4 * c + 4] = tgt[i]
        w_in[i, 4 * c:4 * c + 4] = 1.0
    return {"Rois": jnp.asarray(out_rois.astype("float32")),
            "LabelsInt32": jnp.asarray(labels.astype("int32")[:, None]),
            "BboxTargets": jnp.asarray(bbox_targets),
            "BboxInsideWeights": jnp.asarray(w_in),
            "BboxOutsideWeights": jnp.asarray(
                (w_in > 0).astype("float32"))}


@register_op("roi_perspective_transform")
def _roi_perspective_transform(ins, attrs):
    """Perspective-warp quadrilateral RoIs to a fixed grid
    (roi_perspective_transform_op.cc): solve the homography per RoI,
    bilinear-sample."""
    x = ins["X"][0]                                    # [N, C, H, W]
    rois = ins["ROIs"][0]                              # [R, 8] quad pts
    oh = int(attrs.get("transformed_height", 8))
    ow = int(attrs.get("transformed_width", 8))
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape

    def warp_one(roi):
        pts = (roi * scale).reshape(4, 2)              # tl,tr,br,bl
        dst = jnp.asarray([[0.0, 0.0], [ow - 1.0, 0.0],
                           [ow - 1.0, oh - 1.0], [0.0, oh - 1.0]])
        # solve 8-dof homography dst -> src via least squares
        rows = []
        for k in range(4):
            dx, dy = dst[k]
            sx, sy = pts[k]
            rows.append(jnp.asarray(
                [dx, dy, 1, 0, 0, 0, -dx * sx, -dy * sx]))
            rows.append(jnp.asarray(
                [0, 0, 0, dx, dy, 1, -dx * sy, -dy * sy]))
        a_mat = jnp.stack(rows)
        b_vec = jnp.stack([pts[0, 0], pts[0, 1], pts[1, 0], pts[1, 1],
                           pts[2, 0], pts[2, 1], pts[3, 0], pts[3, 1]])
        hvec = jnp.linalg.solve(a_mat + 1e-8 * jnp.eye(8), b_vec)
        hm = jnp.concatenate([hvec, jnp.ones((1,))]).reshape(3, 3)
        gy, gx2 = jnp.meshgrid(jnp.arange(oh, dtype=x.dtype),
                               jnp.arange(ow, dtype=x.dtype),
                               indexing="ij")
        ones = jnp.ones_like(gx2)
        src = jnp.einsum("ij,jhw->ihw",
                         hm, jnp.stack([gx2, gy, ones]))
        sx = src[0] / (src[2] + 1e-10)
        sy = src[1] / (src[2] + 1e-10)
        from .vision_extra_ops import _bilinear_sample_nchw
        return _bilinear_sample_nchw(x[0], sy, sx)     # [C, oh, ow]

    out = jax.vmap(warp_one)(rois)
    return {"Out": out}


@register_op("deformable_psroi_pooling")
def _deformable_psroi_pooling(ins, attrs):
    """PSRoI pooling with learned per-part offsets
    (deformable_psroi_pooling_op.cc); offsets shift each bin's sampling
    region before position-sensitive averaging."""
    x = ins["Input"][0]
    rois = ins["ROIs"][0]
    trans = ins["Trans"][0] if ins.get("Trans") else None
    ph = int(attrs.get("pooled_height", attrs.get("pooled_size", 7)))
    pw = int(attrs.get("pooled_width", attrs.get("pooled_size", 7)))
    out_c = int(attrs.get("output_dim"))
    scale = float(attrs.get("spatial_scale", 1.0))
    trans_std = float(attrs.get("trans_std", 0.1))
    sample = int(attrs.get("sample_per_part", 4))
    n, c, h, w = x.shape
    xs = x.reshape(n, out_c, ph, pw, h, w) if c == out_c * ph * pw \
        else None
    from .vision_extra_ops import _roi_batch_ids
    roi_batch = _roi_batch_ids(ins, rois.shape[0])

    def pool_one(roi, bi, ti):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw, bh = rw / pw, rh / ph
        i = jnp.arange(ph, dtype=x.dtype)
        j = jnp.arange(pw, dtype=x.dtype)
        if trans is not None:
            off_y = ti[0] * trans_std * rh               # [ph, pw]
            off_x = ti[1] * trans_std * rw
        else:
            off_y = jnp.zeros((ph, pw), x.dtype)
            off_x = jnp.zeros((ph, pw), x.dtype)
        sy = (y1 + i[:, None] * bh + off_y)              # [ph, pw]
        sx = (x1 + j[None, :] * bw + off_x)
        # sample x at an SxS grid in each bin and average
        ss = jnp.arange(sample, dtype=x.dtype) / sample
        gy = sy[..., None, None] + ss[None, None, :, None] * bh
        gx = sx[..., None, None] + ss[None, None, None, :] * bw
        from .vision_extra_ops import _bilinear_sample_nchw
        if xs is not None:
            feat = xs[bi].reshape(out_c * ph * pw, h, w)
        else:
            feat = x[bi]
        samp = _bilinear_sample_nchw(
            feat, gy.reshape(ph, pw, -1), gx.reshape(ph, pw, -1))
        samp = samp.mean(-1)                             # [C', ph, pw]
        if xs is not None:
            samp = samp.reshape(out_c, ph, pw, ph, pw)
            ii = jnp.arange(ph)
            jj = jnp.arange(pw)
            samp = samp[:, ii[:, None], jj[None, :],
                        ii[:, None], jj[None, :]]
        return samp

    ts = (trans.reshape(rois.shape[0], 2, ph, pw) if trans is not None
          else jnp.zeros((rois.shape[0], 2, ph, pw), x.dtype))
    out = jax.vmap(pool_one)(rois, roi_batch, ts)
    return {"Output": out, "TopCount": jnp.ones_like(out)}


# -- misc helpers ------------------------------------------------------------

@register_op("hsigmoid")
def _hsigmoid(ins, attrs):
    """Hierarchical sigmoid over a complete binary tree
    (hierarchical_sigmoid_op.cc default path): for label l, the path is
    the binary expansion of l + num_classes-1 walked from the root;
    W [num_classes-1, D] holds internal-node params."""
    x = ins["X"][0]                                    # [N, D]
    w = ins["W"][0]                                    # [K-1, D]
    label = ins["Label"][0].reshape(-1).astype(jnp.int32)
    num_classes = int(attrs.get("num_classes", w.shape[0] + 1))
    bias = ins["Bias"][0].reshape(-1) if ins.get("Bias") else None
    depth = max(int(np.ceil(np.log2(max(num_classes, 2)))), 1)
    node = label + num_classes - 1                     # leaf index
    losses = []
    for _ in range(depth):
        parent = (node - 1) // 2
        is_right = (node % 2 == 0)                     # right child
        valid = node > 0
        pw = jnp.take(w, jnp.clip(parent, 0, w.shape[0] - 1), 0)
        s = jnp.einsum("nd,nd->n", x, pw)
        if bias is not None:
            s = s + bias[jnp.clip(parent, 0, bias.shape[0] - 1)]
        sign = jnp.where(is_right, -1.0, 1.0)
        step_loss = jnp.where(
            valid, -jax.nn.log_sigmoid(sign * s), 0.0)
        losses.append(step_loss)
        node = parent
    return {"Out": sum(losses)[:, None],
            "PreOut": jnp.zeros((x.shape[0], depth), x.dtype)}


@register_op("sampled_softmax_with_cross_entropy", needs_rng=True)
def _sampled_softmax_with_cross_entropy(ins, attrs):
    outs = get_op("sample_logits").compute(
        {"Logits": ins["Logits"], "Labels": ins["Label"]}, dict(attrs))
    sl = outs["SampledLogits"]
    sl = sl[0] if isinstance(sl, list) else sl
    nt = ins["Label"][0].shape[1]
    logp = jax.nn.log_softmax(sl, -1)
    loss = -logp[:, :nt].sum(-1, keepdims=True) / nt
    return {"Loss": loss, "Softmax": jnp.exp(logp)}


@register_op("random_crop", needs_rng=True)
def _random_crop(ins, attrs):
    x = ins["X"][0]
    shape = attrs["shape"]                             # cropped tail dims
    key = attrs["_rng_key"]
    nd = len(shape)
    starts = []
    for i, s in enumerate(shape):
        key, sub = jax.random.split(key)
        extent = x.shape[x.ndim - nd + i] - s
        starts.append(jax.random.randint(sub, (), 0, max(extent, 0) + 1))
    out = x
    for i, s in enumerate(shape):
        axis = x.ndim - nd + i
        out = jax.lax.dynamic_slice_in_dim(out, starts[i], s, axis)
    return {"Out": out, "SeedOut": jnp.zeros((1,), jnp.int64)}


@register_op("similarity_focus", no_jit=True)
def _similarity_focus(ins, attrs):
    """similarity_focus_op.cc: for each selected slice along `axis`
    (1, 2 or 3), greedily pick the largest values such that each row and
    each column is used at most once (min(B, C) picks), mark those
    positions 1, OR over indexes, broadcast back to x's shape. Host-side
    (no_jit): the greedy selection is inherently sequential — the
    reference ships only a CPU kernel for it too."""
    import numpy as np

    x = np.asarray(ins["X"][0])                        # [N, A, B, C]
    axis = int(attrs.get("axis", 1))
    indexes = list(attrs.get("indexes", [0]))
    if axis not in (1, 2, 3):
        raise ValueError(
            "similarity_focus: axis must be 1, 2 or 3 (reference "
            "similarity_focus_op.cc:28)")
    perm = [0, axis] + [d for d in (1, 2, 3) if d != axis]
    xt = np.transpose(x, perm)                         # [N, K, B, C]
    n, _, b, c = xt.shape
    mark = np.zeros((n, 1, b, c), x.dtype)
    for bi in range(n):
        for idx in indexes:
            t = xt[bi, idx]
            order = np.argsort(-t, axis=None, kind="stable")
            used_r = np.zeros(b, bool)
            used_c = np.zeros(c, bool)
            picked = 0
            for pos in order:
                r, col = divmod(int(pos), c)
                if used_r[r] or used_c[col]:
                    continue
                mark[bi, 0, r, col] = 1
                used_r[r] = used_c[col] = True
                picked += 1
                if picked == min(b, c):
                    break
    out = np.broadcast_to(mark, xt.shape)
    inv = np.argsort(perm)
    return {"Out": jnp.asarray(np.ascontiguousarray(
        np.transpose(out, inv)))}


@register_op("add_position_encoding")
def _add_position_encoding(ins, attrs):
    """add_position_encoding_op.cc: out = alpha*x + beta*sinusoid."""
    x = ins["X"][0]                                    # [B, T, D]
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    b, t, d = x.shape
    pos = jnp.arange(t, dtype=jnp.float32)[:, None]
    half = d // 2
    freq = jnp.power(10000.0, -jnp.arange(half, dtype=jnp.float32)
                     / max(half, 1))
    ang = pos * freq[None, :]
    enc = jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], 1)
    if enc.shape[1] < d:
        enc = jnp.pad(enc, ((0, 0), (0, d - enc.shape[1])))
    return {"Out": alpha * x + beta * enc[None, :, :].astype(x.dtype)}


@register_op("box_decoder_and_assign")
def _box_decoder_and_assign(ins, attrs):
    """box_decoder_and_assign_op.cc: decode per-class center-size deltas
    TargetBox [N, C*4] against PriorBox [N, 4], clamp dw/dh at box_clip,
    and assign each row its argmax-score class slice (background class 0
    excluded from the argmax like the reference)."""
    prior = ins["PriorBox"][0]                          # [N, 4]
    pvar = ins["PriorBoxVar"][0]                        # [N, 4] or [4]
    tb = ins["TargetBox"][0]                            # [N, C*4]
    score = ins["BoxScore"][0]                          # [N, C]
    clip = float(attrs.get("box_clip", 4.135))
    n = tb.shape[0]
    c = tb.shape[1] // 4
    deltas = tb.reshape(n, c, 4)
    if pvar.ndim == 1:
        pvar = jnp.broadcast_to(pvar[None, :], (n, 4))
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    d = deltas * pvar[:, None, :]
    dw = jnp.clip(d[..., 2], -clip, clip)
    dh = jnp.clip(d[..., 3], -clip, clip)
    cx = d[..., 0] * pw[:, None] + pcx[:, None]
    cy = d[..., 1] * ph[:, None] + pcy[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    dec = jnp.stack([cx - w * 0.5, cy - h * 0.5,
                     cx + w * 0.5 - 1.0, cy + h * 0.5 - 1.0], -1)
    best = jnp.argmax(score[:, 1:], axis=1) + 1         # skip background
    assigned = dec[jnp.arange(n), best]
    return {"DecodeBox": dec.reshape(n, c * 4),
            "OutputAssignBox": assigned}


@register_op("mine_hard_examples", no_jit=True, dynamic_shape=True)
def _mine_hard_examples(ins, attrs):
    """SSD hard-negative mining (reference:
    mine_hard_examples_op.cc:52). max_negative: negatives are unmatched
    priors under the dist threshold, capped at neg_pos_ratio * positives;
    hard_example: every prior is a candidate ranked by (cls+loc) loss,
    capped at sample_size, and unselected positives get their match
    erased. Outputs NegIndices [K,1] with NegIndicesLod offsets [N+1]."""
    cls_loss = np.asarray(ins["ClsLoss"][0])
    loc_loss = np.asarray(ins["LocLoss"][0]) if ins.get("LocLoss") \
        else None
    match_indices = np.asarray(ins["MatchIndices"][0]).copy()
    match_dist = np.asarray(ins["MatchDist"][0])
    neg_pos_ratio = float(attrs.get("neg_pos_ratio", 1.0))
    neg_dist_threshold = float(attrs.get("neg_dist_threshold", 0.5))
    sample_size = int(attrs.get("sample_size", 0))
    mining_type = attrs.get("mining_type", "max_negative")
    batch, prior_num = match_indices.shape
    neg_all, starts = [], [0]
    for n in range(batch):
        if mining_type == "max_negative":
            eligible = [m for m in range(prior_num)
                        if match_indices[n, m] == -1
                        and match_dist[n, m] < neg_dist_threshold]
        else:  # hard_example
            eligible = list(range(prior_num))
        losses = cls_loss[n]
        if mining_type == "hard_example" and loc_loss is not None:
            losses = losses + loc_loss[n]
        eligible.sort(key=lambda m: -losses[m])
        if mining_type == "max_negative":
            num_pos = int((match_indices[n] != -1).sum())
            neg_sel = min(int(num_pos * neg_pos_ratio), len(eligible))
        else:
            neg_sel = min(sample_size, len(eligible))
        sel = set(eligible[:neg_sel])
        if mining_type == "hard_example":
            neg = []
            for m in range(prior_num):
                if match_indices[n, m] > -1:
                    if m not in sel:
                        match_indices[n, m] = -1
                elif m in sel:
                    neg.append(m)
        else:
            neg = sorted(sel)
        neg_all.extend(neg)
        starts.append(len(neg_all))
    return {"NegIndices": np.asarray(neg_all, np.int32).reshape(-1, 1),
            "NegIndicesLod": np.asarray(starts, np.int64),
            "UpdatedMatchIndices": match_indices}


def _map_clip01(b):
    return np.clip(b, 0.0, 1.0)


@register_op("detection_map", no_jit=True, dynamic_shape=True)
def _detection_map(ins, attrs):
    """Detection mAP metric (reference: detection_map_op.h:59).
    DetectRes rows [label, score, x1, y1, x2, y2]; Label rows
    [label, x1, y1, x2, y2] or [label, is_difficult, x1, y1, x2, y2].
    Per-image segments ride the optional DetectResLod / LabelLod offset
    inputs (LoD-as-input, padded-representation convention); without
    them the whole tensor is one image. Streaming accumulation state
    enters via PosCount/TruePos/FalsePos (+ *Lod offsets per class) when
    HasState[0] != 0 and leaves via the Accum* outputs, exactly like the
    reference's LoD-carried state."""
    detect = np.asarray(ins["DetectRes"][0], np.float32)
    label = np.asarray(ins["Label"][0], np.float32)
    d_lod = np.asarray(ins["DetectResLod"][0]).reshape(-1).astype(int) \
        if ins.get("DetectResLod") else np.asarray([0, len(detect)])
    l_lod = np.asarray(ins["LabelLod"][0]).reshape(-1).astype(int) \
        if ins.get("LabelLod") else np.asarray([0, len(label)])
    overlap_threshold = float(attrs.get("overlap_threshold", 0.5))
    evaluate_difficult = bool(attrs.get("evaluate_difficult", True))
    ap_type = attrs.get("ap_type", "integral")
    class_num = int(attrs["class_num"])
    background = int(attrs.get("background_label", 0))

    # per-class streaming state; defaultdicts so out-of-range /
    # sentinel class ids (e.g. a -1 no-detection row) accumulate
    # harmlessly instead of KeyError'ing (reference uses std::map)
    import collections

    pos_count = {}
    true_pos = collections.defaultdict(list)
    false_pos = collections.defaultdict(list)
    has_state = (int(np.asarray(ins["HasState"][0]).reshape(-1)[0])
                 if ins.get("HasState") else 0)
    if has_state and ins.get("PosCount"):
        pc = np.asarray(ins["PosCount"][0]).reshape(-1)
        for c in range(min(class_num, len(pc))):
            if pc[c]:
                pos_count[c] = int(pc[c])
        for key, slot in (("TruePos", true_pos), ("FalsePos", false_pos)):
            if not ins.get(key):
                continue
            arr = np.asarray(ins[key][0], np.float32).reshape(-1, 2)
            lod = np.asarray(ins[key + "Lod"][0]).reshape(-1).astype(int) \
                if ins.get(key + "Lod") else np.asarray([0, len(arr)])
            for c in range(len(lod) - 1):
                for r in arr[lod[c]:lod[c + 1]]:
                    slot[c].append((float(r[0]), int(r[1])))

    n_img = len(l_lod) - 1
    has_difficult = label.shape[1] == 6 if len(label) else False
    for n in range(n_img):
        gts = {}
        for i in range(l_lod[n], l_lod[n + 1]):
            row = label[i]
            cls = int(row[0])
            if has_difficult:
                box, difficult = row[2:6], bool(abs(row[1]) > 1e-6)
            else:
                box, difficult = row[1:5], False
            gts.setdefault(cls, []).append((box, difficult))
        for cls, items in gts.items():
            cnt = len(items) if evaluate_difficult else \
                sum(1 for _, d in items if not d)
            if cnt:
                pos_count[cls] = pos_count.get(cls, 0) + cnt
        dets = {}
        for i in range(d_lod[n], d_lod[n + 1]):
            row = detect[i]
            dets.setdefault(int(row[0]), []).append(
                (float(row[1]), row[2:6]))
        for cls, preds in dets.items():
            if cls not in gts:
                for score, _ in preds:
                    true_pos[cls].append((score, 0))
                    false_pos[cls].append((score, 1))
                continue
            matched = gts[cls]
            visited = [False] * len(matched)
            preds = sorted(preds, key=lambda p: -p[0])
            for score, box in preds:
                box = _map_clip01(box)  # reference clips pred box only
                overlaps = [_np_iou_xyxy(box[None], mb[None])[0, 0]
                            for mb, _d in matched]
                max_idx = int(np.argmax(overlaps)) if overlaps else 0
                max_ov = overlaps[max_idx] if overlaps else -1.0
                if max_ov > overlap_threshold:
                    ok = evaluate_difficult or not matched[max_idx][1]
                    if ok:
                        if not visited[max_idx]:
                            true_pos[cls].append((score, 1))
                            false_pos[cls].append((score, 0))
                            visited[max_idx] = True
                        else:
                            true_pos[cls].append((score, 0))
                            false_pos[cls].append((score, 1))
                else:
                    true_pos[cls].append((score, 0))
                    false_pos[cls].append((score, 1))

    # mAP over classes with positives (reference CalcMAP)
    mAP, count = 0.0, 0
    for cls, num_pos in pos_count.items():
        # the reference's literal code compares the POSITIVE COUNT to
        # background_label (detection_map_op.h:423, an upstream quirk);
        # we implement the documented intent: skip the background CLASS
        if cls == background:
            continue
        tp = sorted(true_pos.get(cls, []), key=lambda p: -p[0])
        fp = sorted(false_pos.get(cls, []), key=lambda p: -p[0])
        if not tp:
            count += 1
            continue
        tp_sum = np.cumsum([c for _, c in tp])
        fp_sum = np.cumsum([c for _, c in fp])
        precision = tp_sum / np.maximum(tp_sum + fp_sum, 1e-12)
        recall = tp_sum / float(num_pos)
        if ap_type == "11point":
            max_precisions = np.zeros(11)
            start_idx = len(tp_sum) - 1
            for j in range(10, -1, -1):
                for i in range(start_idx, -1, -1):
                    if recall[i] < j / 10.0:
                        start_idx = i
                        if j > 0:
                            max_precisions[j - 1] = max_precisions[j]
                        break
                    if max_precisions[j] < precision[i]:
                        max_precisions[j] = precision[i]
            mAP += max_precisions.sum() / 11.0
            count += 1
        else:  # integral
            ap, prev_recall = 0.0, 0.0
            for p, r in zip(precision, recall):
                if abs(r - prev_recall) > 1e-6:
                    ap += p * abs(r - prev_recall)
                prev_recall = r
            mAP += ap
            count += 1
    if count:
        mAP /= count

    out_pc = np.zeros((class_num, 1), np.int32)
    for c, v in pos_count.items():
        if 0 <= c < class_num:
            out_pc[c, 0] = v
    tp_rows, fp_rows = [], []
    tp_lod, fp_lod = [0], [0]
    for c in range(class_num):
        for s, v in true_pos[c]:
            tp_rows.append([s, v])
        tp_lod.append(len(tp_rows))
        for s, v in false_pos[c]:
            fp_rows.append([s, v])
        fp_lod.append(len(fp_rows))
    return {"MAP": np.asarray([mAP], np.float32),
            "AccumPosCount": out_pc,
            "AccumTruePos": np.asarray(tp_rows, np.float32).reshape(-1, 2),
            "AccumTruePosLod": np.asarray(tp_lod, np.int64),
            "AccumFalsePos": np.asarray(fp_rows,
                                        np.float32).reshape(-1, 2),
            "AccumFalsePosLod": np.asarray(fp_lod, np.int64)}


def _poly2mask_grid(xy, m):
    """Even-odd rasterization of one polygon onto an [m, m] grid at
    pixel centers (reference mask_util.cc:45 Poly2Mask uses the cocoapi
    boundary-walk RLE; pixel-center crossing parity matches it for
    well-formed polygons up to boundary-pixel rounding, documented)."""
    px = np.arange(m) + 0.5
    gx, gy = np.meshgrid(px, px)  # [row=y, col=x]
    inside = np.zeros((m, m), bool)
    x0, y0 = xy[:, 0], xy[:, 1]
    x1, y1 = np.roll(x0, -1), np.roll(y0, -1)
    for ax, ay, bx, by in zip(x0, y0, x1, y1):
        if ay == by:
            continue
        cond = (ay > gy) != (by > gy)
        xint = ax + (gy - ay) * (bx - ax) / (by - ay)
        inside ^= cond & (gx < xint)
    return inside


@register_op("generate_mask_labels", no_jit=True, dynamic_shape=True)
def _generate_mask_labels(ins, attrs):
    """Mask R-CNN mask targets (reference:
    generate_mask_labels_op.cc:139 SampleMaskForOneImage, single image):
    each fg roi is matched to the gt mask whose polygon bbox overlaps it
    most; that gt's polygons are rasterized w.r.t. the roi at
    `resolution`; targets expand to [P, num_classes*M*M] with -1 ignore
    elsewhere. Polygon structure rides GtSegmsPolyLod (per-gt poly
    offsets) + GtSegmsPointLod (per-poly point offsets) over the flat
    GtSegms [S, 2] points."""
    im_info = np.asarray(ins["ImInfo"][0], np.float32).reshape(-1)
    gt_classes = np.asarray(ins["GtClasses"][0]).reshape(-1).astype(int)
    is_crowd = np.asarray(ins["IsCrowd"][0]).reshape(-1).astype(int)
    segms = np.asarray(ins["GtSegms"][0], np.float32).reshape(-1, 2)
    rois = np.asarray(ins["Rois"][0], np.float32).reshape(-1, 4)
    labels = np.asarray(ins["LabelsInt32"][0]).reshape(-1).astype(int)
    if not ins.get("GtSegmsPolyLod") or not ins.get("GtSegmsPointLod"):
        raise ValueError(
            "generate_mask_labels: wire GtSegmsPolyLod (per-gt polygon "
            "offsets) and GtSegmsPointLod (per-polygon point offsets) — "
            "the padded representation carries the reference's 3-level "
            "GtSegms LoD through these inputs")
    poly_lod = np.asarray(ins["GtSegmsPolyLod"][0]).reshape(-1).astype(int)
    point_lod = np.asarray(
        ins["GtSegmsPointLod"][0]).reshape(-1).astype(int)
    num_classes = int(attrs["num_classes"])
    m = int(attrs["resolution"])
    im_scale = float(im_info[2]) if im_info.size >= 3 else 1.0

    gt_polys = []  # per kept gt: list of [k,2] arrays
    for i in range(len(gt_classes)):
        if gt_classes[i] > 0 and is_crowd[i] == 0:
            polys = []
            for j in range(poly_lod[i], poly_lod[i + 1]):
                polys.append(segms[point_lod[j]:point_lod[j + 1]])
            gt_polys.append(polys)
    fg_inds = [i for i in range(len(labels)) if labels[i] > 0]

    if fg_inds and gt_polys:
        boxes_from_polys = np.asarray(
            [[min(p[:, 0].min() for p in ps),
              min(p[:, 1].min() for p in ps),
              max(p[:, 0].max() for p in ps),
              max(p[:, 1].max() for p in ps)] for ps in gt_polys],
            np.float32)
        rois_fg = rois[fg_inds] / im_scale
        overlaps = _np_iou_xyxy(rois_fg, boxes_from_polys)
        fg_mask_inds = overlaps.argmax(axis=1)
        masks = np.zeros((len(fg_inds), m * m), np.int32)
        cls_labels = labels[np.asarray(fg_inds)]
        for i, gt_i in enumerate(fg_mask_inds):
            x0, y0, x1, y1 = rois_fg[i]
            w = max(x1 - x0, 1.0)
            h = max(y1 - y0, 1.0)
            acc = np.zeros((m, m), bool)
            for poly in gt_polys[gt_i]:
                p = np.stack([(poly[:, 0] - x0) * m / w,
                              (poly[:, 1] - y0) * m / h], axis=1)
                acc |= _poly2mask_grid(p, m)
            masks[i] = acc.astype(np.int32).reshape(-1)
        roi_has_mask = list(fg_inds)
        rois_out = rois_fg * im_scale
    else:
        # no fg: one bg roi with an all-ignore mask, class 0
        bg = next((i for i in range(len(labels)) if labels[i] == 0), 0)
        rois_out = rois[bg:bg + 1].copy() if len(rois) else \
            np.zeros((1, 4), np.float32)
        masks = np.full((1, m * m), -1, np.int32)
        cls_labels = np.asarray([0])
        roi_has_mask = [bg]

    expand = np.full((len(masks), num_classes * m * m), -1, np.int32)
    for i, cls in enumerate(np.asarray(cls_labels).reshape(-1)):
        if cls > 0:
            expand[i, cls * m * m:(cls + 1) * m * m] = masks[i]
    return {"MaskRois": rois_out.astype(np.float32),
            "RoiHasMaskInt32": np.asarray(
                roi_has_mask, np.int32).reshape(-1, 1),
            "MaskInt32": expand}
