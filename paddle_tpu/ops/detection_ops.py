"""Detection operators.

Reference parity: `paddle/fluid/operators/detection/` — prior_box,
density_prior_box, box_coder, yolo_box, iou_similarity, box_clip,
anchor_generator, roi_align, roi_pool; multiclass_nms runs un-jitted on
host (dynamic output count, reference returns a LoDTensor).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("iou_similarity")
def _iou_similarity(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]  # [n,4], [m,4] xyxy
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return {"Out": inter / (area_x[:, None] + area_y[None, :] - inter)}


@register_op("box_clip")
def _box_clip(ins, attrs):
    boxes, im_info = ins["Input"][0], ins["ImInfo"][0]
    h = im_info[0, 0] - 1.0
    w = im_info[0, 1] - 1.0
    x1 = jnp.clip(boxes[..., 0], 0, w)
    y1 = jnp.clip(boxes[..., 1], 0, h)
    x2 = jnp.clip(boxes[..., 2], 0, w)
    y2 = jnp.clip(boxes[..., 3], 0, h)
    return {"Output": jnp.stack([x1, y1, x2, y2], axis=-1)}


@register_op("box_coder")
def _box_coder(ins, attrs):
    # reference: box_coder_op.cc — encode/decode center-size
    prior, tb = ins["PriorBox"][0], ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    one = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if code_type.startswith("encode"):
        tw = tb[:, 2] - tb[:, 0] + one
        th = tb[:, 3] - tb[:, 1] + one
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if var is not None:
            out = out / var[None, :, :]
        return {"OutputBox": out}
    # decode: tb [n, p, 4]
    d = tb
    if var is not None:
        d = d * var[None, :, :]
    cx = d[..., 0] * pw[None, :] + pcx[None, :]
    cy = d[..., 1] * ph[None, :] + pcy[None, :]
    w = jnp.exp(d[..., 2]) * pw[None, :]
    h = jnp.exp(d[..., 3]) * ph[None, :]
    return {"OutputBox": jnp.stack(
        [cx - w * 0.5, cy - h * 0.5, cx + w * 0.5 - one,
         cy + h * 0.5 - one], axis=-1)}


@register_op("prior_box")
def _prior_box(ins, attrs):
    inp, image = ins["Input"][0], ins["Image"][0]
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ars_in = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", False)
    clip = attrs.get("clip", False)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    offset = attrs.get("offset", 0.5)
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    fh, fw = inp.shape[2], inp.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = step_w or iw / fw
    sh = step_h or ih / fh
    ars = [1.0]
    for ar in ars_in:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    for ms in min_sizes:
        for ar in ars:
            bw = ms * np.sqrt(ar) / 2.0
            bh = ms / np.sqrt(ar) / 2.0
            boxes.append((bw, bh))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            s = np.sqrt(ms * mx) / 2.0
            boxes.append((s, s))
    num_priors = len(boxes)
    cx = (jnp.arange(fw) + offset) * sw
    cy = (jnp.arange(fh) + offset) * sh
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    out = []
    for bw, bh in boxes:
        out.append(jnp.stack([(gx - bw) / iw, (gy - bh) / ih,
                              (gx + bw) / iw, (gy + bh) / ih], -1))
    out = jnp.stack(out, axis=2)  # [fh, fw, np, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, out.dtype),
                           out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("density_prior_box")
def _density_prior_box(ins, attrs):
    inp, image = ins["Input"][0], ins["Image"][0]
    fixed_sizes = attrs.get("fixed_sizes", [])
    fixed_ratios = attrs.get("fixed_ratios", [1.0])
    densities = attrs.get("densities", [1])
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    offset = attrs.get("offset", 0.5)
    clip = attrs.get("clip", False)
    fh, fw = inp.shape[2], inp.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw, sh = iw / fw, ih / fh
    boxes = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            step = size / density
            for di in range(density):
                for dj in range(density):
                    ox = -size / 2.0 + step / 2.0 + dj * step
                    oy = -size / 2.0 + step / 2.0 + di * step
                    boxes.append((ox, oy, bw / 2.0, bh / 2.0))
    cx = (jnp.arange(fw) + offset) * sw
    cy = (jnp.arange(fh) + offset) * sh
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    out = []
    for ox, oy, bw, bh in boxes:
        ccx, ccy = gx + ox, gy + oy
        out.append(jnp.stack([(ccx - bw) / iw, (ccy - bh) / ih,
                              (ccx + bw) / iw, (ccy + bh) / ih], -1))
    out = jnp.stack(out, axis=2)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, out.dtype), out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("anchor_generator")
def _anchor_generator(ins, attrs):
    inp = ins["Input"][0]
    anchor_sizes = attrs.get("anchor_sizes", [64.0])
    ars = attrs.get("aspect_ratios", [1.0])
    stride = attrs.get("stride", [16.0, 16.0])
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    offset = attrs.get("offset", 0.5)
    fh, fw = inp.shape[2], inp.shape[3]
    boxes = []
    for size in anchor_sizes:
        area = size * size
        for ar in ars:
            w = np.sqrt(area / ar)
            h = w * ar
            boxes.append((w / 2.0, h / 2.0))
    cx = (jnp.arange(fw) + offset) * stride[0]
    cy = (jnp.arange(fh) + offset) * stride[1]
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    out = []
    for bw, bh in boxes:
        out.append(jnp.stack([gx - bw, gy - bh, gx + bw, gy + bh], -1))
    out = jnp.stack(out, axis=2)
    var = jnp.broadcast_to(jnp.asarray(variances, out.dtype), out.shape)
    return {"Anchors": out, "Variances": var}


@register_op("yolo_box")
def _yolo_box(ins, attrs):
    # reference: yolo_box_op.cc
    x, img_size = ins["X"][0], ins["ImgSize"][0]
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = attrs.get("downsample_ratio", 32)
    n, c, h, w = x.shape
    an_num = len(anchors) // 2
    x5 = x.reshape(n, an_num, 5 + class_num, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    pred_x = (jax.nn.sigmoid(x5[:, :, 0]) + grid_x) / w
    pred_y = (jax.nn.sigmoid(x5[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2], x.dtype).reshape(1, an_num, 1, 1)
    ah = jnp.asarray(anchors[1::2], x.dtype).reshape(1, an_num, 1, 1)
    input_h = downsample * h
    input_w = downsample * w
    pred_w = jnp.exp(x5[:, :, 2]) * aw / input_w
    pred_h = jnp.exp(x5[:, :, 3]) * ah / input_h
    conf = jax.nn.sigmoid(x5[:, :, 4])
    keep = (conf >= conf_thresh).astype(x.dtype)
    imh = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    imw = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    x1 = (pred_x - pred_w / 2.0) * imw
    y1 = (pred_y - pred_h / 2.0) * imh
    x2 = (pred_x + pred_w / 2.0) * imw
    y2 = (pred_y + pred_h / 2.0) * imh
    boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(n, -1, 4)
    probs = jax.nn.sigmoid(x5[:, :, 5:]) * (conf * keep)[:, :, None]
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    return {"Boxes": boxes, "Scores": scores}


@register_op("roi_align")
def _roi_align(ins, attrs):
    # reference: roi_align_op.cc — average of 4 bilinear samples per bin
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    ratio = attrs.get("sampling_ratio", -1)
    ratio = 2 if ratio <= 0 else ratio
    n, c, h, w = x.shape
    num_rois = rois.shape[0]
    batch_idx = ins["RoisNum"][0] if ins.get("RoisNum") else None

    x1 = rois[:, 0] * scale
    y1 = rois[:, 1] * scale
    x2 = rois[:, 2] * scale
    y2 = rois[:, 3] * scale
    rw = jnp.maximum(x2 - x1, 1.0)
    rh = jnp.maximum(y2 - y1, 1.0)
    bw = rw / pw
    bh = rh / ph

    iy = (jnp.arange(ph)[:, None] + (jnp.arange(ratio)[None, :] + 0.5)
          / ratio).reshape(-1)  # [ph*ratio]
    ix = (jnp.arange(pw)[:, None] + (jnp.arange(ratio)[None, :] + 0.5)
          / ratio).reshape(-1)
    sy = y1[:, None] + bh[:, None] * iy[None, :]  # [R, ph*ratio]
    sx = x1[:, None] + bw[:, None] * ix[None, :]

    y0f = jnp.floor(sy)
    x0f = jnp.floor(sx)
    wy1 = sy - y0f
    wx1 = sx - x0f

    def gather(yy, xx):
        yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        # x[0] batch assumed (single image) unless RoisNum given
        feat = x[0] if batch_idx is None else x[0]
        return feat[:, yi[:, :, None], xi[:, None, :]]

    v00 = gather(y0f, x0f)
    v01 = gather(y0f, x0f + 1)
    v10 = gather(y0f + 1, x0f)
    v11 = gather(y0f + 1, x0f + 1)
    wy1e = wy1[None, :, :, None]
    wx1e = wx1[None, :, None, :]
    val = (v00 * (1 - wy1e) * (1 - wx1e) + v01 * (1 - wy1e) * wx1e
           + v10 * wy1e * (1 - wx1e) + v11 * wy1e * wx1e)
    # [c, R, ph*ratio, pw*ratio] -> bins
    val = val.reshape(c, num_rois, ph, ratio, pw, ratio)
    out = jnp.mean(val, axis=(3, 5)).transpose(1, 0, 2, 3)
    return {"Out": out}


@register_op("roi_pool")
def _roi_pool(ins, attrs):
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape
    num_rois = rois.shape[0]
    x1 = jnp.round(rois[:, 0] * scale)
    y1 = jnp.round(rois[:, 1] * scale)
    x2 = jnp.round(rois[:, 2] * scale)
    y2 = jnp.round(rois[:, 3] * scale)
    rw = jnp.maximum(x2 - x1 + 1, 1.0)
    rh = jnp.maximum(y2 - y1 + 1, 1.0)
    # sample a dense grid then max-pool per bin (approximation-free for
    # integer bin edges when grid covers every cell)
    gh, gw = ph * 8, pw * 8
    yy = y1[:, None] + (jnp.arange(gh)[None, :] + 0.5) * rh[:, None] / gh
    xx = x1[:, None] + (jnp.arange(gw)[None, :] + 0.5) * rw[:, None] / gw
    yi = jnp.clip(jnp.floor(yy), 0, h - 1).astype(jnp.int32)
    xi = jnp.clip(jnp.floor(xx), 0, w - 1).astype(jnp.int32)
    feat = x[0]
    vals = feat[:, yi[:, :, None], xi[:, None, :]]
    vals = vals.reshape(c, num_rois, ph, 8, pw, 8)
    out = jnp.max(vals, axis=(3, 5)).transpose(1, 0, 2, 3)
    return {"Out": out, "Argmax": jnp.zeros(out.shape, jnp.int64)}


@register_op("multiclass_nms", no_jit=True,
             dynamic_shape=True)
def _multiclass_nms(ins, attrs):
    # host-side (dynamic output count; reference outputs a LoDTensor)
    boxes = np.asarray(ins["BBoxes"][0])
    scores = np.asarray(ins["Scores"][0])
    score_threshold = attrs.get("score_threshold", 0.0)
    nms_threshold = attrs.get("nms_threshold", 0.3)
    nms_top_k = attrs.get("nms_top_k", 400)
    keep_top_k = attrs.get("keep_top_k", 200)
    background = attrs.get("background_label", 0)
    n = boxes.shape[0]
    results = []
    for b in range(n):
        dets = []
        for cls in range(scores.shape[1]):
            if cls == background:
                continue
            s = scores[b, cls]
            keep = np.where(s > score_threshold)[0]
            order = keep[np.argsort(-s[keep])][:nms_top_k]
            bb = list(boxes[b, order])
            ss = list(s[order])
            while bb:
                b0, s0 = bb.pop(0), ss.pop(0)
                dets.append([cls, s0] + list(b0))
                nbb, nss = [], []
                for bi, si in zip(bb, ss):
                    x1 = max(b0[0], bi[0])
                    y1 = max(b0[1], bi[1])
                    x2 = min(b0[2], bi[2])
                    y2 = min(b0[3], bi[3])
                    inter = max(x2 - x1, 0) * max(y2 - y1, 0)
                    a0 = (b0[2] - b0[0]) * (b0[3] - b0[1])
                    a1 = (bi[2] - bi[0]) * (bi[3] - bi[1])
                    iou = inter / max(a0 + a1 - inter, 1e-10)
                    if iou <= nms_threshold:
                        nbb.append(bi)
                        nss.append(si)
                bb, ss = nbb, nss
        dets.sort(key=lambda d: -d[1])
        results.append(np.asarray(dets[:keep_top_k], np.float32).reshape(
            -1, 6))
    out = np.concatenate(results, axis=0) if results else \
        np.zeros((0, 6), np.float32)
    return {"Out": out}
