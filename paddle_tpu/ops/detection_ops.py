"""Detection operators.

Reference parity: `paddle/fluid/operators/detection/` — prior_box,
density_prior_box, box_coder, yolo_box, iou_similarity, box_clip,
anchor_generator, roi_align, roi_pool; multiclass_nms runs un-jitted on
host (dynamic output count, reference returns a LoDTensor).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("iou_similarity")
def _iou_similarity(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]  # [n,4], [m,4] xyxy
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return {"Out": inter / (area_x[:, None] + area_y[None, :] - inter)}


@register_op("box_clip")
def _box_clip(ins, attrs):
    boxes, im_info = ins["Input"][0], ins["ImInfo"][0]
    h = im_info[0, 0] - 1.0
    w = im_info[0, 1] - 1.0
    x1 = jnp.clip(boxes[..., 0], 0, w)
    y1 = jnp.clip(boxes[..., 1], 0, h)
    x2 = jnp.clip(boxes[..., 2], 0, w)
    y2 = jnp.clip(boxes[..., 3], 0, h)
    return {"Output": jnp.stack([x1, y1, x2, y2], axis=-1)}


@register_op("box_coder")
def _box_coder(ins, attrs):
    # reference: box_coder_op.cc — encode/decode center-size
    prior, tb = ins["PriorBox"][0], ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    one = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if code_type.startswith("encode"):
        tw = tb[:, 2] - tb[:, 0] + one
        th = tb[:, 3] - tb[:, 1] + one
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
        dw = jnp.log(tw[:, None] / pw[None, :])
        dh = jnp.log(th[:, None] / ph[None, :])
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if var is not None:
            out = out / var[None, :, :]
        return {"OutputBox": out}
    # decode: tb [n, p, 4]
    d = tb
    if var is not None:
        d = d * var[None, :, :]
    cx = d[..., 0] * pw[None, :] + pcx[None, :]
    cy = d[..., 1] * ph[None, :] + pcy[None, :]
    w = jnp.exp(d[..., 2]) * pw[None, :]
    h = jnp.exp(d[..., 3]) * ph[None, :]
    return {"OutputBox": jnp.stack(
        [cx - w * 0.5, cy - h * 0.5, cx + w * 0.5 - one,
         cy + h * 0.5 - one], axis=-1)}


@register_op("prior_box")
def _prior_box(ins, attrs):
    inp, image = ins["Input"][0], ins["Image"][0]
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ars_in = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", False)
    clip = attrs.get("clip", False)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    offset = attrs.get("offset", 0.5)
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    fh, fw = inp.shape[2], inp.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = step_w or iw / fw
    sh = step_h or ih / fh
    ars = [1.0]
    for ar in ars_in:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    boxes = []
    for ms in min_sizes:
        for ar in ars:
            bw = ms * np.sqrt(ar) / 2.0
            bh = ms / np.sqrt(ar) / 2.0
            boxes.append((bw, bh))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            s = np.sqrt(ms * mx) / 2.0
            boxes.append((s, s))
    num_priors = len(boxes)
    cx = (jnp.arange(fw) + offset) * sw
    cy = (jnp.arange(fh) + offset) * sh
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    out = []
    for bw, bh in boxes:
        out.append(jnp.stack([(gx - bw) / iw, (gy - bh) / ih,
                              (gx + bw) / iw, (gy + bh) / ih], -1))
    out = jnp.stack(out, axis=2)  # [fh, fw, np, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, out.dtype),
                           out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("density_prior_box")
def _density_prior_box(ins, attrs):
    inp, image = ins["Input"][0], ins["Image"][0]
    fixed_sizes = attrs.get("fixed_sizes", [])
    fixed_ratios = attrs.get("fixed_ratios", [1.0])
    densities = attrs.get("densities", [1])
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    offset = attrs.get("offset", 0.5)
    clip = attrs.get("clip", False)
    fh, fw = inp.shape[2], inp.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw, sh = iw / fw, ih / fh
    boxes = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            step = size / density
            for di in range(density):
                for dj in range(density):
                    ox = -size / 2.0 + step / 2.0 + dj * step
                    oy = -size / 2.0 + step / 2.0 + di * step
                    boxes.append((ox, oy, bw / 2.0, bh / 2.0))
    cx = (jnp.arange(fw) + offset) * sw
    cy = (jnp.arange(fh) + offset) * sh
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    out = []
    for ox, oy, bw, bh in boxes:
        ccx, ccy = gx + ox, gy + oy
        out.append(jnp.stack([(ccx - bw) / iw, (ccy - bh) / ih,
                              (ccx + bw) / iw, (ccy + bh) / ih], -1))
    out = jnp.stack(out, axis=2)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, out.dtype), out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("anchor_generator")
def _anchor_generator(ins, attrs):
    inp = ins["Input"][0]
    anchor_sizes = attrs.get("anchor_sizes", [64.0])
    ars = attrs.get("aspect_ratios", [1.0])
    stride = attrs.get("stride", [16.0, 16.0])
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    offset = attrs.get("offset", 0.5)
    fh, fw = inp.shape[2], inp.shape[3]
    boxes = []
    for size in anchor_sizes:
        area = size * size
        for ar in ars:
            w = np.sqrt(area / ar)
            h = w * ar
            boxes.append((w / 2.0, h / 2.0))
    cx = (jnp.arange(fw) + offset) * stride[0]
    cy = (jnp.arange(fh) + offset) * stride[1]
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    out = []
    for bw, bh in boxes:
        out.append(jnp.stack([gx - bw, gy - bh, gx + bw, gy + bh], -1))
    out = jnp.stack(out, axis=2)
    var = jnp.broadcast_to(jnp.asarray(variances, out.dtype), out.shape)
    return {"Anchors": out, "Variances": var}


@register_op("yolo_box")
def _yolo_box(ins, attrs):
    # reference: yolo_box_op.cc
    x, img_size = ins["X"][0], ins["ImgSize"][0]
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = attrs.get("downsample_ratio", 32)
    n, c, h, w = x.shape
    an_num = len(anchors) // 2
    x5 = x.reshape(n, an_num, 5 + class_num, h, w)
    grid_x = jnp.arange(w).reshape(1, 1, 1, w)
    grid_y = jnp.arange(h).reshape(1, 1, h, 1)
    pred_x = (jax.nn.sigmoid(x5[:, :, 0]) + grid_x) / w
    pred_y = (jax.nn.sigmoid(x5[:, :, 1]) + grid_y) / h
    aw = jnp.asarray(anchors[0::2], x.dtype).reshape(1, an_num, 1, 1)
    ah = jnp.asarray(anchors[1::2], x.dtype).reshape(1, an_num, 1, 1)
    input_h = downsample * h
    input_w = downsample * w
    pred_w = jnp.exp(x5[:, :, 2]) * aw / input_w
    pred_h = jnp.exp(x5[:, :, 3]) * ah / input_h
    conf = jax.nn.sigmoid(x5[:, :, 4])
    keep = (conf >= conf_thresh).astype(x.dtype)
    imh = img_size[:, 0].reshape(n, 1, 1, 1).astype(x.dtype)
    imw = img_size[:, 1].reshape(n, 1, 1, 1).astype(x.dtype)
    x1 = (pred_x - pred_w / 2.0) * imw
    y1 = (pred_y - pred_h / 2.0) * imh
    x2 = (pred_x + pred_w / 2.0) * imw
    y2 = (pred_y + pred_h / 2.0) * imh
    boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(n, -1, 4)
    probs = jax.nn.sigmoid(x5[:, :, 5:]) * (conf * keep)[:, :, None]
    scores = probs.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    return {"Boxes": boxes, "Scores": scores}


@register_op("roi_align")
def _roi_align(ins, attrs):
    # reference: roi_align_op.cc — average of 4 bilinear samples per bin
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    ratio = attrs.get("sampling_ratio", -1)
    ratio = 2 if ratio <= 0 else ratio
    n, c, h, w = x.shape
    num_rois = rois.shape[0]
    batch_idx = ins["RoisNum"][0] if ins.get("RoisNum") else None

    x1 = rois[:, 0] * scale
    y1 = rois[:, 1] * scale
    x2 = rois[:, 2] * scale
    y2 = rois[:, 3] * scale
    rw = jnp.maximum(x2 - x1, 1.0)
    rh = jnp.maximum(y2 - y1, 1.0)
    bw = rw / pw
    bh = rh / ph

    iy = (jnp.arange(ph)[:, None] + (jnp.arange(ratio)[None, :] + 0.5)
          / ratio).reshape(-1)  # [ph*ratio]
    ix = (jnp.arange(pw)[:, None] + (jnp.arange(ratio)[None, :] + 0.5)
          / ratio).reshape(-1)
    sy = y1[:, None] + bh[:, None] * iy[None, :]  # [R, ph*ratio]
    sx = x1[:, None] + bw[:, None] * ix[None, :]

    y0f = jnp.floor(sy)
    x0f = jnp.floor(sx)
    wy1 = sy - y0f
    wx1 = sx - x0f

    def gather(yy, xx):
        yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        # x[0] batch assumed (single image) unless RoisNum given
        feat = x[0] if batch_idx is None else x[0]
        return feat[:, yi[:, :, None], xi[:, None, :]]

    v00 = gather(y0f, x0f)
    v01 = gather(y0f, x0f + 1)
    v10 = gather(y0f + 1, x0f)
    v11 = gather(y0f + 1, x0f + 1)
    wy1e = wy1[None, :, :, None]
    wx1e = wx1[None, :, None, :]
    val = (v00 * (1 - wy1e) * (1 - wx1e) + v01 * (1 - wy1e) * wx1e
           + v10 * wy1e * (1 - wx1e) + v11 * wy1e * wx1e)
    # [c, R, ph*ratio, pw*ratio] -> bins
    val = val.reshape(c, num_rois, ph, ratio, pw, ratio)
    out = jnp.mean(val, axis=(3, 5)).transpose(1, 0, 2, 3)
    return {"Out": out}


@register_op("roi_pool")
def _roi_pool(ins, attrs):
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape
    num_rois = rois.shape[0]
    x1 = jnp.round(rois[:, 0] * scale)
    y1 = jnp.round(rois[:, 1] * scale)
    x2 = jnp.round(rois[:, 2] * scale)
    y2 = jnp.round(rois[:, 3] * scale)
    rw = jnp.maximum(x2 - x1 + 1, 1.0)
    rh = jnp.maximum(y2 - y1 + 1, 1.0)
    # sample a dense grid then max-pool per bin (approximation-free for
    # integer bin edges when grid covers every cell)
    gh, gw = ph * 8, pw * 8
    yy = y1[:, None] + (jnp.arange(gh)[None, :] + 0.5) * rh[:, None] / gh
    xx = x1[:, None] + (jnp.arange(gw)[None, :] + 0.5) * rw[:, None] / gw
    yi = jnp.clip(jnp.floor(yy), 0, h - 1).astype(jnp.int32)
    xi = jnp.clip(jnp.floor(xx), 0, w - 1).astype(jnp.int32)
    feat = x[0]
    vals = feat[:, yi[:, :, None], xi[:, None, :]]
    vals = vals.reshape(c, num_rois, ph, 8, pw, 8)
    out = jnp.max(vals, axis=(3, 5)).transpose(1, 0, 2, 3)
    return {"Out": out, "Argmax": jnp.zeros(out.shape, jnp.int64)}


def _np_iou_pair(a, b, normalized=True):
    """Single-pair IoU: delegates to the one vectorized implementation
    (detection_extra_ops._np_iou_xyxy) so the normalized/+1 semantics
    can never diverge between the NMS family members."""
    from .detection_extra_ops import _np_iou_xyxy

    return float(_np_iou_xyxy(np.asarray(a, np.float64)[None],
                              np.asarray(b, np.float64)[None],
                              normalized=normalized)[0, 0])


def _greedy_select(order, iou_of, nms_threshold, eta):
    """Greedy suppress-by-IoU with the reference's adaptive eta rule
    (multiclass_nms_op.cc NMSFast): keep a candidate iff its IoU with
    every kept box is <= the adaptive threshold."""
    selected = []
    adaptive = nms_threshold
    for idx in order:
        ok = True
        for kept in selected:
            if iou_of(idx, kept) > adaptive:
                ok = False
                break
        if ok:
            selected.append(int(idx))
            if eta < 1.0 and adaptive > 0.5:
                adaptive *= eta
    return selected


def _nms_one_batch(boxes_b, scores_b, attrs):
    """Greedy per-class NMS for one image; returns (dets, box_indices)
    sorted by score desc, keep_top_k applied (reference:
    multiclass_nms_op.cc MultiClassNMS/MultiClassOutput)."""
    score_threshold = attrs.get("score_threshold", 0.0)
    nms_threshold = attrs.get("nms_threshold", 0.3)
    nms_top_k = attrs.get("nms_top_k", 400)
    keep_top_k = attrs.get("keep_top_k", 200)
    background = attrs.get("background_label", 0)
    eta = attrs.get("nms_eta", 1.0)
    normalized = attrs.get("normalized", True)
    dets, det_idx = [], []
    for cls in range(scores_b.shape[0]):
        if cls == background:
            continue
        s = scores_b[cls]
        keep = np.where(s > score_threshold)[0]
        order = keep[np.argsort(-s[keep], kind="stable")][:nms_top_k]
        selected = _greedy_select(
            order, lambda i, k: _np_iou_pair(boxes_b[i], boxes_b[k],
                                             normalized=normalized),
            nms_threshold, eta)
        for idx in selected:
            dets.append([cls, s[idx]] + list(boxes_b[idx]))
            det_idx.append(idx)
    order = sorted(range(len(dets)), key=lambda i: -dets[i][1])
    order = order[:keep_top_k] if keep_top_k > -1 else order
    return ([dets[i] for i in order], [det_idx[i] for i in order])


@register_op("multiclass_nms", no_jit=True,
             dynamic_shape=True)
def _multiclass_nms(ins, attrs):
    # host-side (dynamic output count; reference outputs a LoDTensor)
    boxes = np.asarray(ins["BBoxes"][0])
    scores = np.asarray(ins["Scores"][0])
    results = []
    for b in range(boxes.shape[0]):
        dets, _ = _nms_one_batch(boxes[b], scores[b], attrs)
        results.append(np.asarray(dets, np.float32).reshape(-1, 6))
    out = np.concatenate(results, axis=0) if results else \
        np.zeros((0, 6), np.float32)
    return {"Out": out}


@register_op("multiclass_nms2", no_jit=True,
             dynamic_shape=True)
def _multiclass_nms2(ins, attrs):
    """multiclass_nms + Index output: kept boxes' indices into the
    flattened [N*M] box table (reference: multiclass_nms_op.cc:493
    MultiClassNMS2Op, Index filled at :321 with start + idx)."""
    boxes = np.asarray(ins["BBoxes"][0])
    scores = np.asarray(ins["Scores"][0])
    num_boxes = boxes.shape[1]
    results, indices = [], []
    for b in range(boxes.shape[0]):
        dets, idx = _nms_one_batch(boxes[b], scores[b], attrs)
        results.append(np.asarray(dets, np.float32).reshape(-1, 6))
        indices.append(np.asarray(idx, np.int32) + b * num_boxes)
    out = np.concatenate(results, axis=0) if results else \
        np.zeros((0, 6), np.float32)
    index = np.concatenate(indices, axis=0).reshape(-1, 1) if indices \
        else np.zeros((0, 1), np.int32)
    return {"Out": out, "Index": index}


@register_op("locality_aware_nms", no_jit=True,
             dynamic_shape=True)
def _locality_aware_nms(ins, attrs):
    """EAST-style NMS: consecutive overlapping boxes are first merged
    score-weighted (reference: locality_aware_nms_op.cc:88
    PolyWeightedMerge + :96 GetMaxScoreIndexWithLocalityAware), then
    standard greedy NMS runs on the merged set. Quad (8-point) boxes use
    their axis-aligned bbox for overlap (PolyIoU descope, documented)."""
    boxes = np.asarray(ins["BBoxes"][0]).copy()
    scores = np.asarray(ins["Scores"][0]).copy()
    score_threshold = attrs.get("score_threshold", 0.0)
    nms_threshold = attrs.get("nms_threshold", 0.3)
    nms_top_k = attrs.get("nms_top_k", 400)
    keep_top_k = attrs.get("keep_top_k", 200)
    background = attrs.get("background_label", -1)
    eta = attrs.get("nms_eta", 1.0)
    normalized = attrs.get("normalized", True)
    box_size = boxes.shape[-1]

    def aabb(v):
        if box_size == 4:
            return v
        xs, ys = v[0::2], v[1::2]
        return np.asarray([xs.min(), ys.min(), xs.max(), ys.max()])

    results = []
    for b in range(boxes.shape[0]):
        dets = []
        for cls in range(scores.shape[1]):
            if cls == background:
                continue
            bb = boxes[b].copy()
            ss = scores[b, cls].copy()
            # locality-aware pass: merge each box into the running
            # anchor while they overlap; anchor score accumulates
            index = -1
            skip = np.ones(len(ss), bool)
            for i in range(len(ss)):
                if index > -1:
                    iou = _np_iou_pair(aabb(bb[i]), aabb(bb[index]),
                                       normalized=normalized)
                    if iou > nms_threshold:
                        # score-weighted merge (PolyWeightedMerge); the
                        # zero-sum guard avoids the reference's 0/0 NaN
                        # when two zero-score (padded) boxes overlap
                        tot = ss[i] + ss[index]
                        if tot > 0:
                            bb[index] = (bb[i] * ss[i]
                                         + bb[index] * ss[index]) / tot
                        ss[index] += ss[i]
                    else:
                        skip[index] = False
                        index = i
                else:
                    index = i
            if index > -1:
                skip[index] = False
            cand = [i for i in range(len(ss))
                    if ss[i] > score_threshold and not skip[i]]
            cand.sort(key=lambda i: -ss[i])
            cand = cand[:nms_top_k] if nms_top_k > -1 else cand
            selected = _greedy_select(
                cand,
                lambda i, k: _np_iou_pair(aabb(bb[i]), aabb(bb[k]),
                                          normalized=normalized),
                nms_threshold, eta)
            for i in selected:
                dets.append([cls, ss[i]] + list(bb[i]))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k] if keep_top_k > -1 else dets
        results.append(np.asarray(dets, np.float32).reshape(
            -1, 2 + box_size))
    out = np.concatenate(results, axis=0) if results else \
        np.zeros((0, 2 + box_size), np.float32)
    return {"Out": out}


@register_op("matrix_nms", no_jit=True, dynamic_shape=True)
def _matrix_nms(ins, attrs):
    """Matrix NMS: soft decay by max-IoU statistics instead of hard
    suppression (reference: matrix_nms_op.cc:95 NMSMatrix + :165
    MatrixNMSKernel). Outputs Out [K, box_dim+2], Index [K,1] into the
    flattened box table, RoisNum [N] per-image counts."""
    boxes = np.asarray(ins["BBoxes"][0])
    scores = np.asarray(ins["Scores"][0])
    score_threshold = attrs.get("score_threshold", 0.0)
    post_threshold = attrs.get("post_threshold", 0.0)
    nms_top_k = attrs.get("nms_top_k", -1)
    keep_top_k = attrs.get("keep_top_k", -1)
    background = attrs.get("background_label", 0)
    use_gaussian = attrs.get("use_gaussian", False)
    sigma = attrs.get("gaussian_sigma", 2.0)
    normalized = attrs.get("normalized", True)
    batch, _, num_boxes = scores.shape
    box_dim = boxes.shape[-1]
    all_out, all_idx, rois_num = [], [], []
    for b in range(batch):
        cand = []  # (decayed_score, cls, box_idx)
        for cls in range(scores.shape[1]):
            if cls == background:
                continue
            s = scores[b, cls]
            perm = np.where(s > score_threshold)[0]
            perm = perm[np.argsort(-s[perm], kind="stable")]
            if nms_top_k > -1:
                perm = perm[:nms_top_k]
            m = len(perm)
            if m == 0:
                continue
            from .detection_extra_ops import _np_iou_xyxy

            sel = boxes[b, perm]
            # strictly-lower-triangular pairwise IoU: row i holds
            # iou(i, j<i); row max = reference iou_max[i] (IoUs >= 0)
            ious = np.tril(_np_iou_xyxy(sel, sel,
                                        normalized=normalized), k=-1)
            iou_max = ious.max(axis=1)
            if s[perm[0]] > post_threshold:
                cand.append((float(s[perm[0]]), cls, int(perm[0])))
            for i in range(1, m):
                if use_gaussian:
                    decay = np.exp((iou_max[:i] ** 2 - ious[i, :i] ** 2)
                                   * sigma)
                else:
                    decay = (1.0 - ious[i, :i]) / (1.0 - iou_max[:i])
                ds = float(decay.min() * s[perm[i]])
                if ds > post_threshold:
                    cand.append((ds, cls, int(perm[i])))
        cand.sort(key=lambda t: -t[0])
        if keep_top_k > -1:
            cand = cand[:keep_top_k]
        rois_num.append(len(cand))
        for ds, cls, idx in cand:
            all_out.append([cls, ds] + list(boxes[b, idx]))
            all_idx.append(b * num_boxes + idx)
    out = np.asarray(all_out, np.float32).reshape(-1, box_dim + 2)
    idx = np.asarray(all_idx, np.int32).reshape(-1, 1)
    return {"Out": out, "Index": idx,
            "RoisNum": np.asarray(rois_num, np.int32)}
