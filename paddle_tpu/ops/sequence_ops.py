"""Sequence operators over padded [batch, time, ...] tensors + length masks.

Reference parity: `paddle/fluid/operators/sequence_ops/` operate on
LoDTensors (ragged rows, `lod_tensor.h:52-104`). XLA wants static shapes, so
the TPU-native representation is dense padding + an explicit SeqLen tensor
(SURVEY.md §7 hard part (a)); ops take an optional "Length" input.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _mask(x, ins, time_axis=1):
    if not ins.get("Length"):
        return None
    length = ins["Length"][0].reshape((-1,))
    t = x.shape[time_axis]
    return (jnp.arange(t)[None, :] < length[:, None])


@register_op("sequence_mask")
def _sequence_mask(ins, attrs):
    x = ins["X"][0].reshape((-1,))
    maxlen = attrs.get("maxlen", -1)
    if maxlen < 0:
        maxlen = int(jnp.max(x)) if not hasattr(x, "aval") else x.shape[0]
    from ..core.types import to_numpy_dtype

    dtype = to_numpy_dtype(attrs.get("out_dtype", "int64"))
    out = (jnp.arange(maxlen)[None, :] < x[:, None]).astype(dtype)
    return {"Y": out}


@register_op("sequence_pool")
def _sequence_pool(ins, attrs):
    # padded [B, T, D] + Length → pooled [B, D]
    x = ins["X"][0]
    ptype = attrs.get("pooltype", "SUM").upper()
    m = _mask(x, ins)
    if m is not None:
        mf = m.astype(x.dtype)[..., None]
        x_masked = x * mf
        denom = jnp.maximum(jnp.sum(mf, axis=1), 1.0)
    else:
        x_masked = x
        denom = jnp.asarray(x.shape[1], x.dtype)
    if ptype == "SUM":
        out = jnp.sum(x_masked, axis=1)
    elif ptype in ("AVERAGE", "MEAN"):
        out = jnp.sum(x_masked, axis=1) / denom
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(
            x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        xm = jnp.where(m[..., None], x, neg) if m is not None else x
        out = jnp.max(xm, axis=1)
    elif ptype == "SQRT":
        out = jnp.sum(x_masked, axis=1) / jnp.sqrt(denom)
    elif ptype == "LAST":
        if m is not None:
            idx = jnp.maximum(
                jnp.sum(m.astype(jnp.int32), axis=1) - 1, 0)
            out = jnp.take_along_axis(
                x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        else:
            out = x[:, -1]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise NotImplementedError(ptype)
    return {"Out": out, "MaxIndex": jnp.zeros(out.shape, jnp.int32)}


@register_op("sequence_softmax")
def _sequence_softmax(ins, attrs):
    x = ins["X"][0]
    m = _mask(x, ins)
    if m is None:
        return {"Out": jnp.exp(x) / jnp.sum(jnp.exp(x), axis=1,
                                            keepdims=True)}
    neg = jnp.finfo(x.dtype).min
    xm = jnp.where(m, x, neg)
    e = jnp.exp(xm - jnp.max(xm, axis=1, keepdims=True))
    e = jnp.where(m, e, 0.0)
    return {"Out": e / jnp.maximum(jnp.sum(e, axis=1, keepdims=True), 1e-9)}


@register_op("sequence_expand")
def _sequence_expand(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    reps = y.shape[1] if y.ndim > 1 else 1
    return {"Out": jnp.repeat(x, reps, axis=0)}


@register_op("sequence_reshape")
def _sequence_reshape(ins, attrs):
    x = ins["X"][0]
    dim = attrs["new_dim"]
    return {"Out": x.reshape((-1, dim))}


@register_op("sequence_concat")
def _sequence_concat(ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=1)}


@register_op("sequence_reverse")
def _sequence_reverse(ins, attrs):
    x = ins["X"][0]
    m = _mask(x, ins)
    if m is None:
        return {"Y": jnp.flip(x, axis=1)}
    length = jnp.sum(m.astype(jnp.int32), axis=1)
    t = x.shape[1]
    idx = jnp.arange(t)[None, :]
    rev_idx = jnp.where(idx < length[:, None], length[:, None] - 1 - idx, idx)
    return {"Y": jnp.take_along_axis(
        x, rev_idx[..., None].astype(jnp.int32), axis=1)
        if x.ndim == 3 else jnp.take_along_axis(x, rev_idx, axis=1)}
