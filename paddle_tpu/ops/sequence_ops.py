"""Sequence operators over padded [batch, time, ...] tensors + length masks.

Reference parity: `paddle/fluid/operators/sequence_ops/` operate on
LoDTensors (ragged rows, `lod_tensor.h:52-104`). XLA wants static shapes, so
the TPU-native representation is dense padding + an explicit SeqLen tensor
(SURVEY.md §7 hard part (a)); ops take an optional "Length" input.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _mask(x, ins, time_axis=1):
    if not ins.get("Length"):
        return None
    length = ins["Length"][0].reshape((-1,))
    t = x.shape[time_axis]
    return (jnp.arange(t)[None, :] < length[:, None])


@register_op("sequence_mask")
def _sequence_mask(ins, attrs):
    x = ins["X"][0].reshape((-1,))
    maxlen = attrs.get("maxlen", -1)
    if maxlen < 0:
        maxlen = int(jnp.max(x)) if not hasattr(x, "aval") else x.shape[0]
    from ..core.types import to_numpy_dtype

    dtype = to_numpy_dtype(attrs.get("out_dtype", "int64"))
    out = (jnp.arange(maxlen)[None, :] < x[:, None]).astype(dtype)
    return {"Y": out}


@register_op("sequence_pool")
def _sequence_pool(ins, attrs):
    # padded [B, T, D] + Length → pooled [B, D]
    x = ins["X"][0]
    ptype = attrs.get("pooltype", "SUM").upper()
    m = _mask(x, ins)
    if m is not None:
        mf = m.astype(x.dtype)[..., None]
        x_masked = x * mf
        denom = jnp.maximum(jnp.sum(mf, axis=1), 1.0)
    else:
        x_masked = x
        denom = jnp.asarray(x.shape[1], x.dtype)
    if ptype == "SUM":
        out = jnp.sum(x_masked, axis=1)
    elif ptype in ("AVERAGE", "MEAN"):
        out = jnp.sum(x_masked, axis=1) / denom
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(
            x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        xm = jnp.where(m[..., None], x, neg) if m is not None else x
        out = jnp.max(xm, axis=1)
    elif ptype == "SQRT":
        out = jnp.sum(x_masked, axis=1) / jnp.sqrt(denom)
    elif ptype == "LAST":
        if m is not None:
            idx = jnp.maximum(
                jnp.sum(m.astype(jnp.int32), axis=1) - 1, 0)
            out = jnp.take_along_axis(
                x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        else:
            out = x[:, -1]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        # reference InEnum (sequence_pool_op.cc:69); layers.sequence_pool
        # already validates at construction — this backstops direct op use
        raise ValueError("sequence_pool: unknown pooltype %r" % (ptype,))
    return {"Out": out, "MaxIndex": jnp.zeros(out.shape, jnp.int32)}


@register_op("sequence_softmax")
def _sequence_softmax(ins, attrs):
    x = ins["X"][0]
    m = _mask(x, ins)
    if m is None:
        return {"Out": jnp.exp(x) / jnp.sum(jnp.exp(x), axis=1,
                                            keepdims=True)}
    neg = jnp.finfo(x.dtype).min
    xm = jnp.where(m, x, neg)
    e = jnp.exp(xm - jnp.max(xm, axis=1, keepdims=True))
    e = jnp.where(m, e, 0.0)
    return {"Out": e / jnp.maximum(jnp.sum(e, axis=1, keepdims=True), 1e-9)}


@register_op("sequence_expand")
def _sequence_expand(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    reps = y.shape[1] if y.ndim > 1 else 1
    return {"Out": jnp.repeat(x, reps, axis=0)}


@register_op("sequence_reshape")
def _sequence_reshape(ins, attrs):
    x = ins["X"][0]
    dim = attrs["new_dim"]
    return {"Out": x.reshape((-1, dim))}


@register_op("sequence_concat")
def _sequence_concat(ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=1)}


@register_op("sequence_reverse")
def _sequence_reverse(ins, attrs):
    x = ins["X"][0]
    m = _mask(x, ins)
    if m is None:
        return {"Y": jnp.flip(x, axis=1)}
    length = jnp.sum(m.astype(jnp.int32), axis=1)
    t = x.shape[1]
    idx = jnp.arange(t)[None, :]
    rev_idx = jnp.where(idx < length[:, None], length[:, None] - 1 - idx, idx)
    return {"Y": jnp.take_along_axis(
        x, rev_idx[..., None].astype(jnp.int32), axis=1)
        if x.ndim == 3 else jnp.take_along_axis(x, rev_idx, axis=1)}


@register_op("sequence_pad")
def _sequence_pad(ins, attrs):
    # padded-representation identity + Length passthrough (reference:
    # sequence_pad_op.cc converts LoD->padded; here data is already
    # padded, so this materializes the pad value + emits lengths)
    x = ins["X"][0]
    m = _mask(x, ins)
    pad_value = ins["PadValue"][0].reshape(()) if ins.get("PadValue") \
        else jnp.asarray(0, x.dtype)
    if m is None:
        length = jnp.full((x.shape[0],), x.shape[1], jnp.int64)
        return {"Out": x, "Length": length}
    mm = m.reshape(m.shape + (1,) * (x.ndim - 2))
    out = jnp.where(mm, x, pad_value.astype(x.dtype))
    return {"Out": out,
            "Length": jnp.sum(m.astype(jnp.int64), axis=1)}


@register_op("sequence_unpad")
def _sequence_unpad(ins, attrs):
    # keeps the padded layout (static shapes); zeroes the tail
    x = ins["X"][0]
    length = ins["Length"][0].reshape((-1,))
    t = x.shape[1]
    m = jnp.arange(t)[None, :] < length[:, None]
    mm = m.reshape(m.shape + (1,) * (x.ndim - 2))
    return {"Out": jnp.where(mm, x, 0)}


@register_op("sequence_slice")
def _sequence_slice(ins, attrs):
    x = ins["X"][0]
    offset = ins["Offset"][0].reshape((-1,))
    length = ins["Length"][0].reshape((-1,))
    t = x.shape[1]
    idx = jnp.arange(t)[None, :]
    sel = (idx >= offset[:, None]) & (idx < (offset + length)[:, None])
    # gather each row's slice to the front, zero-pad the tail
    order = jnp.argsort(~sel, axis=1, stable=True)
    g = jnp.take_along_axis(
        x, order.reshape(order.shape + (1,) * (x.ndim - 2)), axis=1)
    keep = jnp.arange(t)[None, :] < length[:, None]
    return {"Out": jnp.where(
        keep.reshape(keep.shape + (1,) * (x.ndim - 2)), g, 0)}


@register_op("sequence_erase")
def _sequence_erase(ins, attrs):
    # tokens in `tokens` are removed; survivors compact to the front
    x = ins["X"][0]
    tokens = jnp.asarray(attrs.get("tokens", []), x.dtype)
    keep = jnp.logical_not(
        jnp.any(x[..., None] == tokens[None, None, :], axis=-1)) \
        if tokens.size else jnp.ones_like(x, bool)
    order = jnp.argsort(~keep, axis=1, stable=True)
    g = jnp.take_along_axis(x, order, axis=1)
    count = jnp.sum(keep, axis=1)
    mask = jnp.arange(x.shape[1])[None, :] < count[:, None]
    return {"Out": jnp.where(mask, g, 0),
            "Length": count.astype(jnp.int64)}


@register_op("sequence_expand_as")
def _sequence_expand_as(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    reps = y.shape[0] // x.shape[0]
    return {"Out": jnp.repeat(x, reps, axis=0)}


@register_op("sequence_enumerate")
def _sequence_enumerate(ins, attrs):
    x = ins["X"][0]
    win = attrs.get("win_size", 2)
    pad_value = attrs.get("pad_value", 0)
    t = x.shape[-1] if x.ndim > 1 else x.shape[0]
    x2 = x.reshape(-1, t)
    cols = []
    for i in range(win):
        shifted = jnp.concatenate(
            [x2[:, i:], jnp.full((x2.shape[0], i), pad_value, x.dtype)],
            axis=1)
        cols.append(shifted)
    return {"Out": jnp.stack(cols, axis=-1)}


@register_op("sequence_conv")
def _sequence_conv(ins, attrs):
    # reference: sequence_conv_op.cc — context-window conv over time
    x, filt = ins["X"][0], ins["Filter"][0]
    ctx_len = attrs.get("contextLength", 3)
    ctx_start = attrs.get("contextStart", -(ctx_len // 2))
    b, t, d = x.shape
    cols = []
    for i in range(ctx_len):
        off = ctx_start + i
        if off < 0:
            shifted = jnp.concatenate(
                [jnp.zeros((b, -off, d), x.dtype), x[:, :t + off]], axis=1)
        elif off > 0:
            shifted = jnp.concatenate(
                [x[:, off:], jnp.zeros((b, off, d), x.dtype)], axis=1)
        else:
            shifted = x
        cols.append(shifted)
    ctx = jnp.concatenate(cols, axis=-1)  # [b, t, ctx_len*d]
    return {"Out": ctx @ filt}


@register_op("sequence_scatter")
def _sequence_scatter(ins, attrs):
    x, ids, updates = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    return {"Out": x.at[ids.reshape(-1).astype(jnp.int32)].add(
        updates.reshape((-1,) + x.shape[1:]))}


@register_op("lod_reset")
def _lod_reset(ins, attrs):
    # LoD is host metadata in this framework; data passes through
    return {"Out": ins["X"][0]}


@register_op("sequence_number_count")
def _sequence_number_count(ins, attrs):
    x = ins["X"][0]
    return {"Out": jnp.sum(jnp.ones_like(x, jnp.int64))}


@register_op("ctc_align", no_jit=True)
def _ctc_align(ins, attrs):
    """CTC decode alignment: merge repeats then drop blanks (reference:
    operators/ctc_align_op.cc). Host-side (ragged output compacted to
    padded-with-zeros rows)."""
    import numpy as np

    x = np.asarray(ins["Input"][0])
    blank = attrs.get("blank", 0)
    merge = attrs.get("merge_repeated", True)
    padding_value = attrs.get("padding_value", 0)
    out = np.full_like(x, padding_value)
    in_len = np.asarray(ins["InputLength"][0]).reshape(-1) \
        if ins.get("InputLength") else np.full((x.shape[0],), x.shape[1])
    lengths = np.zeros((x.shape[0],), np.int64)
    for b in range(x.shape[0]):
        prev = None
        k = 0
        for t in x[b, :int(in_len[b])]:
            t = int(t)
            if merge and prev == t:
                continue
            prev = t
            if t != blank:
                out[b, k] = t
                k += 1
        lengths[b] = k
    return {"Output": out, "OutputLength": lengths.reshape(-1, 1)}
