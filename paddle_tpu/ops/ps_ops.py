"""Parameter-server and comm-bootstrap operator registrations.

Reference parity: `paddle/fluid/operators/distributed_ops/` —
`listen_and_serv` (`listen_and_serv_op.cc:336`),
`distributed_lookup_table_op.cc`, `recv_save_op.cc`, and the pslib-style
`pull_sparse`/`push_sparse`/`pull_box_sparse` family (`pull_sparse_op.cc`,
`push_box_sparse_op.cc`); comm bootstrap ops from
`operators/collective/c_gen_nccl_id_op.cc`, `c_comm_init_op.cc:35-56`,
`c_comm_init_all_op.cc`, `distributed_ops/gen_nccl_id_op.cc`, and
`split_byref_op.cc`.

TPU-native design: the PS tier is the host-RPC machinery in
`paddle_tpu/distributed/ps.py` (trainer `PSCommunicator`, server
`ParameterServer`); these op registrations make programs that CONTAIN the
ops executable — the executor's PS integration normally drives the
communicator around the jitted step, so the ops delegate to the same
machinery. The NCCL bootstrap ops are no-ops by design: mesh/axis setup
replaces communicator construction (SURVEY.md §3C TPU mapping — ring_id
maps to a named mesh axis at trace time, `parallel/env.py`), so the ops
only validate and record the ring registration.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .registry import register_op
from .framework_ops import _save_arrays

# Process-global PS communicator installed by the executor/fleet runtime
# when a transpiled trainer program runs (distributed/ps.py).
_COMMUNICATOR = None


def set_ps_communicator(comm):
    global _COMMUNICATOR
    _COMMUNICATOR = comm


def get_ps_communicator():
    return _COMMUNICATOR


def _need_comm(op):
    if _COMMUNICATOR is None:
        raise RuntimeError(
            "op %r needs an active parameter-server communicator; run the "
            "program through fleet PS mode (DistributeTranspiler) so the "
            "executor installs one (paddle_tpu/distributed/ps.py)" % op)
    return _COMMUNICATOR


@register_op("listen_and_serv", no_jit=True)
def _listen_and_serv(ins, attrs):
    """Blocking pserver loop. The transpiler-generated pserver program is
    normally launched via distributed.ps.listen_and_serv directly; the op
    form serves programs that embed it (reference pserver main program)."""
    from ..distributed.ps import listen_and_serv as serve
    serve(attrs["pserver_program"],
          attrs.get("pserver_startup"),
          endpoint=attrs.get("endpoint", "127.0.0.1:0"),
          trainers=int(attrs.get("Fanin", attrs.get("trainers", 1))),
          mode=attrs.get("mode", "sync"))
    return {}


@register_op("distributed_lookup_table", no_jit=True)
def _distributed_lookup_table(ins, attrs):
    """Pull embedding rows for Ids from the remote sharded table
    (reference: distributed_lookup_table_op.cc + parameter_prefetch.cc).
    Falls back to a local W input when no communicator is active (single
    -process execution of a PS program)."""
    ids = np.asarray(ins["Ids"][0]).astype(np.int64)
    if ids.ndim > 1 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    table_name = attrs.get("table_name", "")
    comm = _COMMUNICATOR
    if comm is not None and table_name in comm.cfg.get("sparse_tables", {}):
        meta = comm.cfg["sparse_tables"][table_name]
        flat = ids.reshape(-1)
        uniq, inverse = np.unique(flat, return_inverse=True)
        (rows,) = comm._client(meta["endpoint"]).call(
            "lookup_rows", table_name, uniq.astype(np.int64))
        out = np.asarray(rows)[inverse].reshape(ids.shape + (-1,))
    elif ins.get("W"):
        out = np.asarray(ins["W"][0])[ids]
    else:
        raise RuntimeError(
            "distributed_lookup_table: table %r is not configured on the "
            "active PS communicator and no local W input was provided"
            % table_name)
    return {"Outputs": jnp.asarray(out.astype(np.float32))}


@register_op("recv_save", no_jit=True)
def _recv_save(ins, attrs):
    """Fetch a remote param shard and save it to disk (recv_save_op.cc,
    the pserver-side checkpoint path)."""
    comm = _need_comm("recv_save")
    pname = attrs["param_name"]
    ep = comm.cfg["param_endpoint"].get(pname)
    if ep is None:
        raise KeyError("recv_save: param %r has no pserver" % pname)
    (val,) = comm._client(ep).call("pull_dense", pname)
    _save_arrays(attrs["file_path"], {pname: np.asarray(val)})
    return {}


def _pull_sparse(ins, attrs):
    comm = _need_comm("pull_sparse")
    table = attrs.get("table_name") or attrs.get("TableName", "")
    meta = comm.cfg["sparse_tables"][table]
    outs = []
    for ids_arr in ins["Ids"]:
        ids = np.asarray(ids_arr).astype(np.int64)
        if ids.ndim > 1 and ids.shape[-1] == 1:
            ids = ids[..., 0]
        flat = ids.reshape(-1)
        uniq, inverse = np.unique(flat, return_inverse=True)
        (rows,) = comm._client(meta["endpoint"]).call(
            "lookup_rows", table, uniq)
        outs.append(jnp.asarray(
            np.asarray(rows)[inverse].reshape(ids.shape + (-1,))
            .astype(np.float32)))
    return {"Out": outs}


register_op("pull_sparse", no_jit=True)(_pull_sparse)
register_op("pull_sparse_v2", no_jit=True)(_pull_sparse)
register_op("pull_box_sparse", no_jit=True)(_pull_sparse)


def _push_sparse(ins, attrs):
    comm = _need_comm("push_sparse")
    table = attrs.get("table_name") or attrs.get("TableName", "")
    meta = comm.cfg["sparse_tables"][table]
    for ids_arr, grad_arr in zip(ins["Ids"], ins.get("Grads", ins.get(
            "Out@GRAD", []))):
        ids = np.asarray(ids_arr).astype(np.int64).reshape(-1)
        grads = np.asarray(grad_arr, dtype=np.float32)
        grads = grads.reshape(ids.shape[0], -1)
        uniq, inverse = np.unique(ids, return_inverse=True)
        summed = np.zeros((len(uniq), grads.shape[1]), np.float32)
        np.add.at(summed, inverse, grads)
        comm._client(meta["endpoint"]).call(
            "sparse_push", table, uniq, summed, comm.tid)
    return {}


register_op("push_sparse", no_jit=True)(_push_sparse)
register_op("push_sparse_v2", no_jit=True)(_push_sparse)
register_op("push_box_sparse", no_jit=True)(_push_sparse)
register_op("push_box_extended_sparse", no_jit=True)(_push_sparse)


@register_op("split_byref", no_jit=True)
def _split_byref(ins, attrs):
    """Row-section split of a dense tensor (split_byref_op.cc — the PS
    send path splits a param into per-server sections; 'byref' aliasing
    is meaningless under XLA, and the dense/sparse section logic lives
    in split_selected_rows)."""
    from .registry import get_op as _get
    return _get("split_selected_rows").compute(ins, attrs)


# -- comm bootstrap (no-ops under the mesh model) ---------------------------

def _comm_bootstrap(ins, attrs):
    """c_gen_nccl_id / gen_nccl_id / c_comm_init / c_comm_init_all:
    under XLA the communicator is the compiled collective over a named
    mesh axis — bootstrap is `jax.distributed.initialize` + Mesh
    construction at trace time. The ops validate the ring registration
    so transpiled startup programs run unchanged."""
    ring_id = int(attrs.get("ring_id", 0))
    from ..parallel import env
    if env.axis_name_for_ring(ring_id) is None:
        # default registration: ring spans the data-parallel world
        env.register_ring(ring_id, "dp", env.trainer_num())
    return {}


register_op("c_gen_nccl_id", no_jit=True)(_comm_bootstrap)
register_op("gen_nccl_id", no_jit=True)(_comm_bootstrap)
register_op("c_comm_init", no_jit=True)(_comm_bootstrap)
register_op("c_comm_init_all", no_jit=True)(_comm_bootstrap)
