"""Loss operators.

Reference parity: `paddle/fluid/operators/` loss kernels — hinge_loss_op,
rank_loss_op, margin_rank_loss_op, bpr_loss_op, log_loss_op,
sigmoid_focal_loss_op, center_loss_op, teacher_student_sigmoid_loss_op,
cos_sim_op, npair (layer-level), dice (layer-level). Pure jnp; XLA fuses
these into surrounding computations.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("hinge_loss")
def _hinge_loss(ins, attrs):
    # reference: hinge_loss_op.cc — labels in {0,1}
    logits, labels = ins["Logits"][0], ins["Labels"][0]
    y = 2.0 * labels.astype(logits.dtype) - 1.0
    return {"Loss": jnp.maximum(0.0, 1.0 - y * logits)}


@register_op("modified_huber_loss")
def _modified_huber_loss(ins, attrs):
    """Reference: modified_huber_loss_op.h — labels in {0,1} scaled to
    {-1,+1}; piecewise: -4v for v<-1, (1-v)^2 for v<1, else 0. The
    IntermediateVal output (v = x*(2y-1)) feeds the reference's grad
    kernel; jax.vjp differentiates through the jnp.where directly."""
    x, y = ins["X"][0], ins["Y"][0]
    v = x * (2.0 * y.astype(x.dtype) - 1.0)
    loss = jnp.where(v < -1.0, -4.0 * v,
                     jnp.where(v < 1.0, jnp.square(1.0 - v), 0.0))
    return {"IntermediateVal": v, "Out": loss}


@register_op("squared_l2_distance")
def _squared_l2_distance(ins, attrs):
    """Reference: squared_l2_distance_op.h — rows flattened to
    [N, cols]; Y broadcasts when it has one row; Out[i] = sum((x-y)^2)
    per row, sub_result cached for the grad kernel."""
    x, y = ins["X"][0], ins["Y"][0]
    xr = x.reshape(x.shape[0], -1)
    yr = y.reshape(y.shape[0], -1)
    sub = xr - yr  # [1, cols] Y broadcasts over rows
    return {"sub_result": sub,
            "Out": jnp.sum(jnp.square(sub), axis=1, keepdims=True)}


@register_op("rank_loss")
def _rank_loss(ins, attrs):
    # reference: rank_loss_op.cc — RankNet pairwise loss
    label = ins["Label"][0]
    left, right = ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": jnp.logaddexp(0.0, d) - label.astype(d.dtype) * d}


@register_op("margin_rank_loss")
def _margin_rank_loss(ins, attrs):
    label = ins["Label"][0]
    x1, x2 = ins["X1"][0], ins["X2"][0]
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label.astype(x1.dtype) * (x1 - x2) + margin)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("bpr_loss")
def _bpr_loss(ins, attrs):
    # reference: bpr_loss_op.cc — Bayesian Personalized Ranking
    x, label = ins["X"][0], ins["Label"][0]
    n, c = x.shape
    pos = jnp.take_along_axis(x, label.reshape(n, 1).astype(jnp.int32), 1)
    d = x - pos  # [n, c]
    lse = jnp.log1p(jnp.exp(d))
    mask = jnp.ones((n, c), x.dtype).at[
        jnp.arange(n), label.reshape(-1).astype(jnp.int32)].set(0.0)
    loss = jnp.sum(lse * mask, axis=1, keepdims=True) / jnp.maximum(
        c - 1, 1)
    return {"Y": loss}


@register_op("log_loss")
def _log_loss(ins, attrs):
    p, label = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    lf = label.astype(p.dtype)
    return {"Loss": -lf * jnp.log(p + eps)
            - (1.0 - lf) * jnp.log(1.0 - p + eps)}


@register_op("sigmoid_focal_loss")
def _sigmoid_focal_loss(ins, attrs):
    # reference: sigmoid_focal_loss_op.cu — per-class focal loss with
    # integer labels (0 = background) and fg normalizer
    x, label = ins["X"][0], ins["Label"][0]
    fg = ins["FgNum"][0].reshape(()).astype(x.dtype) if ins.get("FgNum") \
        else jnp.asarray(1.0, x.dtype)
    gamma = attrs.get("gamma", 2.0)
    alpha = attrs.get("alpha", 0.25)
    n, c = x.shape
    lbl = label.reshape(-1).astype(jnp.int32)
    target = (lbl[:, None] == (jnp.arange(c)[None, :] + 1)).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    ce = jnp.logaddexp(0.0, x) - x * target
    p_t = p * target + (1 - p) * (1 - target)
    alpha_t = alpha * target + (1 - alpha) * (1 - target)
    loss = alpha_t * jnp.power(1 - p_t, gamma) * ce
    return {"Out": loss / jnp.maximum(fg, 1.0)}


@register_op("teacher_student_sigmoid_loss")
def _ts_sigmoid_loss(ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    soft_max_up = attrs.get("soft_max_up_bound", 15.0)
    soft_max_lo = attrs.get("soft_max_lower_bound", -15.0)
    lf = label.astype(x.dtype)
    xc = jnp.clip(x, soft_max_lo, soft_max_up)
    # teacher (soft) part when label in (0,1); student hard part
    loss = jnp.logaddexp(0.0, xc) - xc * lf
    return {"Y": loss}


@register_op("cos_sim")
def _cos_sim(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), -1, keepdims=True))
    out = jnp.sum(x * y, -1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    return {"Out": out, "XNorm": xn, "YNorm": yn}


@register_op("center_loss")
def _center_loss(ins, attrs):
    # reference: center_loss_op.cc — pulls features toward class centers
    x, label = ins["X"][0], ins["Label"][0]
    centers = ins["Centers"][0]
    lr = ins["CenterUpdateRate"][0].reshape(()) if \
        ins.get("CenterUpdateRate") else jnp.asarray(0.5, x.dtype)
    alpha = attrs.get("alpha", lr)
    lbl = label.reshape(-1).astype(jnp.int32)
    diff = x - centers[lbl]
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=-1, keepdims=True)
    if attrs.get("need_update", True):
        counts = jnp.zeros((centers.shape[0],), x.dtype).at[lbl].add(1.0)
        upd = jnp.zeros_like(centers).at[lbl].add(diff)
        centers_out = centers + alpha * upd / (counts[:, None] + 1.0)
    else:
        centers_out = centers
    return {"Loss": loss, "SampleCenterDiff": diff,
            "CentersOut": centers_out}


@register_op("npair_loss")
def _npair_loss(ins, attrs):
    anchor, positive = ins["Anchor"][0], ins["Positive"][0]
    labels = ins["Labels"][0].reshape(-1)
    l2_reg = attrs.get("l2_reg", 0.002)
    sim = anchor @ positive.T
    tgt = (labels[:, None] == labels[None, :]).astype(sim.dtype)
    tgt = tgt / jnp.sum(tgt, -1, keepdims=True)
    logp = jax.nn.log_softmax(sim, -1)
    ce = -jnp.sum(tgt * logp, -1)
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), -1))
                    + jnp.mean(jnp.sum(jnp.square(positive), -1))) / 2
    return {"Out": jnp.mean(ce) + reg}


@register_op("dice_loss")
def _dice_loss(ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    eps = attrs.get("epsilon", 1e-5)
    lf = label.astype(x.dtype)
    reduce_dims = tuple(range(1, x.ndim))
    inter = 2.0 * jnp.sum(x * lf, reduce_dims)
    union = jnp.sum(x, reduce_dims) + jnp.sum(lf, reduce_dims)
    return {"Out": 1.0 - (inter + eps) / (union + eps)}


@register_op("mse_loss")
def _mse_loss(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.square(x - y)}


@register_op("l1_loss")
def _l1_loss(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.abs(x - y)}


@register_op("cross_entropy2")
def _cross_entropy2(ins, attrs):
    # reference: cross_entropy_op.cc (soft_label=False index variant 2)
    x, label = ins["X"][0], ins["Label"][0]
    lbl = label.reshape(label.shape[:-1]).astype(jnp.int32)
    p = jnp.take_along_axis(x, lbl[..., None], -1)
    xent = -jnp.log(jnp.maximum(p, 1e-20))
    return {"Y": xent, "XShape": jnp.zeros_like(x),
            "MatchX": p}


@register_op("bce_loss")
def _bce_loss(ins, attrs):
    # reference: bce_loss_op.cc — inputs are probabilities, not logits
    x, label = ins["X"][0], ins["Label"][0]
    x = jnp.clip(x, 1e-12, 1.0 - 1e-12)
    out = -(label * jnp.log(x) + (1.0 - label) * jnp.log(1.0 - x))
    return {"Out": out}


@register_op("nll_loss")
def _nll_loss(ins, attrs):
    # reference: nll_loss_op.cc — X is log-probabilities [N, C] or
    # [N, C, d1, d2]; Label int64; optional per-class Weight.
    x, label = ins["X"][0], ins["Label"][0]
    reduction = attrs.get("reduction", "mean")
    ignore_index = int(attrs.get("ignore_index", -100))
    c_axis = 1
    lbl = label.astype(jnp.int32)
    safe = jnp.where(lbl == ignore_index, 0, lbl)
    picked = jnp.take_along_axis(x, safe[:, None] if x.ndim == 2
                                 else safe[:, None, ...], c_axis)
    picked = jnp.squeeze(picked, c_axis)
    if ins.get("Weight"):
        w = ins["Weight"][0][safe]
    else:
        w = jnp.ones_like(picked)
    w = jnp.where(lbl == ignore_index, 0.0, w)
    loss = -picked * w
    if reduction == "none":
        return {"Out": loss, "Total_weight": jnp.sum(w)}
    total_w = jnp.sum(w)
    if reduction == "sum":
        return {"Out": jnp.sum(loss), "Total_weight": total_w}
    return {"Out": jnp.sum(loss) / jnp.maximum(total_w, 1e-12),
            "Total_weight": total_w}
