"""Linear-algebra and extended math operators.

Reference parity: `paddle/fluid/operators/` — bmm_op, dot_op, kron_op,
cross_op, trace_op, cholesky_op, inverse_op, matrix_power_op, addmm_op,
addcmul (contrib), logsumexp (reduce variant), bilinear_tensor_product_op,
histogram/bincount (2.0), cumprod. MXU note: bmm/addmm/bilinear map to
dot_general; factorizations lower to XLA's native cholesky/triangular
solves.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("bmm")
def _bmm(ins, attrs):
    return {"Out": jnp.matmul(ins["X"][0], ins["Y"][0])}


@register_op("dot")
def _dot(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.sum(x * y, axis=-1, keepdims=x.ndim == 1)}


@register_op("kron")
def _kron(ins, attrs):
    return {"Out": jnp.kron(ins["X"][0], ins["Y"][0])}


@register_op("cross")
def _cross(ins, attrs):
    axis = attrs.get("dim", attrs.get("axis", 9))
    x, y = ins["X"][0], ins["Y"][0]
    if axis == 9:  # reference sentinel: first dim of size 3
        axis = next(i for i, d in enumerate(x.shape) if d == 3)
    return {"Out": jnp.cross(x, y, axis=axis)}


@register_op("trace")
def _trace(ins, attrs):
    x = ins["Input"][0] if ins.get("Input") else ins["X"][0]
    return {"Out": jnp.trace(x, offset=attrs.get("offset", 0),
                             axis1=attrs.get("axis1", 0),
                             axis2=attrs.get("axis2", 1))}


@register_op("cholesky")
def _cholesky(ins, attrs):
    x = ins["X"][0]
    upper = attrs.get("upper", False)
    l = jnp.linalg.cholesky(x)
    return {"Out": jnp.swapaxes(l, -1, -2) if upper else l}


@register_op("inverse")
def _inverse(ins, attrs):
    x = ins["Input"][0] if ins.get("Input") else ins["X"][0]
    return {"Output": jnp.linalg.inv(x)}


@register_op("matrix_power")
def _matrix_power(ins, attrs):
    x = ins["X"][0]
    return {"Out": jnp.linalg.matrix_power(x, attrs.get("n", 1))}


@register_op("addmm")
def _addmm(ins, attrs):
    inp, x, y = ins["Input"][0], ins["X"][0], ins["Y"][0]
    alpha = attrs.get("Alpha", attrs.get("alpha", 1.0))
    beta = attrs.get("Beta", attrs.get("beta", 1.0))
    return {"Out": beta * inp + alpha * (x @ y)}


@register_op("addcmul")
def _addcmul(ins, attrs):
    inp = ins["Input"][0]
    t1, t2 = ins["Tensor1"][0], ins["Tensor2"][0]
    return {"Out": inp + attrs.get("value", 1.0) * t1 * t2}


@register_op("logsumexp")
def _logsumexp(ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", attrs.get("dim", None))
    keepdim = attrs.get("keepdim", False)
    if axis in (None, [], ()):
        axis = tuple(range(x.ndim))
    elif isinstance(axis, int):
        axis = (axis,)
    else:
        axis = tuple(axis)
    return {"Out": jax.scipy.special.logsumexp(x, axis=axis,
                                               keepdims=keepdim)}


@register_op("bilinear_tensor_product")
def _bilinear_tensor_product(ins, attrs):
    # reference: bilinear_tensor_product_op.cc — out[b,k] = x W_k y^T + b
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": out}


@register_op("histogram")
def _histogram(ins, attrs):
    x = ins["X"][0].reshape(-1)
    bins = attrs.get("bins", 100)
    lo, hi = attrs.get("min", 0), attrs.get("max", 0)
    if lo == 0 and hi == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    h, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return {"Out": h.astype(jnp.int64)}


@register_op("bincount")
def _bincount(ins, attrs):
    x = ins["X"][0].reshape(-1).astype(jnp.int32)
    minlength = attrs.get("minlength", 0)
    length = max(minlength, 1)
    # static-shape bincount: length must come from attrs for jit; the
    # eager path can size dynamically
    try:
        n = int(jnp.max(x)) + 1
        length = max(length, n)
    except Exception:  # traced: rely on minlength
        pass
    if ins.get("Weights"):
        w = ins["Weights"][0].reshape(-1)
        out = jnp.zeros((length,), w.dtype).at[x].add(w)
    else:
        out = jnp.zeros((length,), jnp.int64).at[x].add(1)
    return {"Out": out}


@register_op("cumprod")
def _cumprod(ins, attrs):
    x = ins["X"][0]
    dim = attrs.get("dim", attrs.get("axis", -1))
    return {"Out": jnp.cumprod(x, axis=dim)}


@register_op("mv")
def _mv(ins, attrs):
    return {"Out": ins["X"][0] @ ins["Vec"][0]}


@register_op("outer")
def _outer(ins, attrs):
    return {"Out": jnp.outer(ins["X"][0], ins["Y"][0])}


@register_op("matmul_transpose")  # helper used by some fused paths
def _matmul_t(ins, attrs):
    return {"Out": ins["X"][0] @ jnp.swapaxes(ins["Y"][0], -1, -2)}


@register_op("triangular_solve")
def _triangular_solve(ins, attrs):
    import jax.scipy.linalg as jsl

    x, y = ins["X"][0], ins["Y"][0]
    upper = attrs.get("upper", True)
    transpose = attrs.get("transpose", False)
    unitriangular = attrs.get("unitriangular", False)
    return {"Out": jsl.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)}


@register_op("cholesky_solve")
def _cholesky_solve(ins, attrs):
    import jax.scipy.linalg as jsl

    x, y = ins["X"][0], ins["Y"][0]
    upper = attrs.get("upper", False)
    return {"Out": jsl.cho_solve((y, not upper), x)}


@register_op("determinant")
def _determinant(ins, attrs):
    x = ins["Input"][0] if ins.get("Input") else ins["X"][0]
    return {"Out": jnp.linalg.det(x)}


@register_op("slogdeterminant")
def _slogdet(ins, attrs):
    x = ins["Input"][0] if ins.get("Input") else ins["X"][0]
    sign, logdet = jnp.linalg.slogdet(x)
    return {"Out": jnp.stack([sign, logdet])}
