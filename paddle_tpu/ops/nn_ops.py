"""Neural-network operators: conv/pool/norm/activation/softmax/dropout/embedding.

Reference parity: `paddle/fluid/operators/conv_op.cc`+`conv_cudnn_op.cu`,
`pool_op.cc`, `batch_norm_op.{cc,cu}`, `layer_norm_op.{cc,cu}`,
`softmax_with_cross_entropy_op.cu`, `activation_op.*`, `dropout_op.*`,
`lookup_table(_v2)_op.*`. TPU-native notes: convs/matmuls map to the MXU via
`lax.conv_general_dilated`/`jnp.matmul`; the cudnn algorithm-search attrs
(exhaustive_search, workspace limits) are obsolete — XLA autotunes; dropout
uses counter-based stateless PRNG (threefry) instead of the reference's
curand states.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


# ---------------------------------------------------------------------------
# Activations (reference: operators/activation_op.cc lists ~30)
# ---------------------------------------------------------------------------

def _register_act(name, fn):
    @register_op(name)
    def _act(ins, attrs, _fn=fn):
        return {"Out": _fn(ins["X"][0], attrs)}


_register_act("relu", lambda x, a: jax.nn.relu(x))
_register_act("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_register_act("tanh", lambda x, a: jnp.tanh(x))
_register_act("sqrt", lambda x, a: jnp.sqrt(x))
_register_act("rsqrt", lambda x, a: lax.rsqrt(x))
_register_act("square", lambda x, a: jnp.square(x))
_register_act("exp", lambda x, a: jnp.exp(x))
_register_act("log", lambda x, a: jnp.log(x))
_register_act("log2", lambda x, a: jnp.log2(x))
_register_act("log10", lambda x, a: jnp.log10(x))
_register_act("log1p", lambda x, a: jnp.log1p(x))
_register_act("abs", lambda x, a: jnp.abs(x))
_register_act("ceil", lambda x, a: jnp.ceil(x))
_register_act("floor", lambda x, a: jnp.floor(x))
_register_act("round", lambda x, a: jnp.round(x))
_register_act("reciprocal", lambda x, a: 1.0 / x)
_register_act("sin", lambda x, a: jnp.sin(x))
_register_act("cos", lambda x, a: jnp.cos(x))
_register_act("asin", lambda x, a: jnp.arcsin(x))
_register_act("acos", lambda x, a: jnp.arccos(x))
_register_act("atan", lambda x, a: jnp.arctan(x))
_register_act("sinh", lambda x, a: jnp.sinh(x))
_register_act("cosh", lambda x, a: jnp.cosh(x))
_register_act("erf", lambda x, a: jax.scipy.special.erf(x))
_register_act("softplus", lambda x, a: jax.nn.softplus(x))
_register_act("softsign", lambda x, a: x / (1 + jnp.abs(x)))
_register_act("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
_register_act("leaky_relu", lambda x, a: jnp.where(
    x >= 0, x, x * a.get("alpha", 0.02)))
_register_act("elu", lambda x, a: jnp.where(
    x >= 0, x, a.get("alpha", 1.0) * (jnp.exp(x) - 1)))
_register_act("gelu", lambda x, a: jax.nn.gelu(
    x, approximate=a.get("approximate", False)))
_register_act("swish", lambda x, a: x * jax.nn.sigmoid(
    a.get("beta", 1.0) * x))
_register_act("silu", lambda x, a: jax.nn.silu(x))
_register_act("mish", lambda x, a: x * jnp.tanh(jax.nn.softplus(x)))
_register_act("hard_sigmoid", lambda x, a: jnp.clip(
    a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0))
_register_act("hard_swish", lambda x, a: x * jnp.clip(
    x + a.get("offset", 3.0), 0.0, a.get("threshold", 6.0))
    / a.get("scale", 6.0))
_register_act("thresholded_relu", lambda x, a: jnp.where(
    x > a.get("threshold", 1.0), x, jnp.zeros_like(x)))
_register_act("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_register_act("sign", lambda x, a: jnp.sign(x))
_register_act("stanh", lambda x, a: a.get("scale_b", 1.7159)
              * jnp.tanh(a.get("scale_a", 0.67) * x))


@register_op("prelu")
def _prelu(ins, attrs):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": jnp.where(x >= 0, x, x * alpha)}


@register_op("softmax")
def _softmax(ins, attrs):
    return {"Out": jax.nn.softmax(ins["X"][0], axis=attrs.get("axis", -1))}


@register_op("log_softmax")
def _log_softmax(ins, attrs):
    return {"Out": jax.nn.log_softmax(ins["X"][0], axis=attrs.get("axis", -1))}


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------

@register_op("cross_entropy")
def _cross_entropy(ins, attrs):
    # reference: operators/cross_entropy_op.cc — input X is probabilities.
    x, label = ins["X"][0], ins["Label"][0]
    eps = 1e-9
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        idx = label.reshape(label.shape[:-1]).astype(jnp.int32)
        picked = jnp.take_along_axis(x, idx[..., None], axis=-1)
        loss = -jnp.log(picked + eps)
        ignore = attrs.get("ignore_index", -100)
        loss = jnp.where(idx[..., None] == ignore, jnp.zeros_like(loss), loss)
    return {"Y": loss}


@register_op("softmax_with_cross_entropy")
def _softmax_ce(ins, attrs):
    logits, label = ins["Logits"][0], ins["Label"][0]
    axis = attrs.get("axis", -1)
    softmax = jax.nn.softmax(logits, axis=axis)
    logp = jax.nn.log_softmax(logits, axis=axis)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        idx = label.astype(jnp.int32)
        squeeze = (idx.ndim == logits.ndim and idx.shape[axis] == 1)
        if squeeze:
            idx = jnp.squeeze(idx, axis=axis)
        loss = -jnp.take_along_axis(logp, idx[..., None], axis=axis)
        ignore = attrs.get("ignore_index", -100)
        loss = jnp.where(idx[..., None] == ignore, jnp.zeros_like(loss), loss)
    return {"Softmax": softmax, "Loss": loss}


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = attrs.get("ignore_index", -100)
    loss = jnp.where(label == ignore, jnp.zeros_like(loss), loss)
    if attrs.get("normalize", False):
        n = jnp.sum((label != ignore).astype(loss.dtype))
        loss = loss / jnp.maximum(n, 1.0)
    return {"Out": loss}


@register_op("square_error_cost")
def _square_error(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": jnp.square(x - y)}


@register_op("huber_loss")
def _huber(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    delta = attrs.get("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    loss = jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))
    return {"Out": loss, "Residual": r}


@register_op("smooth_l1_loss")
def _smooth_l1(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    a = jnp.abs(diff)
    elem = jnp.where(a < 1.0 / s2, 0.5 * s2 * diff * diff, a - 0.5 / s2)
    return {"Out": jnp.sum(elem, axis=-1, keepdims=True), "Diff": diff}


@register_op("kldiv_loss")
def _kldiv(ins, attrs):
    x, target = ins["X"][0], ins["Target"][0]
    loss = jnp.where(target > 0, target * (jnp.log(target) - x),
                     jnp.zeros_like(target))
    red = attrs.get("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss).reshape((1,))
    elif red == "sum":
        loss = jnp.sum(loss).reshape((1,))
    elif red == "batchmean":
        loss = (jnp.sum(loss) / x.shape[0]).reshape((1,))
    return {"Loss": loss}


# ---------------------------------------------------------------------------
# Convolution / pooling
# ---------------------------------------------------------------------------

def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return list(v)
    return [v] * n


@register_op("conv2d")
def _conv2d(ins, attrs):
    # reference: operators/conv_op.cc (NCHW input, OIHW filter)
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1)
    if len(paddings) == 2:
        pads = [(paddings[0], paddings[0]), (paddings[1], paddings[1])]
    else:
        pads = [(paddings[0], paddings[1]), (paddings[2], paddings[3])]
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads,
        rhs_dilation=dilations, feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=None)
    return {"Output": out}


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ins, attrs):
    return _conv2d(ins, attrs)


@register_op("conv2d_transpose")
def _conv2d_transpose(ins, attrs):
    """Transposed conv as the gradient-of-conv: lhs-dilated conv with
    the spatially flipped kernel (weight layout (in, out/groups, kh, kw)
    matching operators/conv_transpose_op.cc). Verified against
    torch.conv_transpose2d for stride/padding/dilation combinations."""
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1))
    kh, kw = w.shape[2], w.shape[3]
    if groups != 1:
        # (in, out/g, kh, kw) -> (in/g, out, kh, kw) with the group index
        # folded MAJOR into the O dim, matching XLA's feature_group_count
        # contract (lhs group i consumes kernel O slice i)
        cin, og = w.shape[0], w.shape[1]
        w = (w.reshape(groups, cin // groups, og, kh, kw)
             .transpose(1, 0, 2, 3, 4)
             .reshape(cin // groups, groups * og, kh, kw))
    pads = [(dilations[0] * (kh - 1) - paddings[0],) * 2,
            (dilations[1] * (kw - 1) - paddings[1],) * 2]
    out = lax.conv_general_dilated(
        x, jnp.flip(w, (2, 3)), window_strides=(1, 1), padding=pads,
        lhs_dilation=tuple(strides), rhs_dilation=tuple(dilations),
        dimension_numbers=("NCHW", "IOHW", "NCHW"),
        feature_group_count=groups)
    return {"Output": out}


@register_op("pool2d")
def _pool2d(ins, attrs):
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", ksize))
    paddings = _pair(attrs.get("paddings", [0, 0]))
    ceil_mode = attrs.get("ceil_mode", False)
    exclusive = attrs.get("exclusive", True)
    adaptive = attrs.get("adaptive", False)
    if attrs.get("global_pooling", False) or (
            adaptive and tuple(ksize) == (1, 1)):
        if ptype == "max":
            return {"Out": jnp.max(x, axis=(2, 3), keepdims=True)}
        return {"Out": jnp.mean(x, axis=(2, 3), keepdims=True)}
    if adaptive:
        # adaptive pooling to output size ksize: split into equal windows
        n, c, h, wdt = x.shape
        oh, ow = ksize
        assert h % oh == 0 and wdt % ow == 0, "adaptive pool needs divisible"
        xr = x.reshape(n, c, oh, h // oh, ow, wdt // ow)
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": red(xr, axis=(3, 5))}

    h, w_ = x.shape[2], x.shape[3]
    pads = []
    for dim, k, s, p in ((h, ksize[0], strides[0], paddings[0]),
                         (w_, ksize[1], strides[1], paddings[1])):
        if ceil_mode:
            out_d = -(-(dim + 2 * p - k) // s) + 1
        else:
            out_d = (dim + 2 * p - k) // s + 1
        extra = max(0, (out_d - 1) * s + k - dim - p)
        pads.append((p, extra))
    window = (1, 1) + tuple(ksize)
    strides4 = (1, 1) + tuple(strides)
    pad4 = [(0, 0), (0, 0)] + pads
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        out = lax.reduce_window(x, init, lax.max, window, strides4, pad4)
    else:
        ssum = lax.reduce_window(x, 0.0, lax.add, window, strides4, pad4)
        if exclusive:
            ones = jnp.ones(x.shape[2:], x.dtype)
            cnt = lax.reduce_window(ones, 0.0, lax.add, tuple(ksize),
                                    tuple(strides), pads)
            out = ssum / cnt[None, None]
        else:
            out = ssum / float(ksize[0] * ksize[1])
    return {"Out": out}


# ---------------------------------------------------------------------------
# Normalisation
# ---------------------------------------------------------------------------

@register_op("batch_norm")
def _batch_norm(ins, attrs):
    # reference: operators/batch_norm_op.cc — running stats update:
    # mean_out = mean * momentum + batch_mean * (1 - momentum)
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False)
    layout = attrs.get("data_layout", "NCHW")
    axes = tuple(i for i in range(x.ndim)
                 if i != (1 if layout == "NCHW" else x.ndim - 1))
    cshape = [1] * x.ndim
    cshape[1 if layout == "NCHW" else -1] = -1
    cshape = tuple(cshape)

    if is_test or attrs.get("use_global_stats", False):
        use_mean, use_var = mean, var
        saved_mean, saved_var = mean, 1.0 / jnp.sqrt(var + eps)
        mean_out, var_out = mean, var
    else:
        f32 = x.astype(jnp.float32)
        bmean = jnp.mean(f32, axis=axes)
        bvar = jnp.mean(jnp.square(f32), axis=axes) - jnp.square(bmean)
        use_mean, use_var = bmean, bvar
        mean_out = mean * momentum + bmean.astype(mean.dtype) * (1 - momentum)
        var_out = var * momentum + bvar.astype(var.dtype) * (1 - momentum)
        saved_mean = bmean
        saved_var = 1.0 / jnp.sqrt(bvar + eps)

    inv = (1.0 / jnp.sqrt(use_var.astype(jnp.float32) + eps))
    y = (x.astype(jnp.float32) - use_mean.reshape(cshape)) \
        * inv.reshape(cshape) * scale.astype(jnp.float32).reshape(cshape) \
        + bias.astype(jnp.float32).reshape(cshape)
    if attrs.get("fused_act") == "relu":
        # fuse_bn_act_pass folded a trailing relu into this op
        y = jnp.maximum(y, 0.0)
    return {"Y": y.astype(x.dtype), "MeanOut": mean_out,
            "VarianceOut": var_out, "SavedMean": saved_mean,
            "SavedVariance": saved_var}


@register_op("layer_norm")
def _layer_norm(ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    f32 = x.astype(jnp.float32)
    mean = jnp.mean(f32, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(f32 - mean), axis=axes, keepdims=True)
    y = (f32 - mean) / jnp.sqrt(var + eps)
    norm_shape = x.shape[begin:]
    if ins.get("Scale"):
        y = y * ins["Scale"][0].astype(jnp.float32).reshape(norm_shape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].astype(jnp.float32).reshape(norm_shape)
    red_shape = tuple(x.shape[:begin])
    return {"Y": y.astype(x.dtype),
            "Mean": mean.reshape(red_shape).astype(jnp.float32),
            "Variance": var.reshape(red_shape).astype(jnp.float32)}


@register_op("instance_norm")
def _instance_norm(ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + eps)
    cshape = (1, -1) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(cshape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(cshape)
    return {"Y": y, "SavedMean": mean.reshape(x.shape[:2]),
            "SavedVariance": (1.0 / jnp.sqrt(var + eps)).reshape(x.shape[:2])}


@register_op("group_norm")
def _group_norm(ins, attrs):
    x = ins["X"][0]
    groups = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, groups, c // groups) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    y = ((xg - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
    cshape = (1, -1) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(cshape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(cshape)
    return {"Y": y, "Mean": mean.reshape(n, groups),
            "Variance": var.reshape(n, groups)}


# ---------------------------------------------------------------------------
# Dropout (stateless threefry PRNG; reference uses curand states)
# ---------------------------------------------------------------------------

@register_op("dropout", needs_rng=True)
def _dropout(ins, attrs):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = x if impl == "upscale_in_train" else x * (1.0 - p)
        return {"Out": out, "Mask": jnp.ones(x.shape, jnp.uint8)}
    key = attrs["_rng_key"]
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))
    else:
        out = jnp.where(keep, x, jnp.zeros_like(x))
    return {"Out": out, "Mask": keep.astype(jnp.uint8)}


# ---------------------------------------------------------------------------
# Embedding
# ---------------------------------------------------------------------------

def _lookup(w, ids, padding_idx):
    out = jnp.take(w, ids.astype(jnp.int32), axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros_like(out), out)
    return out


@register_op("lookup_table")
def _lookup_table(ins, attrs):
    w, ids = ins["W"][0], ins["Ids"][0]
    # v1 ids carry a trailing [..., 1] dim (LoD heritage); squeeze it.
    if ids.ndim > 1 and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    return {"Out": _lookup(w, ids, attrs.get("padding_idx", -1))}


@register_op("lookup_table_v2")
def _lookup_table_v2(ins, attrs):
    w, ids = ins["W"][0], ins["Ids"][0]
    return {"Out": _lookup(w, ids, attrs.get("padding_idx", -1))}


@register_op("embedding")
def _embedding(ins, attrs):
    return _lookup_table_v2(ins, attrs)


@register_op("one_hot")
def _one_hot(ins, attrs):
    x = ins["X"][0]
    depth = attrs["depth"]
    ids = x.reshape(x.shape[:-1]).astype(jnp.int32)
    return {"Out": jax.nn.one_hot(ids, depth, dtype=jnp.float32)}


@register_op("one_hot_v2")
def _one_hot_v2(ins, attrs):
    x = ins["X"][0].astype(jnp.int32)
    return {"Out": jax.nn.one_hot(x, attrs["depth"], dtype=jnp.float32)}


# ---------------------------------------------------------------------------
# Misc NN
# ---------------------------------------------------------------------------

@register_op("label_smooth")
def _label_smooth(ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.0)
    if ins.get("PriorDist"):
        prior = ins["PriorDist"][0]
        out = (1 - eps) * x + eps * prior
    else:
        out = (1 - eps) * x + eps / x.shape[-1]
    return {"Out": out}


@register_op("interp_nearest")
def _interp_nearest(ins, attrs):
    x = ins["X"][0]
    oh, ow = attrs["out_h"], attrs["out_w"]
    n, c, h, w = x.shape
    ridx = (jnp.arange(oh) * (h / oh)).astype(jnp.int32)
    cidx = (jnp.arange(ow) * (w / ow)).astype(jnp.int32)
    return {"Out": x[:, :, ridx][:, :, :, cidx]}


@register_op("pad")
def _pad(ins, attrs):
    x = ins["X"][0]
    paddings = attrs["paddings"]
    value = attrs.get("pad_value", 0.0)
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, cfg, constant_values=value)}


@register_op("pad2d")
def _pad2d(ins, attrs):
    x = ins["X"][0]
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    cfg = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    if mode == "constant":
        return {"Out": jnp.pad(x, cfg,
                               constant_values=attrs.get("pad_value", 0.0))}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": jnp.pad(x, cfg, mode=jmode)}


# ---------------------------------------------------------------------------
# Fused scaled-dot-product attention (flash attention on TPU)
# ---------------------------------------------------------------------------

@register_op("scaled_dot_product_attention", needs_rng=True)
def _sdpa(ins, attrs):
    """Fused attention. Q,K,V: [B, H, S, D]; optional KeyBias: [B, Sk]
    additive key bias. On TPU with no attention-prob dropout this lowers
    to the Pallas flash kernel (paddle_tpu/ops/pallas/flash_attention.py);
    otherwise the XLA reference path (identical semantics) runs, with
    upscale_in_train dropout on the normalized probs.

    Reference parity: fused CUDA attention in
    `paddle/fluid/operators/fused/multihead_matmul_op.cu` and
    `operators/math/bert_encoder_functor.cu` (inference-only there; this
    op also trains)."""
    from .pallas import flash_attention as _flash
    from .pallas import reference_attention as _ref_attn

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins.get("KeyBias", [None])
    bias = bias[0] if bias else None
    mask = ins.get("Mask", [None])  # full additive mask, bcast to
    mask = mask[0] if mask else None  # [B, H, Sq, Sk]
    causal = attrs.get("causal", False)
    sm_scale = attrs.get("sm_scale", None)
    if sm_scale is not None and sm_scale <= 0:
        sm_scale = None
    p_drop = attrs.get("attn_dropout_prob", 0.0)
    is_test = attrs.get("is_test", False)
    drop_active = (not is_test) and p_drop > 0.0

    if mask is None:
        # Pallas flash only where its O(S) memory matters: below the
        # threshold XLA's fused softmax-attention is faster on v5e
        # (FLAGS_flash_attention_min_seq; measured: flash loses up to at
        # least S=2048 forward, but avoids the S^2 score buffer).
        # Dropout-active training takes this path too: the kernel
        # applies prob-dropout in-VMEM (mask regenerated in backward
        # from the seed — no S^2 mask buffer in HBM).
        from ..utils import flags as _flags
        min_seq = int(_flags.get_flags(
            ["FLAGS_flash_attention_min_seq"])
            ["FLAGS_flash_attention_min_seq"])
        if jax.default_backend() == "tpu" and k.shape[-2] >= min_seq:
            seed = None
            if drop_active:
                seed = jax.random.randint(
                    attrs["_rng_key"], (1,), 0, 2 ** 31 - 1,
                    dtype=jnp.int32)
            return {"Out": _flash(q, k, v, key_bias=bias, causal=causal,
                                  sm_scale=sm_scale,
                                  dropout_p=p_drop if drop_active
                                  else 0.0,
                                  dropout_seed=seed)}
        if not drop_active:
            return {"Out": _ref_attn(q, k, v, key_bias=bias,
                                     causal=causal, sm_scale=sm_scale)}

    # Unfused path with dropout on probs (matches layers.softmax+dropout).
    # MXU note: keep the matmul inputs in their compute dtype (bf16 under
    # AMP) with f32 ACCUMULATION — an f32 upcast before the einsum would
    # push the contraction off the bf16 MXU path (~3x slower on TPU).
    import math as _math
    D = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / _math.sqrt(D)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias[:, None, None, :].astype(jnp.float32)
    if mask is not None:
        # [Sq,Sk] -> [1,1,Sq,Sk]; [B,Sq,Sk] -> [B,1,Sq,Sk] (head axis
        # inserted at dim 1, NOT prepended — [1,B,Sq,Sk] would misalign
        # batch with heads)
        if mask.ndim == 2:
            mask = mask[None, None]
        elif mask.ndim == 3:
            mask = mask[:, None]
        if mask.dtype == jnp.bool_:  # True = attend (paddle semantics)
            s = jnp.where(mask, s, -1e30)
        else:
            s = s + mask.astype(jnp.float32)
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        rows = lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        cols = lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where(rows >= cols, s, -1e30)
    probs = jax.nn.softmax(s, axis=-1)
    if drop_active:
        keep = jax.random.bernoulli(attrs["_rng_key"], 1.0 - p_drop,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - p_drop), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v,
                     preferred_element_type=jnp.float32)
    return {"Out": out.astype(q.dtype)}


# ---------------------------------------------------------------------------
# recurrent cells over lax.scan (reference: operators/lstm_op.cc /
# gru_op.cc + python/paddle/fluid/layers/rnn.py LSTMCell/GRUCell).
# TPU-native: one op = the FULL sequence, scanned by XLA (static trip
# count -> unrolled/pipelined on device), gates fused into two matmuls
# per step that land on the MXU.
# ---------------------------------------------------------------------------

@register_op("lstm_seq")
def _lstm_seq(ins, attrs):
    """Single-layer LSTM over a [B,T,D] batch-major sequence.
    Gate layout i,f,g,o in the 4H weight axis."""
    x = ins["Input"][0]
    w_ih = ins["WeightIh"][0]   # (4H, D)
    w_hh = ins["WeightHh"][0]   # (4H, H)
    b = ins["Bias"][0]          # (4H,)
    h0 = ins["InitH"][0]        # (B, H)
    c0 = ins["InitC"][0]        # (B, H)
    reverse = attrs.get("is_reverse", False)
    xs = jnp.swapaxes(x, 0, 1)  # (T,B,D) scan axis first
    if reverse:
        xs = xs[::-1]
    x_proj = jnp.einsum("tbd,gd->tbg", xs, w_ih) + b  # hoisted MXU matmul

    def step(carry, xp):
        h, c = carry
        gates = xp + h @ w_hh.T
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c = f * c + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    (h_last, c_last), ys = lax.scan(step, (h0, c0), x_proj)
    if reverse:
        ys = ys[::-1]
    return {"Out": jnp.swapaxes(ys, 0, 1), "LastH": h_last,
            "LastC": c_last}


@register_op("gru_seq")
def _gru_seq(ins, attrs):
    """Single-layer GRU over [B,T,D]; gate layout r,z,n in the 3H axis."""
    x = ins["Input"][0]
    w_ih = ins["WeightIh"][0]   # (3H, D)
    w_hh = ins["WeightHh"][0]   # (3H, H)
    b_ih = ins["BiasIh"][0]     # (3H,)
    b_hh = ins["BiasHh"][0]     # (3H,)
    h0 = ins["InitH"][0]
    reverse = attrs.get("is_reverse", False)
    xs = jnp.swapaxes(x, 0, 1)
    if reverse:
        xs = xs[::-1]
    x_proj = jnp.einsum("tbd,gd->tbg", xs, w_ih) + b_ih

    def step(h, xp):
        hp = h @ w_hh.T + b_hh
        xr, xz, xn = jnp.split(xp, 3, axis=-1)
        hr, hz, hn = jnp.split(hp, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h = (1.0 - z) * n + z * h
        return h, h

    h_last, ys = lax.scan(step, h0, x_proj)
    if reverse:
        ys = ys[::-1]
    return {"Out": jnp.swapaxes(ys, 0, 1), "LastH": h_last}


# ---------------------------------------------------------------------------
# extended activations (reference: operators/activation_op.cc registrations)
# ---------------------------------------------------------------------------

@register_op("selu")
def _selu(ins, attrs):
    x = ins["X"][0]
    scale = attrs.get("scale", 1.0507009873554805)
    alpha = attrs.get("alpha", 1.6732632423543772)
    return {"Out": scale * jnp.where(x > 0, x,
                                     alpha * (jnp.exp(x) - 1.0))}


@register_op("softshrink")
def _softshrink(ins, attrs):
    x = ins["X"][0]
    l = attrs.get("lambda", attrs.get("threshold", 0.5))
    return {"Out": jnp.where(x > l, x - l, jnp.where(x < -l, x + l, 0.0))}


@register_op("hard_shrink")
def _hard_shrink(ins, attrs):
    x = ins["X"][0]
    t = attrs.get("threshold", 0.5)
    return {"Out": jnp.where(jnp.abs(x) > t, x, 0.0)}


@register_op("tanh_shrink")
def _tanh_shrink(ins, attrs):
    x = ins["X"][0]
    return {"Out": x - jnp.tanh(x)}


@register_op("brelu")
def _brelu(ins, attrs):
    x = ins["X"][0]
    t_min = attrs.get("t_min", 0.0)
    t_max = attrs.get("t_max", 24.0)
    return {"Out": jnp.clip(x, t_min, t_max)}


@register_op("soft_relu")
def _soft_relu(ins, attrs):
    x = ins["X"][0]
    t = attrs.get("threshold", 40.0)
    return {"Out": jnp.log1p(jnp.exp(jnp.clip(x, -t, t)))}


@register_op("expm1")
def _expm1(ins, attrs):
    return {"Out": jnp.expm1(ins["X"][0])}


@register_op("tan")
def _tan(ins, attrs):
    return {"Out": jnp.tan(ins["X"][0])}


@register_op("acosh")
def _acosh(ins, attrs):
    return {"Out": jnp.arccosh(ins["X"][0])}


@register_op("asinh")
def _asinh(ins, attrs):
    return {"Out": jnp.arcsinh(ins["X"][0])}


@register_op("atanh")
def _atanh(ins, attrs):
    return {"Out": jnp.arctanh(ins["X"][0])}


@register_op("maxout")
def _maxout(ins, attrs):
    # reference: maxout_op.cc — NCHW channel groups
    x = ins["X"][0]
    groups = attrs["groups"]
    axis = attrs.get("axis", 1)
    c = x.shape[axis]
    new_shape = (x.shape[:axis] + (c // groups, groups)
                 + x.shape[axis + 1:])
    return {"Out": jnp.max(x.reshape(new_shape), axis=axis + 1)}


@register_op("logit")
def _logit(ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("eps", 1e-6)
    xc = jnp.clip(x, eps, 1.0 - eps)
    return {"Out": jnp.log(xc / (1.0 - xc))}


@register_op("celu")
def _celu(ins, attrs):
    x = ins["X"][0]
    alpha = attrs.get("alpha", 1.0)
    return {"Out": jnp.where(x > 0, x,
                             alpha * (jnp.exp(x / alpha) - 1.0))}


# ---------------------------------------------------------------------------
# extended norm / conv / pool (reference: operators/*norm*, conv3d, pool3d,
# lrn_op, spectral_norm_op, data_norm_op, row_conv_op)
# ---------------------------------------------------------------------------

@register_op("norm")
def _norm(ins, attrs):
    # l2_normalize (reference: norm_op.cc)
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": x / n, "Norm": n}


@register_op("lrn")
def _lrn(ins, attrs):
    # reference: lrn_op.cc — local response norm across channels (NCHW)
    x = ins["X"][0]
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": x / jnp.power(mid, beta), "MidOut": mid}


@register_op("spectral_norm")
def _spectral_norm(ins, attrs):
    # reference: spectral_norm_op.cc — power-iteration weight norm
    w, u, v = ins["Weight"][0], ins["U"][0], ins["V"][0]
    dim = attrs.get("dim", 0)
    power_iters = attrs.get("power_iters", 1)
    eps = attrs.get("eps", 1e-12)
    wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    for _ in range(max(power_iters, 0)):
        v = wm.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = wm @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ wm @ v
    return {"Out": w / sigma}


@register_op("data_norm")
def _data_norm(ins, attrs):
    # reference: data_norm_op.cc — normalization by accumulated stats
    x = ins["X"][0]
    size = ins["BatchSize"][0]
    sums = ins["BatchSum"][0]
    sqs = ins["BatchSquareSum"][0]
    eps = attrs.get("epsilon", 1e-4)
    mean = sums / size
    scale = jnp.sqrt(size / (sqs - size * jnp.square(mean) + eps))
    y = (x - mean) * scale
    return {"Y": y, "Means": jnp.broadcast_to(mean, x.shape),
            "Scales": jnp.broadcast_to(scale, x.shape)}


@register_op("row_conv")
def _row_conv(ins, attrs):
    # reference: row_conv_op.cc — lookahead row convolution [B, T, D]
    x, filt = ins["X"][0], ins["Filter"][0]
    future = filt.shape[0]
    pad = jnp.pad(x, ((0, 0), (0, future - 1), (0, 0)))
    out = sum(pad[:, i:i + x.shape[1]] * filt[i] for i in range(future))
    return {"Out": out}


@register_op("conv3d")
def _conv3d(ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    stride = attrs.get("strides", [1, 1, 1])
    pad = attrs.get("paddings", [0, 0, 0])
    dil = attrs.get("dilations", [1, 1, 1])
    groups = attrs.get("groups", 1)
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dil, feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
    return {"Output": out}


@register_op("pool3d")
def _pool3d(ins, attrs):
    x = ins["X"][0]
    ksize = attrs.get("ksize", [2, 2, 2])
    stride = attrs.get("strides", ksize)
    pad = attrs.get("paddings", [0, 0, 0])
    ptype = attrs.get("pooling_type", "max")
    dims = (1, 1) + tuple(ksize)
    strides = (1, 1) + tuple(stride)
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, dims, strides,
                                    pads)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides,
                                  pads)
        cnt = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                    dims, strides, pads)
        out = s / cnt
    return {"Out": out}


@register_op("max_pool2d_with_index")
def _max_pool2d_with_index(ins, attrs):
    x = ins["X"][0]
    ksize = attrs.get("ksize", [2, 2])
    stride = attrs.get("strides", ksize)
    pad = attrs.get("paddings", [0, 0])
    n, c, h, w = x.shape
    kh, kw = ksize
    xp = jnp.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])),
                 constant_values=-jnp.inf)
    oh = (h + 2 * pad[0] - kh) // stride[0] + 1
    ow = (w + 2 * pad[1] - kw) // stride[1] + 1
    # unfold windows: [n, c, oh, ow, kh*kw]
    idx_h = (jnp.arange(oh)[:, None] * stride[0]
             + jnp.arange(kh)[None, :])  # [oh, kh]
    idx_w = (jnp.arange(ow)[:, None] * stride[1]
             + jnp.arange(kw)[None, :])  # [ow, kw]
    wins = xp[:, :, idx_h[:, :, None, None], idx_w[None, None, :, :]]
    wins = wins.transpose(0, 1, 2, 4, 3, 5).reshape(n, c, oh, ow, kh * kw)
    out = jnp.max(wins, -1)
    amax = jnp.argmax(wins, -1)
    # flat index in the UNPADDED input (reference semantics)
    rh = amax // kw + idx_h[:, 0][None, None, :, None] - pad[0]
    rw = amax % kw + idx_w[:, 0][None, None, None, :] - pad[1]
    flat = (rh * w + rw).astype(jnp.int64)
    return {"Out": out, "Mask": flat}


@register_op("conv3d_transpose")
def _conv3d_transpose(ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    stride = attrs.get("strides", [1, 1, 1])
    pad = attrs.get("paddings", [0, 0, 0])
    out = jax.lax.conv_transpose(
        x, jnp.swapaxes(w, 0, 1), strides=stride,
        padding=[(p, p) for p in pad],
        dimension_numbers=("NCDHW", "IODHW", "NCDHW"),
        transpose_kernel=True)
    return {"Output": out}


@register_op("affine_channel")
def _affine_channel(ins, attrs):
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    layout = attrs.get("data_layout", "NCHW")
    if layout == "NCHW":
        shape = (1, -1) + (1,) * (x.ndim - 2)
    else:
        shape = (1,) * (x.ndim - 1) + (-1,)
    return {"Out": x * scale.reshape(shape) + bias.reshape(shape)}


@register_op("fsp")
def _fsp(ins, attrs):
    # reference: fsp_op.cc — flow of solution procedure matrix (distill)
    x, y = ins["X"][0], ins["Y"][0]
    n, cx = x.shape[0], x.shape[1]
    cy = y.shape[1]
    hw = x.shape[2] * x.shape[3]
    xf = x.reshape(n, cx, hw)
    yf = y.reshape(n, cy, hw)
    return {"Out": jnp.einsum("nch,ndh->ncd", xf, yf) / hw}


@register_op("pixel_shuffle")
def _pixel_shuffle(ins, attrs):
    x = ins["X"][0]
    r = attrs.get("upscale_factor", 1)
    n, c, h, w = x.shape
    oc = c // (r * r)
    out = x.reshape(n, oc, r, r, h, w).transpose(0, 1, 4, 2, 5, 3)
    return {"Out": out.reshape(n, oc, h * r, w * r)}


@register_op("shuffle_channel")
def _shuffle_channel(ins, attrs):
    x = ins["X"][0]
    group = attrs.get("group", 1)
    n, c, h, w = x.shape
    out = x.reshape(n, group, c // group, h, w).transpose(0, 2, 1, 3, 4)
    return {"Out": out.reshape(n, c, h, w)}


@register_op("space_to_depth")
def _space_to_depth(ins, attrs):
    x = ins["X"][0]
    b = attrs.get("blocksize", 1)
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // b, b, w // b, b)
    out = out.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": out.reshape(n, c * b * b, h // b, w // b)}


@register_op("temporal_shift")
def _temporal_shift(ins, attrs):
    # reference: temporal_shift_op.cc — shift 1/4 channels +/-1 in time
    x = ins["X"][0]
    seg = attrs.get("seg_num", 1)
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // seg
    xr = x.reshape(n, seg, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    fwd = jnp.concatenate([xr[:, 1:, :c1], jnp.zeros_like(xr[:, :1, :c1])],
                          axis=1)
    back = jnp.concatenate([jnp.zeros_like(xr[:, :1, c1:c2]),
                            xr[:, :-1, c1:c2]], axis=1)
    keep = xr[:, :, c2:]
    out = jnp.concatenate([fwd, back, keep], axis=2)
    return {"Out": out.reshape(nt, c, h, w)}


@register_op("grid_sampler")
def _grid_sampler(ins, attrs):
    # reference: grid_sampler_op.cc — bilinear sampling, align_corners
    x, grid = ins["X"][0], ins["Grid"][0]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wa = (x1 - gx) * (y1 - gy)
    wb = (x1 - gx) * (gy - y0)
    wc = (gx - x0) * (y1 - gy)
    wd = (gx - x0) * (gy - y0)

    def sample(yy, xx):
        yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0)
                 & (xx <= w - 1)).astype(x.dtype)
        ni = jnp.arange(n)[:, None, None]
        v = x[ni, :, yi, xi]  # [n, gh, gw, c]
        return v * valid[..., None]

    out = (sample(y0, x0) * wa[..., None] + sample(y1, x0) * wb[..., None]
           + sample(y0, x1) * wc[..., None]
           + sample(y1, x1) * wd[..., None])
    return {"Output": out.transpose(0, 3, 1, 2)}


@register_op("affine_grid")
def _affine_grid(ins, attrs):
    theta = ins["Theta"][0]
    out_shape = attrs.get("output_shape")
    if ins.get("OutputShape"):
        try:
            out_shape = [int(v) for v in ins["OutputShape"][0]]
        except Exception:  # traced under jit: static attr required
            pass
    n, _, h, w = out_shape
    ys = jnp.linspace(-1.0, 1.0, h)
    xs = jnp.linspace(-1.0, 1.0, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], -1).reshape(1, h * w, 3)
    grid = base @ jnp.swapaxes(theta, 1, 2)  # [n, h*w, 2]
    return {"Output": grid.reshape(theta.shape[0], h, w, 2)}


@register_op("unfold")
def _unfold(ins, attrs):
    # reference: unfold_op.cc (im2col); out [N, C*kh*kw, L]
    x = ins["X"][0]
    k = attrs["kernel_sizes"]
    s = attrs.get("strides", [1, 1])
    p = attrs.get("paddings", [0, 0, 0, 0])
    d = attrs.get("dilations", [1, 1])
    n, c, h, w = x.shape
    if len(p) == 2:
        p = [p[0], p[1], p[0], p[1]]
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
    kh, kw = k
    oh = (xp.shape[2] - (d[0] * (kh - 1) + 1)) // s[0] + 1
    ow = (xp.shape[3] - (d[1] * (kw - 1) + 1)) // s[1] + 1
    ih = jnp.arange(oh)[:, None] * s[0] + jnp.arange(kh)[None, :] * d[0]
    iw = jnp.arange(ow)[:, None] * s[1] + jnp.arange(kw)[None, :] * d[1]
    cols = xp[:, :, ih[:, :, None, None], iw[None, None, :, :]]
    # [n, c, oh, kh, ow, kw] -> [n, c*kh*kw, oh*ow]
    cols = cols.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * kh * kw,
                                                    oh * ow)
    return {"Y": cols}


@register_op("im2sequence")
def _im2sequence(ins, attrs):
    # reference: im2sequence_op.cc — image patches to sequence rows
    x = ins["X"][0]
    k = attrs["kernels"]
    s = attrs.get("strides", [1, 1])
    p = attrs.get("paddings", [0, 0, 0, 0])
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
    kh, kw = k
    oh = (xp.shape[2] - kh) // s[0] + 1
    ow = (xp.shape[3] - kw) // s[1] + 1
    ih = jnp.arange(oh)[:, None] * s[0] + jnp.arange(kh)[None, :]
    iw = jnp.arange(ow)[:, None] * s[1] + jnp.arange(kw)[None, :]
    patches = xp[:, :, ih[:, :, None, None], iw[None, None, :, :]]
    # [n, c, oh, kh, ow, kw] -> [n*oh*ow, c*kh*kw]
    patches = patches.transpose(0, 2, 4, 1, 3, 5).reshape(
        n * oh * ow, c * kh * kw)
    return {"Out": patches}


@register_op("spp")
def _spp(ins, attrs):
    """Spatial pyramid pooling (reference: spp_op.h:26): levels
    p=0..pyramid_height-1 pool to 2^p x 2^p bins with
    kernel=ceil(dim/bins), pad=(kernel*bins-dim+1)//2, then flatten and
    concat along channels. Composes the registered pool2d kernel —
    XLA fuses the reduce_windows."""
    x = ins["X"][0]
    pyramid_height = int(attrs.get("pyramid_height", 1))
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    import math as _math

    from .registry import run_op as _run

    outs = []
    for p in range(pyramid_height):
        bins = 2 ** p
        kh = _math.ceil(h / bins)
        kw = _math.ceil(w / bins)
        ph = (kh * bins - h + 1) // 2
        pw = (kw * bins - w + 1) // 2
        lvl = _run("pool2d", {"X": [x]},
                   {"pooling_type": ptype, "ksize": [kh, kw],
                    "strides": [kh, kw], "paddings": [ph, pw],
                    "exclusive": True})["Out"][0]
        outs.append(lvl.reshape(n, c * bins * bins))
    return {"Out": jnp.concatenate(outs, axis=1)}
