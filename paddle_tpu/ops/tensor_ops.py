"""Tensor creation / manipulation operators.

Reference parity: `paddle/fluid/operators/` — fill_constant_op, reshape_op
(v2 emits XShape for grad bookkeeping; kept for program compatibility),
transpose_op, concat_op, split_op, slice_op, gather_op, stack_op, expand_op,
squeeze/unsqueeze, top_k_op, arg_max/min, assign_op, shape_op, range_op,
cumsum, where/masked ops, tril_triu.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op
from ..core.types import to_numpy_dtype, normalize_dtype


def _xshape(x):
    # XShape carries the pre-op shape prefixed with 0 (framework convention,
    # reference: operators/reshape_op.cc Reshape2Op). No data.
    return jnp.zeros((0,) + x.shape, x.dtype)


@register_op("fill_constant")
def _fill_constant(ins, attrs):
    shape = attrs.get("shape", [1])
    dtype = to_numpy_dtype(attrs.get("dtype", "float32"))
    value = attrs.get("value", 0.0)
    if isinstance(value, str):
        value = float(value)
    return {"Out": jnp.full(tuple(int(d) for d in shape), value, dtype)}


@register_op("fill_constant_batch_size_like")
def _fill_constant_bsl(ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = to_numpy_dtype(attrs.get("dtype", "float32"))
    return {"Out": jnp.full(tuple(shape), attrs.get("value", 0.0), dtype)}


@register_op("fill_zeros_like")
def _fill_zeros_like(ins, attrs):
    return {"Out": jnp.zeros_like(ins["X"][0])}


@register_op("fill_any_like")
def _fill_any_like(ins, attrs):
    x = ins["X"][0]
    dtype = attrs.get("dtype", None)
    np_dtype = x.dtype if dtype in (None, -1) else to_numpy_dtype(dtype)
    return {"Out": jnp.full(x.shape, attrs.get("value", 0.0), np_dtype)}


@register_op("assign")
def _assign(ins, attrs):
    return {"Out": ins["X"][0]}


@register_op("assign_value")
def _assign_value(ins, attrs):
    dtype = to_numpy_dtype(attrs.get("dtype", "float32"))
    shape = tuple(attrs["shape"])
    values = attrs.get("fp32_values") or attrs.get("int32_values") \
        or attrs.get("int64_values") or attrs.get("values")
    return {"Out": jnp.asarray(np.asarray(values, dtype).reshape(shape))}


@register_op("shape")
def _shape(ins, attrs):
    x = ins["Input"][0]
    return {"Out": jnp.asarray(np.asarray(x.shape, np.int32))}


@register_op("is_empty")
def _is_empty(ins, attrs):
    # reference: is_empty_op.h:23 — Out[0] = numel(X) == 0. Shapes are
    # static under XLA, so the answer is a trace-time constant.
    x = ins["X"][0]
    return {"Out": jnp.asarray([x.size == 0])}


@register_op("reshape")
def _reshape(ins, attrs):
    return {"Out": _do_reshape(ins["X"][0], attrs["shape"])}


def _do_reshape(x, shape):
    shape = [int(s) for s in shape]
    # Paddle rule: 0 means copy the input dim at that position.
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)
             ] if 0 in shape else shape
    return x.reshape(tuple(shape))


@register_op("reshape2")
def _reshape2(ins, attrs):
    x = ins["X"][0]
    if ins.get("Shape"):
        shape = [int(v) for v in np.asarray(ins["Shape"][0])]
    else:
        shape = attrs["shape"]
    return {"Out": _do_reshape(x, shape), "XShape": _xshape(x)}


@register_op("transpose")
def _transpose(ins, attrs):
    return {"Out": jnp.transpose(ins["X"][0], attrs["axis"])}


@register_op("transpose2")
def _transpose2(ins, attrs):
    x = ins["X"][0]
    return {"Out": jnp.transpose(x, attrs["axis"]), "XShape": _xshape(x)}


@register_op("squeeze")
def _squeeze(ins, attrs):
    x = ins["X"][0]
    axes = attrs.get("axes", [])
    if not axes:
        return {"Out": jnp.squeeze(x)}
    axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return {"Out": jnp.squeeze(x, axis=axes)}


@register_op("squeeze2")
def _squeeze2(ins, attrs):
    out = _squeeze(ins, attrs)
    out["XShape"] = _xshape(ins["X"][0])
    return out


@register_op("unsqueeze")
def _unsqueeze(ins, attrs):
    x = ins["X"][0]
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": x}


@register_op("unsqueeze2")
def _unsqueeze2(ins, attrs):
    out = _unsqueeze(ins, attrs)
    out["XShape"] = _xshape(ins["X"][0])
    return out


@register_op("flatten")
def _flatten(ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {"Out": x.reshape((lead, -1))}


@register_op("flatten2")
def _flatten2(ins, attrs):
    out = _flatten(ins, attrs)
    out["XShape"] = _xshape(ins["X"][0])
    return out


@register_op("flatten_contiguous_range")
def _flatten_range(ins, attrs):
    x = ins["X"][0]
    start = attrs.get("start_axis", 1) % max(x.ndim, 1)
    stop = attrs.get("stop_axis", -1) % max(x.ndim, 1)
    shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return {"Out": x.reshape(shape), "XShape": _xshape(x)}


@register_op("concat")
def _concat(ins, attrs):
    return {"Out": jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))}


@register_op("split")
def _split(ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("stack")
def _stack(ins, attrs):
    return {"Y": jnp.stack(ins["X"], axis=attrs.get("axis", 0))}


@register_op("unstack")
def _unstack(ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(s, axis)
                  for s in jnp.split(x, n, axis=axis)]}


@register_op("slice")
def _slice(ins, attrs):
    x = ins["Input"][0]
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        idx[a] = slice(s, e)
    out = x[tuple(idx)]
    for a in sorted(attrs.get("decrease_axis", []), reverse=True):
        out = jnp.squeeze(out, axis=a)
    return {"Out": out}


@register_op("strided_slice")
def _strided_slice(ins, attrs):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"],
                           attrs["strides"]):
        idx[a] = slice(s, e, st)
    return {"Out": x[tuple(idx)]}


@register_op("gather")
def _gather(ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    axis = attrs.get("axis", 0)
    return {"Out": jnp.take(x, idx.astype(jnp.int32), axis=axis)}


@register_op("gather_nd")
def _gather_nd(ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    nd = idx.shape[-1]
    flat_idx = tuple(idx[..., i] for i in range(nd))
    return {"Out": x[flat_idx]}


@register_op("scatter")
def _scatter(ins, attrs):
    x, ids, updates = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    ids = ids.astype(jnp.int32).reshape((-1,))
    if attrs.get("overwrite", True):
        return {"Out": x.at[ids].set(updates)}
    return {"Out": x.at[ids].add(updates)}


@register_op("scatter_nd_add")
def _scatter_nd_add(ins, attrs):
    x, idx, updates = ins["X"][0], ins["Index"][0], ins["Updates"][0]
    nd = idx.shape[-1]
    flat_idx = tuple(idx[..., i] for i in range(nd))
    return {"Out": x.at[flat_idx].add(updates)}


@register_op("index_select")
def _index_select(ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": jnp.take(x, idx.astype(jnp.int32),
                            axis=attrs.get("dim", 0))}


@register_op("expand")
def _expand(ins, attrs):
    x = ins["X"][0]
    times = attrs["expand_times"]
    return {"Out": jnp.tile(x, tuple(times))}


@register_op("expand_v2")
def _expand_v2(ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    # -1 keeps the input dim
    ndiff = len(shape) - x.ndim
    xs = (1,) * ndiff + x.shape
    tgt = tuple(xs[i] if s == -1 else s for i, s in enumerate(shape))
    return {"Out": jnp.broadcast_to(x.reshape(xs), tgt)}


@register_op("expand_as_v2")
def _expand_as(ins, attrs):
    x = ins["X"][0]
    shape = attrs.get("target_shape")
    if shape is None:
        shape = ins["Y"][0].shape
    return {"Out": jnp.broadcast_to(x, tuple(shape))}


@register_op("tile")
def _tile(ins, attrs):
    return {"Out": jnp.tile(ins["X"][0], tuple(attrs["repeat_times"]))}


@register_op("top_k")
def _top_k(ins, attrs):
    x = ins["X"][0]
    k = attrs.get("k", 1)
    vals, idx = lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op("top_k_v2")
def _top_k_v2(ins, attrs):
    x = ins["X"][0]
    k = attrs.get("k", 1)
    axis = attrs.get("axis", -1) % x.ndim
    largest = attrs.get("largest", True)
    xm = jnp.moveaxis(x, axis, -1)
    if not largest:
        xm = -xm
    vals, idx = lax.top_k(xm, k)
    if not largest:
        vals = -vals
    return {"Out": jnp.moveaxis(vals, -1, axis),
            "Indices": jnp.moveaxis(idx.astype(jnp.int64), -1, axis)}


@register_op("arg_max")
def _arg_max(ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    out = jnp.argmax(x, axis=axis)
    if attrs.get("keepdims", False):
        out = jnp.expand_dims(out, axis)
    return {"Out": out.astype(jnp.int64)}


@register_op("arg_min")
def _arg_min(ins, attrs):
    x = ins["X"][0]
    out = jnp.argmin(x, axis=attrs.get("axis", -1))
    return {"Out": out.astype(jnp.int64)}


@register_op("argsort")
def _argsort(ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    desc = attrs.get("descending", False)
    idx = jnp.argsort(-x if desc else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(jnp.int64)}


@register_op("range", no_jit=True)
def _range(ins, attrs):
    # output length depends on VALUES -> host-eval, never jitted
    if ins.get("Start"):
        start = float(np.asarray(ins["Start"][0]).reshape(()))
        end = float(np.asarray(ins["End"][0]).reshape(()))
        step = float(np.asarray(ins["Step"][0]).reshape(()))
        dtype = ins["Start"][0].dtype
    else:  # attr form (paddle.arange 2.0 API)
        from ..core.types import to_numpy_dtype, normalize_dtype

        start, end = attrs["start"], attrs["end"]
        step = attrs["step"]
        dtype = to_numpy_dtype(normalize_dtype(attrs.get("dtype",
                                                         "int64")))
    return {"Out": jnp.arange(start, end, step).astype(dtype)}


@register_op("cumsum")
def _cumsum(ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    if attrs.get("flatten", False):
        x = x.reshape((-1,))
        axis = 0
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    return {"Out": out}


@register_op("where")
def _where(ins, attrs):
    return {"Out": jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])}


@register_op("where_index", no_jit=True,
             dynamic_shape=True)
def _where_index(ins, attrs):
    # dynamic output shape: only usable eagerly (outside jit)
    cond = np.asarray(ins["Condition"][0])
    return {"Out": jnp.asarray(np.argwhere(cond).astype(np.int64))}


@register_op("masked_select", no_jit=True,
             dynamic_shape=True)
def _masked_select(ins, attrs):
    x = np.asarray(ins["X"][0])
    mask = np.asarray(ins["Mask"][0])
    return {"Y": jnp.asarray(x[mask])}


@register_op("tril_triu")
def _tril_triu(ins, attrs):
    x = ins["X"][0]
    diag = attrs.get("diagonal", 0)
    if attrs.get("lower", True):
        return {"Out": jnp.tril(x, diag)}
    return {"Out": jnp.triu(x, diag)}


@register_op("diag_v2")
def _diag(ins, attrs):
    return {"Out": jnp.diag(ins["X"][0], k=attrs.get("offset", 0))}


@register_op("eye")
def _eye(ins, attrs):
    dtype = to_numpy_dtype(attrs.get("dtype", "float32"))
    rows = attrs["num_rows"]
    cols = attrs.get("num_columns", -1)
    return {"Out": jnp.eye(rows, cols if cols > 0 else rows, dtype=dtype)}


@register_op("linspace")
def _linspace(ins, attrs):
    start = np.asarray(ins["Start"][0]).reshape(())
    stop = np.asarray(ins["Stop"][0]).reshape(())
    num = int(np.asarray(ins["Num"][0]).reshape(()))
    dtype = to_numpy_dtype(attrs.get("dtype", "float32"))
    return {"Out": jnp.linspace(start, stop, num, dtype=dtype)}


@register_op("roll")
def _roll(ins, attrs):
    x = ins["X"][0]
    shifts = attrs["shifts"]
    axis = attrs.get("axis", None)
    return {"Out": jnp.roll(x, shifts, axis=tuple(axis) if axis else None)}


@register_op("flip")
def _flip(ins, attrs):
    return {"Out": jnp.flip(ins["X"][0], axis=tuple(attrs["axis"]))}


@register_op("unique", no_jit=True,
             dynamic_shape=True)
def _unique(ins, attrs):
    """Slots follow the 2.0 unique op: Index = inverse mapping (the
    fluid-era output), Indices = first-occurrence positions, Counts.
    Host-side (no_jit): output shape is data-dependent."""
    x = np.asarray(ins["X"][0])
    out, first_idx, inverse, counts = np.unique(
        x, return_index=True, return_inverse=True, return_counts=True)
    return {"Out": jnp.asarray(out),
            "Index": jnp.asarray(inverse.astype(np.int64)),
            "Indices": jnp.asarray(first_idx.astype(np.int64)),
            "Counts": jnp.asarray(counts.astype(np.int64))}


@register_op("take_along_axis")
def _take_along_axis(ins, attrs):
    x, idx = ins["Input"][0], ins["Index"][0]
    return {"Result": jnp.take_along_axis(
        x, idx.astype(jnp.int32), axis=attrs.get("Axis", 0))}


@register_op("meshgrid")
def _meshgrid(ins, attrs):
    outs = jnp.meshgrid(*ins["X"], indexing="ij")
    return {"Out": list(outs)}


@register_op("increment")
def _increment(ins, attrs):
    x = ins["X"][0]
    return {"Out": x + jnp.asarray(attrs.get("step", 1.0), x.dtype)}


# ---------------------------------------------------------------------------
# extended manipulation ops (reference: operators/ pad_constant_like_op,
# crop_op, shard_index_op, index_sample_op, scatter_nd, unbind, unique_v2,
# diag/diag_embed, reverse, partial_*)
# ---------------------------------------------------------------------------

@register_op("pad_constant_like")
def _pad_constant_like(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    pad_value = attrs.get("pad_value", 0.0)
    pads = [(0, int(xd - yd)) for xd, yd in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, pads, constant_values=pad_value)}


@register_op("crop")
def _crop(ins, attrs):
    x = ins["X"][0]
    offsets = attrs.get("offsets")
    if ins.get("Offsets"):
        offsets = [int(v) for v in ins["Offsets"][0]]
    shape = attrs.get("shape")
    if ins.get("Y"):
        shape = ins["Y"][0].shape
    starts = offsets or [0] * x.ndim
    return {"Out": jax.lax.slice(
        x, starts, [s + d for s, d in zip(starts, shape)])}


@register_op("crop_tensor")
def _crop_tensor(ins, attrs):
    return _crop(ins, attrs)


@register_op("shard_index")
def _shard_index(ins, attrs):
    x = ins["X"][0]
    index_num = attrs["index_num"]
    nshards = attrs["nshards"]
    shard_id = attrs["shard_id"]
    ignore_value = attrs.get("ignore_value", -1)
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return {"Out": jnp.where(in_shard, x % shard_size, ignore_value)}


@register_op("index_sample")
def _index_sample(ins, attrs):
    x, index = ins["X"][0], ins["Index"][0]
    return {"Out": jnp.take_along_axis(x, index.astype(jnp.int32),
                                       axis=1)}


@register_op("scatter_nd")
def _scatter_nd(ins, attrs):
    index, updates = ins["Index"][0], ins["Updates"][0]
    shape = attrs["shape"]
    zeros = jnp.zeros(shape, updates.dtype)
    return {"Out": zeros.at[tuple(jnp.moveaxis(
        index.astype(jnp.int32), -1, 0))].add(updates)}


@register_op("unbind")
def _unbind(ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    return {"Out": [jnp.squeeze(s, axis)
                    for s in jnp.split(x, n, axis=axis)]}


@register_op("diag")
def _diag(ins, attrs):
    x = ins["Diagonal"][0] if ins.get("Diagonal") else ins["X"][0]
    return {"Out": jnp.diag(x.reshape(-1))}


@register_op("diag_embed")
def _diag_embed(ins, attrs):
    x = ins["Input"][0] if ins.get("Input") else ins["X"][0]
    offset = attrs.get("offset", 0)
    d1 = attrs.get("dim1", -2)
    d2 = attrs.get("dim2", -1)
    n = x.shape[-1] + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    i = jnp.arange(x.shape[-1])
    r = i + max(-offset, 0)
    c = i + max(offset, 0)
    out = out.at[..., r, c].set(x)
    if (d1, d2) not in ((-2, -1), (x.ndim - 1, x.ndim)):
        out = jnp.moveaxis(out, (-2, -1), (d1, d2))
    return {"Out": out}


@register_op("diagonal")
def _diagonal(ins, attrs):
    x = ins["Input"][0] if ins.get("Input") else ins["X"][0]
    return {"Out": jnp.diagonal(x, offset=attrs.get("offset", 0),
                                axis1=attrs.get("axis1", 0),
                                axis2=attrs.get("axis2", 1))}


@register_op("reverse")
def _reverse(ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", [0])
    if isinstance(axis, int):
        axis = [axis]
    return {"Out": jnp.flip(x, axis=tuple(axis))}


@register_op("partial_sum")
def _partial_sum(ins, attrs):
    start = attrs.get("start_index", 0)
    length = attrs.get("length", -1)
    xs = ins["X"]
    end = start + length if length > 0 else xs[0].shape[1]
    return {"Out": sum(x[:, start:end] for x in xs)}


@register_op("partial_concat")
def _partial_concat(ins, attrs):
    start = attrs.get("start_index", 0)
    length = attrs.get("length", -1)
    xs = ins["X"]
    end = start + length if length > 0 else xs[0].shape[1]
    return {"Out": jnp.concatenate([x[:, start:end] for x in xs],
                                   axis=1)}


@register_op("unique_with_counts", no_jit=True,
             dynamic_shape=True)
def _unique_with_counts(ins, attrs):
    x = np.asarray(ins["X"][0]).reshape(-1)
    out, index, inverse, counts = np.unique(
        x, return_index=True, return_inverse=True, return_counts=True)
    return {"Out": out, "Index": inverse.astype(np.int64),
            "Count": counts.astype(np.int64)}


@register_op("size")
def _size(ins, attrs):
    x = ins["Input"][0] if ins.get("Input") else ins["X"][0]
    return {"Out": jnp.asarray(int(np.prod(x.shape)), jnp.int64)}


@register_op("allclose")
def _allclose(ins, attrs):
    x, y = ins["Input"][0], ins["Other"][0]
    rtol = float(attrs.get("rtol", 1e-5))
    atol = float(attrs.get("atol", 1e-8))
    return {"Out": jnp.allclose(x, y, rtol=rtol, atol=atol,
                                equal_nan=attrs.get("equal_nan", False))}


@register_op("isclose")
def _isclose(ins, attrs):
    x, y = ins["Input"][0], ins["Other"][0]
    rtol = float(attrs.get("rtol", 1e-5))
    atol = float(attrs.get("atol", 1e-8))
    return {"Out": jnp.isclose(x, y, rtol=rtol, atol=atol,
                               equal_nan=attrs.get("equal_nan", False))}


@register_op("logspace")
def _logspace(ins, attrs):
    start = ins["Start"][0].reshape(()) if ins.get("Start") else \
        attrs["start"]
    stop = ins["Stop"][0].reshape(()) if ins.get("Stop") else \
        attrs["stop"]
    try:
        num = int(ins["Num"][0]) if ins.get("Num") else attrs["num"]
    except Exception:  # traced under jit: static attr required
        num = attrs["num"]
    base = attrs.get("base", 10.0)
    return {"Out": jnp.power(base, jnp.linspace(start, stop, num))}


@register_op("split_ids")
def _split_ids(ins, attrs):
    # PS helper (reference: operators/distributed_ops/split_ids_op.cc):
    # route ids to N shards by modulo
    ids = ins["Ids"][0].reshape(-1)
    n = len(ins.get("Out_shapes", [])) or attrs.get("num_shards", 1)
    outs = []
    for shard in range(n):
        mask = (ids % n) == shard
        order = jnp.argsort(~mask, stable=True)
        g = ids[order]
        cnt = jnp.sum(mask)
        outs.append(jnp.where(jnp.arange(g.shape[0]) < cnt, g, 0))
    return {"Out": outs}


@register_op("merge_ids")
def _merge_ids(ins, attrs):
    rows = jnp.concatenate([r.reshape(-1) for r in ins["Ids"]])
    vals = jnp.concatenate([v for v in ins["X"]], axis=0)
    order = jnp.argsort(rows, stable=True)
    return {"Out": vals[order]}


@register_op("numel")
def _numel(ins, attrs):
    x = ins["Input"][0] if ins.get("Input") else ins["X"][0]
    return {"Out": jnp.asarray(int(np.prod(x.shape)), jnp.int64)}


@register_op("rank")  # helper: ndim as scalar
def _rank(ins, attrs):
    x = ins["X"][0]
    return {"Out": jnp.asarray(x.ndim, jnp.int32)}


@register_op("pad3d")
def _pad3d(ins, attrs):
    x = ins["X"][0]
    p = attrs.get("paddings", [0] * 6)
    mode = attrs.get("mode", "constant")
    value = attrs.get("value", 0.0)
    pads = ((0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1]))
    if mode == "constant":
        return {"Out": jnp.pad(x, pads, constant_values=value)}
    jmode = {"reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    return {"Out": jnp.pad(x, pads, mode=jmode)}


@register_op("broadcast_to")
def _broadcast_to(ins, attrs):
    x = ins["X"][0]
    return {"Out": jnp.broadcast_to(x, attrs["shape"])}


@register_op("expand_as")
def _expand_as(ins, attrs):
    x, y = ins["X"][0], ins["target_tensor"][0] if \
        ins.get("target_tensor") else ins["Y"][0]
    return {"Out": jnp.broadcast_to(x, y.shape)}


@register_op("gaussian_random_batch_size_like", needs_rng=True)
def _gaussian_random_bsl(ins, attrs):
    import jax as _jax

    ref = ins["Input"][0]
    shape = list(attrs.get("shape", ref.shape))
    shape[attrs.get("input_dim_idx", 0)] = ref.shape[
        attrs.get("input_dim_idx", 0)]
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    from ..core.types import to_numpy_dtype

    dtype = to_numpy_dtype(attrs.get("dtype", "float32"))
    out = mean + std * _jax.random.normal(attrs["_rng_key"],
                                          tuple(shape))
    return {"Out": out.astype(dtype)}


@register_op("fill")
def _fill(ins, attrs):
    """Out = reshape(value_list, shape) (reference: fill_op.h:43 — the
    buffer is authored host-side from the attr then copied in)."""
    from ..core.types import to_numpy_dtype

    shape = tuple(int(d) for d in attrs["shape"])
    dtype = to_numpy_dtype(attrs.get("dtype", "float32"))
    vals = np.asarray(attrs["value"], np.float64).astype(dtype)
    return {"Out": jnp.asarray(vals.reshape(shape))}


@register_op("fill_zeros_like2")
def _fill_zeros_like2(ins, attrs):
    """fill_zeros_like with an explicit dtype attr (reference:
    fill_zeros_like_op.cc FillZerosLike2)."""
    from ..core.types import to_numpy_dtype

    x = ins["X"][0]
    dtype = attrs.get("dtype")
    dt = to_numpy_dtype(dtype) if dtype is not None else x.dtype
    return {"Out": jnp.zeros(x.shape, dt)}
