"""Fused operators.

Reference parity: `paddle/fluid/operators/fused/` — CUDA kernels that
hand-fuse chains the GPU compiler can't (fused_elemwise_activation,
fused_embedding_seq_pool, fusion_gru/fusion_lstm, multihead_matmul,
fused_fc_elementwise_layernorm, fused_embedding_eltwise_layernorm).
TPU-native: these register the same op TYPES for program compatibility
but compose the unfused jnp pieces — XLA's fusion pass produces the
fused kernels the reference wrote by hand (SURVEY.md §7: fusion passes
become thin layers over the compiler)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register_op, get_op


_UNARY = {
    "relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
    "identity": lambda x: x, "": lambda x: x,
    "gelu": jax.nn.gelu,
}


def _unary(name, attrs):
    if name == "scale":
        sc = attrs.get("scale", 1.0)
        return lambda x: x * sc
    return _UNARY[name]


def _layernorm(h, eps, scale=None, bias=None):
    # shared epilogue (f32 stats like the registered layer_norm op)
    hf = h.astype(jnp.float32)
    mu = jnp.mean(hf, -1, keepdims=True)
    var = jnp.mean(jnp.square(hf - mu), -1, keepdims=True)
    out = (hf - mu) / jnp.sqrt(var + eps)
    if scale is not None:
        out = out * scale
    if bias is not None:
        out = out + bias
    return out.astype(h.dtype), mu, var

_BINARY = {
    "elementwise_add": jnp.add, "elementwise_mul": jnp.multiply,
    "elementwise_sub": jnp.subtract,
}


def _bcast(x, y, axis):
    if x.ndim == y.ndim:
        return x, y
    if axis < 0:
        axis = x.ndim - y.ndim
    return x, y.reshape((1,) * axis + y.shape
                        + (1,) * (x.ndim - axis - y.ndim))


@register_op("fused_elemwise_activation")
def _fused_elemwise_activation(ins, attrs):
    # reference: fused_elemwise_activation_op.cc — functor_list like
    # ["elementwise_add", "relu"] (binary then unary) or reversed
    x, y = ins["X"][0], ins["Y"][0]
    functors = [f.strip() for f in attrs["functor_list"]]
    axis = attrs.get("axis", -1)
    # reference fused_elemwise_activation_op.h: the FIRST functor is the
    # OUTER one — ["elementwise_add","scale"] = add(X, scale(Y)),
    # ["scale","elementwise_add"] = scale(add(X, Y))
    if functors[0] in _BINARY:
        mid = _unary(functors[1], attrs)(y)
        xb, yb = _bcast(x, mid, axis)
        out = _BINARY[functors[0]](xb, yb)
    else:
        xb, yb = _bcast(x, y, axis)
        mid = _BINARY[functors[1]](xb, yb)
        out = _unary(functors[0], attrs)(mid)
    return {"Out": out, "IntermediateOut": mid}


@register_op("fused_embedding_seq_pool")
def _fused_embedding_seq_pool(ins, attrs):
    # reference: fused_embedding_seq_pool_op.cc — lookup + sum pool
    w, ids = ins["W"][0], ins["Ids"][0]
    emb = jnp.take(w, ids.reshape(ids.shape[:2] + (-1,))[..., 0]
                   if ids.ndim > 2 else ids, axis=0)
    return {"Out": jnp.sum(emb, axis=1)}


@register_op("fused_fc_elementwise_layernorm")
def _fused_fc_eltwise_ln(ins, attrs):
    x, w = ins["X"][0], ins["W"][0]
    y = ins["Y"][0]
    h = x.reshape(x.shape[0], -1) @ w
    if ins.get("Bias0"):
        h = h + ins["Bias0"][0]
    h = h + y
    out, mu, var = _layernorm(
        h, attrs.get("epsilon", 1e-5),
        scale=ins["Scale"][0] if ins.get("Scale") else None,
        bias=ins["Bias1"][0] if ins.get("Bias1") else None)
    return {"Out": out, "Mean": mu[..., 0], "Variance": var[..., 0]}


@register_op("fused_embedding_eltwise_layernorm")
def _fused_embedding_eltwise_ln(ins, attrs):
    # reference: fused/fused_embedding_eltwise_layernorm_op.cc — sum of
    # N embeddings + layernorm (BERT input block)
    embs = []
    for w, ids in zip(ins["Embs"], ins["Ids"]):
        idx = ids.reshape(ids.shape[:2]) if ids.ndim == 3 else ids
        embs.append(jnp.take(w, idx, axis=0))
    h = sum(embs)
    out, _, _ = _layernorm(h, attrs.get("epsilon", 1e-5),
                           scale=ins["Scale"][0], bias=ins["Bias"][0])
    return {"Out": out}


@register_op("multihead_matmul")
def _multihead_matmul(ins, attrs):
    # reference: fused/multihead_matmul_op.cu — fused QKV attention for
    # inference; Input [B, S, 3*H*D] packed or separate W path
    x = ins["Input"][0]
    w = ins["W"][0]          # [D_in, 3, H, D_h]
    bias = ins["Bias"][0]    # [3, H, D_h]
    bias_qk = ins["BiasQK"][0] if ins.get("BiasQK") else None
    n_head = attrs["head_number"]
    b, s, d_in = x.shape
    qkv = jnp.einsum("bsd,dkhe->bkhse", x,
                     w.reshape(d_in, 3, n_head, -1))
    qkv = qkv + bias.reshape(1, 3, n_head, 1, -1)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # [b, h, s, dh]
    dh = q.shape[-1]
    # reference op carries the QK scale in `alpha` (exporters bake the
    # chosen scale in; do NOT override it with 1/sqrt(dh))
    alpha = attrs.get("alpha", 1.0 / math.sqrt(dh))
    scores = (q @ jnp.swapaxes(k, -1, -2)) * alpha
    if bias_qk is not None:
        scores = scores + bias_qk
    probs = jax.nn.softmax(scores, -1)
    ctx = probs @ v
    return {"Out": ctx.transpose(0, 2, 1, 3).reshape(b, s, -1)}


@register_op("fusion_gru")
def _fusion_gru(ins, attrs):
    """Reference: fused/fusion_gru_op.cc — inputs {X, WeightX (D,3H),
    WeightH (H,3H), Bias (1,3H), H0}. Paddle GRU semantics (NOT the
    torch-style r,z,n cell): gate columns are [update, reset |
    candidate]; candidate = act(x_c + (r (.) h_prev) @ W_c);
    h_t = u (.) candidate + (1-u) (.) h_prev (jit/refer/refer.h
    GRUHtPart2: out = zt*ht~ + (1-zt)*ht_1). XX is the input projection
    x @ WeightX (+bias), as the reference emits."""
    x = ins["X"][0]
    wx = ins["WeightX"][0]          # (D, 3H)
    wh = ins["WeightH"][0]          # (H, 3H)
    H = wh.shape[0]
    bias = ins["Bias"][0].reshape(-1) if ins.get("Bias") else \
        jnp.zeros((3 * H,), x.dtype)
    h0 = ins["H0"][0] if ins.get("H0") else \
        jnp.zeros((x.shape[0], H), x.dtype)
    act = _UNARY.get(attrs.get("activation", "tanh"), jnp.tanh)
    gate_act = _UNARY.get(attrs.get("gate_activation", "sigmoid"),
                          jax.nn.sigmoid)
    reverse = attrs.get("is_reverse", False)

    xx = x @ wx + bias              # [B, T, 3H]
    xs = jnp.swapaxes(xx, 0, 1)
    if reverse:
        xs = xs[::-1]
    wh_g = wh[:, :2 * H]            # update|reset recurrence
    wh_c = wh[:, 2 * H:]            # candidate recurrence

    def step(h, xp):
        g = gate_act(xp[:, :2 * H] + h @ wh_g)
        u, r = g[:, :H], g[:, H:]
        c = act(xp[:, 2 * H:] + (r * h) @ wh_c)
        h_new = u * c + (1.0 - u) * h
        return h_new, h_new

    _, hs = jax.lax.scan(step, h0, xs)
    if reverse:
        hs = hs[::-1]
    return {"Hidden": jnp.swapaxes(hs, 0, 1), "XX": xx}


@register_op("fusion_lstm")
def _fusion_lstm(ins, attrs):
    """Reference: fused/fusion_lstm_op.cc:162 — {X, WeightX (D,4H),
    WeightH (H,4H), Bias (1,4H), H0, C0}; gate columns [c, i, f, o]
    (CANDIDATE first: W = {W_cx, W_ix, W_fx, W_ox}, confirmed by
    jit/refer/refer.h:170). Emits BOTH the hidden and cell
    sequences."""
    x = ins["X"][0]
    # WeightX optional: fused_embedding_fc_lstm feeds X already projected
    wx = ins["WeightX"][0] if ins.get("WeightX") else None
    wh = ins["WeightH"][0]
    H = wh.shape[0]
    bias = ins["Bias"][0].reshape(-1)[:4 * H] if ins.get("Bias") else \
        jnp.zeros((4 * H,), x.dtype)
    h0 = ins["H0"][0] if ins.get("H0") else \
        jnp.zeros((x.shape[0], H), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros_like(h0)
    act = _UNARY.get(attrs.get("candidate_activation", "tanh"),
                     jnp.tanh)
    gate_act = _UNARY.get(attrs.get("gate_activation", "sigmoid"),
                          jax.nn.sigmoid)
    cell_act = _UNARY.get(attrs.get("cell_activation", "tanh"),
                          jnp.tanh)
    reverse = attrs.get("is_reverse", False)

    xx = (x @ wx if wx is not None else x) + bias
    xs = jnp.swapaxes(xx, 0, 1)
    if reverse:
        xs = xs[::-1]

    def step(carry, xp):
        h, c = carry
        proj = xp + h @ wh
        cand = act(proj[:, :H])
        i = gate_act(proj[:, H:2 * H])
        f = gate_act(proj[:, 2 * H:3 * H])
        o = gate_act(proj[:, 3 * H:])
        c_new = f * c + i * cand
        h_new = o * cell_act(c_new)
        return (h_new, c_new), (h_new, c_new)

    _, (hs, cs) = jax.lax.scan(step, (h0, c0), xs)
    if reverse:
        hs, cs = hs[::-1], cs[::-1]
    return {"Hidden": jnp.swapaxes(hs, 0, 1),
            "Cell": jnp.swapaxes(cs, 0, 1), "XX": xx}


@register_op("fusion_seqconv_eltadd_relu")
def _fusion_seqconv_eltadd_relu(ins, attrs):
    conv = get_op("sequence_conv").compute(
        {"X": ins["X"], "Filter": ins["Filter"]}, attrs)["Out"]
    if ins.get("Bias"):
        conv = conv + ins["Bias"][0]
    return {"Out": jax.nn.relu(conv)}


@register_op("fused_gemm_epilogue")
def _fused_gemm_epilogue(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    out = x @ y
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    act = attrs.get("activation", "none")
    if act in _UNARY:
        out = _UNARY[act](out)
    return {"Out": out}


@register_op("fused_linear_softmax_xent")
def _fused_linear_softmax_xent(ins, attrs):
    """Classifier head fused with softmax cross-entropy.

    loss[i] = -log softmax(x @ w + b)[i, label[i]], streamed over vocab
    chunks with an online logsumexp and a rematerialized scan body, so
    the [N, V] logits tensor is NEVER materialized — not in forward, not
    as a residual for backward (the chunk logits are recomputed in the
    vjp). TPU rationale: at BERT-base MLM scale ([~5k, 30522]) the
    unfused mul + softmax_with_cross_entropy chain materializes ~600MB
    of fp32 logits/log-softmax per step — pure HBM traffic — while the
    matmul itself is MXU-cheap. Matmuls accumulate in fp32 via
    preferred_element_type, so bf16 AMP inputs are safe.

    Reference counterpart: the unfused fc + softmax_with_cross_entropy
    stack (`paddle/fluid/operators/softmax_with_cross_entropy_op.cu`);
    the reference has no fused equivalent — this op exists for the TPU
    memory ceiling, and is what lets BERT batch 512 fit in 16G HBM.
    """
    x, w = ins["X"][0], ins["W"][0]
    label = ins["Label"][0]
    b = ins["Bias"][0] if ins.get("Bias") else None
    lead_shape = x.shape[:-1]
    h = x.shape[-1]
    v = w.shape[1]
    x2 = x.reshape(-1, h)
    n = x2.shape[0]
    lbl = label.reshape(-1).astype(jnp.int32)

    chunk = min(int(attrs.get("chunk_size", 8192)), v)
    n_chunks = -(-v // chunk)
    v_pad = n_chunks * chunk
    f32 = jnp.float32
    bias = (b if b is not None else jnp.zeros((v,), x.dtype)).astype(f32)
    if v_pad != v:
        # padded columns get bias -1e30 so their exp-mass is exactly 0
        w = jnp.pad(w, ((0, 0), (0, v_pad - v)))
        bias = jnp.pad(bias, (0, v_pad - v), constant_values=-1e30)

    def body(carry, start):
        m, s, picked = carry
        w_c = jax.lax.dynamic_slice_in_dim(w, start, chunk, axis=1)
        b_c = jax.lax.dynamic_slice_in_dim(bias, start, chunk)
        logits = jnp.dot(x2, w_c, preferred_element_type=f32) + b_c
        cm = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - cm) + jnp.sum(
            jnp.exp(logits - cm[:, None]), axis=-1)
        rel = lbl - start
        inside = (rel >= 0) & (rel < chunk)
        safe = jnp.clip(rel, 0, chunk - 1)
        pick = jnp.take_along_axis(logits, safe[:, None], axis=1)[:, 0]
        picked = picked + jnp.where(inside, pick, 0.0)
        return (cm, s, picked), None

    init = (jnp.full((n,), -jnp.inf, f32), jnp.zeros((n,), f32),
            jnp.zeros((n,), f32))
    starts = jnp.arange(n_chunks, dtype=jnp.int32) * chunk
    (m, s, picked), _ = jax.lax.scan(jax.checkpoint(body), init, starts)
    loss = m + jnp.log(s) - picked
    return {"Loss": loss.reshape(lead_shape + (1,))}


@register_op("fc")
def _fc(ins, attrs):
    """Fused FC (reference: fc_op.h:49): flatten Input at
    in_num_col_dims, matmul W, optional Bias broadcast-add, optional
    relu. padding_weights (cuDNN alignment trick) is meaningless under
    XLA and rejected."""
    if attrs.get("padding_weights", False):
        raise NotImplementedError(
            "fc padding_weights is a cuDNN alignment layout; XLA tiles "
            "weights itself — store W unpadded")
    x = ins["Input"][0]
    w = ins["W"][0]
    ncd = int(attrs.get("in_num_col_dims", 1))
    lead = x.shape[:ncd]
    x2 = x.reshape((-1, int(jnp.prod(jnp.asarray(x.shape[ncd:])))
                    if x.ndim > ncd else x.shape[-1]))
    out = x2 @ w
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape((1, -1))
    if attrs.get("activation_type", "") == "relu":
        out = jax.nn.relu(out)
    return {"Out": out.reshape(lead + (w.shape[1],))}


@register_op("conv2d_fusion")
def _conv2d_fusion(ins, attrs):
    """conv2d + bias + activation (+ residual) in one op (reference:
    fused/conv2d_fusion_op.cc — a cuDNN fused kernel; XLA fuses this
    composition automatically, so it is expressed as one)."""
    if attrs.get("split_channels"):
        raise NotImplementedError(
            "conv2d_fusion split_channels (multi-output slice) is not "
            "supported; emit separate conv2d ops — XLA fuses them")
    conv_out = get_op("conv2d").compute(
        {"Input": ins["Input"], "Filter": ins["Filter"]}, attrs)["Output"]
    if ins.get("Bias"):
        conv_out = conv_out + ins["Bias"][0].reshape(1, -1, 1, 1)
    if ins.get("ResidualData"):
        conv_out = conv_out + ins["ResidualData"][0]
    act = attrs.get("activation", "relu")
    if act == "relu":
        conv_out = jax.nn.relu(conv_out)
    elif act == "identity" or not act:
        pass
    else:
        raise NotImplementedError("conv2d_fusion activation %r" % act)
    return {"Output": conv_out}


@register_op("fused_batch_norm_act")
def _fused_batch_norm_act(ins, attrs):
    """batch_norm + activation (reference: fused/fused_bn_activation_op
    — a cuDNN fused kernel; composed here, XLA fuses)."""
    outs = get_op("batch_norm").compute(ins, attrs)
    act = attrs.get("act_type", "relu")
    if act == "relu":
        outs["Y"] = jax.nn.relu(outs["Y"])
    elif act:
        raise NotImplementedError("fused_batch_norm_act %r" % act)
    return outs


@register_op("fusion_seqpool_cvm_concat")
def _fusion_seqpool_cvm_concat(ins, attrs):
    """seqpool each input (SUM/AVERAGE/SQRT), apply the CVM transform
    IN PLACE on the two leading slots (reference:
    fused/fusion_seqpool_cvm_concat_op.cc:127-129 —
    dst[0] = log(show+1), dst[1] = log(click+1) - log(show+1); the
    reference supports only use_cvm=true here), concat along axis 1.
    Composes the registered sequence_pool (Length slot convention);
    XLA fuses the chain."""
    pooled = []
    lengths = ins.get("Length", [])
    ptype = attrs.get("pooltype", "SUM")
    for i, x in enumerate(ins["X"]):
        sub = {"X": [x]}
        if i < len(lengths):
            sub["Length"] = [lengths[i]]
        p = get_op("sequence_pool").compute(
            sub, {"pooltype": ptype})["Out"]
        if isinstance(p, (list, tuple)):
            p = p[0]
        show = jnp.log(p[:, :1] + 1.0)
        click = jnp.log(p[:, 1:2] + 1.0) - show
        pooled.append(jnp.concatenate([show, click, p[:, 2:]], axis=1))
    return {"Out": jnp.concatenate(pooled, axis=1)}


@register_op("fusion_transpose_flatten_concat")
def _fusion_transpose_flatten_concat(ins, attrs):
    """transpose(trans_axis) -> flatten(flatten_axis) -> concat
    (reference: fused/fusion_transpose_flatten_concat_op.cc)."""
    trans = tuple(attrs["trans_axis"])
    flat_axis = int(attrs["flatten_axis"])
    concat_axis = int(attrs["concat_axis"])
    outs = []
    for x in ins["X"]:
        t = jnp.transpose(x, trans)
        lead = 1
        for d in t.shape[:flat_axis]:
            lead *= d
        outs.append(t.reshape(lead, -1))
    return {"Out": jnp.concatenate(outs, axis=concat_axis)}


@register_op("lookup_table_dequant", no_jit=True)
def _lookup_table_dequant(ins, attrs):
    """int8-quantized embedding lookup (reference:
    lookup_table_dequant_op.h:40): each table row is [min, max,
    packed bytes] in float32 slots — 4 uint8 codes per slot; out =
    (max-min)/256 * code + min. padding_idx rows emit zeros."""
    import numpy as np

    ids = np.asarray(ins["Ids"][0]).reshape(-1).astype(np.int64)
    table = np.asarray(ins["W"][0], np.float32)
    padding_idx = int(attrs.get("padding_idx", -1))
    quant_number = table.shape[1]
    row_width = (quant_number - 2) * 4
    rows = table[ids]                                   # [N, quant]
    mins = rows[:, 0:1]
    maxs = rows[:, 1:2]
    scale = (maxs - mins) / 256.0
    codes = rows[:, 2:].astype(np.float32).view(np.uint8).reshape(
        len(ids), row_width)
    out = scale * codes.astype(np.float32) + mins
    if padding_idx != -1:
        out[ids == padding_idx] = 0.0
    # reference InferShape drops Ids' trailing 1:
    # lookup_table_dequant_op.cc:61-71
    shape = tuple(np.asarray(ins["Ids"][0]).shape)[:-1] + (row_width,)
    return {"Out": out.reshape(shape)}
