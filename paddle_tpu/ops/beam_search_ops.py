"""Beam-search operators.

Reference parity: `paddle/fluid/operators/beam_search_op.cc` (one search
step over candidate ids/scores), `beam_search_decode_op.cc` (backtrack
the beam lattice into full hypotheses), and `gather_tree` (2.0). The
reference walks LoD levels on the host; TPU-native form is static-shape
[batch, beam, ...] tensors — one jit-able step usable inside
lax.while_loop (layers.dynamic_decode drives it).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("beam_search")
def _beam_search(ins, attrs):
    """One step. ids/scores: [batch, beam, K] candidates (K=vocab or
    pre-topk), pre_ids [batch, beam], pre_scores [batch, beam].
    Outputs: selected_ids/selected_scores [batch, beam], parent_idx
    [batch, beam] (which source beam each winner came from)."""
    pre_ids = ins["pre_ids"][0]
    pre_scores = ins["pre_scores"][0]
    ids = ins["ids"][0] if ins.get("ids") else None
    scores = ins["scores"][0]
    beam_size = attrs.get("beam_size", scores.shape[1])
    end_id = attrs.get("end_id", 0)

    batch, beam, k = scores.shape
    # finished beams only propagate themselves (score frozen)
    finished = pre_ids == end_id
    total = pre_scores[..., None] + jnp.where(finished[..., None],
                                              0.0, scores)
    # a finished beam keeps exactly one candidate (its end token)
    cand_mask = jnp.where(
        finished[..., None],
        jnp.arange(k)[None, None, :] == 0,
        jnp.ones((1, 1, k), bool))
    neg = jnp.finfo(total.dtype).min
    total = jnp.where(cand_mask, total, neg)

    flat = total.reshape(batch, beam * k)
    top_scores, top_idx = jax.lax.top_k(flat, beam_size)
    parent = (top_idx // k).astype(jnp.int64)
    cand_pos = top_idx % k
    if ids is None:
        sel_ids = cand_pos.astype(jnp.int64)
    else:
        sel_ids = jnp.take_along_axis(
            ids.reshape(batch, beam * k),
            top_idx, axis=1).astype(jnp.int64)
    parent_fin = jnp.take_along_axis(finished, parent, axis=1)
    sel_ids = jnp.where(parent_fin, end_id, sel_ids)
    return {"selected_ids": sel_ids, "selected_scores": top_scores,
            "parent_idx": parent}


@register_op("gather_tree")
def _gather_tree(ins, attrs):
    """ids/parents: [T, batch, beam] -> backtracked full sequences."""
    ids, parents = ins["Ids"][0], ins["Parents"][0]
    t = ids.shape[0]

    def body(carry, xs):
        beam_idx = carry  # [batch, beam]
        step_ids, step_parents = xs
        out = jnp.take_along_axis(step_ids, beam_idx, axis=1)
        nxt = jnp.take_along_axis(step_parents, beam_idx, axis=1)
        return nxt.astype(beam_idx.dtype), out

    init = jnp.broadcast_to(
        jnp.arange(ids.shape[2], dtype=jnp.int64)[None, :],
        ids.shape[1:]).astype(jnp.int64)
    _, outs = jax.lax.scan(body, init, (ids[::-1], parents[::-1]))
    return {"Out": outs[::-1]}


@register_op("beam_search_decode")
def _beam_search_decode(ins, attrs):
    """Backtrack stacked per-step ids/parents into final sequences.
    Inputs Ids/ParentIdx: [T, batch, beam]; SentenceIds = backtracked
    token lattice, SentenceScores = final beam scores broadcast."""
    ids = ins["Ids"][0]
    parents = ins["ParentIdx"][0]
    scores = ins["Scores"][0] if ins.get("Scores") else None
    out = _gather_tree({"Ids": [ids], "Parents": [parents]}, {})["Out"]
    res = {"SentenceIds": out}
    if scores is not None:
        res["SentenceScores"] = scores
    return res
