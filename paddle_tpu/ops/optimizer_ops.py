"""Optimizer update operators.

Reference parity: `paddle/fluid/operators/optimizers/` — sgd, momentum
(+nesterov, lars), adam/adamax/adamw, adagrad/adadelta/decayed_adagrad,
rmsprop, ftrl, lamb, dpsgd — each with .cc+.cu kernels there; here each is a
pure functional update XLA fuses into one kernel per parameter (or one fused
update when the whole train step is jitted).

All follow the framework convention: Param/Grad/<state> inputs,
ParamOut/<state>Out outputs; the lowering aliases ParamOut back onto the
Param variable name (donated buffers — in-place on TPU).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _lr(ins):
    return ins["LearningRate"][0].reshape(()).astype(jnp.float32)


@register_op("sgd")
def _sgd(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = _lr(ins).astype(p.dtype)
    return {"ParamOut": p - lr * g.astype(p.dtype)}


@register_op("momentum")
def _momentum(ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    lr = _lr(ins).astype(p.dtype)
    mu = attrs.get("mu", 0.9)
    g = g.astype(p.dtype)
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": p_out, "VelocityOut": v_out}


@register_op("lars_momentum")
def _lars_momentum(ins, attrs):
    # reference: optimizers/lars_momentum_op.cc — layer-wise adaptive LR
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    lr = _lr(ins)
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    wd = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 0.0)
    pf, gf = p.astype(jnp.float32), g.astype(jnp.float32)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(gf)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * coeff * p_norm / (g_norm + wd * p_norm + eps), lr)
    v_out = mu * v.astype(jnp.float32) + local_lr * (gf + wd * pf)
    p_out = pf - v_out
    return {"ParamOut": p_out.astype(p.dtype),
            "VelocityOut": v_out.astype(v.dtype)}


@register_op("adam")
def _adam(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    lr = _lr(ins)
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    gf = g.astype(jnp.float32)
    m1o = b1 * m1 + (1 - b1) * gf
    m2o = b2 * m2 + (1 - b2) * jnp.square(gf)
    b1pf = b1p.reshape(()).astype(jnp.float32)
    b2pf = b2p.reshape(()).astype(jnp.float32)
    alpha = lr * jnp.sqrt(1 - b2pf * b2) / (1 - b1pf * b1)
    p_out = p.astype(jnp.float32) - alpha * m1o / (jnp.sqrt(m2o) + eps)
    return {"ParamOut": p_out.astype(p.dtype), "Moment1Out": m1o,
            "Moment2Out": m2o, "Beta1PowOut": b1p * b1,
            "Beta2PowOut": b2p * b2}


@register_op("adamw")
def _adamw(ins, attrs):
    coeff = attrs.get("coeff", attrs.get("weight_decay", 0.01))
    outs = _adam(ins, attrs)
    p = ins["Param"][0]
    lr = _lr(ins).astype(jnp.float32)
    if attrs.get("with_decay", True):
        decayed = outs["ParamOut"].astype(jnp.float32) \
            - lr * coeff * p.astype(jnp.float32)
        outs["ParamOut"] = decayed.astype(p.dtype)
    return outs


@register_op("adamax")
def _adamax(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, n = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0].reshape(()).astype(jnp.float32)
    lr = _lr(ins)
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    gf = g.astype(jnp.float32)
    m_out = b1 * m + (1 - b1) * gf
    n_out = jnp.maximum(b2 * n, jnp.abs(gf))
    p_out = p.astype(jnp.float32) - (lr / (1 - b1p)) * (m_out / (n_out + eps))
    return {"ParamOut": p_out.astype(p.dtype), "MomentOut": m_out,
            "InfNormOut": n_out}


@register_op("adagrad")
def _adagrad(ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = _lr(ins)
    eps = attrs.get("epsilon", 1e-6)
    gf = g.astype(jnp.float32)
    m_out = m + jnp.square(gf)
    p_out = p.astype(jnp.float32) - lr * gf / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out.astype(p.dtype), "MomentOut": m_out}


@register_op("decayed_adagrad")
def _decayed_adagrad(ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = _lr(ins)
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    gf = g.astype(jnp.float32)
    m_out = decay * m + (1 - decay) * jnp.square(gf)
    p_out = p.astype(jnp.float32) - lr * gf / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out.astype(p.dtype), "MomentOut": m_out}


@register_op("adadelta")
def _adadelta(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    avg_sq_g, avg_sq_u = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    gf = g.astype(jnp.float32)
    g_acc = rho * avg_sq_g + (1 - rho) * jnp.square(gf)
    update = -jnp.sqrt((avg_sq_u + eps) / (g_acc + eps)) * gf
    u_acc = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    p_out = p.astype(jnp.float32) + update
    return {"ParamOut": p_out.astype(p.dtype),
            "AvgSquaredGradOut": g_acc, "AvgSquaredUpdateOut": u_acc}


@register_op("rmsprop")
def _rmsprop(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    lr = _lr(ins)
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    centered = attrs.get("centered", False)
    gf = g.astype(jnp.float32)
    ms_out = rho * ms + (1 - rho) * jnp.square(gf)
    if centered:
        mg = ins["MeanGrad"][0]
        mg_out = rho * mg + (1 - rho) * gf
        denom = ms_out - jnp.square(mg_out) + eps
    else:
        mg_out = None
        denom = ms_out + eps
    mom_out = momentum * mom + lr * gf / jnp.sqrt(denom)
    p_out = p.astype(jnp.float32) - mom_out
    outs = {"ParamOut": p_out.astype(p.dtype), "MeanSquareOut": ms_out,
            "MomentOut": mom_out}
    if mg_out is not None:
        outs["MeanGradOut"] = mg_out
    return outs


@register_op("ftrl")
def _ftrl(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    lr = _lr(ins)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    gf = g.astype(jnp.float32)
    new_sq = sq + jnp.square(gf)
    sigma = (new_sq ** (-lr_power) - sq ** (-lr_power)) / lr
    lin_out = lin + gf - sigma * p.astype(jnp.float32)
    x = jnp.clip(lin_out, -l1, l1) - lin_out
    y = new_sq ** (-lr_power) / lr + 2 * l2
    p_out = x / y
    return {"ParamOut": p_out.astype(p.dtype), "SquaredAccumOut": new_sq,
            "LinearAccumOut": lin_out}


@register_op("lamb")
def _lamb(ins, attrs):
    # reference: optimizers/lamb_op.cc — layer-adaptive large-batch Adam
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p = ins["Beta1Pow"][0].reshape(()).astype(jnp.float32)
    b2p = ins["Beta2Pow"][0].reshape(()).astype(jnp.float32)
    lr = _lr(ins)
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    gf = g.astype(jnp.float32)
    pf = p.astype(jnp.float32)
    m1o = b1 * m1 + (1 - b1) * gf
    m2o = b2 * m2 + (1 - b2) * jnp.square(gf)
    m1hat = m1o / (1 - b1p * b1)
    m2hat = m2o / (1 - b2p * b2)
    r = m1hat / (jnp.sqrt(m2hat) + eps) + wd * pf
    p_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    p_out = pf - lr * trust * r
    return {"ParamOut": p_out.astype(p.dtype), "Moment1Out": m1o,
            "Moment2Out": m2o, "Beta1PowOut": ins["Beta1Pow"][0] * b1,
            "Beta2PowOut": ins["Beta2Pow"][0] * b2}


@register_op("dpsgd", needs_rng=True)
def _dpsgd(ins, attrs):
    import jax

    p, g = ins["Param"][0], ins["Grad"][0]
    lr = _lr(ins)
    clip = attrs.get("clip", 10.0)
    sigma = attrs.get("sigma", 1.0)
    batch_size = attrs.get("batch_size", 16.0)
    gf = g.astype(jnp.float32)
    g_norm = jnp.sqrt(jnp.sum(jnp.square(gf)))
    gf = gf / jnp.maximum(1.0, g_norm / clip)
    noise = jax.random.normal(attrs["_rng_key"], g.shape) * sigma * clip
    p_out = p.astype(jnp.float32) - lr / batch_size * (gf + noise)
    return {"ParamOut": p_out.astype(p.dtype)}


@register_op("lookahead_step")
def _lookahead_step(ins, attrs):
    """Lookahead slow-weight update (reference: optimizer.py:4777
    LookaheadOptimizer). Runs every step; the interpolation + snap-back
    applies only when the step counter hits a multiple of k."""
    p, slow = ins["Param"][0], ins["SlowParam"][0]
    step = ins["Step"][0]
    alpha = attrs.get("alpha", 0.5)
    k = int(attrs.get("k", 5))
    do = (jnp.reshape(step, ()).astype(jnp.int32) % k) == 0
    pf, sf = p.astype(jnp.float32), slow.astype(jnp.float32)
    slow2 = jnp.where(do, sf + alpha * (pf - sf), sf)
    p2 = jnp.where(do, slow2, pf)
    return {"ParamOut": p2.astype(p.dtype),
            "SlowParamOut": slow2.astype(slow.dtype)}


@register_op("proximal_gd")
def _proximal_gd(ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = _lr(ins)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p.astype(jnp.float32) - lr * g.astype(jnp.float32)
    out = jnp.sign(prox) * jnp.maximum(
        jnp.abs(prox) - lr * l1, 0.0) / (1.0 + lr * l2)
    return {"ParamOut": out.astype(p.dtype)}


@register_op("proximal_adagrad")
def _proximal_adagrad(ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = _lr(ins)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    gf = g.astype(jnp.float32)
    m_out = m + jnp.square(gf)
    alr = lr / jnp.sqrt(m_out)
    prox = p.astype(jnp.float32) - alr * gf
    out = jnp.sign(prox) * jnp.maximum(
        jnp.abs(prox) - alr * l1, 0.0) / (1.0 + alr * l2)
    return {"ParamOut": out.astype(p.dtype), "MomentOut": m_out}


@register_op("dgc_momentum")
def _dgc_momentum(ins, attrs):
    """Reference `optimizers/dgc_momentum_op.cc`: momentum update while
    current_step < rampup_begin_step (dense warmup), plain SGD after
    (the dgc op's own momentum correction takes over, so running
    momentum here too would double-apply it)."""
    p = ins["Param"][0]
    g = ins["Grad"][0]
    v = ins["Velocity"][0]
    lr = ins["LearningRate"][0].reshape(())
    step = ins["CurrentStep"][0].reshape(())
    mu = attrs.get("mu", 0.9)
    rampup = float(attrs.get("rampup_begin_step", 0.0))
    use_nesterov = attrs.get("use_nesterov", False)

    warm = step < rampup
    v_new = mu * v + g
    if use_nesterov:
        p_momentum = p - lr * (g + mu * v_new)
    else:
        p_momentum = p - lr * v_new
    p_sgd = p - lr * g
    p_out = jnp.where(warm, p_momentum, p_sgd)
    v_out = jnp.where(warm, v_new, v)
    return {"ParamOut": p_out, "VelocityOut": v_out}


@register_op("average_accumulates")
def _average_accumulates(ins, attrs):
    """Sliding-window parameter-sum accumulator for ModelAverage
    (reference: average_accumulates_op.h:41). Per step: sum_1 += param,
    counters ++; precision shuffle every 16384 updates folds sum_1 into
    sum_2; when the window overflows (num_accumulates >= min_window and
    >= min(max_window, num_updates*average_window)) rotate:
    sum_3 <- sum_1+sum_2, zero sum_1/sum_2, old_num <- num (REPLACED),
    num <- 0. Masked jnp.where keeps it one jittable computation."""
    p = ins["Param"][0]
    s1 = ins["in_sum_1"][0]
    s2 = ins["in_sum_2"][0]
    s3 = ins["in_sum_3"][0]
    num = ins["in_num_accumulates"][0].reshape(()).astype(jnp.int64)
    old = ins["in_old_num_accumulates"][0].reshape(()).astype(jnp.int64)
    upd = ins["in_num_updates"][0].reshape(()).astype(jnp.int64)
    avg_win = attrs.get("average_window", 0.0)
    # int32-safe "unbounded" default: jnp would overflow on 2**62 with
    # x64 disabled (the repo default)
    max_win = min(int(attrs.get("max_average_window", 2 ** 31 - 1)),
                  2 ** 31 - 1)
    min_win = attrs.get("min_average_window", 10000)
    k_max_acc = 16384  # reference kMaxNumAccumulates

    upd = upd + 1
    num = num + 1
    s1 = s1 + p
    shuffle = (upd % k_max_acc) == 0
    s2 = jnp.where(shuffle, s1 + s2, s2)
    s1 = jnp.where(shuffle, jnp.zeros_like(s1), s1)

    thresh = jnp.minimum(
        jnp.asarray(max_win, num.dtype),
        (upd.astype(jnp.float32) * avg_win).astype(num.dtype))
    rotate = (num >= min_win) & (num >= thresh)
    s3 = jnp.where(rotate, s1 + s2, s3)
    s1 = jnp.where(rotate, jnp.zeros_like(s1), s1)
    s2 = jnp.where(rotate, jnp.zeros_like(s2), s2)
    old = jnp.where(rotate, num, old)
    num = jnp.where(rotate, jnp.int64(0), num)
    return {"out_sum_1": s1, "out_sum_2": s2, "out_sum_3": s3,
            "out_num_accumulates": num.reshape((1,)),
            "out_old_num_accumulates": old.reshape((1,)),
            "out_num_updates": upd.reshape((1,))}


@register_op("dgc_clip_by_norm")
def _dgc_clip_by_norm(ins, attrs):
    """clip_by_norm gated on the DGC rampup step (reference:
    dgc_clip_by_norm_op.h:23 — delegates to the registered clip_by_norm
    exactly as the reference kernel inherits ClipByNormKernel; both
    sides of the comparison truncate to int, mirroring the
    static_cast<int> semantics)."""
    from .math_ops import _clip_by_norm

    x = ins["X"][0]
    rampup = int(float(attrs.get("rampup_begin_step", 0.0)))
    if rampup < 0:  # reference: negative rampup disables clipping
        return {"Out": x}
    step = ins["current_step"][0].reshape(()).astype(jnp.int32) \
        if ins.get("current_step") else jnp.int32(0)
    clipped = _clip_by_norm(
        {"X": [x]}, {"max_norm": attrs.get("max_norm", 1.0)})["Out"]
    return {"Out": jnp.where(step >= rampup, clipped, x)}


# ---------------------------------------------------------------------------
# Coalesced optimizer updates (reference: the fuse_optimizer_ops_pass
# family, framework/ir/fuse_optimizer_ops_pass/ — per-group fused sgd/
# momentum/adam kernels over coalesced gradient buffers). Here the group
# flattens into ONE [total] vector so the update lowers to a handful of
# HLO ops instead of ~6 per parameter: on ResNet50 the per-param
# optimizer chains were ~60% of the train step's StableHLO lines.
# Exact math preservation: elementwise updates are concat/split-stable;
# per-parameter scalars (adam beta pows) broadcast into their segment.
# ---------------------------------------------------------------------------

def _concat_flat(tensors, dtype=None):
    return jnp.concatenate([
        (t if dtype is None else t.astype(dtype)).reshape(-1)
        for t in tensors])


def _split_back(vec, like):
    import numpy as np

    outs, off = [], 0
    for t in like:
        size = int(np.prod(t.shape)) if t.shape else 1
        outs.append(vec[off:off + size].reshape(t.shape))
        off += size
    return outs


@register_op("fused_sgd")
def _fused_sgd(ins, attrs):
    ps, gs = ins["Param"], ins["Grad"]
    lr = _lr(ins).astype(ps[0].dtype)
    pc = _concat_flat(ps)
    gc = _concat_flat(gs, ps[0].dtype)
    return {"ParamOut": _split_back(pc - lr * gc, ps)}


@register_op("fused_momentum")
def _fused_momentum(ins, attrs):
    ps, gs, vs = ins["Param"], ins["Grad"], ins["Velocity"]
    dtype = ps[0].dtype
    lr = _lr(ins).astype(dtype)
    mu = attrs.get("mu", 0.9)
    pc = _concat_flat(ps)
    gc = _concat_flat(gs, dtype)
    vc = _concat_flat(vs)
    v_out = mu * vc + gc
    if attrs.get("use_nesterov", False):
        p_out = pc - (gc + mu * v_out) * lr
    else:
        p_out = pc - lr * v_out
    return {"ParamOut": _split_back(p_out, ps),
            "VelocityOut": _split_back(v_out, vs)}


@register_op("fused_adam")
def _fused_adam(ins, attrs):
    import numpy as np

    ps, gs = ins["Param"], ins["Grad"]
    m1s, m2s = ins["Moment1"], ins["Moment2"]
    b1ps, b2ps = ins["Beta1Pow"], ins["Beta2Pow"]
    lr = _lr(ins)
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    pc = _concat_flat(ps, jnp.float32)
    gc = _concat_flat(gs, jnp.float32)
    m1c = _concat_flat(m1s)
    m2c = _concat_flat(m2s)
    m1o = b1 * m1c + (1 - b1) * gc
    m2o = b2 * m2c + (1 - b2) * jnp.square(gc)
    # per-parameter bias-corrected step size, broadcast into segments —
    # beta pows are per-param state vars, so equality across the group
    # is NOT assumed
    sizes = [int(np.prod(p.shape)) if p.shape else 1 for p in ps]
    alphas = []
    for b1p, b2p, size in zip(b1ps, b2ps, sizes):
        b1pf = b1p.reshape(()).astype(jnp.float32)
        b2pf = b2p.reshape(()).astype(jnp.float32)
        a = lr * jnp.sqrt(1 - b2pf * b2) / (1 - b1pf * b1)
        alphas.append(jnp.broadcast_to(a, (size,)))
    alpha_vec = jnp.concatenate(alphas)
    p_out = pc - alpha_vec * m1o / (jnp.sqrt(m2o) + eps)
    return {
        "ParamOut": [o.astype(p.dtype) for o, p in
                     zip(_split_back(p_out, ps), ps)],
        "Moment1Out": _split_back(m1o, m1s),
        "Moment2Out": _split_back(m2o, m2s),
        "Beta1PowOut": [b1p * b1 for b1p in b1ps],
        "Beta2PowOut": [b2p * b2 for b2p in b2ps],
    }
