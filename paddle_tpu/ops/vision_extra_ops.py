"""Vision extras: unpooling, deformable convolution, depthwise transposed
convolution, circular correlation, precise / position-sensitive RoI
pooling, 3D max-pool-with-index, bilateral slicing.

Reference parity: `paddle/fluid/operators/unpool_op.cc`,
`deformable_conv_op.cc` / `deformable_conv_v1_op.cc`,
`conv_transpose_op.cc` (depthwise_conv2d_transpose),
`conv_shift_op.cc`, `detection/prroi_pool_op.cc`, `psroi_pool_op.cc`,
`max_pool_with_index_op.cc` (3D variant), `bilateral_slice_op.cc`.

TPU-native design notes: everything stays dense and statically shaped —
deformable sampling is one vectorized bilinear gather feeding a single
MXU einsum; PrRoI pooling uses the closed-form separable integral of the
bilinear hat function (exact, no sampling loop); PSRoI uses masked means
over the full feature map instead of data-dependent slicing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op


@register_op("unpool")
def _unpool(ins, attrs):
    """Max-unpool2d: scatter X into zeros at Indices (flat h*w positions
    inside each [N, C] plane, as produced by max_pool2d_with_index)."""
    x, idx = ins["X"][0], ins["Indices"][0]
    oh, ow = attrs["unpooled_height"], attrs["unpooled_width"]
    n, c, h, w = x.shape
    flat = jnp.zeros((n, c, oh * ow), x.dtype)
    out = jax.vmap(jax.vmap(
        lambda f, i, v: f.at[i].set(v)))(
            flat, idx.reshape(n, c, -1).astype(jnp.int32),
            x.reshape(n, c, -1))
    return {"Out": out.reshape(n, c, oh, ow)}


@register_op("max_pool3d_with_index")
def _max_pool3d_with_index(ins, attrs):
    x = ins["X"][0]
    ksize = attrs.get("ksize", [2, 2, 2])
    stride = attrs.get("strides", ksize)
    pad = attrs.get("paddings", [0, 0, 0])
    n, c, d, h, w = x.shape
    kd, kh, kw = ksize
    xp = jnp.pad(x, ((0, 0), (0, 0)) + tuple((p, p) for p in pad),
                 constant_values=-jnp.inf)
    od = (d + 2 * pad[0] - kd) // stride[0] + 1
    oh = (h + 2 * pad[1] - kh) // stride[1] + 1
    ow = (w + 2 * pad[2] - kw) // stride[2] + 1
    i_d = jnp.arange(od)[:, None] * stride[0] + jnp.arange(kd)[None, :]
    i_h = jnp.arange(oh)[:, None] * stride[1] + jnp.arange(kh)[None, :]
    i_w = jnp.arange(ow)[:, None] * stride[2] + jnp.arange(kw)[None, :]
    wins = xp[:, :, i_d[:, :, None, None, None, None],
              i_h[None, None, :, :, None, None],
              i_w[None, None, None, None, :, :]]
    # [n,c,od,kd,oh,kh,ow,kw] -> [n,c,od,oh,ow,kd*kh*kw]
    wins = wins.transpose(0, 1, 2, 4, 6, 3, 5, 7).reshape(
        n, c, od, oh, ow, kd * kh * kw)
    out = jnp.max(wins, -1)
    amax = jnp.argmax(wins, -1)
    rd = amax // (kh * kw) + i_d[:, 0][None, None, :, None, None] - pad[0]
    rh = (amax // kw) % kh + i_h[:, 0][None, None, None, :, None] - pad[1]
    rw = amax % kw + i_w[:, 0][None, None, None, None, :] - pad[2]
    flat = ((rd * h + rh) * w + rw).astype(jnp.int64)
    return {"Out": out, "Mask": flat}


@register_op("depthwise_conv2d_transpose")
def _depthwise_conv2d_transpose(ins, attrs):
    """groups == in_channels transposed conv: filter [C, 1, kh, kw]."""
    x, w = ins["Input"][0], ins["Filter"][0]
    stride = attrs.get("strides", [1, 1])
    pad = attrs.get("paddings", [0, 0])
    dilation = attrs.get("dilations", [1, 1])
    c = x.shape[1]
    kh, kw = w.shape[2], w.shape[3]
    # transposed conv == lhs-dilated conv with flipped kernel
    w_flip = w[:, :, ::-1, ::-1]
    out = lax.conv_general_dilated(
        x, w_flip,
        window_strides=(1, 1),
        padding=((dilation[0] * (kh - 1) - pad[0],
                  dilation[0] * (kh - 1) - pad[0]),
                 (dilation[1] * (kw - 1) - pad[1],
                  dilation[1] * (kw - 1) - pad[1])),
        lhs_dilation=tuple(stride),
        rhs_dilation=tuple(dilation),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=c)
    return {"Output": out}


@register_op("conv_shift")
def _conv_shift(ins, attrs):
    """Circular correlation (conv_shift_op.cc): X [B,N], Y [B,M] (M odd),
    out[b,j] = sum_k X[b, (j + k - M//2) mod N] * Y[b, k]."""
    x, y = ins["X"][0], ins["Y"][0]
    n, m = x.shape[1], y.shape[1]
    k = jnp.arange(m) - m // 2
    idx = (jnp.arange(n)[:, None] + k[None, :]) % n   # [N, M]
    return {"Out": jnp.einsum("bnm,bm->bn", x[:, idx], y)}


def _bilinear_sample_nchw(x, py, px):
    """Sample x [C, H, W] at fractional (py, px) [...], zero outside."""
    h, w = x.shape[1], x.shape[2]
    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy = py - y0
    wx = px - x0
    out = 0.0
    for dy, wyy in ((0, 1.0 - wy), (1, wy)):
        for dx, wxx in ((0, 1.0 - wx), (1, wx)):
            yy = (y0 + dy).astype(jnp.int32)
            xx = (x0 + dx).astype(jnp.int32)
            valid = ((yy >= 0) & (yy < h) & (xx >= 0) & (xx < w))
            yc = jnp.clip(yy, 0, h - 1)
            xc = jnp.clip(xx, 0, w - 1)
            v = x[:, yc, xc]                       # [C, ...]
            out = out + v * (wyy * wxx * valid.astype(x.dtype))[None]
    return out


def _deformable_conv(ins, attrs, modulated):
    x, offset, weight = ins["Input"][0], ins["Offset"][0], ins["Filter"][0]
    mask = ins["Mask"][0] if (modulated and ins.get("Mask")) else None
    stride = attrs.get("strides", [1, 1])
    pad = attrs.get("paddings", [0, 0])
    dil = attrs.get("dilations", [1, 1])
    groups = int(attrs.get("groups", 1))
    dg = int(attrs.get("deformable_groups", 1))
    n, c, h, w = x.shape
    cout, c_g, kh, kw = weight.shape
    ho = (h + 2 * pad[0] - (dil[0] * (kh - 1) + 1)) // stride[0] + 1
    wo = (w + 2 * pad[1] - (dil[1] * (kw - 1) + 1)) // stride[1] + 1

    off = offset.reshape(n, dg, kh * kw, 2, ho, wo)
    base_y = (jnp.arange(ho) * stride[0] - pad[0])[None, :, None]
    base_x = (jnp.arange(wo) * stride[1] - pad[1])[None, None, :]
    ky = (jnp.arange(kh * kw) // kw * dil[0])[:, None, None]
    kx = (jnp.arange(kh * kw) % kw * dil[1])[:, None, None]
    py = base_y + ky + off[:, :, :, 0]             # [n, dg, K, ho, wo]
    px = base_x + kx + off[:, :, :, 1]

    def sample_one(xi, pyi, pxi):
        # xi [C,H,W]; pyi/pxi [dg, K, ho, wo]
        xg = xi.reshape(dg, c // dg, h, w)
        samp = jax.vmap(_bilinear_sample_nchw)(xg, pyi, pxi)
        return samp.reshape(c, kh * kw, ho, wo)

    cols = jax.vmap(sample_one)(x, py, px)
    if mask is not None:
        ms = mask.reshape(n, dg, 1, kh * kw, ho, wo)
        cols = (cols.reshape(n, dg, c // dg, kh * kw, ho, wo)
                * ms).reshape(n, c, kh * kw, ho, wo)
    wg = weight.reshape(groups, cout // groups, c_g * kh * kw)
    colsg = cols.reshape(n, groups, c_g * kh * kw, ho, wo)
    out = jnp.einsum("gok,ngkhw->ngohw", wg, colsg)
    return {"Output": out.reshape(n, cout, ho, wo)}


@register_op("deformable_conv")
def _deformable_conv_v2(ins, attrs):
    return _deformable_conv(ins, attrs, modulated=True)


@register_op("deformable_conv_v1")
def _deformable_conv_v1(ins, attrs):
    return _deformable_conv(ins, attrs, modulated=False)


def _roi_batch_ids(ins, n_rois):
    """Per-ROI image index from rois-per-image counts. Reference
    prroi_pool_op.h:282-289 expands BatchRoINums ([N] int64 counts) to a
    per-ROI batch id; `RoisNum` is the same convention used by the repo's
    detection ops."""
    counts = None
    for slot in ("BatchRoINums", "RoisNum"):
        if ins.get(slot):
            counts = ins[slot][0].reshape((-1,))
            break
    if counts is None:
        return jnp.zeros((n_rois,), jnp.int32)
    bounds = jnp.cumsum(counts)
    return jnp.sum(jnp.arange(n_rois)[:, None] >= bounds[None, :],
                   axis=1).astype(jnp.int32)


def _hat_integral(lo, hi, p):
    """∫ max(0, 1-|t-p|) dt over [lo, hi] (closed form, exact)."""

    def seg(a, b):
        # integral of (1 - |t|) for t in [a, b] ⊂ [-1, 1]
        a = jnp.clip(a, -1.0, 1.0)
        b = jnp.clip(b, -1.0, 1.0)
        def anti(t):
            return jnp.where(t >= 0, t - 0.5 * t * t, t + 0.5 * t * t)
        return anti(b) - anti(a)

    return seg(lo - p, hi - p)


@register_op("prroi_pool")
def _prroi_pool(ins, attrs):
    """Precise RoI pooling: exact integral of the bilinearly-interpolated
    feature over each bin (separable hat-function integral)."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape
    roi_batch = _roi_batch_ids(ins, rois.shape[0])

    px_grid = jnp.arange(w, dtype=x.dtype)
    py_grid = jnp.arange(h, dtype=x.dtype)

    def pool_one(roi, bi):
        x1, y1, x2, y2 = roi[0] * scale, roi[1] * scale, \
            roi[2] * scale, roi[3] * scale
        bw = jnp.maximum((x2 - x1) / pw, 1e-6)
        bh = jnp.maximum((y2 - y1) / ph, 1e-6)
        feat = x[bi]                                  # [C, H, W]
        i = jnp.arange(ph, dtype=x.dtype)
        j = jnp.arange(pw, dtype=x.dtype)
        y_lo = y1 + i * bh
        x_lo = x1 + j * bw
        wy = jax.vmap(lambda lo: _hat_integral(lo, lo + bh, py_grid))(y_lo)
        wx = jax.vmap(lambda lo: _hat_integral(lo, lo + bw, px_grid))(x_lo)
        out = jnp.einsum("ih,jw,chw->cij", wy, wx, feat)
        return out / (bw * bh)

    out = jax.vmap(pool_one)(rois, roi_batch)
    return {"Out": out}


@register_op("psroi_pool")
def _psroi_pool(ins, attrs):
    """Position-sensitive RoI pooling: C = out_c*ph*pw input channels;
    bin (i,j) average-pools channel slice (k, i, j) over its region."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    out_c = int(attrs.get("output_channels"))
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape
    roi_batch = _roi_batch_ids(ins, rois.shape[0])
    xs = x.reshape(n, out_c, ph, pw, h, w)
    ys = jnp.arange(h, dtype=x.dtype)
    xcol = jnp.arange(w, dtype=x.dtype)

    def pool_one(roi, bi):
        x1 = jnp.round(roi[0]) * scale
        y1 = jnp.round(roi[1]) * scale
        x2 = jnp.round(roi[2] + 1.0) * scale
        y2 = jnp.round(roi[3] + 1.0) * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw, bh = rw / pw, rh / ph
        i = jnp.arange(ph, dtype=x.dtype)
        j = jnp.arange(pw, dtype=x.dtype)
        hs = jnp.floor(y1 + i * bh)
        he = jnp.ceil(y1 + (i + 1.0) * bh)
        wss = jnp.floor(x1 + j * bw)
        wee = jnp.ceil(x1 + (j + 1.0) * bw)
        my = ((ys[None, :] >= hs[:, None]) &
              (ys[None, :] < he[:, None])).astype(x.dtype)   # [ph, H]
        mx = ((xcol[None, :] >= wss[:, None]) &
              (xcol[None, :] < wee[:, None])).astype(x.dtype)  # [pw, W]
        feat = xs[bi]                                  # [oc, ph, pw, H, W]
        s = jnp.einsum("ih,jw,kijhw->kij", my, mx, feat)
        cnt = jnp.maximum(my.sum(1)[:, None] * mx.sum(1)[None, :], 1.0)
        return s / cnt[None]

    return {"Out": jax.vmap(pool_one)(rois, roi_batch)}


@register_op("bilateral_slice")
def _bilateral_slice(ins, attrs):
    """HDRNet bilateral slicing (bilateral_slice_op.cc): trilinearly
    sample an affine-coefficient grid at (x, y, guide) and apply it."""
    x, grid, guide = ins["X"][0], ins["Grid"][0], ins["Guide"][0]
    has_offset = bool(attrs.get("has_offset", False))
    n, c_in, h, w = x.shape
    _, gc, gd, gh, gw = grid.shape
    coeff_stride = c_in + 1 if has_offset else c_in
    c_out = gc // coeff_stride

    gy = (jnp.arange(h, dtype=x.dtype) + 0.5) * gh / h - 0.5
    gx = (jnp.arange(w, dtype=x.dtype) + 0.5) * gw / w - 0.5
    z = guide * gd - 0.5                                # [N, H, W]
    y = jnp.broadcast_to(gy[:, None], (h, w))
    xg = jnp.broadcast_to(gx[None, :], (h, w))

    def sample_n(g, zn):
        # g [gc, gd, gh, gw]; zn [H, W]
        acc = jnp.zeros((gc, h, w), x.dtype)
        z0 = jnp.floor(zn)
        y0 = jnp.floor(y)
        x0 = jnp.floor(xg)
        for dz in (0, 1):
            for dy in (0, 1):
                for dx in (0, 1):
                    zz = jnp.clip(z0 + dz, 0, gd - 1).astype(jnp.int32)
                    yy = jnp.clip(y0 + dy, 0, gh - 1).astype(jnp.int32)
                    xx = jnp.clip(x0 + dx, 0, gw - 1).astype(jnp.int32)
                    wgt = (jnp.maximum(0.0, 1.0 - jnp.abs(zn - (z0 + dz)))
                           * jnp.maximum(0.0, 1.0 - jnp.abs(y - (y0 + dy)))
                           * jnp.maximum(0.0,
                                         1.0 - jnp.abs(xg - (x0 + dx))))
                    acc = acc + g[:, zz, yy, xx] * wgt[None]
        return acc

    coeffs = jax.vmap(sample_n)(grid, z)                # [N, gc, H, W]
    co = coeffs.reshape(n, c_out, coeff_stride, h, w)
    out = jnp.einsum("nochw,nchw->nohw", co[:, :, :c_in], x)
    if has_offset:
        out = out + co[:, :, c_in]
    return {"Out": out}
