"""Fake-quantization operators (QAT/PTQ).

Reference parity: `paddle/fluid/operators/fake_quantize_op.cc` —
fake_quantize_abs_max, fake_quantize_moving_average_abs_max,
fake_channel_wise_quantize_abs_max, fake_quantize_dequantize variants,
moving_average_abs_max_scale. TPU-native autodiff note: the reference
hand-writes straight-through-estimator grad kernels; here STE falls out
of expressing quantization as `x + stop_gradient(q(x) - x)` — jax.vjp
then yields identity gradients through the rounding automatically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _qdq(x, scale, bit_length):
    """quantize->dequantize with STE."""
    bnt = (1 << (bit_length - 1)) - 1
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * bnt), -bnt, bnt) * s / bnt
    return x + jax.lax.stop_gradient(q - x)


@register_op("fake_quantize_abs_max")
def _fake_quantize_abs_max(ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    static = attrs.get("static_scale")
    # static_scale: PTQ binds the CALIBRATED scale here, overriding the
    # dynamic per-batch abs-max (QAT's default)
    scale = jnp.float32(static) if static is not None \
        else jnp.max(jnp.abs(x))
    return {"Out": _qdq(x, scale, bits),
            "OutScale": jnp.reshape(scale, (1,))}


@register_op("fake_quantize_dequantize_abs_max")
def _fake_qdq_abs_max(ins, attrs):
    return _fake_quantize_abs_max(ins, attrs)


@register_op("fake_channel_wise_quantize_abs_max")
def _fake_cw_quantize_abs_max(ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    axis = attrs.get("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    out = _qdq(x, scale, bits)
    return {"Out": out, "OutScale": jnp.reshape(scale, (-1,))}


@register_op("fake_quantize_moving_average_abs_max")
def _fake_quantize_ma_abs_max(ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    rate = attrs.get("moving_rate", 0.9)
    in_scale = ins["InScale"][0].reshape(())
    is_test = attrs.get("is_test", False)
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = in_scale
    else:
        scale = jnp.where(in_scale > 0,
                          rate * in_scale + (1 - rate) * cur, cur)
    return {"Out": _qdq(x, scale, bits),
            "OutScale": jnp.reshape(scale, (1,))}


@register_op("fake_quantize_dequantize_moving_average_abs_max")
def _fake_qdq_ma_abs_max(ins, attrs):
    return _fake_quantize_ma_abs_max(ins, attrs)


@register_op("fake_dequantize_max_abs")
def _fake_dequantize_max_abs(ins, attrs):
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(())
    max_range = attrs.get("max_range", 127.0)
    return {"Out": x.astype(jnp.float32) * scale / max_range}


@register_op("moving_average_abs_max_scale")
def _ma_abs_max_scale(ins, attrs):
    x = ins["X"][0]
    rate = attrs.get("moving_rate", 0.9)
    in_scale = ins["InScale"][0].reshape(()) if ins.get("InScale") \
        else jnp.float32(0.0)
    cur = jnp.max(jnp.abs(x))
    scale = jnp.where(in_scale > 0, rate * in_scale + (1 - rate) * cur,
                      cur)
    return {"Out": x, "OutScale": jnp.reshape(scale, (1,))}
