"""Fake-quantization operators (QAT/PTQ).

Reference parity: `paddle/fluid/operators/fake_quantize_op.cc` —
fake_quantize_abs_max, fake_quantize_moving_average_abs_max,
fake_channel_wise_quantize_abs_max, fake_quantize_dequantize variants,
moving_average_abs_max_scale. TPU-native autodiff note: the reference
hand-writes straight-through-estimator grad kernels; here STE falls out
of expressing quantization as `x + stop_gradient(q(x) - x)` — jax.vjp
then yields identity gradients through the rounding automatically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


def _qdq(x, scale, bit_length):
    """quantize->dequantize with STE."""
    bnt = (1 << (bit_length - 1)) - 1
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * bnt), -bnt, bnt) * s / bnt
    return x + jax.lax.stop_gradient(q - x)


@register_op("fake_quantize_abs_max")
def _fake_quantize_abs_max(ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    static = attrs.get("static_scale")
    # static_scale: PTQ binds the CALIBRATED scale here, overriding the
    # dynamic per-batch abs-max (QAT's default)
    scale = jnp.float32(static) if static is not None \
        else jnp.max(jnp.abs(x))
    return {"Out": _qdq(x, scale, bits),
            "OutScale": jnp.reshape(scale, (1,))}


@register_op("fake_quantize_dequantize_abs_max")
def _fake_qdq_abs_max(ins, attrs):
    return _fake_quantize_abs_max(ins, attrs)


@register_op("fake_channel_wise_quantize_abs_max")
def _fake_cw_quantize_abs_max(ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    axis = attrs.get("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    out = _qdq(x, scale, bits)
    return {"Out": out, "OutScale": jnp.reshape(scale, (-1,))}


@register_op("fake_quantize_moving_average_abs_max")
def _fake_quantize_ma_abs_max(ins, attrs):
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    rate = attrs.get("moving_rate", 0.9)
    in_scale = ins["InScale"][0].reshape(())
    is_test = attrs.get("is_test", False)
    cur = jnp.max(jnp.abs(x))
    if is_test:
        scale = in_scale
    else:
        scale = jnp.where(in_scale > 0,
                          rate * in_scale + (1 - rate) * cur, cur)
    return {"Out": _qdq(x, scale, bits),
            "OutScale": jnp.reshape(scale, (1,))}


@register_op("fake_quantize_dequantize_moving_average_abs_max")
def _fake_qdq_ma_abs_max(ins, attrs):
    return _fake_quantize_ma_abs_max(ins, attrs)


@register_op("fake_dequantize_max_abs")
def _fake_dequantize_max_abs(ins, attrs):
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(())
    max_range = attrs.get("max_range", 127.0)
    return {"Out": x.astype(jnp.float32) * scale / max_range}


@register_op("fake_quantize_range_abs_max")
def _fake_quantize_range_abs_max(ins, attrs):
    """Window-max scale QAT quantizer (reference: fake_quantize_op.h:157
    FakeQuantizeRangeAbsMaxKernel + fake_quantize_op.cc:123
    FindRangeAbsMaxFunctor). The window buffer rides the InScales input /
    OutScales output pair (the reference updates one variable in place)."""
    x = ins["X"][0]
    bits = attrs.get("bit_length", 8)
    in_scale = ins["InScale"][0].reshape(())
    if attrs.get("is_test", False):
        return {"Out": _qdq(x, in_scale, bits),
                "OutScale": jnp.reshape(in_scale, (1,))}
    window = int(attrs.get("window_size", 10000))
    it = jnp.reshape(ins["Iter"][0], ()).astype(jnp.int64) \
        if ins.get("Iter") else jnp.int64(0)
    prev = ins["InScales"][0] if ins.get("InScales") \
        else jnp.zeros((window,), jnp.float32)
    cur = jnp.max(jnp.abs(x)).astype(jnp.float32)
    idx = (it % window).astype(jnp.int32)
    removed = prev[idx]
    arr = prev.at[idx].set(cur)
    # recompute the window max only when the evicted slot WAS the max
    # (reference: |removed - last| < 1e-6); scales are >= 0 so masking
    # with 0 is a sound -inf substitute
    # window now holds min(it+1, window) valid entries INCLUDING the
    # slot just written with cur — excluding it would collapse the
    # scale when the evicted slot was the previous max
    size = jnp.clip(it + 1, 1, window)
    mask = (jnp.arange(window) < size).astype(jnp.float32)
    win_max = jnp.max(arr * mask)
    scale = jnp.where(
        in_scale < cur, cur,
        jnp.where(jnp.abs(removed - in_scale) < 1e-6, win_max, in_scale))
    return {"Out": _qdq(x, scale, bits),
            "OutScale": jnp.reshape(scale, (1,)),
            "OutScales": arr}


@register_op("fake_channel_wise_dequantize_max_abs")
def _fake_cw_dequantize_max_abs(ins, attrs):
    """Reference: fake_dequantize_op.h:58 + .cc:37 — one scale tensor =
    per-output-channel weight dequant (dim 0); two = activation path with
    per-dim-1 scales plus a scalar scale."""
    x = ins["X"][0].astype(jnp.float32)
    scales = ins["Scales"]
    quant_bits = attrs.get("quant_bits", [8])
    if len(scales) == 1:
        bnt = (1 << (int(quant_bits[0]) - 1)) - 1
        s = scales[0].reshape((-1,) + (1,) * (x.ndim - 1))
        return {"Out": x * s / bnt}
    bnt0 = (1 << (int(quant_bits[0]) - 1)) - 1
    bnt1 = (1 << (int(quant_bits[1]) - 1)) - 1
    s0 = scales[0].reshape((1, -1) + (1,) * (x.ndim - 2))
    s1 = scales[1].reshape(())
    return {"Out": x * s0 * s1 / (bnt0 * bnt1)}


@register_op("dequantize_abs_max")
def _dequantize_abs_max(ins, attrs):
    """int8 -> float via scalar scale (reference:
    dequantize_abs_max_op.cc:23 DequantizeFunctor)."""
    x = ins["X"][0]
    scale = ins["Scale"][0].reshape(())
    max_range = attrs.get("max_range", 127.0)
    return {"Out": scale * x.astype(jnp.float32) / max_range}


@register_op("dequantize_log")
def _dequantize_log(ins, attrs):
    """int8 -> float through a 128-entry log dictionary (reference:
    dequantize_log_op.cc:24): negative codes mirror to -dict[x+128]."""
    x = ins["X"][0].astype(jnp.int32)
    table = ins["Dict"][0].reshape(-1)
    neg = -table[jnp.clip(x + 128, 0, table.shape[0] - 1)]
    pos = table[jnp.clip(x, 0, table.shape[0] - 1)]
    return {"Out": jnp.where(x < 0, neg, pos)}


@register_op("moving_average_abs_max_scale")
def _ma_abs_max_scale(ins, attrs):
    x = ins["X"][0]
    rate = attrs.get("moving_rate", 0.9)
    in_scale = ins["InScale"][0].reshape(()) if ins.get("InScale") \
        else jnp.float32(0.0)
    if attrs.get("is_test", False):
        # eval/inference must not mutate the calibration state
        # (reference: moving_average_abs_max_scale_op is_test branch)
        return {"Out": x, "OutScale": jnp.reshape(in_scale, (1,))}
    cur = jnp.max(jnp.abs(x))
    scale = jnp.where(in_scale > 0, rate * in_scale + (1 - rate) * cur,
                      cur)
    return {"Out": x, "OutScale": jnp.reshape(scale, (1,))}
