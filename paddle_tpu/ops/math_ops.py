"""Dense math operators.

Reference parity: `paddle/fluid/operators/` — elementwise_* (with the `axis`
mid-broadcast rule, `elementwise_op_function.h`), `mul_op.cc` (x_num_col_dims
flattening), `matmul_op.cc` (transpose/alpha attrs), reduce_* ops, `scale`,
`sum`, `cast`, compare/logical ops. Each is a pure jax function; XLA fuses
elementwise chains into neighbouring matmuls (the reference needed dedicated
fusion passes, `ir/fuse_elewise_add_act_pass.cc`, to do this by hand).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .registry import register_op
from ..core.types import to_numpy_dtype


def _first(ins, slot):
    v = ins.get(slot) or []
    return v[0] if v else None


def _bcast_pair(x, y, axis):
    """Paddle elementwise broadcast: align y into x at `axis`."""
    if x.ndim == y.ndim:
        return x, y
    if x.ndim < y.ndim:
        y2, x2 = _bcast_pair(y, x, axis)
        return x2, y2
    if axis < 0:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return x, y.reshape(new_shape)


def _register_elementwise(name, fn):
    @register_op("elementwise_" + name)
    def _ew(ins, attrs, _fn=fn):
        x, y = ins["X"][0], ins["Y"][0]
        x, y = _bcast_pair(x, y, attrs.get("axis", -1))
        return {"Out": _fn(x, y)}


_register_elementwise("add", jnp.add)
_register_elementwise("sub", jnp.subtract)
_register_elementwise("mul", jnp.multiply)
_register_elementwise("div", jnp.divide)
_register_elementwise("max", jnp.maximum)
_register_elementwise("min", jnp.minimum)
_register_elementwise("pow", jnp.power)
_register_elementwise("mod", jnp.mod)
_register_elementwise("floordiv", jnp.floor_divide)


@register_op("mul")
def _mul(ins, attrs):
    # reference: operators/mul_op.cc — flatten x to 2-D by x_num_col_dims.
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    x2 = x.reshape((int(np.prod(x.shape[:xn])), -1))
    y2 = y.reshape((int(np.prod(y.shape[:yn])), -1))
    out = x2 @ y2
    return {"Out": out.reshape(x.shape[:xn] + y.shape[yn:])}


@register_op("matmul")
def _matmul(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    tx, ty = attrs.get("transpose_X", False), attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": out}


@register_op("matmul_v2")
def _matmul_v2(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    if attrs.get("trans_x", False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("trans_y", False):
        y = jnp.swapaxes(y, -1, -2)
    return {"Out": jnp.matmul(x, y)}


@register_op("scale")
def _scale(ins, attrs):
    x = ins["X"][0]
    scale = attrs.get("scale", 1.0)
    bias = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        out = x * jnp.asarray(scale, x.dtype) + jnp.asarray(bias, x.dtype)
    else:
        out = (x + jnp.asarray(bias, x.dtype)) * jnp.asarray(scale, x.dtype)
    return {"Out": out}


@register_op("sum")
def _sum(ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("mean")
def _mean(ins, attrs):
    return {"Out": jnp.mean(ins["X"][0]).reshape((1,))}


def _reduce_axes(x, attrs):
    if attrs.get("reduce_all", False):
        return None
    dim = attrs.get("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d % x.ndim for d in dim) or None


def _register_reduce(name, fn):
    @register_op("reduce_" + name)
    def _red(ins, attrs, _fn=fn):
        x = ins["X"][0]
        axes = _reduce_axes(x, attrs)
        out = _fn(x, axis=axes, keepdims=attrs.get("keep_dim", False))
        if out.ndim == 0:
            out = out.reshape((1,))
        return {"Out": out}


_register_reduce("sum", jnp.sum)
_register_reduce("mean", jnp.mean)
_register_reduce("max", jnp.max)
_register_reduce("min", jnp.min)
_register_reduce("prod", jnp.prod)
_register_reduce("any", jnp.any)
_register_reduce("all", jnp.all)


@register_op("cast")
def _cast(ins, attrs):
    from ..core.types import normalize_dtype
    out_dtype = to_numpy_dtype(normalize_dtype(attrs["out_dtype"]))
    return {"Out": ins["X"][0].astype(out_dtype)}


@register_op("clip")
def _clip(ins, attrs):
    x = ins["X"][0]
    return {"Out": jnp.clip(x, attrs.get("min"), attrs.get("max"))}


@register_op("clip_by_norm")
def _clip_by_norm(ins, attrs):
    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": x * scale.astype(x.dtype)}


@register_op("squared_l2_norm")
def _squared_l2_norm(ins, attrs):
    return {"Out": jnp.sum(jnp.square(ins["X"][0])).reshape((1,))}


@register_op("p_norm")
def _p_norm(ins, attrs):
    x = ins["X"][0]
    p = attrs.get("porder", 2.0)
    axis = attrs.get("axis", -1)
    keepdim = attrs.get("keepdim", False)
    out = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)
    return {"Out": out}


def _register_cmp(name, fn):
    @register_op(name)
    def _cmp(ins, attrs, _fn=fn):
        return {"Out": _fn(ins["X"][0], ins["Y"][0])}


_register_cmp("equal", jnp.equal)
_register_cmp("not_equal", jnp.not_equal)
_register_cmp("less_than", jnp.less)
_register_cmp("less_equal", jnp.less_equal)
_register_cmp("greater_than", jnp.greater)
_register_cmp("greater_equal", jnp.greater_equal)


@register_op("logical_and")
def _land(ins, attrs):
    return {"Out": jnp.logical_and(ins["X"][0], ins["Y"][0])}


@register_op("logical_or")
def _lor(ins, attrs):
    return {"Out": jnp.logical_or(ins["X"][0], ins["Y"][0])}


@register_op("logical_xor")
def _lxor(ins, attrs):
    return {"Out": jnp.logical_xor(ins["X"][0], ins["Y"][0])}


@register_op("logical_not")
def _lnot(ins, attrs):
    return {"Out": jnp.logical_not(ins["X"][0])}


@register_op("isfinite")
def _isfinite(ins, attrs):
    return {"Out": jnp.all(jnp.isfinite(ins["X"][0])).reshape((1,))}


@register_op("isfinite_v2")
def _isfinite_v2(ins, attrs):
    return {"Out": jnp.isfinite(ins["X"][0])}


@register_op("isnan_v2")
def _isnan(ins, attrs):
    return {"Out": jnp.isnan(ins["X"][0])}


@register_op("isinf_v2")
def _isinf(ins, attrs):
    return {"Out": jnp.isinf(ins["X"][0])}


@register_op("maximum")
def _maximum(ins, attrs):
    return {"Out": jnp.maximum(ins["X"][0], ins["Y"][0])}


@register_op("minimum")
def _minimum(ins, attrs):
    return {"Out": jnp.minimum(ins["X"][0], ins["Y"][0])}


@register_op("pow")
def _pow(ins, attrs):
    x = ins["X"][0]
    factor = _first(ins, "FactorTensor")
    if factor is None:
        factor = attrs.get("factor", 1.0)
    return {"Out": jnp.power(x, factor)}


@register_op("amp_check_finite_and_scale")
def _amp_check(ins, attrs):
    # reference: operators/amp/amp_check_finite_and_scale_op.cc — scales all
    # inputs by Scale and reports a global finiteness flag.
    scale = ins["Scale"][0]
    outs, finite = [], jnp.array(True)
    for x in ins["X"]:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(x)))
        outs.append(x * scale.astype(x.dtype))
    return {"Out": outs, "FoundInfinite": jnp.logical_not(finite).reshape((1,))}


@register_op("check_finite_and_unscale")
def _check_finite_unscale(ins, attrs):
    scale = ins["Scale"][0]
    inv = 1.0 / scale
    outs, finite = [], jnp.array(True)
    for x in ins["X"]:
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(x)))
        outs.append(x * inv.astype(x.dtype))
    return {"Out": outs, "FoundInfinite": jnp.logical_not(finite).reshape((1,))}


@register_op("update_loss_scaling")
def _update_loss_scaling(ins, attrs):
    # reference: operators/amp/update_loss_scaling_op.cc
    found_inf = ins["FoundInfinite"][0].reshape(())
    scale = ins["PrevLossScaling"][0]
    good = ins["InGoodSteps"][0]
    bad = ins["InBadSteps"][0]
    incr_every = attrs.get("incr_every_n_steps", 1000)
    decr_every = attrs.get("decr_every_n_nan_or_inf", 2)
    incr_ratio = attrs.get("incr_ratio", 2.0)
    decr_ratio = attrs.get("decr_ratio", 0.5)

    new_bad = jnp.where(found_inf, bad + 1, jnp.zeros_like(bad))
    new_good = jnp.where(found_inf, jnp.zeros_like(good), good + 1)
    dec = new_bad >= decr_every
    inc = new_good >= incr_every
    new_scale = jnp.where(dec, scale * decr_ratio,
                          jnp.where(inc, scale * incr_ratio, scale))
    new_scale = jnp.maximum(new_scale, jnp.asarray(1.0, scale.dtype))
    new_good = jnp.where(inc, jnp.zeros_like(good), new_good)
    new_bad = jnp.where(dec, jnp.zeros_like(bad), new_bad)
    outs = [jnp.where(found_inf, jnp.zeros_like(x), x) for x in ins["X"]]
    return {"Out": outs, "LossScaling": new_scale,
            "OutGoodSteps": new_good, "OutBadSteps": new_bad}


@register_op("lgamma")
def _lgamma(ins, attrs):
    import jax.scipy.special as jsp

    return {"Out": jsp.gammaln(ins["X"][0])}


@register_op("digamma")
def _digamma(ins, attrs):
    import jax.scipy.special as jsp

    return {"Out": jsp.digamma(ins["X"][0])}


@register_op("erfinv")
def _erfinv(ins, attrs):
    import jax.scipy.special as jsp

    return {"Out": jsp.erfinv(ins["X"][0])}


@register_op("lerp")
def _lerp(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    w = ins["Weight"][0] if ins.get("Weight") else attrs.get("weight", 0.5)
    return {"Out": x + w * (y - x)}


@register_op("frac")
def _frac(ins, attrs):
    x = ins["X"][0]
    return {"Out": x - jnp.trunc(x)}


@register_op("trunc")
def _trunc(ins, attrs):
    return {"Out": jnp.trunc(ins["X"][0])}


@register_op("take")
def _take(ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": jnp.take(x.reshape(-1), idx.astype(jnp.int32))}


@register_op("put_along_axis")
def _put_along_axis(ins, attrs):
    x, idx, v = ins["Input"][0], ins["Index"][0], ins["Value"][0]
    axis = attrs.get("Axis", attrs.get("axis", 0))
    reduce = attrs.get("Reduce", attrs.get("reduce", "assign"))
    idx = idx.astype(jnp.int32)
    return {"Result": _scatter_along(x, idx, v, axis,
                                     add=reduce == "add")}


def _scatter_along(x, idx, v, axis, add):
    # build full index grids for scatter along one axis
    grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape],
                         indexing="ij")
    grids[axis] = idx
    vv = jnp.broadcast_to(v, idx.shape)
    if add:
        return x.at[tuple(grids)].add(vv)
    return x.at[tuple(grids)].set(vv)


@register_op("masked_fill")
def _masked_fill(ins, attrs):
    x, mask = ins["X"][0], ins["Mask"][0]
    value = attrs.get("value", 0.0)
    return {"Out": jnp.where(mask.astype(bool), value, x)}


@register_op("searchsorted")
def _searchsorted(ins, attrs):
    sorted_seq, values = ins["SortedSequence"][0], ins["Values"][0]
    side = "right" if attrs.get("right", False) else "left"
    return {"Out": jnp.searchsorted(sorted_seq.reshape(-1), values,
                                    side=side).astype(jnp.int64)}


@register_op("minus")
def _minus(ins, attrs):
    return {"Out": ins["X"][0] - ins["Y"][0]}


@register_op("l1_norm")
def _l1_norm(ins, attrs):
    # reference: l1_norm_op.cc — scalar sum of |x|
    return {"Out": jnp.sum(jnp.abs(ins["X"][0]))}


@register_op("frobenius_norm")
def _frobenius_norm(ins, attrs):
    x = ins["X"][0]
    dims = attrs.get("dim", None) or tuple(range(x.ndim))
    keep = attrs.get("keep_dim", False)
    return {"Out": jnp.sqrt(jnp.sum(x * x, axis=tuple(dims),
                                    keepdims=keep))}


@register_op("dist")
def _dist(ins, attrs):
    # reference: dist_op.cc — p-norm of elementwise (X - Y), broadcasting
    x, y = ins["X"][0], ins["Y"][0]
    p = float(attrs.get("p", 2.0))
    z = jnp.abs(x - y)
    if p == float("inf"):
        return {"Out": jnp.max(z)}
    if p == float("-inf"):
        return {"Out": jnp.min(z)}
    if p == 0.0:
        return {"Out": jnp.sum((z != 0).astype(x.dtype))}
    return {"Out": jnp.power(jnp.sum(jnp.power(z, p)), 1.0 / p)}
