"""Recurrent unit operators (dynamic_lstm / dynamic_gru families).

Reference parity:
- `lstm` / `lstmp`: `paddle/fluid/operators/lstm_op.cc` + the gate kernel
  `operators/math/detail/lstm_kernel.h:30-51` — packed gate layout along
  the 4D axis is [candidate, input_gate, forget_gate, output_gate]
  (value_in at offset 0, ig at D, fg at 2D, og at 3D), peephole weights
  checkI/checkF applied with the *previous* cell state and checkO with the
  *new* state; `lstmp` (`lstmp_op.cc`) adds a recurrent projection.
- `lstm_unit`: `operators/lstm_unit_op.h:60-75` — X packs [i, f, o, g],
  f gets `forget_bias`, g uses tanh.
- `gru` / `gru_unit`: `operators/gru_op.cc:166-169` — gate layout
  [update, reset, candidate]; h = (1-u)*h_prev + u*c_tilde by default and
  h = u*h_prev + (1-u)*c_tilde when `origin_mode` (both ops default
  origin_mode to False, `gru_unit_op.cc:132-138`).
- `cudnn_lstm`: `operators/cudnn_lstm_op.cc` — multi-layer (optionally
  bidirectional) LSTM over time-major [T, B, D] input. cuDNN's opaque
  packed weight is replaced by a documented flat layout: per layer, per
  direction: W_ih (4H×in), W_hh (4H×H), b_ih (4H), b_hh (4H) with cuDNN
  gate order [i, f, g, o].

TPU-native design: the input-to-gate matmul is hoisted out of the
recurrence (one big MXU matmul over [B*T]), and the recurrence itself is
a `lax.scan` whose body is a single [B,H]x[H,4H] matmul — the same shape
XLA pipelines well on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op

_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}

# gru_unit_op.cc encodes activations as ints: identity=0 sigmoid=1 tanh=2
# relu=3; other rnn ops use the string names.
_ACT_ENUM = {0: "identity", 1: "sigmoid", 2: "tanh", 3: "relu"}


def _act(attrs, key, default):
    v = attrs.get(key, default)
    if isinstance(v, int):
        v = _ACT_ENUM[v]
    return _ACT[v]


def _seq_mask(ins, b, t):
    if ins.get("Length"):
        length = ins["Length"][0].reshape((-1,))
        return (jnp.arange(t)[None, :] < length[:, None])  # [B, T]
    return None


def _lstm_body(ins, attrs, proj=False):
    """Shared dynamic_lstm / lstmp recurrence over padded [B, T, 4D]."""
    x = ins["Input"][0]                    # [B, T, 4D] = x @ W_x (pre-done)
    w = ins["Weight"][0]                   # [R, 4D], R = P (lstmp) or D
    bias = ins["Bias"][0].reshape((-1,))   # [4D] or [7D] w/ peepholes
    b, t = x.shape[0], x.shape[1]
    d = x.shape[2] // 4
    use_peep = bool(attrs.get("use_peepholes", True)) and \
        bias.shape[0] >= 7 * d
    act_gate = _ACT[attrs.get("gate_activation", "sigmoid")]
    act_cell = _ACT[attrs.get("cell_activation", "tanh")]
    act_cand = _ACT[attrs.get("candidate_activation", "tanh")]
    cell_clip = float(attrs.get("cell_clip", 0.0))
    reverse = bool(attrs.get("is_reverse", False))

    gates_x = x + bias[None, None, :4 * d]
    if use_peep:
        ck_i, ck_f, ck_o = (bias[4 * d:5 * d], bias[5 * d:6 * d],
                            bias[6 * d:7 * d])
    else:
        ck_i = ck_f = ck_o = jnp.zeros((d,), x.dtype)

    if proj:
        w_proj = ins["ProjWeight"][0]      # [D, P]
        p = w_proj.shape[1]
        act_proj = _ACT[attrs.get("proj_activation", "identity")]
        proj_clip = float(attrs.get("proj_clip", 0.0))
        r0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, p), x.dtype)
    else:
        r0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, d), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((b, d), x.dtype)

    mask = _seq_mask(ins, b, t)
    xs = jnp.swapaxes(gates_x, 0, 1)       # [T, B, 4D]
    ms = (jnp.swapaxes(mask, 0, 1)[..., None].astype(x.dtype)
          if mask is not None else jnp.ones((t, 1, 1), x.dtype))
    if reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(carry, inp):
        r_prev, c_prev = carry
        xg, m = inp
        gates = xg + r_prev @ w
        cand, ig, fg, og = jnp.split(gates, 4, axis=-1)
        cand = act_cand(cand)
        i = act_gate(ig + c_prev * ck_i)
        f = act_gate(fg + c_prev * ck_f)
        c = cand * i + c_prev * f
        if cell_clip > 0.0:
            c = jnp.clip(c, -cell_clip, cell_clip)
        o = act_gate(og + c * ck_o)
        h = o * act_cell(c)
        if proj:
            r = act_proj(h @ w_proj)
            if proj_clip > 0.0:
                r = jnp.clip(r, -proj_clip, proj_clip)
        else:
            r = h
        # padded steps carry state through unchanged
        r = m * r + (1.0 - m) * r_prev
        c = m * c + (1.0 - m) * c_prev
        return (r, c), (r, c, h)

    (_, _), (rs, cs, hs) = lax.scan(step, (r0, c0), (xs, ms))
    if reverse:
        rs, cs, hs = rs[::-1], cs[::-1], hs[::-1]
    rs = jnp.swapaxes(rs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    if proj:
        return {"Projection": rs, "Cell": cs,
                "Hidden": jnp.swapaxes(hs, 0, 1)}
    return {"Hidden": rs, "Cell": cs}


@register_op("lstm")
def _lstm(ins, attrs):
    return _lstm_body(ins, attrs, proj=False)


@register_op("lstmp")
def _lstmp(ins, attrs):
    return _lstm_body(ins, attrs, proj=True)


@register_op("lstm_unit")
def _lstm_unit(ins, attrs):
    x, c_prev = ins["X"][0], ins["C_prev"][0]
    fb = float(attrs.get("forget_bias", 0.0))
    d = c_prev.shape[-1]
    i = jax.nn.sigmoid(x[:, :d])
    f = jax.nn.sigmoid(x[:, d:2 * d] + fb)
    o = jax.nn.sigmoid(x[:, 2 * d:3 * d])
    g = jnp.tanh(x[:, 3 * d:])
    c = f * c_prev + i * g
    return {"C": c, "H": o * jnp.tanh(c)}


def _gru_gates(xg, h_prev, w_ur, w_c, act_gate, act_node, origin):
    d = h_prev.shape[-1]
    ur = act_gate(xg[..., :2 * d] + h_prev @ w_ur)
    u, r = ur[..., :d], ur[..., d:]
    cand = act_node(xg[..., 2 * d:] + (r * h_prev) @ w_c)
    if origin:
        h = u * h_prev + (1.0 - u) * cand
    else:
        h = (1.0 - u) * h_prev + u * cand
    return h, u, r, cand


@register_op("gru")
def _gru(ins, attrs):
    x = ins["Input"][0]                    # [B, T, 3D] = x @ W_x (pre-done)
    w = ins["Weight"][0]                   # [D, 3D]: [:, :2D] u,r; [:, 2D:] c
    b, t = x.shape[0], x.shape[1]
    d = x.shape[2] // 3
    bias = (ins["Bias"][0].reshape((-1,)) if ins.get("Bias")
            else jnp.zeros((3 * d,), x.dtype))
    act_gate = _ACT[attrs.get("gate_activation", "sigmoid")]
    act_node = _ACT[attrs.get("activation", "tanh")]
    origin = bool(attrs.get("origin_mode", False))
    reverse = bool(attrs.get("is_reverse", False))
    w_ur, w_c = w[:, :2 * d], w[:, 2 * d:]

    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, d), x.dtype)
    mask = _seq_mask(ins, b, t)
    xs = jnp.swapaxes(x + bias[None, None, :], 0, 1)
    ms = (jnp.swapaxes(mask, 0, 1)[..., None].astype(x.dtype)
          if mask is not None else jnp.ones((t, 1, 1), x.dtype))
    if reverse:
        xs, ms = xs[::-1], ms[::-1]

    def step(h_prev, inp):
        xg, m = inp
        h, u, r, cand = _gru_gates(xg, h_prev, w_ur, w_c, act_gate,
                                   act_node, origin)
        h = m * h + (1.0 - m) * h_prev
        return h, (h, u * m, r * m, cand * m, r * h_prev * m)

    _, (hs, us, rs, cands, rhp) = lax.scan(step, h0, (xs, ms))
    if reverse:
        hs, us, rs, cands, rhp = (hs[::-1], us[::-1], rs[::-1],
                                  cands[::-1], rhp[::-1])
    sw = lambda a: jnp.swapaxes(a, 0, 1)  # noqa: E731
    return {"Hidden": sw(hs),
            "BatchGate": jnp.concatenate([sw(us), sw(rs), sw(cands)], -1),
            "BatchResetHiddenPrev": sw(rhp),
            "BatchHidden": sw(hs)}


@register_op("gru_unit")
def _gru_unit(ins, attrs):
    """One GRU step. Reference `gru_unit_op.cc`: Input [B,3D] (= x@W_x),
    HiddenPrev [B,D], Weight [D,3D], optional Bias [1,3D]; origin_mode
    defaults to False (h = (1-u)*h_prev + u*c) like the sequence op."""
    xg = ins["Input"][0]
    h_prev = ins["HiddenPrev"][0]
    w = ins["Weight"][0]
    d = h_prev.shape[-1]
    if ins.get("Bias"):
        xg = xg + ins["Bias"][0].reshape((-1,))[None, :]
    act_gate = _act(attrs, "gate_activation", 1)
    act_node = _act(attrs, "activation", 2)
    origin = bool(attrs.get("origin_mode", False))
    h, u, r, cand = _gru_gates(xg, h_prev, w[:, :2 * d], w[:, 2 * d:],
                               act_gate, act_node, origin)
    return {"Hidden": h, "Gate": jnp.concatenate([u, r, cand], -1),
            "ResetHiddenPrev": r * h_prev}


@register_op("cudnn_lstm")
def _cudnn_lstm(ins, attrs):
    """Multi-layer (bi)LSTM over time-major [T, B, D] input. Flat weight
    layout per (layer, direction): W_ih (4H*in), W_hh (4H*H), b_ih (4H),
    b_hh (4H), cuDNN gate order [i, f, g, o]. Optional SequenceLength
    [B] masks padded steps: the forward direction carries state through
    padding, the reverse direction runs over each row's time-reversed
    VALID region (cudnn_lstm_op.cc padded-batch contract)."""
    x = ins["Input"][0]                    # [T, B, D]
    flat_w = ins["W"][0].reshape((-1,))
    hidden = int(attrs["hidden_size"])
    n_layers = int(attrs.get("num_layers", 1))
    bidirec = bool(attrs.get("is_bidirec", False))
    n_dir = 2 if bidirec else 1
    t, b, d_in = x.shape
    h = hidden
    if ins.get("SequenceLength"):
        seq_len = ins["SequenceLength"][0].reshape(-1).astype(jnp.int32)
        step_mask = (jnp.arange(t)[:, None] < seq_len[None, :]) \
            .astype(x.dtype)[:, :, None]                     # [T, B, 1]
        # per-row time reversal of the valid region only
        rev_idx = jnp.where(
            jnp.arange(t)[:, None] < seq_len[None, :],
            seq_len[None, :] - 1 - jnp.arange(t)[:, None],
            jnp.arange(t)[:, None])                          # [T, B]
    else:
        seq_len = None
        step_mask = jnp.ones((t, 1, 1), x.dtype)
        rev_idx = None

    def rev(seq):
        if rev_idx is None:
            return seq[::-1]
        return jnp.take_along_axis(seq, rev_idx[:, :, None], 0)

    init_h = ins["InitH"][0].reshape((n_layers * n_dir, b, h)) \
        if ins.get("InitH") else jnp.zeros((n_layers * n_dir, b, h), x.dtype)
    init_c = ins["InitC"][0].reshape((n_layers * n_dir, b, h)) \
        if ins.get("InitC") else jnp.zeros((n_layers * n_dir, b, h), x.dtype)

    def run_dir(seq, w_ih, w_hh, b_ih, b_hh, h0, c0, reverse):
        xs = rev(seq) if reverse else seq
        xp = jnp.einsum("tbd,gd->tbg", xs, w_ih) + b_ih + b_hh

        def step(carry, inp):
            xg, m = inp
            hp, cp = carry
            gates = xg + hp @ w_hh.T
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * cp + jax.nn.sigmoid(i) * jnp.tanh(g)
            hh = jax.nn.sigmoid(o) * jnp.tanh(c)
            hh = m * hh + (1.0 - m) * hp
            c = m * c + (1.0 - m) * cp
            return (hh, c), hh

        (hl, cl), ys = lax.scan(step, (h0, c0), (xp, step_mask))
        return (rev(ys) if reverse else ys), hl, cl

    off = 0

    def take(n, shape):
        nonlocal off
        v = flat_w[off:off + n].reshape(shape)
        off += n
        return v

    out = x
    last_h, last_c = [], []
    for layer in range(n_layers):
        d_cur = out.shape[-1]
        outs = []
        for di in range(n_dir):
            w_ih = take(4 * h * d_cur, (4 * h, d_cur))
            w_hh = take(4 * h * h, (4 * h, h))
            b_ih = take(4 * h, (4 * h,))
            b_hh = take(4 * h, (4 * h,))
            idx = layer * n_dir + di
            ys, hl, cl = run_dir(out, w_ih, w_hh, b_ih, b_hh,
                                 init_h[idx], init_c[idx], reverse=di == 1)
            outs.append(ys)
            last_h.append(hl)
            last_c.append(cl)
        out = jnp.concatenate(outs, -1) if n_dir == 2 else outs[0]
    # reference output slots: Out / last_h / last_c (cudnn_lstm_op.cc:98-104)
    return {"Out": out, "last_h": jnp.stack(last_h),
            "last_c": jnp.stack(last_c)}
