"""LoDTensorArray operators.

Reference parity: `paddle/fluid/operators/controlflow/
tensor_array_read_write_op.cc` (array_write/array_read),
`lod_array_length_op.cc`, `array_to_lod_tensor_op.cc`,
`lod_rank_table_op.cc`. TPU-native: a tensor array with a STATIC max
length is a stacked [T, ...] buffer (XLA-friendly); write = dynamic
update slice, read = dynamic slice — the representation lax.scan uses
internally. The python TensorArray helper in layers/control_flow wraps
these for While bodies."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op


@register_op("array_write")
def _array_write(ins, attrs):
    # A stacked buffer [T, ...]; I scalar index; X the value. OutLen
    # tracks the logical length (max written index + 1) so
    # lod_array_length can answer reference semantics; under jit an
    # out-of-range CONCRETE index raises (traced indices follow
    # dynamic_update_slice clamping, documented).
    arr = ins["Array"][0] if ins.get("Array") else None
    x = ins["X"][0]
    i = jnp.reshape(ins["I"][0], ()).astype(jnp.int32)
    if arr is None:
        max_len = attrs.get("max_len", 64)
        arr = jnp.zeros((max_len,) + x.shape, x.dtype)
    try:
        ci = int(i)
        if ci >= arr.shape[0]:
            raise IndexError(
                "array_write index %d out of range for TensorArray of "
                "max_len %d" % (ci, arr.shape[0]))
    except (TypeError, jax.errors.TracerIntegerConversionError,
            jax.errors.ConcretizationTypeError):
        pass
    prev_len = jnp.reshape(ins["Len"][0], ()).astype(jnp.int32) \
        if ins.get("Len") else jnp.int32(0)
    return {"Out": jax.lax.dynamic_update_slice(
        arr, x[None], (i,) + (0,) * x.ndim),
        "OutLen": jnp.maximum(prev_len, i + 1)}


@register_op("array_read")
def _array_read(ins, attrs):
    arr = ins["Array"][0] if ins.get("Array") else ins["X"][0]
    i = jnp.reshape(ins["I"][0], ()).astype(jnp.int32)
    out = jax.lax.dynamic_slice(
        arr, (i,) + (0,) * (arr.ndim - 1), (1,) + arr.shape[1:])
    return {"Out": out[0]}


@register_op("lod_array_length")
def _lod_array_length(ins, attrs):
    # reference: number of elements WRITTEN; thread array_write's OutLen
    # through the Len input to get it. Without it, the static buffer
    # capacity is the only answer available (documented fallback).
    if ins.get("Len"):
        return {"Out": jnp.reshape(ins["Len"][0], (1,)).astype(
            jnp.int64)}
    arr = ins["X"][0]
    return {"Out": jnp.asarray([arr.shape[0]], jnp.int64)}


@register_op("array_to_lod_tensor")
def _array_to_lod_tensor(ins, attrs):
    # stacked [T, B, ...] -> concat over time into [T*B, ...]
    arr = ins["X"][0]
    return {"Out": arr.reshape((-1,) + arr.shape[2:])}


@register_op("lod_tensor_to_array")
def _lod_tensor_to_array(ins, attrs):
    x = ins["X"][0]
    t = attrs.get("max_len", x.shape[0])
    return {"Out": x.reshape((t, -1) + x.shape[1:])}


@register_op("tensor_array_to_tensor")
def _tensor_array_to_tensor(ins, attrs):
    """Concat/stack the TensorArray entries (reference:
    tensor_array_to_tensor_op.cc:85). On the stacked [T, ...] buffer
    representation every entry shares one shape, so concat along `axis`
    is a moveaxis+reshape and stack is a moveaxis; OutIndex records each
    entry's extent along axis, as the reference does."""
    arr = ins["X"][0]  # stacked [T, ...]
    axis = int(attrs.get("axis", 0))
    use_stack = attrs.get("use_stack", False)
    n = arr.shape[0]
    entry_shape = arr.shape[1:]
    if use_stack:
        out = jnp.moveaxis(arr, 0, axis)
        # reference records each ENTRY's extent along `axis` in both
        # modes (tensor_array_to_tensor_op.cc:115-118)
        idx = jnp.full((n,), entry_shape[axis], jnp.int32)
        return {"Out": out, "OutIndex": idx}
    out = jnp.concatenate([arr[i] for i in range(n)], axis=axis)
    idx = jnp.full((n,), entry_shape[axis], jnp.int32)
    return {"Out": out, "OutIndex": idx}


@register_op("reorder_lod_tensor_by_rank")
def _reorder_lod_tensor_by_rank(ins, attrs):
    """Permute batch rows into rank-table order (reference:
    reorder_lod_tensor_by_rank_op.cc:69). Padded representation: the
    rank table is the order index vector from lod_rank_table, so the
    reorder is a gather over dim 0."""
    x = ins["X"][0]
    order = ins["RankTable"][0].reshape(-1).astype(jnp.int32)
    return {"Out": jnp.take(x, order, axis=0)}


@register_op("reorder_lod_tensor_by_rank_grad")
def _reorder_lod_tensor_by_rank_grad(ins, attrs):
    # restore original order: scatter rows back (inverse permutation)
    g = ins["X"][0]
    order = ins["RankTable"][0].reshape(-1).astype(jnp.int32)
    inv = jnp.zeros_like(order).at[order].set(
        jnp.arange(order.shape[0], dtype=jnp.int32))
    return {"Out": jnp.take(g, inv, axis=0)}


@register_op("lod_rank_table")
def _lod_rank_table(ins, attrs):
    # rank table = sequence indices sorted by length desc; with padded
    # representation + Length input
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1)
    else:
        x = ins["X"][0]
        length = jnp.full((x.shape[0],), x.shape[1]
                          if x.ndim > 1 else 1, jnp.int64)
    order = jnp.argsort(-length, stable=True)
    return {"Out": order.astype(jnp.int64),
            "Lengths": length[order].astype(jnp.int64)}


@register_op("max_sequence_len")
def _max_sequence_len(ins, attrs):
    # the rank table alone holds ORDER indices, not lengths; demand a
    # real length source rather than silently returning batch size
    if ins.get("Length"):
        return {"Out": jnp.max(ins["Length"][0]).astype(jnp.int64)}
    if ins.get("Lengths"):
        return {"Out": jnp.max(ins["Lengths"][0]).astype(jnp.int64)}
    raise ValueError(
        "max_sequence_len: wire a Length/Lengths input (feed "
        "lod_rank_table's Lengths output); the rank-table order alone "
        "does not carry sequence lengths in the padded representation")


@register_op("shrink_rnn_memory")
def _shrink_rnn_memory(ins, attrs):
    # reference: shrink_rnn_memory_op.cc — keep the first k rows (the
    # still-active sequences at this timestep); static-shape version
    # masks instead of shrinking
    x = ins["X"][0]
    i = jnp.reshape(ins["I"][0], ()).astype(jnp.int32) if ins.get("I") \
        else 0
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1)
        active = (length > i).astype(x.dtype)
        return {"Out": x * active.reshape(
            (-1,) + (1,) * (x.ndim - 1))}
    return {"Out": x}
