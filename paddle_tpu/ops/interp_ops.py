"""Interpolation (resize) operators.

Reference parity: `paddle/fluid/operators/interpolate_op.cc` — the
{linear,bilinear,trilinear,nearest,bicubic}_interp op family with the
reference's `align_corners` / `align_mode` source-index conventions:

- align_corners=True:          src = dst * (in - 1) / (out - 1)
- align_corners=False, mode 0: src = (dst + 0.5) * in / out - 0.5
- align_corners=False, mode 1: src = dst * in / out
- nearest (align_corners=False): src = floor(dst * in / out)
- bicubic always uses the half-pixel rule when align_corners=False.

TPU-native design: each resize is a separable per-axis gather + weighted
sum built from static output sizes (attrs `out_{d,h,w}` or `scale`), so
XLA sees static shapes and fuses the gathers; there is no dynamic-shape
OutSize path inside jit (an eager OutSize tensor is folded to static ints
before tracing by the layer wrapper).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _src_index(out_size, in_size, align_corners, align_mode):
    """Fractional source coordinates for one axis (linear-family).
    out_size == 1 forces ratio 0 (source index 0) like the reference
    (`interpolate_op.h` sets ratio only when out > 1)."""
    i = jnp.arange(out_size, dtype=jnp.float32)
    if out_size <= 1:
        return jnp.zeros((out_size,), jnp.float32)
    if align_corners:
        ratio = (in_size - 1.0) / (out_size - 1.0)
        src = i * ratio
    elif align_mode == 1:
        src = i * (in_size / out_size)
    else:
        src = (i + 0.5) * (in_size / out_size) - 0.5
    return jnp.clip(src, 0.0, in_size - 1.0)


def _linear_axis(x, axis, out_size, align_corners, align_mode):
    in_size = x.shape[axis]
    src = _src_index(out_size, in_size, align_corners, align_mode)
    i0 = jnp.floor(src).astype(jnp.int32)
    i1 = jnp.minimum(i0 + 1, in_size - 1)
    w1 = (src - i0).astype(x.dtype)
    w0 = (1.0 - w1).astype(x.dtype)
    shape = [1] * x.ndim
    shape[axis] = out_size
    g0 = jnp.take(x, i0, axis=axis)
    g1 = jnp.take(x, i1, axis=axis)
    return g0 * w0.reshape(shape) + g1 * w1.reshape(shape)


def _cubic_weight(t):
    """Cubic convolution kernel, a=-0.75 (reference bicubic_interp)."""
    a = -0.75
    t = jnp.abs(t)
    w_inner = ((a + 2.0) * t - (a + 3.0)) * t * t + 1.0
    w_outer = ((a * t - 5.0 * a) * t + 8.0 * a) * t - 4.0 * a
    return jnp.where(t <= 1.0, w_inner,
                     jnp.where(t < 2.0, w_outer, 0.0))


def _cubic_axis(x, axis, out_size, align_corners):
    in_size = x.shape[axis]
    i = jnp.arange(out_size, dtype=jnp.float32)
    if out_size <= 1:
        src = jnp.zeros((out_size,), jnp.float32)
    elif align_corners:
        src = i * ((in_size - 1.0) / (out_size - 1.0))
    else:
        src = (i + 0.5) * (in_size / out_size) - 0.5
    i0 = jnp.floor(src).astype(jnp.int32)
    frac = src - i0
    shape = [1] * x.ndim
    shape[axis] = out_size
    out = 0.0
    for k in range(-1, 3):
        idx = jnp.clip(i0 + k, 0, in_size - 1)
        w = _cubic_weight(frac - k).astype(x.dtype)
        out = out + jnp.take(x, idx, axis=axis) * w.reshape(shape)
    return out


def _nearest_axis(x, axis, out_size, align_corners):
    in_size = x.shape[axis]
    i = jnp.arange(out_size, dtype=jnp.float32)
    if out_size <= 1:
        idx = jnp.zeros((out_size,), jnp.float32)
    elif align_corners:
        # reference rounds half UP: static_cast<int>(ratio * k + 0.5)
        idx = jnp.floor(i * ((in_size - 1.0) / (out_size - 1.0)) + 0.5)
    else:
        idx = jnp.floor(i * (in_size / out_size))
    return jnp.take(x, jnp.clip(idx.astype(jnp.int32), 0, in_size - 1),
                    axis=axis)


def _layout_axes(x, attrs, n_spatial):
    """Spatial axes + requested output sizes for NCX / NXC layouts."""
    layout = attrs.get("data_layout", "NCHW")
    channel_last = layout in ("NHWC", "NDHWC", "NWC")
    axes = list(range(1, 1 + n_spatial)) if channel_last else \
        list(range(2, 2 + n_spatial))
    keys = {1: ["out_w"], 2: ["out_h", "out_w"],
            3: ["out_d", "out_h", "out_w"]}[n_spatial]
    sizes = []
    scale = attrs.get("scale", 0.0)
    for key, ax in zip(keys, axes):
        out = int(attrs.get(key, -1) or -1)
        if out <= 0:
            if not scale or scale <= 0:
                raise ValueError(
                    "interp op needs %s or a positive scale attr" % key)
            out = int(x.shape[ax] * scale)
        sizes.append(out)
    return axes, sizes


def _linear_family(ins, attrs, n_spatial):
    x = ins["X"][0]
    axes, sizes = _layout_axes(x, attrs, n_spatial)
    ac = bool(attrs.get("align_corners", True))
    am = int(attrs.get("align_mode", 1))
    for ax, size in zip(axes, sizes):
        x = _linear_axis(x, ax, size, ac, am)
    return {"Out": x}


@register_op("linear_interp")
def _linear_interp(ins, attrs):
    return _linear_family(ins, attrs, 1)


@register_op("bilinear_interp")
def _bilinear_interp(ins, attrs):
    return _linear_family(ins, attrs, 2)


@register_op("trilinear_interp")
def _trilinear_interp(ins, attrs):
    return _linear_family(ins, attrs, 3)


@register_op("bicubic_interp")
def _bicubic_interp(ins, attrs):
    x = ins["X"][0]
    axes, sizes = _layout_axes(x, attrs, 2)
    ac = bool(attrs.get("align_corners", True))
    for ax, size in zip(axes, sizes):
        x = _cubic_axis(x, ax, size, ac)
    return {"Out": x}


@register_op("nearest_interp")
def _nearest_interp(ins, attrs):
    x = ins["X"][0]
    axes, sizes = _layout_axes(x, attrs, 2)
    ac = bool(attrs.get("align_corners", True))
    for ax, size in zip(axes, sizes):
        x = _nearest_axis(x, ax, size, ac)
    return {"Out": x}
