"""Specialty operators: CTR/recommendation (cvm, batch_fc,
rank_attention, filter_by_instag, shuffle-free hash embedding),
candidate-sampling losses (sample_logits, nce), structured prediction
(linear_chain_crf, crf_decoding, warpctc), YOLOv3 training loss,
synchronized/in-place batch norm, and the CPU fusion-op family.

Reference parity: `paddle/fluid/operators/cvm_op.h:26-39`,
`batch_fc_op.cc`, `rank_attention_op.cc`, `filter_by_instag_op.cc`,
`sample_logits_op.cc`, `nce_op.cc`, `linear_chain_crf_op.h:216`
(LogLikelihood = negative log-likelihood), `crf_decoding_op.h`,
`warpctc_op.cc`, `detection/yolov3_loss_op.h:280-410`,
`sync_batch_norm_op.cc`, `inplace_abn_op.cc`, `hash_op.cc`,
`fused/attention_lstm_op.cc`, `fused/fused_embedding_fc_lstm_op.cc`,
`fused/fusion_repeated_fc_relu_op.cc`,
`fused/fusion_seqexpand_concat_fc_op.cc`,
`fused/fusion_seqpool_concat_op.cc`,
`fused/fusion_squared_mat_sub_op.cc` ((X·Y)² − X²·Y² scaled).

TPU-native design: CRF/CTC recursions are log-space `lax.scan`s (the
reference's exp-space + per-step L1 renormalization exists only to avoid
underflow, which log-space solves outright); YOLOv3 loss is fully
vectorized gather/scatter instead of the reference's 4-deep loops;
sync_batch_norm takes an optional `axis_name` and psums moments across
the data-parallel mesh axis when run inside shard_map.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, get_op

_NEG = -1e30


# -- CTR / recommendation ---------------------------------------------------

@register_op("cvm")
def _cvm(ins, attrs):
    x = ins["X"][0]
    use_cvm = bool(attrs.get("use_cvm", True))
    if use_cvm:
        show = jnp.log(x[:, :1] + 1.0)
        click = jnp.log(x[:, 1:2] + 1.0) - show
        return {"Y": jnp.concatenate([show, click, x[:, 2:]], axis=1)}
    return {"Y": x[:, 2:]}


@register_op("batch_fc")
def _batch_fc(ins, attrs):
    # Input [slot_pairs, ins, in_dim] x W [slot_pairs, in_dim, out_dim]
    # + per-slot bias [slot_pairs, out_dim]; no activation (batch_fc_op.cu)
    x, w, b = ins["Input"][0], ins["W"][0], ins["Bias"][0]
    return {"Out": jnp.einsum("sni,sio->sno", x, w) + b[:, None, :]}


@register_op("rank_attention")
def _rank_attention(ins, attrs):
    """PaddleRec rank-attention: instance i with rank r_i multiplies its
    features with the parameter blocks of every (r_i, j) rank pair that
    appears in its RankOffset row, averaged over valid pairs.
    RankOffset [N, 1+2*max_rank]: col0 = #valid pairs, then (rank_j,
    param_index) pairs; RankParam [max_rank*max_rank*x_dim, out_dim]."""
    x = ins["X"][0]                                   # [N, D]
    rank_offset = ins["RankOffset"][0].astype(jnp.int32)
    param = ins["RankParam"][0]                       # [R*R*D, P]
    max_rank = int(attrs.get("MaxRank", (rank_offset.shape[1] - 1) // 2))
    n, d = x.shape
    p = param.shape[1]
    blocks = param.reshape(max_rank * max_rank, d, p)

    ins_rank = rank_offset[:, 0]                      # 1-based; <=0 invalid
    pair_rank = rank_offset[:, 1::2]                  # [N, max_rank]
    valid = (pair_rank > 0) & (ins_rank[:, None] > 0)
    block_idx = jnp.clip((ins_rank[:, None] - 1) * max_rank
                         + (pair_rank - 1), 0,
                         max_rank * max_rank - 1)     # [N, max_rank]
    sel = blocks[block_idx]                           # [N, max_rank, D, P]
    per_pair = jnp.einsum("nd,nkdp->nkp", x, sel)
    vf = valid.astype(x.dtype)[..., None]
    out = jnp.sum(per_pair * vf, 1) / jnp.maximum(jnp.sum(vf, 1), 1.0)
    return {"Out": out}


@register_op("filter_by_instag", no_jit=True,
             dynamic_shape=True)
def _filter_by_instag(ins, attrs):
    x1 = np.asarray(ins["Ins"][0])
    tags = np.asarray(ins["Ins_tag"][0]).reshape(-1)
    filter_tags = set(np.asarray(ins["Filter_tag"][0]).reshape(-1)
                      .tolist())
    keep = np.array([t in filter_tags for t in tags], bool)
    idx = np.nonzero(keep)[0]
    out = x1[keep] if keep.any() else np.zeros(
        (1,) + x1.shape[1:], x1.dtype)
    loss_w = np.ones((out.shape[0], 1), "float32") if keep.any() else \
        np.zeros((1, 1), "float32")
    index_map = np.stack([idx, np.arange(len(idx))], 1).astype("int64") \
        if keep.any() else np.zeros((1, 2), "int64")
    return {"Out": jnp.asarray(out), "LossWeight": jnp.asarray(loss_w),
            "IndexMap": jnp.asarray(index_map)}


@register_op("hash", no_jit=True)
def _hash(ins, attrs):
    """BKDR-style rolling hash of each int row into `num_hash` buckets of
    size `mod_by` (reference: hash_op.cc uses xxHash; the op contract —
    deterministic row hash mod space — is what programs rely on)."""
    x = np.asarray(ins["X"][0]).astype(np.uint64)
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 1))
    rows = x.reshape(x.shape[0], -1)
    out = np.zeros((x.shape[0], num_hash, 1), "int64")
    for k in range(num_hash):
        h = np.full(rows.shape[0], 1315423911 ^ (k * 2654435761),
                    np.uint64)
        for j in range(rows.shape[1]):
            h = h * np.uint64(131) + rows[:, j] + np.uint64(k)
        out[:, k, 0] = (h % np.uint64(mod_by)).astype("int64")
    return {"Out": jnp.asarray(out)}


# -- candidate-sampling losses ----------------------------------------------

def _log_uniform_sample(key, num_samples, vocab, shape_prefix=()):
    """Log-uniform (Zipf) sampler: P(k) = log((k+2)/(k+1))/log(V+1);
    inverse-CDF sampling (reference: math/sample_prob.h LogUniformSampler)."""
    u = jax.random.uniform(key, shape_prefix + (num_samples,))
    log_range = jnp.log(vocab + 1.0)
    samples = jnp.floor(jnp.exp(u * log_range) - 1.0).astype(jnp.int64)
    samples = jnp.clip(samples, 0, vocab - 1)
    probs = jnp.log((samples + 2.0) / (samples + 1.0)) / log_range
    return samples, probs


@register_op("sample_logits", needs_rng=True)
def _sample_logits(ins, attrs):
    """Sampled-softmax prep: per row, keep the true-label logits and
    `num_samples` shared log-uniform negatives; logits are corrected by
    -log(Q) unless remove_accidental_hits adjustments apply."""
    logits, labels = ins["Logits"][0], ins["Labels"][0]
    n, vocab = logits.shape
    nt = labels.shape[1]
    num_samples = int(attrs.get("num_samples", 1))
    key = attrs["_rng_key"]
    if ins.get("CustomizedSamples"):
        samples = ins["CustomizedSamples"][0]
        probs = ins["CustomizedProbabilities"][0]
    else:
        neg, negp = _log_uniform_sample(key, num_samples, vocab)
        samples = jnp.concatenate(
            [labels.astype(jnp.int64),
             jnp.broadcast_to(neg, (n, num_samples))], 1)
        tp = jnp.log((labels + 2.0) / (labels + 1.0)) / \
            jnp.log(vocab + 1.0)
        probs = jnp.concatenate(
            [tp, jnp.broadcast_to(negp, (n, num_samples))], 1)
    picked = jnp.take_along_axis(logits, samples.astype(jnp.int32), 1)
    sampled_logits = picked - jnp.log(probs * num_samples + 1e-20)
    if attrs.get("remove_accidental_hits", True):
        # a sampled negative that equals one of the row's true labels
        # must not compete with it
        neg_hit = (samples[:, nt:, None]
                   == samples[:, None, :nt]).any(-1)   # [N, num_samples]
        sampled_logits = sampled_logits.at[:, nt:].add(
            jnp.where(neg_hit, _NEG, 0.0))
    sampled_labels = jnp.broadcast_to(jnp.arange(nt), (n, nt))
    return {"Samples": samples, "Probabilities": probs,
            "SampledLogits": sampled_logits,
            "SampledLabels": sampled_labels.astype(jnp.int64)}


@register_op("nce", needs_rng=True)
def _nce(ins, attrs):
    """Noise-contrastive estimation (nce_op.cc): binary logistic loss of
    true class vs `num_neg_samples` noise classes. P(D=1|s,y) =
    σ(s - log(k·q(y)))."""
    x = ins["Input"][0]                                # [N, D]
    label = ins["Label"][0].astype(jnp.int64)          # [N, T]
    w = ins["Weight"][0]                               # [C, D]
    n, d = x.shape
    nt = label.shape[1] if label.ndim > 1 else 1
    label = label.reshape(n, nt)
    c = w.shape[0]
    k = int(attrs.get("num_neg_samples", 10))
    sampler = int(attrs.get("sampler", 0))
    key = attrs["_rng_key"]
    if sampler == 1:
        neg, negq = _log_uniform_sample(key, k, c)
    else:
        neg = jax.random.randint(key, (k,), 0, c).astype(jnp.int64)
        negq = jnp.full((k,), 1.0 / c)
    bias = ins["Bias"][0].reshape(-1) if ins.get("Bias") else None

    def score(cls):                                    # cls [k] shared negs
        s = jnp.einsum("nd,kd->nk", x, w[cls])
        if bias is not None:
            s = s + bias[cls][None, :]
        return s

    # gathered positive scores: only the labelled rows of W are touched
    s_pos = jnp.einsum("nd,ntd->nt", x, w[label])
    if bias is not None:
        s_pos = s_pos + bias[label]
    q_pos = (jnp.log((label + 2.0) / (label + 1.0))
             / jnp.log(c + 1.0)) if sampler == 1 else \
        jnp.full(label.shape, 1.0 / c)
    s_neg = score(neg)                                 # [N, k]
    logit_pos = s_pos - jnp.log(k * q_pos + 1e-20)
    logit_neg = s_neg - jnp.log(k * negq + 1e-20)[None, :]
    loss = (-jax.nn.log_sigmoid(logit_pos).sum(1, keepdims=True)
            - jax.nn.log_sigmoid(-logit_neg).sum(1, keepdims=True))
    return {"Cost": loss / nt,
            "SampleLogits": jnp.concatenate([s_pos, s_neg], 1),
            "SampleLabels": jnp.concatenate(
                [label, jnp.broadcast_to(neg, (n, k))], 1)}


# -- structured prediction --------------------------------------------------

def _crf_unpack(transition):
    start, stop, trans = transition[0], transition[1], transition[2:]
    return start, stop, trans


@register_op("linear_chain_crf")
def _linear_chain_crf(ins, attrs):
    """Emission [B, T, K] (+ optional Length), Transition [K+2, K],
    Label [B, T]. LogLikelihood = logZ - path_score (the reference
    returns -ll, linear_chain_crf_op.h:216). Log-space forward pass."""
    em = ins["Emission"][0]
    transition = ins["Transition"][0]
    label = ins["Label"][0]
    if label.ndim == 3 and label.shape[-1] == 1:
        label = label[..., 0]
    label = label.astype(jnp.int32)
    b, t, k = em.shape
    start, stop, trans = _crf_unpack(transition)
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((b,), t, jnp.int32)
    steps = jnp.arange(t)
    m = (steps[None, :] < length[:, None])             # [B, T]

    # logZ by forward recursion
    alpha0 = start[None, :] + em[:, 0]

    def fwd(alpha, inp):
        e_t, m_t = inp
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None], axis=1) \
            + e_t
        alpha = jnp.where(m_t[:, None], nxt, alpha)
        return alpha, None

    alpha, _ = lax.scan(fwd, alpha0,
                        (jnp.swapaxes(em, 0, 1)[1:],
                         jnp.swapaxes(m, 0, 1)[1:]))
    logz = jax.nn.logsumexp(alpha + stop[None, :], axis=1)

    # path score
    em_score = jnp.sum(
        jnp.take_along_axis(em, label[..., None], 2)[..., 0] * m, 1)
    y_prev, y_next = label[:, :-1], label[:, 1:]
    trans_score = jnp.sum(trans[y_prev, y_next] * m[:, 1:], 1)
    y_last = jnp.take_along_axis(
        label, jnp.maximum(length - 1, 0)[:, None], 1)[:, 0]
    score = (start[label[:, 0]] + em_score + trans_score + stop[y_last])
    ll = logz - score
    # Alpha is exposed for the grad/decoding contract
    return {"LogLikelihood": ll[:, None], "Alpha": alpha,
            "EmissionExps": jnp.exp(em), "TransitionExps":
            jnp.exp(transition)}


@register_op("crf_decoding")
def _crf_decoding(ins, attrs):
    """Viterbi decode (crf_decoding_op.h). With Label input, emits 1/0
    correctness per position instead of the path."""
    em = ins["Emission"][0]
    transition = ins["Transition"][0]
    b, t, k = em.shape
    start, stop, trans = _crf_unpack(transition)
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    else:
        length = jnp.full((b,), t, jnp.int32)
    m = (jnp.arange(t)[None, :] < length[:, None])

    def vit(carry, inp):
        alpha = carry
        e_t, m_t = inp
        cand = alpha[:, :, None] + trans[None]
        best = jnp.max(cand, 1) + e_t
        arg = jnp.argmax(cand, 1)
        alpha = jnp.where(m_t[:, None], best, alpha)
        return alpha, arg

    alpha0 = start[None, :] + em[:, 0]
    alpha, args = lax.scan(vit, alpha0,
                           (jnp.swapaxes(em, 0, 1)[1:],
                            jnp.swapaxes(m, 0, 1)[1:]))
    # stop contribution only at each sequence's true last step
    y_T = jnp.argmax(alpha + stop[None, :], 1)         # [B]

    def back(y_next, inp):
        arg, m_t = inp                                  # arg [B, K]
        y_prev = jnp.take_along_axis(arg, y_next[:, None], 1)[:, 0]
        y = jnp.where(m_t, y_prev, y_next)
        return y, y_next

    # walk steps T-1..1; each iteration emits the tag at that step and
    # carries the tag at the step before; the final carry is the tag at 0
    y0, path_rev = lax.scan(back, y_T,
                            (args[::-1], jnp.swapaxes(m, 0, 1)[1:][::-1]))
    path = jnp.concatenate(
        [y0[:, None], jnp.swapaxes(path_rev[::-1], 0, 1)], 1)  # [B, T]
    path = jnp.where(m, path, 0)
    if ins.get("Label"):
        label = ins["Label"][0]
        if label.ndim == 3:
            label = label[..., 0]
        return {"ViterbiPath": (path == label.astype(path.dtype))
                .astype(jnp.int64) * m}
    return {"ViterbiPath": path.astype(jnp.int64)}


@register_op("warpctc")
def _warpctc(ins, attrs):
    """CTC loss, log-space alpha recursion over the blank-extended label
    (warpctc_op.cc contract; the libwarpctc kernel is replaced by a
    vmapped lax.scan). Logits [B, T, C] (+LogitsLength), Label [B, L]
    (+LabelLength); Loss [B, 1]."""
    logits = ins["Logits"][0]
    label = ins["Label"][0].astype(jnp.int32)
    b, t, c = logits.shape
    lmax = label.shape[1]
    blank = int(attrs.get("blank", 0))
    if ins.get("LogitsLength"):
        tlen = ins["LogitsLength"][0].reshape(-1).astype(jnp.int32)
    else:
        tlen = jnp.full((b,), t, jnp.int32)
    if ins.get("LabelLength"):
        llen = ins["LabelLength"][0].reshape(-1).astype(jnp.int32)
    else:
        llen = jnp.full((b,), lmax, jnp.int32)
    logp = jax.nn.log_softmax(logits, -1)

    s = 2 * lmax + 1
    sidx = jnp.arange(s)
    z = jnp.where(sidx % 2 == 0, blank,
                  label[:, jnp.clip((sidx - 1) // 2, 0, lmax - 1)])
    z2 = jnp.pad(z, ((0, 0), (2, 0)), constant_values=-1)[:, :-2]
    allow_skip = (sidx[None, :] >= 2) & (z != blank) & (z != z2)
    s_valid = sidx[None, :] < (2 * llen[:, None] + 1)

    lp0 = jnp.take_along_axis(logp[:, 0], z, 1)
    alpha0 = jnp.where(sidx[None, :] < 2, lp0, _NEG)
    alpha0 = jnp.where(s_valid, alpha0, _NEG)

    def step(alpha, inp):
        lp_t, t_i = inp                                # lp_t [B, C]
        a1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                     constant_values=_NEG)[:, :-1]
        a2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                     constant_values=_NEG)[:, :-2]
        acc = jnp.logaddexp(alpha, a1)
        acc = jnp.where(allow_skip, jnp.logaddexp(acc, a2), acc)
        nxt = acc + jnp.take_along_axis(lp_t, z, 1)
        nxt = jnp.where(s_valid, nxt, _NEG)
        active = (t_i < tlen)[:, None]
        return jnp.where(active, nxt, alpha), None

    alpha, _ = lax.scan(
        step, alpha0,
        (jnp.swapaxes(logp, 0, 1)[1:], jnp.arange(1, t)))
    end = 2 * llen                                      # blank after last
    a_end = jnp.take_along_axis(alpha, end[:, None], 1)[:, 0]
    a_pre = jnp.take_along_axis(alpha, jnp.maximum(end - 1, 0)[:, None],
                                1)[:, 0]
    ll = jnp.logaddexp(a_end, a_pre)
    loss = -ll[:, None]
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(tlen[:, None].astype(loss.dtype), 1.0)
    return {"Loss": loss}


# -- yolov3 loss ------------------------------------------------------------

def _sce(x, lbl):
    # stable sigmoid cross entropy with soft target
    return jnp.maximum(x, 0.0) - x * lbl + jnp.log1p(jnp.exp(-jnp.abs(x)))


def _wh_iou(w1, h1, w2, h2):
    inter = jnp.minimum(w1, w2) * jnp.minimum(h1, h2)
    return inter / (w1 * h1 + w2 * h2 - inter + 1e-10)


def _box_iou_xywh(b1, b2):
    # boxes as (cx, cy, w, h), broadcastable
    lt = jnp.maximum(b1[..., :2] - b1[..., 2:] / 2,
                     b2[..., :2] - b2[..., 2:] / 2)
    rb = jnp.minimum(b1[..., :2] + b1[..., 2:] / 2,
                     b2[..., :2] + b2[..., 2:] / 2)
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    a1 = b1[..., 2] * b1[..., 3]
    a2 = b2[..., 2] * b2[..., 3]
    return inter / (a1 + a2 - inter + 1e-10)


@register_op("yolov3_loss")
def _yolov3_loss(ins, attrs):
    x = ins["X"][0]                                    # [N, M*(5+C), H, W]
    gtbox = ins["GTBox"][0]                            # [N, B, 4] xywh/img
    gtlabel = ins["GTLabel"][0].astype(jnp.int32)      # [N, B]
    anchors = jnp.asarray(attrs["anchors"], jnp.float32).reshape(-1, 2)
    anchor_mask = jnp.asarray(attrs["anchor_mask"], jnp.int32)
    class_num = int(attrs["class_num"])
    ignore_thresh = float(attrs.get("ignore_thresh", 0.7))
    downsample = int(attrs.get("downsample_ratio", 32))
    use_label_smooth = bool(attrs.get("use_label_smooth", True))
    scale_xy = float(attrs.get("scale_x_y", 1.0))
    bias_xy = -0.5 * (scale_xy - 1.0)
    n, _, h, w = x.shape
    m = anchor_mask.shape[0]
    nb = gtbox.shape[1]
    input_size = downsample * h
    x = x.reshape(n, m, 5 + class_num, h, w)
    gtscore = (ins["GTScore"][0] if ins.get("GTScore")
               else jnp.ones((n, nb), x.dtype))

    label_pos, label_neg = 1.0, 0.0
    if use_label_smooth:
        sw = min(1.0 / class_num, 1.0 / 40.0)
        label_pos, label_neg = 1.0 - sw, sw

    gt_valid = (gtbox[..., 2] > 0) & (gtbox[..., 3] > 0)   # [N, B]

    # predicted boxes (normalized to image) for the ignore-mask pass
    gx = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    gy = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    aw = anchors[anchor_mask, 0][None, :, None, None] / input_size
    ah = anchors[anchor_mask, 1][None, :, None, None] / input_size
    px = (gx + jax.nn.sigmoid(x[:, :, 0]) * scale_xy + bias_xy) / w
    py = (gy + jax.nn.sigmoid(x[:, :, 1]) * scale_xy + bias_xy) / h
    pw = jnp.exp(x[:, :, 2]) * aw
    ph = jnp.exp(x[:, :, 3]) * ah
    pred = jnp.stack([px, py, pw, ph], -1)             # [N, M, H, W, 4]
    gtb = jnp.where(gt_valid[..., None], gtbox, 0.0)
    iou = _box_iou_xywh(pred[:, :, :, :, None, :],
                        gtb[:, None, None, None, :, :])  # [N,M,H,W,B]
    iou = jnp.where(gt_valid[:, None, None, None, :], iou, 0.0)
    best_iou = jnp.max(iou, -1)
    obj_mask = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)  # [N,M,H,W]

    # per-gt best anchor (over the FULL anchor set, wh-only IoU)
    an_iou = _wh_iou(anchors[None, None, :, 0] / input_size,
                     anchors[None, None, :, 1] / input_size,
                     gtb[..., 2:3], gtb[..., 3:4])     # [N, B, An]
    best_n = jnp.argmax(an_iou, -1)                    # [N, B]
    mask_hit = (anchor_mask[None, None, :] == best_n[..., None])
    mask_idx = jnp.where(mask_hit.any(-1),
                         jnp.argmax(mask_hit, -1), -1)  # [N, B]
    gt_match = jnp.where(gt_valid, mask_idx, -1)

    gi = jnp.clip((gtb[..., 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gtb[..., 1] * h).astype(jnp.int32), 0, h - 1)
    pos = gt_valid & (mask_idx >= 0)                   # [N, B]
    posf = pos.astype(x.dtype) * gtscore

    # gather the prediction vector at each gt cell
    bidx = jnp.broadcast_to(jnp.arange(n)[:, None], (n, nb))
    mcl = jnp.clip(mask_idx, 0, m - 1)
    cell = x[bidx, mcl, :, gj, gi]                     # [N, B, 5+C]
    tx = gtb[..., 0] * w - gi
    ty = gtb[..., 1] * h - gj
    a_w = anchors[best_n, 0] / input_size
    a_h = anchors[best_n, 1] / input_size
    tw = jnp.log(jnp.clip(gtb[..., 2] / jnp.maximum(a_w, 1e-10),
                          1e-9, None))
    th = jnp.log(jnp.clip(gtb[..., 3] / jnp.maximum(a_h, 1e-10),
                          1e-9, None))
    box_scale = 2.0 - gtb[..., 2] * gtb[..., 3]
    loc = (_sce(cell[..., 0], tx) + _sce(cell[..., 1], ty)
           + jnp.abs(cell[..., 2] - tw) + jnp.abs(cell[..., 3] - th))
    loc_loss = jnp.sum(loc * box_scale * posf, 1)

    onehot = jax.nn.one_hot(gtlabel, class_num)
    cls_target = onehot * label_pos + (1.0 - onehot) * label_neg
    cls = jnp.sum(_sce(cell[..., 5:], cls_target), -1)
    cls_loss = jnp.sum(cls * posf, 1)

    # positive cells override the ignore mask with their score; invalid
    # gts are routed to an out-of-bounds batch index and dropped so they
    # can't collide with a real positive at the same cell
    bidx_pos = jnp.where(pos, bidx, n)
    obj_mask = obj_mask.at[bidx_pos, mcl, gj, gi].set(gtscore,
                                                      mode="drop")
    pobj = x[:, :, 4]
    obj_loss = jnp.sum(
        jnp.where(obj_mask > 0, _sce(pobj, 1.0) * obj_mask,
                  jnp.where(obj_mask == 0, _sce(pobj, 0.0), 0.0)),
        (1, 2, 3))
    return {"Loss": loc_loss + cls_loss + obj_loss,
            "ObjectnessMask": obj_mask,
            "GTMatchMask": gt_match.astype(jnp.int32)}


# -- synchronized / in-place batch norm ------------------------------------

@register_op("sync_batch_norm")
def _sync_batch_norm(ins, attrs):
    """batch_norm whose moments are additionally psum'd over the data-
    parallel mesh axis when an `axis_name` attr is provided and the op
    runs inside shard_map/pmap (reference sync_batch_norm_op.cu syncs
    via ncclAllReduce; under plain GSPMD jit the reduction is already
    global so axis_name is unnecessary)."""
    axis = attrs.get("axis_name", None)
    if not axis:
        return get_op("batch_norm").compute(ins, attrs)
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    layout = attrs.get("data_layout", "NCHW")
    caxis = 1 if layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != caxis)
    cshape = [1] * x.ndim
    cshape[caxis] = -1
    if attrs.get("is_test", False) or attrs.get("use_global_stats", False):
        return get_op("batch_norm").compute(ins, attrs)
    f32 = x.astype(jnp.float32)
    bmean = lax.pmean(jnp.mean(f32, axis=axes), axis)
    bsq = lax.pmean(jnp.mean(jnp.square(f32), axis=axes), axis)
    bvar = bsq - jnp.square(bmean)
    inv = 1.0 / jnp.sqrt(bvar + eps)
    y = ((f32 - bmean.reshape(cshape)) * inv.reshape(cshape)
         * scale.astype(jnp.float32).reshape(cshape)
         + bias.astype(jnp.float32).reshape(cshape))
    return {"Y": y.astype(x.dtype),
            "MeanOut": mean * momentum + bmean.astype(mean.dtype)
            * (1 - momentum),
            "VarianceOut": var * momentum + bvar.astype(var.dtype)
            * (1 - momentum),
            "SavedMean": bmean, "SavedVariance": inv}


@register_op("inplace_abn")
def _inplace_abn(ins, attrs):
    """Activated batch norm (inplace_abn_op.cc): batch_norm + leaky_relu
    or elu epilogue; XLA fuses it, so 'inplace' is just the activation."""
    outs = get_op("batch_norm").compute(ins, attrs)
    act = attrs.get("activation", "identity")
    y = outs["Y"]
    if act == "leaky_relu":
        alpha = attrs.get("alpha", 0.01)
        y = jnp.where(y >= 0, y, alpha * y)
    elif act == "elu":
        alpha = attrs.get("alpha", 1.0)
        y = jnp.where(y >= 0, y, alpha * (jnp.exp(y) - 1.0))
    outs["Y"] = y
    return outs


# -- fused CPU-inference family ---------------------------------------------

@register_op("attention_lstm")
def _attention_lstm(ins, attrs):
    """fused/attention_lstm_op.cc: at each step, attention over the
    source sequence conditioned on the previous cell state produces a
    context vector that feeds one LSTM step. X [B, T, M] padded;
    AttentionWeight [M+D, 1]; LSTMWeight [M+D, 4D] with gate order
    [c, i, f, o] (same kernel family as fusion_lstm)."""
    x = ins["X"][0]
    aw = ins["AttentionWeight"][0]                     # [M+D, 1]
    lw = ins["LSTMWeight"][0]                          # [M+D, 4D]
    lb = ins["LSTMBias"][0].reshape(-1)                # [4D]
    d4 = lw.shape[1]
    d = d4 // 4
    b, t, mdim = x.shape
    ab = ins["AttentionBias"][0].reshape(-1) if ins.get("AttentionBias") \
        else jnp.zeros((1,), x.dtype)
    a_scalar = (ins["AttentionScalar"][0].reshape(())
                if ins.get("AttentionScalar") else None)
    a_scalar_b = (ins["AttentionScalarBias"][0].reshape(())
                  if ins.get("AttentionScalarBias") else None)
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((b, d), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((b, d), x.dtype)
    gate_act = _fused_act(attrs, "gate_activation", "sigmoid")
    cell_act = _fused_act(attrs, "cell_activation", "tanh")
    cand_act = _fused_act(attrs, "candidate_activation", "tanh")
    if ins.get("Length"):
        length = ins["Length"][0].reshape(-1)
        pad_mask = (jnp.arange(t)[None, :] >= length[:, None])  # [B, T]
    else:
        pad_mask = None

    aw_x, aw_c = aw[:mdim], aw[mdim:]                  # split fc weight

    def step(carry, t_i):
        h, c = carry
        e = (x @ aw_x)[..., 0] + (c @ aw_c)[..., 0][:, None] + ab[0]
        if a_scalar is not None:
            e = a_scalar * e
        if a_scalar_b is not None:
            e = jax.nn.relu(a_scalar_b + e)
        if pad_mask is not None:
            e = jnp.where(pad_mask, _NEG, e)           # no mass on pads
        a = jax.nn.softmax(e, -1)                      # [B, T]
        ctx = jnp.einsum("bt,btm->bm", a, x)
        gates = jnp.concatenate([ctx, h], 1) @ lw + lb
        cand = cand_act(gates[:, :d])
        i = gate_act(gates[:, d:2 * d])
        f = gate_act(gates[:, 2 * d:3 * d])
        o = gate_act(gates[:, 3 * d:])
        c_new = f * c + i * cand
        h_new = o * cell_act(c_new)
        return (h_new, c_new), h_new

    (h_last, c_last), hs = lax.scan(step, (h0, c0), jnp.arange(t))
    return {"Hidden": jnp.swapaxes(hs, 0, 1), "Cell": c_last,
            "LastH": h_last}


def _fused_act(attrs, key, default):
    from .fused_ops import _UNARY
    return _UNARY.get(attrs.get(key, default), _UNARY[default])


@register_op("fused_embedding_fc_lstm")
def _fused_embedding_fc_lstm(ins, attrs):
    """fused/fused_embedding_fc_lstm_op.cc: lookup_table + fc + lstm in
    one op: Ids [B, T], Embeddings [V, 4D] (the embedding IS the
    projected gate input), WeightH [D, 4D], Bias [1, 4D]."""
    ids = ins["Ids"][0]
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    emb = ins["Embeddings"][0]
    xx = jnp.take(emb, ids.astype(jnp.int32), 0)       # [B, T, 4D]
    # the embedding rows ARE the projected gate input: skip WeightX
    sub = {"X": [xx], "WeightH": ins["WeightH"], "Bias": ins["Bias"]}
    for slot in ("H0", "C0"):
        if ins.get(slot):
            sub[slot] = ins[slot]
    return get_op("fusion_lstm").compute(sub, attrs)


@register_op("fusion_repeated_fc_relu")
def _fusion_repeated_fc_relu(ins, attrs):
    x = ins["X"][0]
    ws, bs = ins["W"], ins["Bias"]
    for wi, bi in zip(ws, bs):
        x = jax.nn.relu(x @ wi + bi.reshape(-1))
    return {"Out": x}


@register_op("fusion_seqpool_concat")
def _fusion_seqpool_concat(ins, attrs):
    from .registry import normalize_outs
    pooled = []
    lengths = ins.get("Length", [])
    for i, x in enumerate(ins["X"]):
        sub = {"X": [x]}
        if i < len(lengths):
            sub["Length"] = [lengths[i]]
        pooled.append(normalize_outs(get_op("sequence_pool").compute(
            sub, {"pooltype": attrs.get("pooltype", "SUM")}))["Out"][0])
    return {"Out": jnp.concatenate(pooled, -1)}


@register_op("fusion_seqexpand_concat_fc")
def _fusion_seqexpand_concat_fc(ins, attrs):
    """X[0] [B, T, D0] sequence + X[1:] [B, Di] per-sequence vectors
    broadcast over time, concat, fc (+act)."""
    xs = ins["X"]
    seq = xs[0]
    b, t = seq.shape[0], seq.shape[1]
    parts = [seq] + [jnp.broadcast_to(v[:, None, :], (b, t, v.shape[-1]))
                     for v in xs[1:]]
    cat = jnp.concatenate(parts, -1)
    w = ins["FCWeight"][0]
    out = cat @ w
    if ins.get("FCBias"):
        out = out + ins["FCBias"][0].reshape(-1)
    act = _fused_act(attrs, "fc_activation", "identity")
    return {"Out": act(out)}


@register_op("fusion_squared_mat_sub")
def _fusion_squared_mat_sub(ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    scalar = float(attrs.get("scalar", 1.0))
    return {"Out": (jnp.square(x @ y) - jnp.square(x) @ jnp.square(y))
            * scalar,
            "SquaredX": jnp.square(x), "SquaredY": jnp.square(y),
            "SquaredXY": jnp.square(x @ y)}


# -- tree / variable-size text-matching ops ---------------------------------

@register_op("tree_conv", no_jit=True)
def _tree_conv(ins, attrs):
    """Tree-based convolution (tree_conv_op.cc, TBCNN): for each node, a
    window over itself + direct children with positional weights eta_t
    (top), eta_l (left), eta_r (right); Filter [F, 3, out, num_filters]."""
    nodes = np.asarray(ins["NodesVector"][0])          # [N, max_n, F]
    edges = np.asarray(ins["EdgeSet"][0]).astype(int)  # [N, max_e, 2]
    filt = np.asarray(ins["Filter"][0])                # [F, 3, out, K]
    n, max_n, feat = nodes.shape
    _, _, out_c, k = filt.shape
    result = np.zeros((n, max_n, out_c, k), "float32")
    for i in range(n):
        children = {}
        for (p, cch) in edges[i]:
            if p <= 0 and cch <= 0:
                continue
            children.setdefault(int(p), []).append(int(cch))
        for node in range(max_n):
            ch = children.get(node, [])
            win = [(node, 1.0, 0.5, 0.5)]
            nc = len(ch)
            for j, cnode in enumerate(ch):
                eta_r = 0.5 if nc == 1 else j / (nc - 1.0)
                win.append((cnode, 0.0, 1.0 - eta_r, eta_r))
            acc = np.zeros((out_c, k), "float32")
            for (idx, et, el, er) in win:
                if idx >= max_n:
                    continue
                v = nodes[i, idx]
                wsum = (et * filt[:, 0] + el * filt[:, 1]
                        + er * filt[:, 2])             # [F, out, K]
                acc += np.einsum("f,fok->ok", v, wsum)
            result[i, node] = np.tanh(acc)
    return {"Out": jnp.asarray(result.reshape(n, max_n, out_c * k))}


@register_op("var_conv_2d", no_jit=True)
def _var_conv_2d(ins, attrs):
    """Variable-size 2D conv over per-row [H_i, W_i] images stored as a
    padded batch (var_conv_2d_op.cc); stride-1 'same' conv per row."""
    x = np.asarray(ins["X"][0])                        # [B, H, W]
    w = np.asarray(ins["W"][0])                        # [out, kh*kw]
    kh = int(attrs.get("kernel_h", 3))
    kw = int(attrs.get("kernel_w", 3))
    out_c = w.shape[0]
    b, h, wd = x.shape
    pad_h, pad_w = kh // 2, kw // 2
    xp = np.pad(x, ((0, 0), (pad_h, pad_h), (pad_w, pad_w)))
    out = np.zeros((b, out_c, h, wd), "float32")
    for i in range(kh):
        for j in range(kw):
            patch = xp[:, i:i + h, j:j + wd]
            out += w[:, i * kw + j][None, :, None, None] \
                * patch[:, None, :, :]
    return {"Out": jnp.asarray(out)}


@register_op("pyramid_hash", no_jit=True)
def _pyramid_hash(ins, attrs):
    """Pyramid hash embedding (pyramid_hash_op.cc): for every n-gram
    window of sizes 2..pyramid_layer over each int sequence, hash into
    the embedding space and sum the looked-up vectors."""
    x = np.asarray(ins["X"][0]).astype(np.uint64)      # [B, T]
    w = np.asarray(ins["W"][0])                        # [space, rand_len]
    num_emb = int(attrs.get("num_emb", w.shape[1]))
    layers = int(attrs.get("pyramid_layer", 2))
    space = w.shape[0]
    b, t = x.shape
    out = np.zeros((b, num_emb), "float32")
    for bi in range(b):
        acc = np.zeros((num_emb,), "float32")
        cnt = 0
        for win in range(2, layers + 2):
            for s in range(t - win + 1):
                seg = x[bi, s:s + win]
                h = np.uint64(1315423911)
                for v in seg:
                    h = h * np.uint64(131) + v
                acc += w[int(h % np.uint64(space))][:num_emb]
                cnt += 1
        out[bi] = acc / max(cnt, 1)
    return {"Out": jnp.asarray(out)}


@register_op("match_matrix_tensor", no_jit=True, dynamic_shape=True)
def _match_matrix_tensor(ins, attrs):
    """Text-matching bilinear similarity (reference:
    match_matrix_tensor_op.cc:168): per pair of ragged sequences,
    out[b, t, i, j] = x_i^T W_t y_j, flattened to the LoD layout
    [sum_b dim_t*len_l*len_r, 1]; Tmp caches x @ W for the grad kernel.
    LoD offsets ride the XLod/YLod inputs (padded-representation
    convention)."""
    x = np.asarray(ins["X"][0], np.float32)
    y = np.asarray(ins["Y"][0], np.float32)
    w = np.asarray(ins["W"][0], np.float32)
    dim_t = int(attrs.get("dim_t", w.shape[1]))
    dim_in = x.shape[1]
    x_lod = np.asarray(ins["XLod"][0]).reshape(-1).astype(int) \
        if ins.get("XLod") else np.asarray([0, len(x)])
    y_lod = np.asarray(ins["YLod"][0]).reshape(-1).astype(int) \
        if ins.get("YLod") else np.asarray([0, len(y)])
    # Tmp = x @ W  -> [total_l, dim_t * dim_in]
    wt = w.reshape(dim_in, dim_t * dim_in)
    tmp = x @ wt
    out_chunks = []
    for b in range(len(x_lod) - 1):
        xl = tmp[x_lod[b]:x_lod[b + 1]].reshape(-1, dim_t, dim_in)
        yr = y[y_lod[b]:y_lod[b + 1]]                 # [len_r, dim_in]
        # [dim_t, len_l, len_r]
        scores = np.einsum("ltd,rd->tlr", xl, yr)
        out_chunks.append(scores.reshape(-1))
    out = np.concatenate(out_chunks) if out_chunks else \
        np.zeros((0,), np.float32)
    return {"Out": out.reshape(-1, 1), "Tmp": tmp}


@register_op("sequence_topk_avg_pooling", no_jit=True,
             dynamic_shape=True)
def _sequence_topk_avg_pooling(ins, attrs):
    """Top-k average pooling over each row of per-pair match matrices
    (reference: sequence_topk_avg_pooling_op.h:69): X holds
    [channel, row, col] blocks per batch (LoD), out[row] gets, per
    channel and per k in topks, the mean of that row's top-k values.
    Short rows pad with the reference's TopKPosPaddingId=-1 semantics
    (prefix sums repeat)."""
    x = np.asarray(ins["X"][0], np.float32).reshape(-1)
    topks = [int(k) for k in attrs["topks"]]
    channel_num = int(attrs["channel_num"])
    max_k = max(topks)
    k_num = len(topks)
    x_lod = np.asarray(ins["XLod"][0]).reshape(-1).astype(int) \
        if ins.get("XLod") else np.asarray([0, x.size])
    # offsets ride ROWLod/COLUMNLod; the ROW/COLUMN slots (reference
    # LoDTensor inputs whose lod is the payload) are an accepted alias
    row_lod = np.asarray(
        (ins.get("ROWLod") or ins["ROW"])[0]).reshape(-1).astype(int)
    col_lod = np.asarray(
        (ins.get("COLUMNLod") or ins["COLUMN"])[0]).reshape(-1).astype(int)
    total_rows = int(row_lod[-1])
    out = np.zeros((total_rows, channel_num * k_num), np.float32)
    pos = np.full((total_rows * channel_num * max_k,), -1, np.int32)
    for b in range(len(row_lod) - 1):
        row_size = row_lod[b + 1] - row_lod[b]
        col_size = col_lod[b + 1] - col_lod[b]
        feat = x[x_lod[b]:x_lod[b + 1]].reshape(
            channel_num, row_size, col_size)
        for j in range(channel_num):
            for r in range(row_size):
                row_data = feat[j, r]
                k_real = min(max_k, col_size)
                order = np.argsort(-row_data, kind="stable")[:k_real]
                p0 = ((row_lod[b] + r) * channel_num + j) * max_k
                pos[p0:p0 + k_real] = order
                sums = np.zeros(max_k, np.float32)
                run = 0.0
                for k in range(max_k):
                    if k < k_real:
                        run += row_data[order[k]]
                    sums[k] = run
                for ki, tk in enumerate(topks):
                    out[row_lod[b] + r, j * k_num + ki] = \
                        sums[tk - 1] / tk
    return {"Out": out, "pos": pos}


@register_op("tdm_child")
def _tdm_child(ins, attrs):
    """Tree-based deep match: children of each node id (reference:
    tdm_child_op.h:36). TreeInfo rows: [item_id, layer_id, ancestor,
    child_0..child_n-1]; nodes without children (id 0 or child_0 == 0)
    emit zeros; LeafMask marks children that are items (item_id != 0)."""
    x = ins["X"][0].astype(jnp.int32)
    info = ins["TreeInfo"][0].astype(jnp.int32)
    child_nums = int(attrs.get("child_nums", info.shape[1] - 3))
    flat = x.reshape(-1)
    rows = info[flat]                                # [N, len]
    children = rows[:, 3:3 + child_nums]             # [N, child_nums]
    has_child = ((flat != 0) & (rows[:, 3] != 0))[:, None]
    children = jnp.where(has_child, children, 0)
    is_item = (info[children.reshape(-1), 0] != 0).reshape(
        children.shape).astype(jnp.int32)
    mask = jnp.where(has_child, is_item, 0)
    out_shape = tuple(x.shape) + (child_nums,)
    return {"Child": children.reshape(out_shape),
            "LeafMask": mask.reshape(out_shape)}


@register_op("tdm_sampler", no_jit=True)
def _tdm_sampler(ins, attrs):
    """Per-layer negative sampling along each item's tree path
    (reference: tdm_sampler_op.h:39): for every input id, walk its
    Travel path; per layer emit the positive (optional) plus
    `neg_samples_num_list[layer]` rejection-sampled negatives drawn
    uniformly from that layer (excluding the positive and duplicates);
    padding positions (travel id 0) emit zeros with mask 0."""
    x = np.asarray(ins["X"][0]).reshape(-1).astype(int)
    travel = np.asarray(ins["Travel"][0]).astype(int)
    layer = np.asarray(ins["Layer"][0]).reshape(-1).astype(int)
    neg_nums = [int(v) for v in attrs["neg_samples_num_list"]]
    layer_offset = [int(v) for v in attrs["layer_offset_lod"]]
    output_positive = bool(attrs.get("output_positive", True))
    seed = int(attrs.get("seed", 0))
    rng = np.random.RandomState(seed if seed else None)
    layer_nums = len(neg_nums)
    res_len = sum(n + int(output_positive) for n in neg_nums)
    n = x.size
    out = np.zeros((n, res_len), np.int64)
    labels = np.zeros((n, res_len), np.int64)
    mask = np.ones((n, res_len), np.int64)
    travel = travel.reshape(-1, layer_nums)
    for i, input_id in enumerate(x):
        offset = 0
        for li in range(layer_nums):
            sample_num = neg_nums[li]
            node_nums = layer_offset[li + 1] - layer_offset[li]
            if sample_num > node_nums - 1:
                raise ValueError(
                    "tdm_sampler: neg sample num %d at layer %d must "
                    "be <= layer node count %d - 1 (positive included)"
                    % (sample_num, li, node_nums))
            positive = int(travel[input_id, li])
            if positive == 0:  # padding path tail
                width = sample_num + int(output_positive)
                out[i, offset:offset + width] = 0
                labels[i, offset:offset + width] = 0
                mask[i, offset:offset + width] = 0
                offset += width
                continue
            if output_positive:
                out[i, offset] = positive
                labels[i, offset] = 1
                offset += 1
            chosen = set()
            for _ in range(sample_num):
                while True:
                    s = int(rng.randint(0, node_nums))
                    if s in chosen:
                        continue
                    cand = int(layer[layer_offset[li] + s])
                    if cand != positive:
                        break
                chosen.add(s)
                out[i, offset] = cand
                labels[i, offset] = 0
                offset += 1
    return {"Out": out.reshape(n * res_len, 1),
            "Labels": labels.reshape(n * res_len, 1),
            "Mask": mask.reshape(n * res_len, 1)}
