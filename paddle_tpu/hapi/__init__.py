"""High-level Model API (hapi).

Reference parity: `python/paddle/incubate/hapi/` — `Model.fit/evaluate/
predict` (`model.py:652,1128,1337,1443`), callbacks (`callbacks.py`),
progress bar (`progressbar.py`), metrics (`metrics.py`), datasets
(`datasets/`). TPU-native: the training loop drives the dygraph engine
(eager ops dispatch through per-op jitted XLA computations), so `fit`
gets XLA-compiled steps without a static graph.
"""
from .model import Model, Input  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler,
)
from .metrics import Metric, Accuracy  # noqa: F401
from . import datasets  # noqa: F401
from .distributed import DistributedBatchSampler  # noqa: F401

__all__ = [
    "Model", "Input", "Callback", "ProgBarLogger", "ModelCheckpoint",
    "EarlyStopping", "LRScheduler", "Metric", "Accuracy", "datasets",
    "DistributedBatchSampler",
]
from . import vision  # noqa: F401,E402
from . import text  # noqa: F401,E402
