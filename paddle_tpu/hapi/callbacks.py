"""Training callbacks (reference:
`python/paddle/incubate/hapi/callbacks.py` — Callback, CallbackList,
ProgBarLogger, ModelCheckpoint)."""
from __future__ import annotations

from .progressbar import ProgressBar


class Callback:
    """Base class; hapi fires these hooks around fit/evaluate/predict."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def _call(self, name, *args):
        for cb in self.callbacks:
            getattr(cb, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *args: self._call(name, *args)
        raise AttributeError(name)


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks):
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "epochs": epochs, "steps": steps, "verbose": verbose,
        "metrics": metrics or ["loss"],
    })
    return lst


class ProgBarLogger(Callback):
    """Per-step metric logging with a progress bar (reference:
    callbacks.py ProgBarLogger)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self._progbar = None

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        if self.verbose:
            print("Epoch %d/%s" % (epoch + 1, self.epochs or "?"))
        self._progbar = ProgressBar(num=self.steps, verbose=self.verbose)
        self._step = 0

    def _updates(self, logs):
        metrics = self.params.get("metrics") or []
        return [(k, logs[k]) for k in metrics if k in (logs or {})]

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        if self.verbose and self._step % self.log_freq == 0:
            self._progbar.update(self._step, self._updates(logs or {}))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose and self._progbar is not None:
            self._progbar.update(self._step, self._updates(logs or {}))

    def on_eval_begin(self, logs=None):
        self._eval_step = 0

    def on_eval_batch_end(self, step, logs=None):
        self._eval_step += 1

    def on_eval_end(self, logs=None):
        if self.verbose and logs:
            print("Eval - " + " - ".join(
                "%s: %s" % (k, v) for k, v in logs.items()))


class ModelCheckpoint(Callback):
    """Save `<save_dir>/<epoch>` every `save_freq` epochs and
    `<save_dir>/final` at train end (reference: callbacks.py
    ModelCheckpoint)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and \
                epoch % self.save_freq == 0:
            self.model.save("%s/%d" % (self.save_dir, epoch))

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save("%s/final" % self.save_dir)


class EarlyStopping(Callback):
    """Stop fit() when a monitored metric stops improving."""

    def __init__(self, monitor="loss", mode="min", patience=0,
                 min_delta=0.0, baseline=None):
        super().__init__()
        self.monitor = monitor
        self.mode = mode
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.best = None
        self.wait = 0

    def _value(self, logs):
        v = (logs or {}).get(self.monitor)
        if isinstance(v, (list, tuple)):
            v = v[0]
        return None if v is None else float(v)

    def _improved(self, value):
        if self.best is None:
            return True
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_train_begin(self, logs=None):
        self.best = self.baseline
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        value = self._value(logs)
        if value is None:
            return
        if self._improved(value):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience and self.model is not None:
                self.model.stop_training = True


class LRScheduler(Callback):
    """Step a learning-rate scheduler each epoch (or each batch with
    by_step=True)."""

    def __init__(self, by_step=False, by_epoch=True):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()
