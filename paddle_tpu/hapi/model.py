"""`Model` — Keras-like fit/evaluate/predict over the dygraph engine.

Reference parity: `python/paddle/incubate/hapi/model.py` — `Model.fit`
(`model.py:1128`), `evaluate` (`:1337`), `predict` (`:1443`),
`train_batch/eval_batch/test_batch` (`:652` DynamicGraphAdapter), and
`save/load` (`:907,960`). TPU-native: batches run through the eager
engine whose ops are per-signature jitted XLA computations, so the hot
loop is compiled after the first step; there is no separate static
adapter because `paddle_tpu.fluid` programs already lower to one XLA
computation when needed.
"""
from __future__ import annotations

import contextlib
import os
import pickle
from typing import List, Optional

import numpy as np

from ..fluid import framework
from ..fluid.dygraph import base as dy_base
from ..fluid.dygraph.checkpoint import save_dygraph
from ..fluid.reader import DataLoader
from .callbacks import config_callbacks
from .metrics import Metric


class Input:
    """Input spec (reference: hapi/input.py Input(shape, dtype, name))."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = tuple(shape or ())
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return "Input(shape=%s, dtype=%s, name=%s)" % (
            self.shape, self.dtype, self.name)


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _as_variables(arrays):
    from ..reader.prefetcher import is_on_device

    out = []
    for a in arrays:
        if isinstance(a, dy_base.Tensor):
            out.append(a)
        elif is_on_device(a):
            # pre-put device batch (DataLoader use_buffer_reader /
            # reader.prefetch_to_device): wrap without the host
            # round-trip np.asarray would force
            out.append(dy_base.to_variable(a))
        else:
            out.append(dy_base.to_variable(np.asarray(a)))
    return out


class _DeferredLogs(dict):
    """Per-step logs for the deferred-fetch fit loop. Reading any
    metric key ("loss", metric names — anything but "step") forces the
    pending device->host sync first, so a callback that consumes
    per-step losses in on_train_batch_end sees fresh, correct values
    (it simply pays the sync it asked for). The default ProgBarLogger
    reads logs only every log_freq steps — exactly where fit flushes
    anyway — so the deferred path keeps its ceil(steps/log_freq) sync
    bound. (fit additionally disables deferral outright when
    user-supplied callbacks are present, since C-level reads like
    dict(logs) bypass these overrides.)"""

    def __init__(self, model, pending):
        super().__init__()
        self._model = model
        self._pending = pending  # SHARED list with the fit loop

    def _flush(self):
        if self._pending:
            losses = self._model._sync_losses(self._pending)
            del self._pending[:]
            super().update(self._model._merge_logs(losses))

    def __getitem__(self, k):
        if k != "step":
            self._flush()
        return super().__getitem__(k)

    def __contains__(self, k):
        if k != "step":
            self._flush()
        return super().__contains__(k)

    def get(self, k, default=None):
        if k != "step":
            self._flush()
        return super().get(k, default)

    def items(self):
        self._flush()
        return super().items()

    def values(self):
        self._flush()
        return super().values()

    def keys(self):
        self._flush()
        return super().keys()

    def __iter__(self):
        self._flush()
        return super().__iter__()

    def __len__(self):
        self._flush()
        return super().__len__()

    def copy(self):
        self._flush()
        return dict(super().items())


class Model:
    """Wraps a dygraph `Layer` network with train/eval/predict loops."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _to_list(inputs)
        self._labels = _to_list(labels)
        self._optimizer = None
        self._loss_function = None
        self._metrics: List[Metric] = []
        self._amp_level = "O0"
        self.stop_training = False
        # own tracer, activated only inside batch methods — fit() must not
        # flip the process-global dygraph mode for unrelated static code
        self._tracer = framework._dygraph_tracer() or dy_base.Tracer()

    @contextlib.contextmanager
    def _dygraph_guard(self):
        if framework.in_dygraph_mode():
            yield
            return
        old = framework._switch_tracer(self._tracer)
        try:
            yield
        finally:
            framework._switch_tracer(old)

    # -- setup -------------------------------------------------------------
    def prepare(self, optimizer=None, loss_function=None, metrics=None,
                amp_level=None):
        """`amp_level`: None/'O0' = fp32 (default); 'O1' = the network's
        float32 parameters are cast to bfloat16 for forward/backward
        (activation memory and MXU throughput win, updates in bf16);
        'O2' = 'O1' plus fp32 MASTER weights — the optimizer updates an
        fp32 copy per parameter and the live bf16 param is re-derived
        from it each step, so update precision never degrades to bf16
        round-off (contrib.mixed_precision.EagerMasterWeightOptimizer;
        the static-graph analogue is mixed_precision.decorate, whose
        masters additionally live ZeRO-sharded — see
        paddle_tpu/parallel/README.md "Mixed precision & ZeRO-2")."""
        level = str(amp_level).upper() if amp_level else "O0"
        if level not in ("O0", "O1", "O2"):
            raise ValueError(
                "amp_level must be one of None/'O0'/'O1'/'O2', got %r"
                % (amp_level,))
        self._amp_level = level
        if level in ("O1", "O2"):
            self._amp_cast_params()
            if level == "O2" and optimizer is not None:
                from ..fluid.contrib.mixed_precision import \
                    EagerMasterWeightOptimizer

                if not isinstance(optimizer, EagerMasterWeightOptimizer):
                    optimizer = EagerMasterWeightOptimizer(optimizer)
        self._optimizer = optimizer
        self._loss_function = loss_function
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            assert isinstance(m, Metric), (
                "metrics must be hapi.Metric instances, got %r" % (m,))
        return self

    def _amp_cast_params(self):
        """Cast the network's TRAINABLE fp32 params to bf16 (amp_level
        O1/O2). Non-trainable statistics (BatchNorm running
        mean/variance) stay fp32 — their momentum update accumulates,
        and bf16's 8-bit mantissa would degrade eval-mode normalization
        (the static-graph policy black-lists batch_norm for the same
        reason). Re-applied after load(): set_dict restores the
        checkpoint's (fp32) dtypes."""
        import jax.numpy as jnp

        for p in self.network.parameters():
            if not getattr(p, "trainable", True):
                continue
            val = p._value()
            if val.dtype == jnp.float32:
                p._assign_raw(val.astype(jnp.bfloat16))

    def parameters(self):
        return self.network.parameters()

    # -- single-batch entry points ----------------------------------------
    def _split_batch(self, data):
        data = _to_list(data)
        if self._inputs:
            n_in = len(self._inputs)
        elif self._labels:
            n_in = len(data) - len(self._labels)
        else:
            n_in = max(1, len(data) - 1)
        return data[:n_in], data[n_in:]

    def _compute_loss(self, outputs, labels):
        if self._loss_function is None:
            return outputs[0]
        losses = self._loss_function(*(outputs + labels))
        losses = _to_list(losses)
        total = losses[0]
        for x in losses[1:]:
            total = total + x
        return total

    def _train_batch_device(self, inputs, labels=None):
        """One train step with everything left device-resident: returns
        (loss_tensor, outputs, labels) without a host sync, so the
        dispatch queue never drains between logged steps (fit defers the
        materialization to every log_freq steps). Wraps the dygraph
        data-parallel idiom when the network is a DataParallel layer
        (scale_loss -> backward -> apply_collective_grads).

        Each step publishes ONE record into the metrics registry
        (observability.on_executor_step — the same step stream
        Executor.run feeds), so dygraph fit/evaluate runs show up in
        `tools/perf_analysis.py --stragglers` and the
        `tools/timeline.py --telemetry` merge instead of being
        invisible to the telemetry tier."""
        assert self._optimizer is not None, "call prepare() first"
        import time as _time

        from ..fluid.dygraph.parallel import DataParallel

        t0 = _time.perf_counter()
        with self._dygraph_guard():
            self.network.train()
            inputs = _as_variables(_to_list(inputs))
            labels = _as_variables(_to_list(labels))
            outputs = _to_list(self.network(*inputs))
            loss = self._compute_loss(outputs, labels)
            if isinstance(self.network, DataParallel):
                self.network.scale_loss(loss).backward()
                self.network.apply_collective_grads()
            else:
                loss.backward()
            self._optimizer.minimize(
                loss, parameter_list=self.network.parameters())
            self.network.clear_gradients()
        self._publish_step_record(_time.perf_counter() - t0)
        return loss, outputs, labels

    @staticmethod
    def _publish_step_record(dt):
        """One dygraph train step -> one registry step record. The
        eager step is dispatch-dominated (no executor feed/compile
        phases to split); host syncs ride separately through
        _sync_losses' sync-phase accounting. Never raises."""
        try:
            from .. import observability as _obs

            _obs.on_executor_step({"dispatch_ms": dt * 1e3,
                                   "total_ms": dt * 1e3})
        except Exception:  # noqa: BLE001 - telemetry never gates a step
            pass

    def _sync_losses(self, pending):
        """Materialize a buffer of deferred (loss, outputs, labels)
        triples: ONE host sync point (profiler event 'hapi/loss_sync' +
        sync step phase), metric updates in step order. Returns the last
        step's loss value list."""
        from ..fluid import profiler

        losses = None
        with profiler.RecordEvent("hapi/loss_sync"):
            import time as _time

            t0 = _time.perf_counter()
            for loss, outputs, labels in pending:
                if outputs is not None:
                    for m in self._metrics:
                        m.update(*_to_list(
                            m.compute(outputs[0], *labels)))
                losses = [float(np.asarray(
                    loss.numpy()).reshape(-1)[0])]
            dt = _time.perf_counter() - t0
            profiler.record_step_phase("sync", dt, t0)
            self._telemetry_sync_event("train", len(pending), dt)
        return losses

    @staticmethod
    def _telemetry_sync_event(mode, n_steps, dt):
        """Deferred-fetch sync cadence into the telemetry stream: how
        many device-resident steps each hapi host sync drained, and
        what it cost — the log_freq-vs-sync tradeoff becomes visible
        in the per-rank JSONL instead of only in profiler counters."""
        try:
            from ..observability.registry import registry

            registry().event("hapi_sync", mode=mode, n_steps=n_steps,
                             dur_ms=round(dt * 1e3, 4))
        except Exception:  # noqa: BLE001 - telemetry never gates a sync
            pass

    def train_batch(self, inputs, labels=None):
        loss, outputs, labels = self._train_batch_device(inputs, labels)
        metrics = []
        for m in self._metrics:
            res = m.update(*_to_list(m.compute(outputs[0], *labels)))
            metrics.append(res)
        return ([float(np.asarray(loss.numpy()).reshape(-1)[0])], metrics)

    def _eval_batch_device(self, inputs, labels=None):
        """One eval step with everything left device-resident (the
        evaluate() analogue of _train_batch_device): returns
        (loss_tensor_or_None, outputs, labels) without a host sync, so
        deferred eval loops never drain the dispatch queue between
        logged steps. Publishes a step record like the train path, so
        evaluate() runs show up in the telemetry stream too."""
        import time as _time

        t0 = _time.perf_counter()
        with self._dygraph_guard():
            self.network.eval()
            with dy_base.no_grad():
                inputs = _as_variables(_to_list(inputs))
                labels = _as_variables(_to_list(labels))
                outputs = _to_list(self.network(*inputs))
                loss = self._compute_loss(outputs, labels) \
                    if labels else None
        self._publish_step_record(_time.perf_counter() - t0)
        return loss, outputs, labels

    def eval_batch(self, inputs, labels=None):
        loss, outputs, labels = self._eval_batch_device(inputs, labels)
        metrics = []
        for m in self._metrics:
            res = m.update(*_to_list(m.compute(outputs[0], *labels)))
            metrics.append(res)
        lv = [float(np.asarray(loss.numpy()).reshape(-1)[0])] \
            if loss is not None else []
        return (lv, metrics)

    def _sync_eval(self, pending):
        """Materialize deferred eval steps: ONE host sync point
        (profiler event 'hapi/loss_sync' + sync step phase), metric
        updates in step order. Returns the per-step loss values."""
        from ..fluid import profiler

        losses = []
        with profiler.RecordEvent("hapi/loss_sync"):
            import time as _time

            t0 = _time.perf_counter()
            for loss, outputs, labels in pending:
                if outputs is not None:
                    for m in self._metrics:
                        m.update(*_to_list(
                            m.compute(outputs[0], *labels)))
                if loss is not None:
                    losses.append(float(np.asarray(
                        loss.numpy()).reshape(-1)[0]))
            dt = _time.perf_counter() - t0
            profiler.record_step_phase("sync", dt, t0)
            self._telemetry_sync_event("eval", len(pending), dt)
        return losses

    def _test_batch_device(self, inputs):
        with self._dygraph_guard():
            self.network.eval()
            with dy_base.no_grad():
                inputs = _as_variables(_to_list(inputs))
                outputs = _to_list(self.network(*inputs))
        return outputs

    def test_batch(self, inputs):
        return [o.numpy() for o in self._test_batch_device(inputs)]

    predict_batch = test_batch

    # -- loops -------------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, drop_last,
                     num_workers):
        if data is None or isinstance(data, DataLoader) or (
                hasattr(data, "__iter__") and
                not hasattr(data, "__getitem__")):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    def fit(self, train_data=None, eval_data=None, batch_size=1,
            epochs=1, eval_freq=1, log_freq=10, save_dir=None,
            save_freq=1, verbose=2, drop_last=False, shuffle=True,
            num_workers=0, callbacks=None, auto_checkpoint_dir=None,
            checkpoint_num=3):
        """With auto_checkpoint_dir set, fit resumes from the latest
        numbered checkpoint under it (params + optimizer state + the
        completed-epoch TrainStatus) and publishes a new checkpoint
        after every epoch — preemption-safe training (reference: fleet
        collective save/load_checkpoint,
        incubate/fleet/collective/__init__.py:236-341)."""
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   drop_last, num_workers)
        steps = len(loader) if hasattr(loader, "__len__") else None
        metric_names = ["loss"]
        for m in self._metrics:
            metric_names.extend(_to_list(m.name()))
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, verbose=verbose, save_freq=save_freq,
            save_dir=save_dir, metrics=metric_names)

        start_epoch = 0
        if auto_checkpoint_dir:
            from ..fluid import checkpoint as ckpt_mod

            latest = ckpt_mod.latest_checkpoint_dir(auto_checkpoint_dir)
            if latest is not None:
                self.load(os.path.join(latest, "model"))
                start_epoch = ckpt_mod.read_status(latest).next()

        # deferred fetches: keep per-step losses/metric inputs on device
        # and sync to host only every log_freq steps (+ epoch end), so
        # between logged steps the host never blocks the dispatch queue.
        # The computation is identical — only WHEN the host blocks moves
        # — so losses match the synchronous path bit for bit. Deferral
        # engages only under _defer_ok's built-in-callback gate (user
        # callbacks may read logs every step through paths _DeferredLogs
        # cannot intercept, e.g. dict(logs), so they keep the
        # synchronous contract).
        defer = self._defer_ok(cbks)
        self.stop_training = False
        cbks.on_train_begin({})
        history = []
        for epoch in range(start_epoch, epochs):
            cbks.on_epoch_begin(epoch, {})
            for m in self._metrics:
                m.reset()
            pending = []
            logs = _DeferredLogs(self, pending) if defer else {}
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step, {})
                inputs, labels = self._split_batch(batch)
                if defer:
                    loss, outs, lbls = self._train_batch_device(
                        inputs, labels)
                    if not self._metrics:
                        # no metric consumers: keep only the scalar
                        # loss handle — buffering outputs/labels for
                        # log_freq steps would pin HBM for nothing
                        outs = lbls = None
                    pending.append((loss, outs, lbls))
                    if (step + 1) % max(log_freq, 1) == 0:
                        logs._flush()
                else:
                    losses, _ = self.train_batch(inputs, labels)
                    logs = self._merge_logs(losses)
                logs["step"] = step
                cbks.on_train_batch_end(step, logs)
            if defer:
                logs._flush()  # epoch tail shorter than log_freq
            cbks.on_epoch_end(epoch, logs)
            history.append(dict(logs))

            if auto_checkpoint_dir:
                from ..fluid import checkpoint as ckpt_mod

                ckpt_mod.publish_checkpoint_dir(
                    auto_checkpoint_dir,
                    lambda tmp: self.save(os.path.join(tmp, "model")),
                    ckpt_mod.TrainStatus(epoch_no=epoch), checkpoint_num)

            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size,
                              log_freq=log_freq, verbose=verbose,
                              num_workers=num_workers, callbacks=cbks)
            if self.stop_training:
                break
        cbks.on_train_end({})
        return history

    def _merge_logs(self, losses):
        logs = {"loss": losses[0] if losses else None}
        for m in self._metrics:
            names = _to_list(m.name())
            vals = _to_list(m.accumulate())
            for n, v in zip(names, vals):
                logs[n] = float(v)
        return logs

    def _defer_ok(self, cbks):
        """Deferred fetches engage only under the known built-in
        callbacks (same contract as fit): they read logs at log_freq /
        end-of-loop cadence, so batching the host syncs is invisible.
        User callbacks keep the synchronous per-step contract."""
        from ..utils.flags import get_flag

        from .callbacks import (
            EarlyStopping, ModelCheckpoint, ProgBarLogger,
        )

        return bool(get_flag("FLAGS_tpu_deferred_fetch", True)) and \
            all(isinstance(c, (ProgBarLogger, ModelCheckpoint,
                               EarlyStopping))
                for c in getattr(cbks, "callbacks", []))

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        """Deferred-fetch eval (ROADMAP open item): per-step losses and
        metric inputs stay device-resident and sync to host only every
        `log_freq` steps (+ loop end), exactly like fit's train loop —
        the computation is identical, only WHEN the host blocks moves,
        so losses/metrics match the synchronous path bit for bit."""
        loader = self._make_loader(eval_data, batch_size, False, False,
                                   num_workers)
        cbks = callbacks if callbacks is not None else config_callbacks(
            None, model=self, steps=len(loader) if hasattr(
                loader, "__len__") else None,
            log_freq=log_freq, verbose=verbose, mode="eval")
        defer = self._defer_ok(cbks)
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin({})
        losses = []
        pending = []
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step, {})
            inputs, labels = self._split_batch(batch)
            if defer:
                loss_t, outs, lbls = self._eval_batch_device(inputs,
                                                             labels)
                if not self._metrics:
                    # no metric consumers: keep only the scalar loss
                    # handle — buffering outputs/labels for log_freq
                    # steps would pin HBM for nothing (same guard as
                    # fit's train loop)
                    outs = lbls = None
                pending.append((loss_t, outs, lbls))
                if (step + 1) % max(log_freq, 1) == 0:
                    losses.extend(self._sync_eval(pending))
                    del pending[:]
                cbks.on_eval_batch_end(step, {"step": step})
            else:
                lv, _ = self.eval_batch(inputs, labels)
                if lv:
                    losses.append(lv[0])
                cbks.on_eval_batch_end(step, {"loss": lv})
        if pending:
            losses.extend(self._sync_eval(pending))  # loop tail
        result = {}
        if losses:
            result["loss"] = [float(np.mean(losses))]
        for m in self._metrics:
            names = _to_list(m.name())
            vals = _to_list(m.accumulate())
            for n, v in zip(names, vals):
                result[n] = float(v)
        cbks.on_eval_end(result)
        return result

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None):
        """Deferred-fetch predict: per-step outputs stay device-resident
        and materialize in log_freq-sized windows (the fit default, 10),
        so the dispatch queue never drains between steps; outputs are
        identical to the synchronous path."""
        loader = self._make_loader(test_data, batch_size, False, False,
                                   num_workers)
        cbks = callbacks if callbacks is not None else config_callbacks(
            None, model=self, verbose=0, mode="predict")
        defer = self._defer_ok(cbks)
        cbks.on_predict_begin({})
        outputs = None
        pending = []

        def flush():
            from ..fluid import profiler

            with profiler.RecordEvent("hapi/loss_sync"):
                for outs in pending:
                    for i, o in enumerate(outs):
                        outputs[i].append(o.numpy())
            del pending[:]

        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step, {})
            inputs, _ = self._split_batch(batch)
            if defer:
                outs = self._test_batch_device(inputs)
                if outputs is None:
                    outputs = [[] for _ in outs]
                pending.append(outs)
                if (step + 1) % 10 == 0:  # fit's log_freq default
                    flush()
            else:
                outs = self.test_batch(inputs)
                if outputs is None:
                    outputs = [[] for _ in outs]
                for i, o in enumerate(outs):
                    outputs[i].append(o)
            cbks.on_predict_batch_end(step, {})
        if pending:
            flush()
        cbks.on_predict_end({})
        if outputs is None:
            return []
        if stack_outputs:
            return [np.concatenate(chunks, axis=0) for chunks in outputs]
        return outputs

    # -- persistence -------------------------------------------------------
    def save(self, path):
        """Write `<path>.pdparams` (+ `<path>.pdopt` when an optimizer
        with state is attached) — reference: model.py:907. Optimizer
        accumulators are keyed `<structured param key>||<acc name>` so
        they restore into a freshly built network."""
        save_dygraph(self.network.state_dict(), path)
        if self._optimizer is None:
            return
        name_map = {p.name: structured for structured, p
                    in self.network.state_dict().items()}
        opt_state = {}
        for accname, accs in self._optimizer._accumulators.items():
            for pname, var in accs.items():
                key = "%s||%s" % (name_map.get(pname, pname), accname)
                opt_state[key] = var.numpy() if hasattr(var, "numpy") \
                    else np.asarray(var)
        if opt_state:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path + ".pdopt", "wb") as f:
                pickle.dump(opt_state, f, protocol=2)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        with open(path + ".pdparams", "rb") as f:
            state = pickle.load(f)
        self.network.set_dict(state)
        if self._amp_level in ("O1", "O2"):
            # set_dict restores the checkpoint's dtypes (an fp32 save
            # would silently turn AMP off — the eager master wrapper
            # skips fp32 params); re-apply the compute-dtype cast. The
            # wrapper's per-object liveness tracking re-seeds its fp32
            # masters from the loaded values on the next step.
            self._amp_cast_params()
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            with open(opt_path, "rb") as f:
                opt_state = pickle.load(f)
            rev = {structured: p.name for structured, p
                   in self.network.state_dict().items()}
            runtime = {}
            for key, val in opt_state.items():
                structured, _, accname = key.rpartition("||")
                pname = rev.get(structured)
                if pname is None:
                    if not skip_mismatch:
                        raise KeyError(
                            "optimizer state %r has no matching "
                            "parameter" % key)
                    continue
                runtime["%s_%s" % (pname, accname)] = val
            self._optimizer.set_state_dict(runtime)
        return self

    def summary(self):
        total = 0
        rows = []
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape)) if p.shape else 1
            total += n
            rows.append((name, tuple(p.shape), n))
        return {"total_params": total, "layers": rows}
