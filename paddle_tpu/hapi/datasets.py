"""hapi datasets (reference:
`python/paddle/incubate/hapi/datasets/` — map-style Dataset base,
MNIST idx-file parser). No network egress: MNIST reads local idx
files; downloads are not supported."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np


class Dataset:
    """Map-style dataset (reference: datasets/folder.py base usage +
    fluid/dataloader/dataset.py Dataset)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class TensorDataset(Dataset):
    def __init__(self, *tensors):
        arrays = [np.asarray(t) for t in tensors]
        n = len(arrays[0])
        assert all(len(a) == n for a in arrays)
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


class MNIST(Dataset):
    """MNIST from local idx(.gz) files (reference: datasets/mnist.py;
    download path removed — this environment has no egress)."""

    _FILES = {
        "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, root=None, mode="train", transform=None,
                 backend="numpy", download=False):
        assert mode in self._FILES, mode
        if download:
            raise RuntimeError(
                "MNIST download is unavailable (no network egress); "
                "place the idx files under `root` instead")
        root = root or os.path.join(
            os.path.expanduser("~"), ".cache", "paddle_tpu", "mnist")
        img_name, lbl_name = self._FILES[mode]
        img_path = self._find(root, img_name)
        lbl_path = self._find(root, lbl_name)
        self.images = _read_idx(img_path).astype("float32") / 255.0
        self.labels = _read_idx(lbl_path).astype("int64")
        self.transform = transform

    @staticmethod
    def _find(root, base):
        for cand in (os.path.join(root, base),
                     os.path.join(root, base + ".gz")):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(
            "MNIST file %s(.gz) not found under %s" % (base, root))

    def __getitem__(self, idx):
        img = self.images[idx][None, ...]  # 1xHxW
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], dtype="int64")

    def __len__(self):
        return len(self.images)


class SyntheticImages(Dataset):
    """Deterministic synthetic classification dataset for tests and
    smoke runs (label is derived from the image so it is learnable)."""

    def __init__(self, num_samples=256, image_shape=(1, 8, 8),
                 num_classes=10, seed=0):
        r = np.random.RandomState(seed)
        self.images = r.rand(num_samples, *image_shape).astype("float32")
        proj = r.rand(int(np.prod(image_shape)), num_classes)
        logits = self.images.reshape(num_samples, -1) @ proj
        self.labels = logits.argmax(-1).astype("int64")
        self.num_classes = num_classes

    def __getitem__(self, idx):
        return self.images[idx], np.asarray([self.labels[idx]], "int64")

    def __len__(self):
        return len(self.images)


# -- filesystem-backed image folders (reference: hapi/datasets/folder.py
# :60 DatasetFolder, :197 ImageFolder) --------------------------------

IMG_EXTENSIONS = ('.jpg', '.jpeg', '.png', '.ppm', '.bmp', '.pgm',
                  '.tif', '.tiff', '.webp', '.npy')


def has_valid_extension(filename, extensions):
    """Case-insensitive suffix check (reference folder.py:24)."""
    return str(filename).lower().endswith(tuple(extensions))


def default_loader(path):
    """.npy -> ndarray directly (zero-egress test convenience);
    anything else via PIL (reference folder.py cv2/PIL loader)."""
    if str(path).lower().endswith(".npy"):
        return np.load(path)
    from PIL import Image

    with open(path, "rb") as f:
        img = Image.open(f)
        return img.convert("RGB")


def make_dataset(dir, class_to_idx, extensions=None,
                 is_valid_file=None):
    """(path, class_index) samples under per-class subdirs (reference
    folder.py:37)."""
    if (extensions is None) == (is_valid_file is None):
        raise ValueError("exactly one of extensions / is_valid_file "
                         "must be given")
    if is_valid_file is None:
        def is_valid_file(p):
            return has_valid_extension(p, extensions)
    samples = []
    for target in sorted(class_to_idx):
        d = os.path.join(dir, target)
        if not os.path.isdir(d):
            continue
        for root, _, fnames in sorted(os.walk(d)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                if is_valid_file(path):
                    samples.append((path, class_to_idx[target]))
    return samples


class DatasetFolder(Dataset):
    """Generic folder-of-class-subfolders dataset (reference
    folder.py:60): root/class_x/xxx.ext -> (sample, class_index)."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        classes = sorted(d.name for d in os.scandir(root) if d.is_dir())
        if not classes:
            raise RuntimeError("no class folders under %r" % root)
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = make_dataset(root, self.class_to_idx,
                                    extensions, is_valid_file)
        if not self.samples:
            raise RuntimeError("found 0 files under %r" % root)
        self.targets = [t for _, t in self.samples]

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat folder of images, no labels (reference folder.py:197)."""

    def __init__(self, root, loader=None, extensions=None,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or default_loader
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS

        def valid(p):
            return (is_valid_file(p) if is_valid_file is not None
                    else has_valid_extension(p, extensions))

        samples = []
        for rootd, _, fnames in sorted(os.walk(root)):
            for fname in sorted(fnames):
                p = os.path.join(rootd, fname)
                if valid(p):
                    samples.append(p)
        if not samples:
            raise RuntimeError("found 0 files under %r" % root)
        self.samples = samples

    def __getitem__(self, index):
        sample = self.loader(self.samples[index])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
