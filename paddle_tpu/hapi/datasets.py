"""hapi datasets (reference:
`python/paddle/incubate/hapi/datasets/` — map-style Dataset base,
MNIST idx-file parser). No network egress: MNIST reads local idx
files; downloads are not supported."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np


class Dataset:
    """Map-style dataset (reference: datasets/folder.py base usage +
    fluid/dataloader/dataset.py Dataset)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class TensorDataset(Dataset):
    def __init__(self, *tensors):
        arrays = [np.asarray(t) for t in tensors]
        n = len(arrays[0])
        assert all(len(a) == n for a in arrays)
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


class MNIST(Dataset):
    """MNIST from local idx(.gz) files (reference: datasets/mnist.py;
    download path removed — this environment has no egress)."""

    _FILES = {
        "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, root=None, mode="train", transform=None,
                 backend="numpy", download=False):
        assert mode in self._FILES, mode
        if download:
            raise RuntimeError(
                "MNIST download is unavailable (no network egress); "
                "place the idx files under `root` instead")
        root = root or os.path.join(
            os.path.expanduser("~"), ".cache", "paddle_tpu", "mnist")
        img_name, lbl_name = self._FILES[mode]
        img_path = self._find(root, img_name)
        lbl_path = self._find(root, lbl_name)
        self.images = _read_idx(img_path).astype("float32") / 255.0
        self.labels = _read_idx(lbl_path).astype("int64")
        self.transform = transform

    @staticmethod
    def _find(root, base):
        for cand in (os.path.join(root, base),
                     os.path.join(root, base + ".gz")):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(
            "MNIST file %s(.gz) not found under %s" % (base, root))

    def __getitem__(self, idx):
        img = self.images[idx][None, ...]  # 1xHxW
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], dtype="int64")

    def __len__(self):
        return len(self.images)


class SyntheticImages(Dataset):
    """Deterministic synthetic classification dataset for tests and
    smoke runs (label is derived from the image so it is learnable)."""

    def __init__(self, num_samples=256, image_shape=(1, 8, 8),
                 num_classes=10, seed=0):
        r = np.random.RandomState(seed)
        self.images = r.rand(num_samples, *image_shape).astype("float32")
        proj = r.rand(int(np.prod(image_shape)), num_classes)
        logits = self.images.reshape(num_samples, -1) @ proj
        self.labels = logits.argmax(-1).astype("int64")
        self.num_classes = num_classes

    def __getitem__(self, idx):
        return self.images[idx], np.asarray([self.labels[idx]], "int64")

    def __len__(self):
        return len(self.images)
