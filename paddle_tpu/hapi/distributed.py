"""hapi.distributed — DistributedBatchSampler (reference:
`python/paddle/incubate/hapi/distributed.py:36`): each rank iterates a
disjoint, padded-to-even subset of the dataset so data-parallel hapi
training sees the whole dataset exactly once per epoch across ranks.
Rank/nranks come from the trainer env (`parallel/env.py`, the same
PADDLE_* contract the launcher sets)."""
from __future__ import annotations

import math

import numpy as np

from ..fluid.reader import BatchSampler


class DistributedBatchSampler(BatchSampler):
    """Deterministic per-rank subsampling: indices are padded by
    wrap-around to nranks*num_samples, optionally shuffled with the
    epoch as the seed (identical permutation on every rank), then each
    rank takes its interleaved batch-size slices (reference
    distributed.py:107 _get_indices_by_batch_size)."""

    def __init__(self, dataset, batch_size, shuffle=False,
                 drop_last=False):
        assert isinstance(batch_size, int) and batch_size > 0, \
            "batch_size should be a positive integer"
        assert isinstance(shuffle, bool), \
            "shuffle should be a boolean value"
        assert isinstance(drop_last, bool), \
            "drop_last should be a boolean number"
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last

        from ..parallel import env as penv

        self.nranks = max(1, penv.trainer_num())
        self.local_rank = penv.trainer_id()
        self.epoch = 0
        self.num_samples = int(
            math.ceil(len(dataset) * 1.0 / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def set_epoch(self, epoch):
        """Pin the shuffle seed for resumable training (reference
        contract: same epoch -> same permutation on every rank)."""
        self.epoch = int(epoch)

    def _local_indices(self):
        n = len(self.dataset)
        indices = list(range(n))
        indices += indices[:self.total_size - n]  # wrap-around pad
        assert len(indices) == self.total_size
        if self.shuffle:
            np.random.RandomState(self.epoch).shuffle(indices)
            self.epoch += 1

        out = []
        last = self.total_size % (self.batch_size * self.nranks)
        assert last % self.nranks == 0
        last_local = last // self.nranks
        for i in range(self.local_rank * self.batch_size,
                       self.total_size - last,
                       self.batch_size * self.nranks):
            out.extend(indices[i:i + self.batch_size])
        tail = indices[self.total_size - last:]
        out.extend(tail[self.local_rank * last_local:
                        (self.local_rank + 1) * last_local])
        return out

    def __iter__(self):
        idx = self._local_indices()
        batch = []
        for i in idx:
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) \
            // self.batch_size
