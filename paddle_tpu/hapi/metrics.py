"""hapi Metric API (reference:
`python/paddle/incubate/hapi/metrics.py` — Metric base with
compute/update/reset/accumulate/name, Accuracy with top-k)."""
from __future__ import annotations

import numpy as np


def _to_numpy(x):
    if hasattr(x, "numpy"):
        return x.numpy()
    return np.asarray(x)


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional device-side pre-computation; the returned values are
        handed to update() as numpy."""
        return args


class Accuracy(Metric):
    """Top-k accuracy (reference: metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        self.topk = tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _to_numpy(pred)
        label = _to_numpy(label)
        order = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        correct = (order == label[..., None]).astype("float32")
        return correct

    def update(self, correct, *args):
        correct = _to_numpy(correct)
        # samples = every batch position (all dims except the top-k one)
        num = int(np.prod(correct.shape[:-1])) if correct.ndim else 1
        for i, k in enumerate(self.topk):
            c = correct[..., :k].max(axis=-1).sum()
            self.total[i] += float(c)
        self.count += num
        return [self.total[i] / max(1, self.count)
                for i in range(len(self.topk))]

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(1, self.count) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return ["%s_top%d" % (self._name, k) for k in self.topk]
