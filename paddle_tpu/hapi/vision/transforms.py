"""Image transforms (reference:
`python/paddle/incubate/hapi/vision/transforms/transforms.py`): numpy
HWC(uint8/float) image pipeline for dataset preprocessing. Host-side by
design — augmentation runs on CPU while the accelerator computes."""
from __future__ import annotations

import random as _random

import numpy as np

__all__ = [
    "Compose", "Resize", "RandomResizedCrop", "CenterCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Normalize", "Permute",
    "GaussianNoise", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "ColorJitter",
]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, *data):
        for t in self.transforms:
            if isinstance(data, tuple) and len(data) > 1:
                data = (t(data[0]),) + tuple(data[1:])
            else:
                data = t(data[0] if isinstance(data, tuple) else data)
                data = (data,)
        return data[0] if len(data) == 1 else data


def _resize(img, size, interp="bilinear"):
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            oh, ow = size, int(size * w / h)
        else:
            oh, ow = int(size * h / w), size
    else:
        oh, ow = size
    ys = np.clip((np.arange(oh) + 0.5) * h / oh - 0.5, 0, h - 1)
    xs = np.clip((np.arange(ow) + 0.5) * w / ow - 0.5, 0, w - 1)
    if interp == "nearest":
        return img[np.round(ys).astype(int)][:, np.round(xs).astype(int)]
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None] if img.ndim == 3 else (ys - y0)[:, None]
    wx = (xs - x0)[None, :, None] if img.ndim == 3 else (xs - x0)[None, :]
    f = img.astype("float32")
    top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
    bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return _resize(img, self.size, self.interpolation)


class RandomResizedCrop:
    def __init__(self, output_size, scale=(0.08, 1.0),
                 ratio=(3. / 4, 4. / 3)):
        self.output_size = (output_size, output_size) \
            if isinstance(output_size, int) else tuple(output_size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = _random.uniform(*self.scale) * area
            ar = _random.uniform(*self.ratio)
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                x = _random.randint(0, w - cw)
                y = _random.randint(0, h - ch)
                crop = img[y:y + ch, x:x + cw]
                return _resize(crop, self.output_size)
        return _resize(img, self.output_size)   # fallback: whole image


class CenterCrop:
    def __init__(self, output_size):
        self.output_size = (output_size, output_size) \
            if isinstance(output_size, int) else tuple(output_size)

    def __call__(self, img):
        h, w = img.shape[:2]
        ch, cw = self.output_size
        y = max((h - ch) // 2, 0)
        x = max((w - cw) // 2, 0)
        return img[y:y + ch, x:x + cw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if _random.random() < self.prob:
            return img[:, ::-1].copy()
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if _random.random() < self.prob:
            return img[::-1].copy()
        return img


class Normalize:
    def __init__(self, mean=0.0, std=1.0):
        self.mean = np.asarray(mean, "float32")
        self.std = np.asarray(std, "float32")

    def __call__(self, img):
        return (img.astype("float32") - self.mean) / self.std


class Permute:
    """HWC -> CHW with optional BGR->RGB flip (reference Permute:
    to_rgb=True reverses the channel order of 3-channel input)."""

    def __init__(self, mode="CHW", to_rgb=True):
        if mode != "CHW":
            raise ValueError("Permute only supports mode='CHW', got %r"
                             % mode)
        self.mode = mode
        self.to_rgb = to_rgb

    def __call__(self, img):
        if img.ndim == 2:
            img = img[:, :, None]
        if self.to_rgb and img.shape[-1] == 3:
            img = img[:, :, ::-1]
        return np.ascontiguousarray(img.transpose(2, 0, 1))


class GaussianNoise:
    def __init__(self, mean=0.0, std=1.0):
        self.mean = mean
        self.std = std

    def __call__(self, img):
        noise = np.random.normal(self.mean, self.std, img.shape)
        return (img.astype("float32") + noise).astype("float32")


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(img.astype("float32") * alpha, 0,
                       255 if img.dtype == np.uint8 else None) \
            .astype(img.dtype)


class ContrastTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        alpha = 1 + np.random.uniform(-self.value, self.value)
        f = img.astype("float32")
        mean = f.mean()
        out = mean + alpha * (f - mean)
        return np.clip(out, 0, 255 if img.dtype == np.uint8
                       else None).astype(img.dtype)


def _rgb_to_gray(f):
    return (0.299 * f[..., 0] + 0.587 * f[..., 1]
            + 0.114 * f[..., 2])[..., None]


class SaturationTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0 or img.ndim != 3 or img.shape[-1] != 3:
            return img   # saturation is undefined for grayscale
        alpha = 1 + np.random.uniform(-self.value, self.value)
        f = img.astype("float32")
        gray = _rgb_to_gray(f)
        out = gray + alpha * (f - gray)
        return np.clip(out, 0, 255 if img.dtype == np.uint8
                       else None).astype(img.dtype)


class HueTransform:
    """Channel-rotation hue jitter (reference HueTransform uses HSV;
    the cheap YIQ rotation here matches its visual effect for small
    values)."""

    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0 or img.ndim != 3 or img.shape[-1] != 3:
            return img   # hue rotation needs RGB channels
        theta = np.random.uniform(-self.value, self.value) * np.pi
        f = img.astype("float32")
        cos, sin = np.cos(theta), np.sin(theta)
        t_yiq = np.array([[0.299, 0.587, 0.114],
                          [0.596, -0.274, -0.321],
                          [0.211, -0.523, 0.311]], "float32")
        t_rgb = np.linalg.inv(t_yiq)
        rot = np.array([[1, 0, 0], [0, cos, -sin], [0, sin, cos]],
                       "float32")
        m = t_rgb @ rot @ t_yiq
        out = f @ m.T
        return np.clip(out, 0, 255 if img.dtype == np.uint8
                       else None).astype(img.dtype)


class ColorJitter:
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def __call__(self, img):
        order = list(range(4))
        _random.shuffle(order)
        for i in order:
            img = self.transforms[i](img)
        return img
