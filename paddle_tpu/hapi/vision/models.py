"""Vision model zoo (reference:
`python/paddle/incubate/hapi/vision/models/` — lenet.py, vgg.py,
mobilenetv1.py, mobilenetv2.py, resnet.py). Dygraph Layers usable
standalone or under hapi.Model; the static-graph ResNet builder lives in
`paddle_tpu/models/resnet.py`."""
from __future__ import annotations

from ...fluid.dygraph.layers import Layer, Sequential
from ...fluid.dygraph import nn as dnn

__all__ = ["LeNet", "VGG", "vgg16", "MobileNetV1", "MobileNetV2",
           "lenet", "mobilenet_v1", "mobilenet_v2"]


class LeNet(Layer):
    """reference lenet.py: conv(6)-pool-conv(16)-pool-fc(120)-fc(84)-fc."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            dnn.Conv2D(1, 6, 3, stride=1, padding=1, act="relu"),
            dnn.Pool2D(2, pool_type="max", pool_stride=2),
            dnn.Conv2D(6, 16, 5, stride=1, padding=0, act="relu"),
            dnn.Pool2D(2, pool_type="max", pool_stride=2),
        )
        self.fc = Sequential(
            dnn.Linear(400, 120), dnn.Linear(120, 84),
            dnn.Linear(84, num_classes),
        )

    def forward(self, x):
        from ...tensor import manipulation as M

        x = self.features(x)
        x = M.flatten(x, 1)
        return self.fc(x)


_VGG_CFG = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
         "M", 512, 512, 512, "M"],
    19: [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(Layer):
    """reference vgg.py: stacked 3x3 convs + maxpools + 3 fc;
    batch_norm=True inserts BN after every conv (the *_bn variants)."""

    def __init__(self, depth=16, num_classes=1000, batch_norm=False):
        super().__init__()
        layers = []
        c_in = 3
        for v in _VGG_CFG[depth]:
            if v == "M":
                layers.append(dnn.Pool2D(2, pool_type="max",
                                         pool_stride=2))
            elif batch_norm:
                layers.append(dnn.Conv2D(c_in, v, 3, padding=1,
                                         act=None))
                layers.append(dnn.BatchNorm(v, act="relu"))
                c_in = v
            else:
                layers.append(dnn.Conv2D(c_in, v, 3, padding=1,
                                         act="relu"))
                c_in = v
        self.features = Sequential(*layers)
        self.classifier = Sequential(
            dnn.Linear(512 * 7 * 7, 4096, act="relu"),
            dnn.Linear(4096, 4096, act="relu"),
            dnn.Linear(4096, num_classes),
        )

    def forward(self, x):
        from ...tensor import manipulation as M

        x = self.features(x)
        x = M.flatten(x, 1)
        return self.classifier(x)


def _vgg(depth, pretrained, batch_norm, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a state dict")
    return VGG(depth, batch_norm=batch_norm, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(16, pretrained, batch_norm, **kwargs)


class _ConvBN(Layer):
    def __init__(self, c_in, c_out, k, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = dnn.Conv2D(c_in, c_out, k, stride=stride,
                               padding=padding, groups=groups,
                               bias_attr=False)
        self.bn = dnn.BatchNorm(c_out, act=act)

    def forward(self, x):
        return self.bn(self.conv(x))


class MobileNetV1(Layer):
    """reference mobilenetv1.py: depthwise-separable stacks."""

    def __init__(self, num_classes=1000, scale=1.0):
        super().__init__()

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [  # (out, stride) per depthwise-separable block
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
            (1024, 1),
        ]
        blocks = [_ConvBN(3, c(32), 3, stride=2, padding=1)]
        c_in = c(32)
        for out, stride in cfg:
            blocks.append(_ConvBN(c_in, c_in, 3, stride=stride,
                                  padding=1, groups=c_in))   # depthwise
            blocks.append(_ConvBN(c_in, c(out), 1))          # pointwise
            c_in = c(out)
        self.features = Sequential(*blocks)
        self.fc = dnn.Linear(c(1024), num_classes)

    def forward(self, x):
        from ...tensor import manipulation as M
        from ...fluid.layers import nn as N

        x = self.features(x)
        x = N.pool2d(x, pool_size=x.shape[2], pool_type="avg")
        return self.fc(M.flatten(x, 1))


class _InvertedResidual(Layer):
    """reference mobilenetv2.py InvertedResidualUnit."""

    def __init__(self, c_in, c_out, stride, expand):
        super().__init__()
        hidden = c_in * expand
        self.use_res = stride == 1 and c_in == c_out
        layers = []
        if expand != 1:
            layers.append(_ConvBN(c_in, hidden, 1, act="relu6"))
        layers.append(_ConvBN(hidden, hidden, 3, stride=stride,
                              padding=1, groups=hidden, act="relu6"))
        layers.append(_ConvBN(hidden, c_out, 1, act=None))
        self.blocks = Sequential(*layers)

    def forward(self, x):
        out = self.blocks(x)
        if self.use_res:
            from ...fluid.layers import nn as N

            out = N.elementwise_add(out, x)
        return out


class MobileNetV2(Layer):
    def __init__(self, num_classes=1000, scale=1.0):
        super().__init__()

        def c(ch):
            return max(int(ch * scale), 8)

        cfg = [  # t (expand), c (out), n (repeats), s (stride)
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
        ]
        blocks = [_ConvBN(3, c(32), 3, stride=2, padding=1, act="relu6")]
        c_in = c(32)
        for t, out, n, s in cfg:
            for i in range(n):
                blocks.append(_InvertedResidual(
                    c_in, c(out), s if i == 0 else 1, t))
                c_in = c(out)
        blocks.append(_ConvBN(c_in, c(1280), 1, act="relu6"))
        self.features = Sequential(*blocks)
        self.fc = dnn.Linear(c(1280), num_classes)

    def forward(self, x):
        from ...tensor import manipulation as M
        from ...fluid.layers import nn as N

        x = self.features(x)
        x = N.pool2d(x, pool_size=x.shape[2], pool_type="avg")
        return self.fc(M.flatten(x, 1))


def lenet(num_classes=10, **kwargs):
    return LeNet(num_classes=num_classes, **kwargs)


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a state dict")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a state dict")
    return MobileNetV2(scale=scale, **kwargs)


class _BasicBlock(Layer):
    """ResNet v1 basic block (reference resnet.py:74): two 3x3 conv-bn,
    identity or 1x1-projection shortcut."""
    expansion = 1

    def __init__(self, c_in, c_out, stride=1):
        super().__init__()
        self.conv1 = _ConvBN(c_in, c_out, 3, stride=stride, padding=1)
        self.conv2 = _ConvBN(c_out, c_out, 3, padding=1, act=None)
        # NOTE: never pre-assign None — a plain-attr None in __dict__
        # shadows the Layer registered later in _sub_layers
        if stride != 1 or c_in != c_out:
            self.short = _ConvBN(c_in, c_out, 1, stride=stride, act=None)

    def forward(self, x):
        y = self.conv2(self.conv1(x))
        short = getattr(self, "short", None)
        s = x if short is None else short(x)
        from ...fluid import layers as L

        return L.relu(L.elementwise_add(y, s))


class _BottleneckBlock(Layer):
    """ResNet v1 bottleneck (reference resnet.py:117): 1x1 reduce,
    3x3, 1x1 expand (x4)."""
    expansion = 4

    def __init__(self, c_in, c_mid, stride=1):
        super().__init__()
        c_out = c_mid * 4
        self.conv1 = _ConvBN(c_in, c_mid, 1)
        self.conv2 = _ConvBN(c_mid, c_mid, 3, stride=stride, padding=1)
        self.conv3 = _ConvBN(c_mid, c_out, 1, act=None)
        if stride != 1 or c_in != c_out:
            self.short = _ConvBN(c_in, c_out, 1, stride=stride, act=None)

    def forward(self, x):
        y = self.conv3(self.conv2(self.conv1(x)))
        short = getattr(self, "short", None)
        s = x if short is None else short(x)
        from ...fluid import layers as L

        return L.relu(L.elementwise_add(y, s))


_RESNET_CFG = {
    18: (_BasicBlock, [2, 2, 2, 2]),
    34: (_BasicBlock, [3, 4, 6, 3]),
    50: (_BottleneckBlock, [3, 4, 6, 3]),
    101: (_BottleneckBlock, [3, 4, 23, 3]),
    152: (_BottleneckBlock, [3, 8, 36, 3]),
}


class ResNet(Layer):
    """Dygraph ResNet v1 (reference resnet.py:169): 7x7 stem, 4 stages,
    global avg pool + fc. num_classes <= 0 skips the classifier head."""

    def __init__(self, depth=50, num_classes=1000, with_pool=True):
        super().__init__()
        block, counts = _RESNET_CFG[depth]
        self.with_pool = with_pool
        self.num_classes = num_classes
        self.stem = _ConvBN(3, 64, 7, stride=2, padding=3)
        self.maxpool = dnn.Pool2D(3, pool_type="max", pool_stride=2,
                                  pool_padding=1)
        stages = []
        c_in = 64
        for i, (c_mid, n) in enumerate(zip([64, 128, 256, 512], counts)):
            for j in range(n):
                stride = 2 if (i > 0 and j == 0) else 1
                stages.append(block(c_in, c_mid, stride=stride))
                c_in = c_mid * block.expansion
        self.stages = Sequential(*stages)
        self.out_channels = c_in
        if with_pool:
            self.gap = dnn.Pool2D(pool_type="avg", global_pooling=True)
        if num_classes > 0:
            self.fc = dnn.Linear(c_in, num_classes)

    def forward(self, x):
        from ...tensor import manipulation as M

        x = self.stages(self.maxpool(self.stem(x)))
        if self.with_pool:
            x = self.gap(x)
        if self.num_classes > 0:
            x = M.flatten(x, 1)
            x = self.fc(x)
        return x


def _resnet(depth, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled; load a state dict")
    return ResNet(depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(152, pretrained, **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(11, pretrained, batch_norm, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(13, pretrained, batch_norm, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg(19, pretrained, batch_norm, **kwargs)


__all__ += ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101",
            "resnet152", "vgg11", "vgg13", "vgg19"]
