"""hapi.text (reference: `python/paddle/incubate/hapi/text/text.py`,
~3k LoC of RNN/seq2seq/CNN/transformer building blocks). The heavy
machinery lives in `paddle_tpu.nn` (rnn/transformer) and
`fluid.layers.rnn_decode`; this module provides the hapi-named surface
over it plus the cells/conv-pool encoders the reference defines here."""
from __future__ import annotations

import numpy as np

from ..fluid.dygraph.layers import Layer
from ..fluid.dygraph import nn as dnn
from ..nn.rnn import LSTM, GRU  # noqa: F401 (re-exported hapi names)
from ..nn.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
)
from ..fluid.layers.rnn_decode import (  # noqa: F401
    RNNCell, GRUCell as BasicGRUCell, BeamSearchDecoder, dynamic_decode,
)

__all__ = [
    "RNNCell", "BasicLSTMCell", "BasicGRUCell", "RNN", "LSTM", "GRU",
    "BidirectionalLSTM", "BidirectionalGRU", "Conv1dPoolLayer",
    "CNNEncoder", "MultiHeadAttention", "TransformerEncoderLayer",
    "TransformerEncoder", "BeamSearchDecoder", "DynamicDecode",
]


class BasicLSTMCell(RNNCell):
    """reference text.py:186 — one LSTM step cell (i,f,o,g gates with
    forget_bias), for use with RNN/dynamic_decode."""

    def __init__(self, input_size, hidden_size, forget_bias=1.0,
                 param_attr=None, name="basic_lstm_cell"):
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.forget_bias = float(forget_bias)
        self._param_attr = param_attr
        self._name = name
        self._w = None
        self._b = None

    def call(self, inputs, states):
        from ..fluid.layer_helper import LayerHelper, apply_op
        from ..fluid.layers import nn as N
        from ..fluid.layers import tensor as T

        h, c = states
        if self._w is None:
            helper = LayerHelper(self._name, param_attr=self._param_attr)
            self._w = helper.create_parameter(
                helper.param_attr,
                shape=[self.input_size + self.hidden_size,
                       4 * self.hidden_size], dtype="float32")
            self._b = helper.create_parameter(
                None, shape=[4 * self.hidden_size], dtype="float32",
                is_bias=True)
        concat = T.concat([inputs, h], axis=1)
        gates = N.elementwise_add(N.matmul(concat, self._w), self._b)
        # lstm_unit packs [i, f, o, g] and adds forget_bias to f
        outs = apply_op("lstm_unit", "lstm_unit",
                        {"X": [gates], "C_prev": [c]},
                        {"forget_bias": self.forget_bias}, ["C", "H"],
                        out_dtype="float32")
        new_c, new_h = outs[0], outs[1]
        return new_h, (new_h, new_c)


class RNN(Layer):
    """reference text.py:476 — run a cell over [B, T, D]."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states):
        from ..fluid.layers import nn as N
        from ..fluid.layers import tensor as T

        if self.time_major:
            inputs = T.transpose(inputs, [1, 0, 2])
        t = inputs.shape[1]
        steps = range(t - 1, -1, -1) if self.is_reverse else range(t)
        states = initial_states
        outs = [None] * t
        for i in steps:
            x_t = N.squeeze(
                N.slice(inputs, axes=[1], starts=[i], ends=[i + 1]),
                axes=[1])
            out, states = self.cell(x_t, states)
            outs[i] = out
        stacked = N.stack(outs, axis=1)
        if self.time_major:
            stacked = T.transpose(stacked, [1, 0, 2])
        return stacked, states


def _merge_directions(out, hidden_size, mode):
    """Apply the reference merge_mode over the concat [..., 2H] output
    (text.py BidirectionalRNN: concat | sum | ave | mul | zip)."""
    if mode in (None, "concat"):
        return out
    from ..fluid.layers import nn as N

    fwd = N.slice(out, axes=[out.ndim - 1], starts=[0],
                  ends=[hidden_size])
    bwd = N.slice(out, axes=[out.ndim - 1], starts=[hidden_size],
                  ends=[2 * hidden_size])
    if mode == "sum":
        return N.elementwise_add(fwd, bwd)
    if mode in ("ave", "average"):
        from ..fluid.layers import tensor as T

        return T.scale(N.elementwise_add(fwd, bwd), scale=0.5)
    if mode == "mul":
        return N.elementwise_mul(fwd, bwd)
    raise ValueError("unsupported merge_mode %r" % mode)


class BidirectionalLSTM(Layer):
    """reference text.py:1144 — fwd + bwd LSTM; merge_mode selects how
    the direction outputs combine (concat/sum/ave/mul)."""

    def __init__(self, input_size, hidden_size, merge_mode="concat",
                 num_layers=1):
        super().__init__()
        from ..nn.rnn import LSTM as _LSTM

        self._impl = _LSTM(input_size, hidden_size,
                           num_layers=num_layers,
                           direction="bidirectional")
        self._merge = merge_mode
        self._hidden = hidden_size

    def forward(self, inputs, initial_states=None):
        out = self._impl(inputs, initial_states)
        seq, states = out if isinstance(out, tuple) else (out, None)
        seq = _merge_directions(seq, self._hidden, self._merge)
        return (seq, states) if states is not None else seq


class BidirectionalGRU(Layer):
    def __init__(self, input_size, hidden_size, merge_mode="concat",
                 num_layers=1):
        super().__init__()
        from ..nn.rnn import GRU as _GRU

        self._impl = _GRU(input_size, hidden_size, num_layers=num_layers,
                          direction="bidirectional")
        self._merge = merge_mode
        self._hidden = hidden_size

    def forward(self, inputs, initial_states=None):
        out = self._impl(inputs, initial_states)
        seq, states = out if isinstance(out, tuple) else (out, None)
        seq = _merge_directions(seq, self._hidden, self._merge)
        return (seq, states) if states is not None else seq


class Conv1dPoolLayer(Layer):
    """reference text.py:1980 — Conv1D (as a 1-wide Conv2D) + Pool1D."""

    def __init__(self, num_channels, num_filters, filter_size,
                 pool_size, conv_stride=1, pool_stride=1, conv_padding=0,
                 act=None, pool_type="max", global_pooling=False):
        super().__init__()
        self.conv = dnn.Conv2D(num_channels, num_filters,
                               (filter_size, 1), stride=(conv_stride, 1),
                               padding=(conv_padding, 0), act=act)
        self._pool_args = (pool_size, pool_type, pool_stride,
                           global_pooling)

    def forward(self, x):
        from ..fluid.layers import nn as N
        from ..tensor import manipulation as M

        # x [B, C, T] -> [B, C, T, 1]
        x4 = M.unsqueeze(x, [3]) if x.ndim == 3 else x
        c = self.conv(x4)
        size, ptype, stride, global_p = self._pool_args
        if global_p:
            size = c.shape[2]
            stride = 1
        p = N.pool2d(c, pool_size=(size, 1), pool_type=ptype,
                     pool_stride=(stride, 1))
        p = M.squeeze(p, [3])
        if global_p:
            p = M.squeeze(p, [2])    # [B, C, 1] -> [B, C]
        return p


class CNNEncoder(Layer):
    """reference text.py:2109 — parallel Conv1dPool branches concat'd
    along channels (TextCNN)."""

    def __init__(self, num_channels, num_filters, filter_size,
                 pool_size=1, num_layers=1, conv_stride=1, pool_stride=1,
                 act=None, pool_type="max", global_pooling=True):
        super().__init__()
        sizes = filter_size if isinstance(filter_size, (list, tuple)) \
            else [filter_size]
        chans = num_channels if isinstance(num_channels, (list, tuple)) \
            else [num_channels] * len(sizes)
        filts = num_filters if isinstance(num_filters, (list, tuple)) \
            else [num_filters] * len(sizes)
        self.branches = []
        for i, (c, f, k) in enumerate(zip(chans, filts, sizes)):
            stack = []
            c_in = c
            # num_layers stacks convs before the (optionally global)
            # pool, like the reference's layered Conv1dPoolLayer chains
            for layer in range(num_layers):
                last = layer == num_layers - 1
                br = Conv1dPoolLayer(
                    c_in, f, k, pool_size,
                    conv_stride=conv_stride,
                    pool_stride=pool_stride if last else 1,
                    conv_padding=(0 if last else k // 2), act=act,
                    pool_type=pool_type,
                    global_pooling=global_pooling and last)
                self.add_sublayer("branch_%d_%d" % (i, layer), br)
                stack.append(br)
                c_in = f
            self.branches.append(stack)

    def forward(self, x):
        from ..fluid.layers import tensor as T

        outs = []
        for stack in self.branches:
            h = x
            for br in stack:
                h = br(h)
            outs.append(h)
        return T.concat(outs, axis=1) if len(outs) > 1 else outs[0]


class DynamicDecode(Layer):
    """reference text.py:1762 — Layer wrapper over dynamic_decode.
    Decoding unrolls to max_step_num (static shapes under XLA), so a
    None max_step_num is rejected rather than silently capped."""

    def __init__(self, decoder, max_step_num=None, output_time_major=False,
                 impute_finished=False, is_test=False,
                 return_length=False):
        super().__init__()
        if max_step_num is None:
            raise ValueError(
                "DynamicDecode needs an explicit max_step_num: decoding "
                "unrolls to a static step count under XLA")
        if impute_finished:
            raise NotImplementedError(
                "impute_finished is not supported; finished beams carry "
                "their end token (gather_tree finalization)")
        self.decoder = decoder
        self.max_step_num = max_step_num
        self.output_time_major = output_time_major
        self.return_length = return_length

    def forward(self, inits=None, **kwargs):
        # dynamic_decode natively supports both flags; constructor args
        # win over accidental duplicates in **kwargs
        kwargs.pop("output_time_major", None)
        kwargs.pop("return_length", None)
        return dynamic_decode(self.decoder, inits=inits,
                              max_step_num=self.max_step_num,
                              output_time_major=self.output_time_major,
                              return_length=self.return_length,
                              **kwargs)
