"""Terminal progress bar (reference:
`python/paddle/incubate/hapi/progressbar.py` ProgressBar)."""
from __future__ import annotations

import sys
import time


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, file=sys.stdout):
        self._num = num
        self._width = width if num else 0
        self._verbose = verbose
        self._file = file
        self._start = time.time()
        self._last_update = 0

    def _format_values(self, values):
        parts = []
        for k, v in values:
            if isinstance(v, (float,)):
                parts.append("%s: %.4f" % (k, v))
            elif isinstance(v, (list, tuple)):
                parts.append("%s: %s" % (
                    k, "/".join("%.4f" % float(x) for x in v)))
            else:
                parts.append("%s: %s" % (k, v))
        return " - ".join(parts)

    def update(self, current_num, values=None):
        values = values or []
        now = time.time()
        msg = self._format_values(values)
        if self._verbose == 1:
            if self._num is not None:
                frac = min(1.0, current_num / max(1, self._num))
                filled = int(frac * self._width)
                bar = "=" * filled + ">" + "." * (self._width - filled)
                line = "step %d/%d [%s] - %s" % (
                    current_num, self._num, bar, msg)
            else:
                line = "step %d - %s" % (current_num, msg)
            self._file.write("\r" + line)
            if self._num is not None and current_num >= self._num:
                self._file.write("\n")
            self._file.flush()
            self._last_update = now
        elif self._verbose == 2:
            self._file.write("step %d/%s - %s\n" % (
                current_num, self._num or "?", msg))
            self._file.flush()
