"""paddle.imperative 2.0 namespace (reference:
`python/paddle/imperative/__init__.py`) — dygraph re-exports."""
from ..fluid.dygraph.base import (  # noqa: F401
    guard, no_grad, to_variable, grad,
)
from ..fluid.framework import in_dygraph_mode as enabled  # noqa: F401
from ..fluid.dygraph.checkpoint import (  # noqa: F401
    load_dygraph as load, save_dygraph as save,
)
from ..fluid.dygraph.parallel import (  # noqa: F401
    ParallelEnv, DataParallel, prepare_context,
)
from ..fluid.dygraph.jit import TracedLayer, declarative  # noqa: F401
from ..fluid.dygraph.dygraph_to_static.program_translator import (  # noqa: F401,E501
    ProgramTranslator,
)
