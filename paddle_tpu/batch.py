"""paddle.batch (reference: `python/paddle/batch.py:18`): group a
sample reader into minibatch lists."""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    if batch_size <= 0:
        raise ValueError("batch_size should be a positive integer")
    return batch_reader
