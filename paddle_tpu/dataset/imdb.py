"""IMDB sentiment reader creators (reference:
`python/paddle/dataset/imdb.py`: word_dict() + train/test yielding
(token-id list, 0/1 label)). Synthetic corpus with a class-correlated
vocabulary keeps the contract without downloads."""
from __future__ import annotations

import numpy as np

__all__ = ["word_dict", "train", "test"]

_VOCAB = 5149  # reference vocabulary size ballpark


def word_dict():
    return {("w%d" % i).encode(): i for i in range(_VOCAB)}


def _gen(n, seed):
    r = np.random.RandomState(seed)
    pos_words = np.arange(10, _VOCAB // 2)
    neg_words = np.arange(_VOCAB // 2, _VOCAB - 10)
    for _ in range(n):
        label = int(r.randint(0, 2))
        pool = pos_words if label == 0 else neg_words
        length = int(r.randint(8, 64))
        ids = r.choice(pool, length).tolist()
        yield ids, label


def train(word_idx=None):
    return lambda: _gen(512, 0)


def test(word_idx=None):
    return lambda: _gen(128, 1)
