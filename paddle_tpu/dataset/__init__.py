"""paddle.dataset (reference: `python/paddle/dataset/` — mnist, cifar,
imdb, imikolov, uci_housing, ... loaders exposed as reader creators).

Zero-egress build: loaders read the reference on-disk formats from
`~/.cache/paddle_tpu/dataset/<name>/` when files are present and
otherwise fall back to DETERMINISTIC synthetic data with the same
shapes/dtypes/vocabulary contract, so pipelines and tests run without
downloads (the reference downloads from paddle's CDN at import time)."""
from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
