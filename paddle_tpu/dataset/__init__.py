"""paddle.dataset (reference: `python/paddle/dataset/` — mnist, cifar,
imdb, imikolov, uci_housing, ... loaders exposed as reader creators).

Zero-egress build: loaders read the reference on-disk formats from
`~/.cache/paddle_tpu/dataset/<name>/` when files are present and
otherwise fall back to DETERMINISTIC synthetic data with the same
shapes/dtypes/vocabulary contract, so pipelines and tests run without
downloads (the reference downloads from paddle's CDN at import time)."""
from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import conll05  # noqa: F401
from . import movielens  # noqa: F401
from . import mq2007  # noqa: F401
from . import sentiment  # noqa: F401
from . import flowers  # noqa: F401
from . import voc2012  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
from . import image  # noqa: F401
