"""MNIST reader creators (reference: `python/paddle/dataset/mnist.py`
train()/test() yielding (784-float image in [-1,1], int label)). Reads
idx files from the cache when present, else deterministic synthetic
digits."""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import common

__all__ = ["train", "test"]

_N_SYN_TRAIN = 1024
_N_SYN_TEST = 256


def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, path
        data = np.frombuffer(f.read(n * rows * cols), np.uint8)
    return data.reshape(n, rows * cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, path
        return np.frombuffer(f.read(n), np.uint8)


def _cached(kind):
    names = {
        "train": ("train-images-idx3-ubyte.gz",
                  "train-labels-idx1-ubyte.gz"),
        "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
    }[kind]
    paths = [os.path.join(common.DATA_HOME, "mnist", n) for n in names]
    alt = [p[:-3] for p in paths]  # non-gz variants
    for cand in (paths, alt):
        if all(os.path.exists(p) for p in cand):
            return cand
    return None


def _synthetic(n, seed):
    """Deterministic stand-in digits: blurred class-dependent strokes."""
    r = np.random.RandomState(seed)
    labels = r.randint(0, 10, n).astype("int64")
    imgs = np.zeros((n, 28, 28), "float32")
    for i, lbl in enumerate(labels):
        rr = np.random.RandomState(1000 + int(lbl))
        base = rr.rand(28, 28) > 0.82
        imgs[i] = base * (0.6 + 0.4 * r.rand(28, 28))
    return imgs.reshape(n, 784) * 2.0 - 1.0, labels


def _creator(kind, n_syn, seed):
    def reader():
        cached = _cached(kind)
        if cached is not None:
            imgs = _read_idx_images(cached[0]).astype("float32")
            imgs = imgs / 127.5 - 1.0
            labels = _read_idx_labels(cached[1]).astype("int64")
        else:
            imgs, labels = _synthetic(n_syn, seed)
        for img, lbl in zip(imgs, labels):
            yield img, int(lbl)

    return reader


def train():
    return _creator("train", _N_SYN_TRAIN, 0)


def test():
    return _creator("test", _N_SYN_TEST, 1)
