"""CIFAR reader creators (reference: `python/paddle/dataset/cifar.py`
train10/test10/train100/test100 yielding (3072-float image in [0,1],
int label)); synthetic fallback keeps the contract without downloads."""
from __future__ import annotations

import numpy as np

__all__ = ["train10", "test10", "train100", "test100"]


def _synthetic(n, n_classes, seed):
    r = np.random.RandomState(seed)
    labels = r.randint(0, n_classes, n).astype("int64")
    base = np.linspace(0, 1, 3072, dtype="float32")

    def img(lbl, i):
        rr = np.random.RandomState(int(lbl))
        hue = rr.rand(3072).astype("float32")
        noise = np.random.RandomState(seed + i).rand(3072) * 0.2
        return np.clip(0.6 * hue + 0.3 * base + noise, 0, 1) \
            .astype("float32")

    for i, lbl in enumerate(labels):
        yield img(lbl, i), int(lbl)


def _creator(n, n_classes, seed):
    def reader():
        return _synthetic(n, n_classes, seed)

    return reader


def train10():
    return _creator(1024, 10, 0)


def test10():
    return _creator(256, 10, 1)


def train100():
    return _creator(1024, 100, 2)


def test100():
    return _creator(256, 100, 3)
