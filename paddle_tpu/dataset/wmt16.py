"""WMT16 en-de reader creators (reference:
`python/paddle/dataset/wmt16.py`: train/test/validation(src_dict_size,
trg_dict_size, src_lang) yielding (src_ids, trg_ids, trg_next_ids);
get_dict(lang, dict_size, reverse)). Synthetic parallel corpus keeps
the contract without downloads."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "validation", "get_dict"]

_LANGS = ("en", "de")


def _dict(lang, size):
    d = {"<s>": 0, "<e>": 1, "<unk>": 2}
    for i in range(3, size):
        d["%s%d" % (lang, i)] = i
    return d


def _gen(n, seed, src_size, trg_size):
    r = np.random.RandomState(seed)
    for _ in range(n):
        sl = int(r.randint(3, 24))
        src = r.randint(3, src_size, sl).tolist()
        trg = [(t * 2) % (trg_size - 3) + 3 for t in src]
        yield src, [0] + trg, trg + [1]


def _check_lang(src_lang):
    if src_lang not in _LANGS:
        raise ValueError("src_lang must be 'en' or 'de'")


def train(src_dict_size, trg_dict_size, src_lang="en"):
    _check_lang(src_lang)
    return lambda: _gen(256, 51, src_dict_size, trg_dict_size)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    _check_lang(src_lang)
    return lambda: _gen(64, 52, src_dict_size, trg_dict_size)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    _check_lang(src_lang)
    return lambda: _gen(64, 53, src_dict_size, trg_dict_size)


def get_dict(lang, dict_size, reverse=False):
    _check_lang(lang)
    d = _dict(lang, dict_size)
    return {v: k for k, v in d.items()} if reverse else d


def fetch():
    pass
