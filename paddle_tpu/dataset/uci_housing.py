"""UCI housing reader creators (reference:
`python/paddle/dataset/uci_housing.py`: 13 normalized features +
target). Deterministic synthetic regression data with a fixed linear
ground truth keeps the fit/eval contract."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "feature_names"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD",
    "TAX", "PTRATIO", "B", "LSTAT",
]

_W = np.linspace(-1.0, 1.0, 13).astype("float32")


def _gen(n, seed):
    r = np.random.RandomState(seed)
    x = r.randn(n, 13).astype("float32")
    y = x @ _W + 0.1 * r.randn(n).astype("float32")
    for i in range(n):
        yield x[i], np.asarray([y[i]], "float32")


def train():
    return lambda: _gen(404, 0)


def test():
    return lambda: _gen(102, 1)
