"""Oxford-102 flowers reader creators (reference:
`python/paddle/dataset/flowers.py`: train()/test()/valid() yielding
(CHW float image, 0..101 label)). Synthetic images keep the contract
without downloads."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "valid"]

_CLASSES = 102
_SHAPE = (3, 32, 32)  # small synthetic stand-in for the 224-crops


def _gen(n, seed):
    r = np.random.RandomState(seed)
    for _ in range(n):
        label = int(r.randint(0, _CLASSES))
        img = r.rand(*_SHAPE).astype("float32")
        img[label % 3] += 0.1  # weak class signal
        yield img, label


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    def reader():
        while True:
            yield from _gen(256, 21)
            if not cycle:
                return

    return reader


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    def reader():
        while True:
            yield from _gen(64, 22)
            if not cycle:
                return

    return reader


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return lambda: _gen(64, 23)


def fetch():
    pass
