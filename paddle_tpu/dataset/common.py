"""Dataset commons (reference: `python/paddle/dataset/common.py` —
DATA_HOME, md5file, cached download paths). Zero-egress: `download`
only resolves already-cached files and raises with instructions
otherwise."""
from __future__ import annotations

import hashlib
import os

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def cache_path(module_name, filename):
    d = os.path.join(DATA_HOME, module_name)
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, filename)


def download(url, module_name, md5sum=None, save_name=None):
    """Resolve a dataset file from the local cache. This build has no
    network egress: if the file is absent, raise with the cache path so
    the user can place it there (loaders fall back to synthetic data
    before calling this)."""
    filename = save_name or url.split("/")[-1]
    path = cache_path(module_name, filename)
    if os.path.exists(path):
        if md5sum and md5file(path) != md5sum:
            raise IOError("md5 mismatch for %s" % path)
        return path
    raise IOError(
        "dataset file %r is not cached and downloads are disabled; place "
        "it at %s" % (filename, path))
