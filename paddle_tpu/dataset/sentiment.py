"""NLTK movie-reviews sentiment readers (reference:
`python/paddle/dataset/sentiment.py`: get_word_dict(), train()/test()
yielding (word-id list, 0/1 label)). Synthetic class-correlated corpus
keeps the contract without NLTK downloads."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "get_word_dict"]

_VOCAB = 2048


def get_word_dict():
    return {("s%d" % i): i for i in range(_VOCAB)}


def _gen(n, seed):
    r = np.random.RandomState(seed)
    for _ in range(n):
        label = int(r.randint(0, 2))
        lo, hi = (4, _VOCAB // 2) if label == 0 else (_VOCAB // 2,
                                                      _VOCAB - 4)
        yield r.randint(lo, hi, int(r.randint(6, 48))).tolist(), label


def train():
    return lambda: _gen(400, 11)


def test():
    return lambda: _gen(100, 12)


def fetch():
    pass
