"""Image preprocessing utilities (reference:
`python/paddle/dataset/image.py` — resize_short, to_chw, center_crop,
random_crop, left_right_flip, simple_transform, load_and_transform).
Pure-numpy implementations (the reference shells out to cv2; the math
is identical up to interpolation kernel — nearest here). File decoding
(load_image*) needs an image codec, which this zero-egress build does
not ship: those raise with instructions, and every transform works on
ndarrays."""
from __future__ import annotations

import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
]


def load_image_bytes(bytes_, is_color=True):  # pragma: no cover
    raise NotImplementedError(
        "image decoding needs cv2/PIL, which this build does not ship; "
        "decode to an ndarray yourself and use the transform functions")


def load_image(file, is_color=True):
    if str(file).endswith(".npy"):
        return np.load(file)
    return load_image_bytes(None, is_color)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):  # pragma: no cover
    raise NotImplementedError(
        "tar batching needs image decoding; see load_image")


def resize_short(im, size):
    """Resize so the SHORTER edge equals `size` (nearest-neighbor).
    im: HWC (or HW) ndarray."""
    h, w = im.shape[:2]
    if h < w:
        nh, nw = size, max(int(round(w * size / h)), 1)
    else:
        nh, nw = max(int(round(h * size / w)), 1), size
    ry = (np.arange(nh) * h // nh).clip(0, h - 1)
    rx = (np.arange(nw) * w // nw).clip(0, w - 1)
    return im[ry][:, rx]


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    y0 = max((h - size) // 2, 0)
    x0 = max((w - size) // 2, 0)
    return im[y0:y0 + size, x0:x0 + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    y0 = np.random.randint(0, max(h - size, 0) + 1)
    x0 = np.random.randint(0, max(w - size, 0) + 1)
    return im[y0:y0 + size, x0:x0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train,
                     is_color=True, mean=None):
    """resize_short + (random crop + flip | center crop) + CHW + mean
    subtraction (reference: image.py simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
