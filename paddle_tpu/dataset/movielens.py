"""MovieLens-1M reader creators (reference:
`python/paddle/dataset/movielens.py`: MovieInfo/UserInfo records;
train()/test() yield [user_id, gender_id, age_id, job_id, movie_id,
category_ids, title_ids, score]). Synthetic catalog keeps the contract
without downloads."""
from __future__ import annotations

import numpy as np

__all__ = [
    "train", "test", "get_movie_title_dict", "max_movie_id",
    "max_user_id", "max_job_id", "movie_categories", "user_info",
    "movie_info", "age_table", "MovieInfo", "UserInfo",
]

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_MOVIES = 400
_N_USERS = 600
_N_JOBS = 21
_CATEGORIES = ["Action", "Comedy", "Drama", "Horror", "Sci-Fi",
               "Romance", "Thriller", "Animation"]
_TITLE_WORDS = 512


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index,
                [_CATEGORIES.index(c) for c in self.categories],
                [_title_dict()[w] for w in self.title.split()]]

    def __repr__(self):
        return "<MovieInfo id(%d), title(%s), categories(%s)>" % (
            self.index, self.title, self.categories)


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]

    def __repr__(self):
        return "<UserInfo id(%d), gender(%s), age(%d), job(%d)>" % (
            self.index, "M" if self.is_male else "F",
            age_table[self.age], self.job_id)


_cache = {}


def _title_dict():
    if "titles" not in _cache:
        _cache["titles"] = {("t%d" % i): i for i in range(_TITLE_WORDS)}
    return _cache["titles"]


def _catalog():
    if "movies" in _cache:
        return _cache["movies"], _cache["users"]
    r = np.random.RandomState(7)
    movies = {}
    for i in range(1, _N_MOVIES + 1):
        cats = [_CATEGORIES[j] for j in
                r.choice(len(_CATEGORIES), int(r.randint(1, 3)),
                         replace=False)]
        title = " ".join("t%d" % t for t in
                         r.randint(0, _TITLE_WORDS, int(r.randint(1, 5))))
        movies[i] = MovieInfo(i, cats, title)
    users = {}
    for i in range(1, _N_USERS + 1):
        users[i] = UserInfo(i, "M" if r.rand() < 0.5 else "F",
                            age_table[int(r.randint(len(age_table)))],
                            int(r.randint(0, _N_JOBS)))
    _cache["movies"], _cache["users"] = movies, users
    return movies, users


def _gen(is_test, seed=3, n=2000, test_ratio=0.1):
    movies, users = _catalog()
    r = np.random.RandomState(seed)
    for _ in range(n):
        in_test = r.rand() < test_ratio
        if in_test != is_test:
            continue
        u = users[int(r.randint(1, _N_USERS + 1))]
        m = movies[int(r.randint(1, _N_MOVIES + 1))]
        score = float(r.randint(1, 6))
        yield u.value() + m.value() + [[score]]


def train():
    return lambda: _gen(False)


def test():
    return lambda: _gen(True)


def get_movie_title_dict():
    return _title_dict()


def movie_categories():
    return {c: i for i, c in enumerate(_CATEGORIES)}


def max_movie_id():
    return _N_MOVIES


def max_user_id():
    return _N_USERS


def max_job_id():
    return _N_JOBS - 1


def movie_info():
    return _catalog()[0]


def user_info():
    return _catalog()[1]


def fetch():
    pass
