"""PASCAL VOC2012 segmentation reader creators (reference:
`python/paddle/dataset/voc2012.py`: train()/test()/val() yielding
(CHW uint8-range image, HW int32 label mask)). Synthetic masks keep the
contract without downloads."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "val"]

_CLASSES = 21
_H = _W = 32


def _gen(n, seed):
    r = np.random.RandomState(seed)
    for _ in range(n):
        img = (r.rand(3, _H, _W) * 255).astype("float32")
        label = np.zeros((_H, _W), "int32")
        cls = int(r.randint(1, _CLASSES))
        y0, x0 = r.randint(0, _H // 2), r.randint(0, _W // 2)
        label[y0:y0 + _H // 2, x0:x0 + _W // 2] = cls
        yield img, label


def train():
    return lambda: _gen(128, 31)


def test():
    return lambda: _gen(32, 32)


def val():
    return lambda: _gen(32, 33)


def fetch():
    pass
