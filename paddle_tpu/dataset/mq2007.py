"""MQ2007 learning-to-rank reader creators (reference:
`python/paddle/dataset/mq2007.py`: train/test generators parameterized
by format — pointwise (label, 46-dim feature), pairwise
(high_feature, low_feature), listwise (labels, features)). Synthetic
query groups keep the contract without downloads."""
from __future__ import annotations

import functools

import numpy as np

__all__ = ["train", "test"]

_N_FEATURES = 46


def _queries(n_queries, seed):
    r = np.random.RandomState(seed)
    for _ in range(n_queries):
        n_docs = int(r.randint(4, 12))
        labels = r.randint(0, 3, n_docs).astype("float64")
        feats = r.rand(n_docs, _N_FEATURES)
        # weak signal: first feature correlates with relevance
        feats[:, 0] = labels / 2.0 + 0.1 * feats[:, 0]
        yield labels, feats


def gen_point(labels, feats):
    for lbl, f in zip(labels, feats):
        yield float(lbl), f.tolist()


def gen_pair(labels, feats):
    order = np.argsort(-labels)
    for i in range(len(order)):
        for j in range(i + 1, len(order)):
            hi, lo = order[i], order[j]
            if labels[hi] > labels[lo]:
                yield (np.array([labels[hi]]), feats[hi].tolist(),
                       feats[lo].tolist())


def gen_list(labels, feats):
    yield labels.tolist(), feats.tolist()


def __reader__(n_queries=32, seed=61, format="pairwise"):
    for labels, feats in _queries(n_queries, seed):
        if format == "pointwise":
            yield from gen_point(labels, feats)
        elif format == "pairwise":
            yield from gen_pair(labels, feats)
        elif format == "listwise":
            yield from gen_list(labels, feats)
        else:
            raise ValueError("format must be pointwise/pairwise/listwise")


train = functools.partial(__reader__, n_queries=32, seed=61)
test = functools.partial(__reader__, n_queries=8, seed=62)


def fetch():
    pass
