"""CoNLL-2005 SRL reader creators (reference:
`python/paddle/dataset/conll05.py`: get_dict() -> (word, verb, label)
dicts, get_embedding() -> pretrained matrix, test() yielding the
9-sequence SRL sample (word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
pred, mark, labels)). Synthetic corpus keeps the contract without
downloads."""
from __future__ import annotations

import numpy as np

__all__ = ["test", "get_dict", "get_embedding"]

_WORDS = 4000
_VERBS = 200
# BIO labels over 5 argument types + O (reference label_dict shape)
_LABELS = ["O"] + ["%s-A%d" % (p, i) for i in range(5)
                   for p in ("B", "I")]
_EMB_DIM = 32


def get_dict():
    word_dict = {("w%d" % i): i for i in range(_WORDS)}
    verb_dict = {("v%d" % i): i for i in range(_VERBS)}
    label_dict = {lbl: i for i, lbl in enumerate(_LABELS)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    r = np.random.RandomState(0)
    return (r.rand(_WORDS, _EMB_DIM).astype("float32") - 0.5) * 0.1


def _gen(n, seed):
    r = np.random.RandomState(seed)
    n_label = len(_LABELS)
    for _ in range(n):
        length = int(r.randint(5, 40))
        words = r.randint(0, _WORDS, length).tolist()
        pred_pos = int(r.randint(0, length))
        pred = int(r.randint(0, _VERBS))

        def ctx(off):
            p = min(max(pred_pos + off, 0), length - 1)
            return [words[p]] * length

        mark = [1 if i == pred_pos else 0 for i in range(length)]
        labels = r.randint(0, n_label, length).tolist()
        yield (words, ctx(-2), ctx(-1), ctx(0), ctx(1), ctx(2),
               [pred] * length, mark, labels)


def test():
    return lambda: _gen(64, 5)


def fetch():
    pass
