"""imikolov (PTB) n-gram reader creators (reference:
`python/paddle/dataset/imikolov.py`: build_dict + train/test yielding
n-gram id tuples for word2vec). Synthetic Zipf text keeps the
contract."""
from __future__ import annotations

import numpy as np

__all__ = ["build_dict", "train", "test"]

_VOCAB = 2074


def build_dict(min_word_freq=50):
    return {("w%d" % i): i for i in range(_VOCAB)}


def _gen(n_sent, n, seed):
    r = np.random.RandomState(seed)
    # Zipf-ish id stream: frequent low ids, like real text
    for _ in range(n_sent):
        length = int(r.randint(n + 1, 24))
        ids = np.minimum(
            r.zipf(1.3, length) - 1, _VOCAB - 1).astype(int).tolist()
        for i in range(len(ids) - n + 1):
            yield tuple(ids[i:i + n])


def train(word_idx=None, n=5):
    return lambda: _gen(256, n, 0)


def test(word_idx=None, n=5):
    return lambda: _gen(64, n, 1)
