"""WMT14 en-fr reader creators (reference:
`python/paddle/dataset/wmt14.py`: train(dict_size)/test(dict_size)
yielding (src_ids, trg_ids, trg_next_ids) with <s>/<e>/<unk> at ids
0/1/2; get_dict(dict_size, reverse)). Synthetic parallel corpus keeps
the contract without downloads."""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "get_dict"]

START = "<s>"
END = "<e>"
UNK = "<unk>"
START_ID, END_ID, UNK_ID = 0, 1, 2


def _dicts(dict_size):
    src = {START: 0, END: 1, UNK: 2}
    trg = {START: 0, END: 1, UNK: 2}
    for i in range(3, dict_size):
        src["en%d" % i] = i
        trg["fr%d" % i] = i
    return src, trg


def _gen(n, seed, dict_size):
    r = np.random.RandomState(seed)
    for _ in range(n):
        sl = int(r.randint(3, 30))
        src = r.randint(3, dict_size, sl).tolist()
        trg = [(t + 1) % (dict_size - 3) + 3 for t in src[::-1]]
        trg_in = [START_ID] + trg
        trg_next = trg + [END_ID]
        yield src, trg_in, trg_next


def train(dict_size):
    return lambda: _gen(256, 41, dict_size)


def test(dict_size):
    return lambda: _gen(64, 42, dict_size)


def gen(dict_size):
    return lambda: _gen(64, 43, dict_size)


def get_dict(dict_size, reverse=True):
    src, trg = _dicts(dict_size)
    if reverse:
        src = {v: k for k, v in src.items()}
        trg = {v: k for k, v in trg.items()}
    return src, trg


def fetch():
    pass
