"""Minimal host RPC for the parameter-server tier.

Reference parity: `paddle/fluid/operators/distributed/` gRPC/BRPC client+
server with `send_recv.proto.in` variable serialization (SURVEY.md §2.1
"Parameter-server RPC"). TPU-native scope: the PS tier is host-side CPU
machinery (the dense/sparse tables never touch the accelerator), so a
length-prefixed binary protocol over TCP sockets replaces the gRPC stack;
tensors travel as raw ndarray bytes with a tiny header — no pickle, no
third-party deps.

Wire format per message (little-endian):
  [u64 total_len][u16 n_fields] then per field:
  [u8 kind][u64 len][payload]  (u64 frames: multi-GB dataset blobs must
  not overflow the length prefix)
    kind 0: utf-8 string
    kind 1: ndarray — payload is [u8 dtype_len][dtype str][u8 ndim]
            [u64 x ndim shape][raw bytes]
    kind 2: int64

Fault tolerance (pod-scale preemption/flaky-networking is the common
case, not the exception — see PAPERS.md on TPU concurrency limits):

- every request travels in an envelope ["__rq1__", client_id, seq,
  method, *args]; `seq` increments per client, so the server can
  DEDUPLICATE a retried request after a mid-stream drop. The handler for
  a given (client_id, seq) runs EXACTLY ONCE; a duplicate waits for the
  original invocation and returns its cached response. A retried
  `send_grads_batch` is therefore never double-applied to PS tables.
- `RpcClient.call` transparently reconnects with exponential backoff on
  any connection drop (env knobs: PADDLE_RPC_RETRIES, PADDLE_RPC_BACKOFF_S,
  PADDLE_RPC_BACKOFF_MAX_S) and re-sends the SAME envelope. Each sleep
  is jittered (PADDLE_RPC_BACKOFF_JITTER, default 0.5, 0 disables):
  after a pserver restart EVERY trainer's retry clock fires at the same
  exponential instants otherwise, and the synchronized thundering herd
  re-drops half the reconnects it is trying to heal.
- the server's per-(client_id, seq) dedup table can be snapshotted and
  restored (`dedup_snapshot`/`dedup_restore`) so a stateful server (the
  PS tier) can carry exactly-once across its own death+restart: a
  request applied before the crash is answered from the restored
  marker instead of being re-applied.
- error responses carry the exception type and the full server-side
  traceback — ["exc", type, msg, traceback] — surfaced client-side as
  RpcRemoteError (legacy "err:<msg>" responses are still understood).
- the socket layer calls into distributed/faults.py before every
  send/recv so drops/delays/kills are injectable deterministically
  (PADDLE_FAULTS env or faults.inject ctx manager).
- `RpcServer.shutdown()` is idempotent and thread-safe, including when
  invoked from one of the server's own handler threads.
"""
from __future__ import annotations

import os
import socket
import socketserver
import struct
import threading
import time
import traceback
import uuid
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import faults

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")

_ENVELOPE = "__rq1__"

#: per-thread (client_id, seq) of the request the current RpcServer
#: handler thread is executing — stateful handlers (the PS tier's
#: checkpoint) read it to persist "this request was applied" markers
#: atomically with their own state mutation
_request_ctx = threading.local()


def current_request_ctx():
    """(client_id, seq) of the enveloped request the calling handler
    thread is executing, or None outside a handler / for bare legacy
    frames."""
    return getattr(_request_ctx, "ctx", None)


def _telemetry():
    """The observability registry, or None very early in interpreter
    life (the RPC layer must work before — and after — everything
    else)."""
    try:
        from ..observability.registry import registry

        return registry()
    except Exception:  # noqa: BLE001 - telemetry never gates RPC
        return None


#: RPC methods that are cross-rank BARRIERS (the PS sync barrier —
#: every trainer must arrive or everyone blocks): these record into
#: the in-flight collective trace (observability/watchdog.py) exactly
#: like host-tier collectives, so a hang inside the PS tier gets the
#: same enqueue/complete forensics as one inside a HostCollectiveGroup
_BARRIER_METHODS = frozenset({"send_barrier"})


def _inflight_begin(method, endpoint):
    """In-flight trace token for a barrier-like RPC, or None (tracing
    never gates the RPC path)."""
    if method not in _BARRIER_METHODS:
        return None
    try:
        from ..observability import watchdog as _wd

        return _wd.trace().begin("rpc_" + method,
                                 "%s@%s" % (method, endpoint),
                                 tier="rpc")
    except Exception:  # noqa: BLE001
        return None


def _enc_field(buf: bytearray, v):
    if isinstance(v, str):
        b = v.encode("utf-8")
        buf.append(0)
        buf += _U64.pack(len(b))
        buf += b
    elif isinstance(v, (int, np.integer)):
        buf.append(2)
        buf += _U64.pack(8)
        buf += struct.pack("<q", int(v))
    else:
        a = np.ascontiguousarray(v)
        dt = a.dtype.str.encode()
        payload = bytearray()
        payload.append(len(dt))
        payload += dt
        payload.append(a.ndim)
        for d in a.shape:
            payload += _U64.pack(d)
        payload += a.tobytes()
        buf.append(1)
        buf += _U64.pack(len(payload))
        buf += payload


def encode(fields) -> bytes:
    # u16 field count: a batched send_grads_batch carries 2 fields per
    # hosted table plus the envelope — a u8 silently capped the PS tier
    # at ~125 params per server
    if len(fields) > 0xFFFF:
        raise ValueError("rpc message has %d fields (max 65535); batch "
                         "smaller" % len(fields))
    body = bytearray()
    body += _U16.pack(len(fields))
    for f in fields:
        _enc_field(body, f)
    return _U64.pack(len(body)) + bytes(body)


def _dec_field(mv, off):
    kind = mv[off]
    off += 1
    (ln,) = _U64.unpack_from(mv, off)
    off += 8
    payload = mv[off:off + ln]
    off += ln
    if kind == 0:
        return bytes(payload).decode("utf-8"), off
    if kind == 2:
        return struct.unpack("<q", payload)[0], off
    p = 0
    dt_len = payload[p]
    p += 1
    dtype = np.dtype(bytes(payload[p:p + dt_len]).decode())
    p += dt_len
    ndim = payload[p]
    p += 1
    shape = []
    for _ in range(ndim):
        (d,) = _U64.unpack_from(payload, p)
        shape.append(d)
        p += 8
    arr = np.frombuffer(payload, dtype=dtype, offset=p,
                        count=int(np.prod(shape)) if shape else 1)
    if not shape:
        arr = arr.reshape(())
    else:
        arr = arr.reshape(shape)
    return arr.copy(), off


def decode(body: bytes) -> List:
    mv = memoryview(body)
    (n,) = _U16.unpack_from(mv, 0)
    off = 2
    out = []
    for _ in range(n):
        v, off = _dec_field(mv, off)
        out.append(v)
    return out


def _read_exact(sock, n):
    chunks = []
    while n:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("peer closed")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def read_msg(sock) -> List:
    (ln,) = _U64.unpack(_read_exact(sock, 8))
    return decode(_read_exact(sock, ln))


def write_msg(sock, fields):
    sock.sendall(encode(fields))


class RpcRemoteError(RuntimeError):
    """A handler raised on the server; carries the remote exception type
    and full server-side traceback instead of a bare message string."""

    def __init__(self, method, remote_type, remote_msg, remote_tb=""):
        self.method = method
        self.remote_type = remote_type
        self.remote_msg = remote_msg
        self.remote_traceback = remote_tb
        msg = "rpc %s failed: %s: %s" % (method, remote_type, remote_msg)
        if remote_tb:
            msg += "\n--- remote traceback ---\n%s" % remote_tb.rstrip()
        super().__init__(msg)


class _Stop(Exception):
    """Raised by a handler to acknowledge then stop the server."""


class RpcServer:
    """Threaded TCP server dispatching (method, *args) -> fields.

    Enveloped requests are deduplicated per (client_id, seq): the handler
    runs exactly once; a retried duplicate (client reconnected after a
    drop) waits for the original invocation and is answered from its
    cached response, so side-effecting methods are never double-applied.
    """

    def __init__(self, host, port, handler):
        outer = self
        self._handler = handler

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                threading.current_thread()._rpc_server = outer
                try:
                    while True:
                        faults.on_message("server", "recv", sock=sock)
                        fields = read_msg(sock)
                        resp, stop, method = outer._dispatch(fields)
                        faults.on_message("server", "send", method=method,
                                          sock=sock)
                        write_msg(sock, resp)
                        if stop:
                            outer._stop_evt.set()
                            return
                except (ConnectionError, OSError):
                    return

        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Srv((host, port), _H)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._init_dispatch_state()

    def _init_dispatch_state(self):
        self._stop_evt = threading.Event()
        # (client_id) -> {"seq", "resp", "stop", "cv"}; all entries share
        # _dedup_lock through their per-entry Conditions
        self._dedup: Dict[str, dict] = {}
        self._dedup_lock = threading.Lock()
        self._shutdown_lock = threading.Lock()
        self._closed = False

    @classmethod
    def dispatch_only(cls, handler):
        """A socketless RpcServer: full envelope/dedup/snapshot semantics
        with `_dispatch(fields)` called directly instead of over TCP.
        This is what analysis/proto_models.py model-checks — the dedup
        state machine itself, with the checker (not the kernel's socket
        scheduler) choosing every delivery/retry/crash interleaving."""
        self = cls.__new__(cls)
        self._handler = handler
        self._server = None
        self._thread = None
        self.port = None
        self._init_dispatch_state()
        return self

    # -- request dedup ---------------------------------------------------
    def _dispatch(self, fields) -> Tuple[List, bool, Optional[str]]:
        if fields and fields[0] == _ENVELOPE:
            cid, seq, method = fields[1], int(fields[2]), fields[3]
            args = fields[4:]
        else:  # bare legacy frame: no retry/dedup semantics
            cid, seq, method = None, None, fields[0]
            args = fields[1:]
        if cid is None:
            resp, stop = self._execute(method, args)
            return resp, stop, method
        if method == "__rpc_bye__":
            # clean client close: evict its dedup entry so the cached
            # last response (possibly a gather-sized blob) is released
            with self._dedup_lock:
                self._dedup.pop(cid, None)
            return ["ok"], False, method
        if method == "__rpc_ack__":
            # acked-release: the client confirms it APPLIED the
            # response to the named seq, so the retained blob (a
            # params-sized get_params_batch reply pinned per trainer
            # between steps otherwise) can be freed NOW. The seq marker
            # stays for dedup; a tiny tombstone replaces the payload —
            # safe because a client only acks after receiving, so no
            # retry of that seq can ever need the cached bytes again.
            try:
                acked = int(args[0])
            except (IndexError, TypeError, ValueError):
                return (["exc", "ValueError",
                         "__rpc_ack__ needs the acked seq", ""],
                        False, method)
            with self._dedup_lock:
                ent = self._dedup.get(cid)
                if ent is not None and ent["seq"] == acked \
                        and ent["resp"] is not None:
                    ent["resp"] = ["ok"]
            return ["ok"], False, method

        with self._dedup_lock:
            ent = self._dedup.get(cid)
            if ent is None:
                self._evict_completed_locked()
                ent = self._dedup[cid] = {
                    "seq": -1, "resp": None, "stop": False, "ts": 0.0,
                    "cv": threading.Condition(self._dedup_lock)}
            ent["ts"] = time.monotonic()
            if seq <= ent["seq"]:
                if seq < ent["seq"]:
                    # a client never has two requests in flight, so a
                    # seq older than the newest is a protocol bug
                    return (["exc", "RuntimeError",
                             "stale duplicate request seq=%d (server at "
                             "seq=%d)" % (seq, ent["seq"]), ""],
                            False, method)
                # duplicate of the in-flight/completed newest request:
                # wait for the original handler invocation, answer from
                # its cached response — NEVER re-invoke the handler
                reg = _telemetry()
                if reg is not None:
                    reg.inc("rpc.dedup_hit")
                while (ent["seq"] == seq and ent["resp"] is None
                       and not self._closed):
                    ent["cv"].wait(timeout=0.5)
                if ent["seq"] == seq and ent["resp"] is not None:
                    return ent["resp"], ent["stop"], method
                return (["exc", "ConnectionError",
                         "server shutting down", ""], False, method)
            # new request: claim the slot before executing so a racing
            # duplicate blocks instead of double-invoking the handler
            ent["seq"], ent["resp"], ent["stop"] = seq, None, False

        _request_ctx.ctx = (cid, seq)
        try:
            resp, stop = self._execute(method, args)
        finally:
            _request_ctx.ctx = None
        with self._dedup_lock:
            if ent["seq"] == seq:
                ent["resp"], ent["stop"] = resp, stop
                ent["cv"].notify_all()
        return resp, stop, method

    _DEDUP_MAX_CLIENTS = 1024

    @staticmethod
    def _dedup_idle_evict_s():
        """Minimum idle age before a completed dedup entry may be
        evicted: must exceed the worst-case client retry span (each
        attempt pays up to reconnect-timeout + backoff), or an evicted
        entry's late retry would re-execute a side-effecting request.
        Derived from the same env knobs the clients read."""
        retries = int(os.environ.get("PADDLE_RPC_RETRIES", 8))
        reconnect = float(
            os.environ.get("PADDLE_RPC_RECONNECT_TIMEOUT_S", 5.0))
        backoff_max = float(
            os.environ.get("PADDLE_RPC_BACKOFF_MAX_S", 2.0))
        return max(60.0, 2.0 * retries * (reconnect + backoff_max))

    def _evict_completed_locked(self):
        """Bound the dedup table against client churn (crashed clients
        never say goodbye): once over the cap, drop entries that are
        completed AND idle well past the retry window — evicting a
        recently-active client would let its in-flight retry re-execute
        a side-effecting request, breaking the exactly-once guarantee.
        If everything is recent, correctness wins and the table may
        temporarily exceed the cap. Called with _dedup_lock held."""
        if len(self._dedup) < self._DEDUP_MAX_CLIENTS:
            return
        now = time.monotonic()
        min_idle = self._dedup_idle_evict_s()
        for old_cid in list(self._dedup):
            if len(self._dedup) < self._DEDUP_MAX_CLIENTS:
                break
            e = self._dedup[old_cid]
            if e["resp"] is not None and now - e["ts"] > min_idle:
                del self._dedup[old_cid]

    def _execute(self, method, args) -> Tuple[List, bool]:
        try:
            resp = self._handler(method, args)
            return ["ok"] + list(resp or []), False
        except _Stop:
            return ["ok"], True
        except Exception as e:  # noqa: BLE001
            return (["exc", type(e).__name__, str(e),
                     traceback.format_exc()], False)

    # -- dedup persistence (pserver checkpoint/restore) ------------------
    def dedup_snapshot(self, markers=None):
        """Persistable view of the dedup table: {cid: [seq, resp_bytes]}
        with resp wire-encoded (rpc.encode — the resp is already a list
        of wire-type fields). Only COMPLETED entries are included: an
        in-flight request's mutation may not have happened yet, and
        marking it applied would drop it on restore. `markers` (a
        {cid: (seq, resp_fields)} dict a stateful handler maintains
        under ITS OWN state lock) overrides/extends — that map, not
        this racy table walk, is what carries exactly-once across a
        server restart; the table walk is a best-effort extra."""
        out = {}
        with self._dedup_lock:
            for cid, ent in self._dedup.items():
                if ent["resp"] is not None:
                    # body only (strip the u64 frame length): decode()
                    # takes the unframed field list
                    out[cid] = [int(ent["seq"]),
                                encode(ent["resp"])[8:],
                                bool(ent["stop"])]
        for cid, marker in (markers or {}).items():
            seq, resp = marker[0], marker[1]
            stop = bool(marker[2]) if len(marker) > 2 else False
            out[cid] = [int(seq), encode(list(resp))[8:], stop]
        return out

    def dedup_restore(self, snapshot):
        """Pre-seed the dedup table from a `dedup_snapshot` taken by a
        previous incarnation of this server: a client retrying a
        request the old server applied-and-checkpointed is answered
        from the restored marker instead of re-invoking the handler.
        A marker's `stop` bit survives too — a replayed final shutdown
        request stops the reborn server again instead of leaving it
        serving forever."""
        with self._dedup_lock:
            for cid, marker in (snapshot or {}).items():
                seq, resp_bytes = marker[0], marker[1]
                stop = bool(marker[2]) if len(marker) > 2 else False
                self._dedup[cid] = {
                    "seq": int(seq), "resp": decode(bytes(resp_bytes)),
                    "stop": stop, "ts": time.monotonic(),
                    "cv": threading.Condition(self._dedup_lock)}

    # -- lifecycle -------------------------------------------------------
    def start(self):
        self._thread.start()

    def wait_stopped(self, timeout=None):
        self._stop_evt.wait(timeout)

    def shutdown(self):
        """Idempotent + thread-safe. Safe to call from one of this
        server's own handler threads (hc_shutdown / `complete` paths):
        socketserver.shutdown() joins the serve_forever loop, and a
        handler thread holding resources the loop waits on would
        deadlock — so from a handler thread the blocking part runs on a
        one-shot helper thread instead."""
        with self._shutdown_lock:
            if self._closed:
                return
            self._closed = True
        self._stop_evt.set()
        with self._dedup_lock:
            for ent in self._dedup.values():
                ent["cv"].notify_all()

        if self._server is None:  # dispatch_only: no socket to close
            return

        def _do():
            self._server.shutdown()
            self._server.server_close()

        if getattr(threading.current_thread(), "_rpc_server", None) is self:
            t = threading.Thread(target=_do, daemon=True,
                                 name="rpc-shutdown-helper")
            t.start()
        else:
            _do()


class RpcClient:
    """RPC client with transparent reconnect + idempotent retry.

    Each instance owns a stable client_id and a per-request sequence
    number. On a connection drop (send or recv side) the client
    reconnects with exponential backoff and re-sends the SAME envelope;
    the server's dedup layer guarantees the handler ran exactly once and
    replays the response if the original completed while the wire was
    down. Application-level errors (["exc", ...]) are NOT retried.
    """

    def __init__(self, endpoint: str, timeout=60.0, retries=60,
                 client_id: Optional[str] = None,
                 call_retries: Optional[int] = None):
        host, port = endpoint.rsplit(":", 1)
        self._endpoint = endpoint
        self._addr = (host, int(port))
        self._timeout = timeout
        self._connect_retries = int(retries)
        self._cid = client_id or uuid.uuid4().hex
        self._seq = 0
        self._sock = None
        self._lock = threading.Lock()
        # call_retries=0/1 suits fire-and-forget control paths
        # (heartbeats, teardown): their failures are swallowed anyway,
        # so burning the full retry cycle only stalls shutdown
        self._call_retries = int(
            call_retries if call_retries is not None
            else os.environ.get("PADDLE_RPC_RETRIES", 8))
        self._backoff_s = float(
            os.environ.get("PADDLE_RPC_BACKOFF_S", 0.05))
        self._backoff_max_s = float(
            os.environ.get("PADDLE_RPC_BACKOFF_MAX_S", 2.0))
        # jitter fraction: each backoff sleep is scaled by a uniform
        # draw from [1-j, 1+j] (clamped to >=0). Pure exponential
        # backoff synchronizes the whole cohort's retry clocks after a
        # pserver restart — N trainers reconnect in the same instant,
        # and the herd re-drops connections a spread-out retry would
        # have healed. 0 disables (deterministic tests).
        self._backoff_jitter = min(1.0, max(0.0, float(
            os.environ.get("PADDLE_RPC_BACKOFF_JITTER", 0.5))))
        # retry reconnects use a SHORT connect timeout: a blackholed
        # (preempted, no RST) server would otherwise stall every
        # attempt for the full initial-connect timeout, turning a
        # dead-host error into ~retries x 60s of silence
        self._reconnect_timeout_s = float(
            os.environ.get("PADDLE_RPC_RECONNECT_TIMEOUT_S", 5.0))
        self._connect()

    def _connect(self):
        last = None
        for _ in range(self._connect_retries):
            try:
                self._sock = socket.create_connection(
                    self._addr, timeout=self._timeout)
                break
            except OSError as e:
                last = e
                time.sleep(0.25)
        else:
            raise ConnectionError("cannot reach pserver %s: %s"
                                  % (self._endpoint, last))
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # blocking after connect: barrier/collective waits legitimately
        # exceed any fixed recv timeout (first-step compiles, slow ranks);
        # the SERVER side owns wait timeouts and always answers
        self._sock.settimeout(None)

    def _drop_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def call(self, method: str, *args) -> List:
        tok = _inflight_begin(method, self._endpoint)
        with self._lock:
            self._seq += 1
            payload = [_ENVELOPE, self._cid, self._seq, method] + list(args)
            try:
                resp = self._call_with_retry(method, payload, tok=tok)
            except BaseException:
                if tok is not None:
                    tok.done(ok=False)
                raise
        if tok is not None:
            # either error shape raises below ("exc" envelope or the
            # legacy "err:" string): the barrier did NOT complete —
            # the trace must not retire it as done
            failed = bool(resp) and (
                resp[0] == "exc"
                or (isinstance(resp[0], str)
                    and resp[0].startswith("err:")))
            tok.done(ok=not failed)
        if resp and resp[0] == "exc":
            raise RpcRemoteError(method, resp[1], resp[2],
                                 resp[3] if len(resp) > 3 else "")
        if isinstance(resp[0], str) and resp[0].startswith("err:"):
            raise RuntimeError("rpc %s failed: %s" % (method, resp[0][4:]))
        return resp[1:]

    def _call_with_retry(self, method, payload, tok=None):
        attempt = 0
        while True:
            try:
                if self._sock is None:
                    # fast single-attempt reconnect here; backoff between
                    # whole attempts is handled below
                    self._sock = socket.create_connection(
                        self._addr,
                        timeout=min(self._reconnect_timeout_s,
                                    self._timeout))
                    self._sock.setsockopt(socket.IPPROTO_TCP,
                                          socket.TCP_NODELAY, 1)
                    self._sock.settimeout(None)
                faults.on_message("client", "send", method=method,
                                  sock=self._sock)
                write_msg(self._sock, payload)
                if tok is not None:
                    # the request bytes left: this rank ARRIVED at the
                    # barrier; what remains is waiting on its peers
                    tok.arrived()
                faults.on_message("client", "recv", method=method,
                                  sock=self._sock)
                return read_msg(self._sock)
            except (ConnectionError, OSError) as e:
                self._drop_sock()
                attempt += 1
                reg = _telemetry()
                if attempt > self._call_retries:
                    if reg is not None:
                        reg.inc("rpc.giveup")
                        reg.event("rpc_giveup", method=method,
                                  endpoint=self._endpoint,
                                  attempts=attempt,
                                  error=str(e)[:200])
                    raise ConnectionError(
                        "rpc %s to %s failed after %d retries: %s"
                        % (method, self._endpoint, self._call_retries,
                           e)) from e
                if reg is not None:
                    reg.inc("rpc.retry")
                    reg.event("rpc_retry", method=method,
                              endpoint=self._endpoint, attempt=attempt)
                time.sleep(self._backoff_sleep_s(attempt))

    def _backoff_sleep_s(self, attempt):
        """Capped exponential backoff with multiplicative jitter."""
        base = min(self._backoff_s * (2 ** (attempt - 1)),
                   self._backoff_max_s)
        if self._backoff_jitter <= 0.0:
            return base
        import random

        return base * random.uniform(1.0 - self._backoff_jitter,
                                     1.0 + self._backoff_jitter)

    def ack_last(self):
        """Acked-release: tell the server the LAST call's response has
        been applied, so it frees the retained dedup blob immediately
        instead of pinning ~response-sized bytes until this client's
        next request. Best-effort and cheap (one tiny round trip on the
        live socket, no retry): if it's lost, the next real request
        frees the blob anyway."""
        reg = _telemetry()
        if reg is not None:
            reg.inc("rpc.ack")
        with self._lock:
            acked = self._seq
            self._seq += 1
            payload = [_ENVELOPE, self._cid, self._seq, "__rpc_ack__",
                       acked]
            try:
                if self._sock is None:
                    return
                faults.on_message("client", "send",
                                  method="__rpc_ack__", sock=self._sock)
                write_msg(self._sock, payload)
                faults.on_message("client", "recv",
                                  method="__rpc_ack__", sock=self._sock)
                read_msg(self._sock)
            except (ConnectionError, OSError):
                self._drop_sock()

    def close(self):
        # best-effort goodbye so the server drops this client's dedup
        # entry (it pins the last response blob otherwise); never block
        # a shutdown path on it
        try:
            if self._sock is not None:
                with self._lock:
                    self._seq += 1
                    self._sock.settimeout(2.0)
                    write_msg(self._sock, [_ENVELOPE, self._cid,
                                           self._seq, "__rpc_bye__"])
                    read_msg(self._sock)
        except Exception:  # noqa: BLE001 - server may already be gone
            pass
        try:
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
