"""Minimal host RPC for the parameter-server tier.

Reference parity: `paddle/fluid/operators/distributed/` gRPC/BRPC client+
server with `send_recv.proto.in` variable serialization (SURVEY.md §2.1
"Parameter-server RPC"). TPU-native scope: the PS tier is host-side CPU
machinery (the dense/sparse tables never touch the accelerator), so a
length-prefixed binary protocol over TCP sockets replaces the gRPC stack;
tensors travel as raw ndarray bytes with a tiny header — no pickle, no
third-party deps.

Wire format per message (little-endian):
  [u64 total_len][u8 n_fields] then per field:
  [u8 kind][u64 len][payload]  (u64 frames: multi-GB dataset blobs must
  not overflow the length prefix)
    kind 0: utf-8 string
    kind 1: ndarray — payload is [u8 dtype_len][dtype str][u8 ndim]
            [u64 x ndim shape][raw bytes]
    kind 2: int64
A request is (method:str, *fields); the response is a plain field list
(first field "ok" or "err:<msg>").
"""
from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import List, Tuple

import numpy as np

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def _enc_field(buf: bytearray, v):
    if isinstance(v, str):
        b = v.encode("utf-8")
        buf.append(0)
        buf += _U64.pack(len(b))
        buf += b
    elif isinstance(v, (int, np.integer)):
        buf.append(2)
        buf += _U64.pack(8)
        buf += struct.pack("<q", int(v))
    else:
        a = np.ascontiguousarray(v)
        dt = a.dtype.str.encode()
        payload = bytearray()
        payload.append(len(dt))
        payload += dt
        payload.append(a.ndim)
        for d in a.shape:
            payload += _U64.pack(d)
        payload += a.tobytes()
        buf.append(1)
        buf += _U64.pack(len(payload))
        buf += payload


def encode(fields) -> bytes:
    body = bytearray()
    body.append(len(fields))
    for f in fields:
        _enc_field(body, f)
    return _U64.pack(len(body)) + bytes(body)


def _dec_field(mv, off):
    kind = mv[off]
    off += 1
    (ln,) = _U64.unpack_from(mv, off)
    off += 8
    payload = mv[off:off + ln]
    off += ln
    if kind == 0:
        return bytes(payload).decode("utf-8"), off
    if kind == 2:
        return struct.unpack("<q", payload)[0], off
    p = 0
    dt_len = payload[p]
    p += 1
    dtype = np.dtype(bytes(payload[p:p + dt_len]).decode())
    p += dt_len
    ndim = payload[p]
    p += 1
    shape = []
    for _ in range(ndim):
        (d,) = _U64.unpack_from(payload, p)
        shape.append(d)
        p += 8
    arr = np.frombuffer(payload, dtype=dtype, offset=p,
                        count=int(np.prod(shape)) if shape else 1)
    if not shape:
        arr = arr.reshape(())
    else:
        arr = arr.reshape(shape)
    return arr.copy(), off


def decode(body: bytes) -> List:
    mv = memoryview(body)
    n = mv[0]
    off = 1
    out = []
    for _ in range(n):
        v, off = _dec_field(mv, off)
        out.append(v)
    return out


def _read_exact(sock, n):
    chunks = []
    while n:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("peer closed")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


def read_msg(sock) -> List:
    (ln,) = _U64.unpack(_read_exact(sock, 8))
    return decode(_read_exact(sock, ln))


def write_msg(sock, fields):
    sock.sendall(encode(fields))


class RpcServer:
    """Threaded TCP server dispatching (method, *args) -> fields."""

    def __init__(self, host, port, handler):
        outer = self

        class _H(socketserver.BaseRequestHandler):
            def handle(self):
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                try:
                    while True:
                        fields = read_msg(sock)
                        method = fields[0]
                        try:
                            resp = handler(method, fields[1:])
                            write_msg(sock, ["ok"] + list(resp or []))
                        except _Stop:
                            write_msg(sock, ["ok"])
                            outer._stop_evt.set()
                            return
                        except Exception as e:  # noqa: BLE001
                            write_msg(sock, ["err:%s" % e])
                except (ConnectionError, OSError):
                    return

        class _Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Srv((host, port), _H)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._stop_evt = threading.Event()

    def start(self):
        self._thread.start()

    def wait_stopped(self, timeout=None):
        self._stop_evt.wait(timeout)

    def shutdown(self):
        self._server.shutdown()
        self._server.server_close()


class _Stop(Exception):
    """Raised by a handler to acknowledge then stop the server."""


class RpcClient:
    def __init__(self, endpoint: str, timeout=60.0, retries=60):
        host, port = endpoint.rsplit(":", 1)
        last = None
        for _ in range(retries):
            try:
                self._sock = socket.create_connection(
                    (host, int(port)), timeout=timeout)
                break
            except OSError as e:
                last = e
                import time

                time.sleep(0.25)
        else:
            raise ConnectionError("cannot reach pserver %s: %s"
                                  % (endpoint, last))
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # blocking after connect: barrier/collective waits legitimately
        # exceed any fixed recv timeout (first-step compiles, slow ranks);
        # the SERVER side owns wait timeouts and always answers
        self._sock.settimeout(None)
        self._lock = threading.Lock()

    def call(self, method: str, *args) -> List:
        with self._lock:
            write_msg(self._sock, [method] + list(args))
            resp = read_msg(self._sock)
        if isinstance(resp[0], str) and resp[0].startswith("err:"):
            raise RuntimeError("rpc %s failed: %s" % (method, resp[0][4:]))
        return resp[1:]

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
