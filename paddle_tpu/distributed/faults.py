"""Deterministic fault injection for the distributed host tier.

At pod scale worker preemption and flaky host networking are the common
case, not the exception (PAPERS.md: "Exploring the limits of Concurrency
in ML Training on Google TPUs" treats restart/resume as table stakes) —
so the retry / liveness / supervised-restart paths in rpc.py,
host_collectives.py and launch.py must be testable on a CPU-only box.
This module injects faults at the RPC socket layer:

    drop   — close the socket and raise ConnectionError (a mid-stream
             TCP drop; the peer sees the close too)
    delay  — sleep `delay_ms` before the socket op (slow network)
    kill   — os._exit(exit_code): a preempted / OOM-killed worker
    stall  — hold the socket op FOREVER (unlike the bounded delay): an
             alive-but-wedged rank — the process keeps running (and
             heartbeating), the op never completes. The deterministic
             trigger for hang-watchdog / desync tests
             (observability/watchdog.py). The stalled thread parks on
             a module Event that `reset()` releases (raising
             FaultError into the op), so in-process tests can unstick
             it; a subprocess stays wedged until its supervisor kills
             it, exactly like the real failure.
    preempt — deliver a preemption NOTICE (distributed/preemption.py)
             with a `grace_s` window and let the op proceed untouched:
             the process keeps running toward the next step boundary,
             where ElasticWorld.sync() turns the notice into a
             group-agreed live resize. The deterministic trigger for
             the zero-downtime elasticity tests — unlike `kill`, the
             rank is warned, not lost.

Injection points (where rpc.py calls back into this module):

    side=client point=send   before the request bytes leave the client
    side=client point=recv   after send, before the response is read —
                             the request may already be APPLIED
                             server-side, so this is the point that
                             exercises idempotent retry/dedup
    side=server point=send   before the server writes a response
    side=server point=recv   before the server reads the next request
                             (the method is not parsed yet at this
                             point, so `method=` filters never match
                             server/recv — filter by side/point only)
    side=ckpt   point=write  inside a checkpoint save, after the
                             payload is written but BEFORE the step is
                             published (fluid publish_checkpoint_dir's
                             tmp-dir; ShardedCheckpointManager.save's
                             uncommitted orbax step) — a kill here is a
                             preemption mid-save, the newest-intact
                             restore fallback's worst case
                             (method= fluid_publish | sharded_save)

Faults fire deterministically on a per-injector event counter filtered
by side/point/method: `every=N` fires on every Nth matching event,
`at=N` fires exactly once on the Nth. Two ways to arm:

    # in-process (tests):
    with faults.inject("drop", side="client", point="recv", every=3):
        ...

    # across process boundaries (launch/subprocess tests):
    PADDLE_FAULTS="drop:side=client,point=recv,every=3;kill:at=40"

The env spec is parsed once, lazily, on the first RPC socket op of the
process. `faults.reset()` clears both injectors and counters.
"""
from __future__ import annotations

import contextlib
import os
import threading
from typing import List, Optional

__all__ = ["FaultInjector", "inject", "install", "reset", "on_message",
           "parse_spec", "FaultError"]


class FaultError(ConnectionError):
    """Injected connection drop — a subclass of ConnectionError so the
    client retry path treats it exactly like a real mid-stream drop."""


class FaultInjector:
    """One armed fault: fires on matching (side, point, method) events
    according to its deterministic counter."""

    KINDS = ("drop", "delay", "kill", "stall", "preempt")

    def __init__(self, kind, side=None, point=None, method=None,
                 every=None, at=None, delay_ms=50, exit_code=137,
                 grace_s=None):
        if kind not in self.KINDS:
            raise ValueError("unknown fault kind %r (want one of %s)"
                             % (kind, "/".join(self.KINDS)))
        if (every is None) == (at is None):
            raise ValueError("exactly one of every=/at= is required")
        self.kind = kind
        self.side = side          # "client" | "server" | None (both)
        self.point = point        # "send" | "recv" | None (both)
        self.method = method      # rpc method name | None (all)
        self.every = int(every) if every is not None else None
        self.at = int(at) if at is not None else None
        self.delay_ms = float(delay_ms)
        self.exit_code = int(exit_code)
        self.grace_s = float(grace_s) if grace_s is not None else None
        self._count = 0
        self._lock = threading.Lock()

    def _matches(self, side, point, method):
        return ((self.side is None or self.side == side)
                and (self.point is None or self.point == point)
                and (self.method is None or self.method == method))

    def fire(self, side, point, method, sock):
        if not self._matches(side, point, method):
            return
        with self._lock:
            self._count += 1
            n = self._count
        hit = (self.every is not None and n % self.every == 0) \
            or (self.at is not None and n == self.at)
        if not hit:
            return
        self._telemetry_event(side, point, method, n)
        if self.kind == "preempt":
            # a WARNED rank, not a lost one: record the pending notice
            # and let the socket op proceed — consumption happens at
            # the next step boundary (preemption.ElasticWorld.sync)
            from . import preemption

            preemption.deliver_notice(grace_s=self.grace_s,
                                      source="fault")
            return
        if self.kind == "delay":
            import time

            time.sleep(self.delay_ms / 1000.0)
            return
        if self.kind == "stall":
            # alive-but-wedged: park this thread on the release event
            # (set only by reset()) — the process lives on, heartbeats
            # keep flowing on their own sockets, but THIS op never
            # completes. That is the hang the watchdog exists to catch.
            _stall_release.wait()
            raise FaultError(
                "fault-injected stall released (%s/%s event #%d)"
                % (side, point, n))
        if self.kind == "kill":
            # a preempted worker leaves a postmortem: dump the flight
            # recorder (last N steps + events, the fatal event on top)
            # before the hard exit — same evidence a real OOM-kill's
            # SIGTERM grace window would leave
            try:
                from ..observability import flight

                # the fault event itself is already in the ring via
                # _telemetry_event above; dump names it fatal
                flight.dump("fault-kill", fatal_event={
                    "kind": "event", "event": "fault",
                    "fault": "kill", "side": side or "",
                    "point": point or "", "method": method or "",
                    "exit_code": self.exit_code, "n": n})
            except Exception:  # noqa: BLE001 - the kill must proceed
                pass
            os._exit(self.exit_code)
        # drop: close our end so the peer observes the drop too, then
        # raise into the caller's socket op
        if sock is not None:
            with contextlib.suppress(OSError):
                sock.close()
        raise FaultError(
            "fault-injected connection drop (%s/%s event #%d)"
            % (side, point, n))

    def _telemetry_event(self, side, point, method, n):
        """Every FIRED fault lands in the telemetry stream (drop/delay
        too — a run whose losses wobble under injected drops should
        show WHEN the drops fired)."""
        try:
            from ..observability.registry import registry

            registry().event("fault", fault=self.kind,
                             side=side or "", point=point or "",
                             method=method or "", n=n)
        except Exception:  # noqa: BLE001 - injection must still fire
            pass

    def __repr__(self):
        trig = ("every=%d" % self.every if self.every is not None
                else "at=%d" % self.at)
        return "FaultInjector(%s, side=%s, point=%s, method=%s, %s)" % (
            self.kind, self.side, self.point, self.method, trig)


_lock = threading.Lock()
_injectors: List[FaultInjector] = []
_env_loaded = False
#: stalled threads park here; reset() sets it (releasing them with a
#: FaultError) and re-arms a fresh event for the next test
_stall_release = threading.Event()


def parse_spec(spec: str) -> List[FaultInjector]:
    """Parse "kind:k=v,k=v;kind:k=v" into injectors.

    Example: "drop:side=client,point=recv,every=3;kill:at=40"
    """
    out = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, rest = part.partition(":")
        kw = {}
        for item in rest.split(","):
            item = item.strip()
            if not item:
                continue
            k, _, v = item.partition("=")
            kw[k.strip()] = v.strip()
        for intkey in ("every", "at", "exit_code"):
            if intkey in kw:
                kw[intkey] = int(kw[intkey])
        if "delay_ms" in kw:
            kw["delay_ms"] = float(kw["delay_ms"])
        if "grace_s" in kw:
            kw["grace_s"] = float(kw["grace_s"])
        out.append(FaultInjector(kind.strip(), **kw))
    return out


def install(injector: FaultInjector) -> FaultInjector:
    with _lock:
        _injectors.append(injector)
    return injector


def reset():
    """Clear every armed injector (incl. env-armed) and re-arm from the
    env on the next socket op only if PADDLE_FAULTS is still set. Also
    releases any thread parked in a `stall` fault (it raises FaultError
    into its socket op)."""
    global _env_loaded, _stall_release
    with _lock:
        _injectors.clear()
        _env_loaded = False
        old = _stall_release
        _stall_release = threading.Event()
    old.set()


def _load_env_once():
    global _env_loaded
    if _env_loaded:
        return
    with _lock:
        if _env_loaded:
            return
        spec = os.environ.get("PADDLE_FAULTS", "")
        if spec:
            _injectors.extend(parse_spec(spec))
        _env_loaded = True


def on_message(side, point, method=None, sock=None):
    """rpc.py hook: called before every socket send/recv. No-op unless
    injectors are armed (env or ctx manager)."""
    _load_env_once()
    if not _injectors:
        return
    for inj in list(_injectors):
        inj.fire(side, point, method, sock)


@contextlib.contextmanager
def inject(kind, **kw):
    """Arm one injector for the duration of a with-block (in-process
    tests; subprocesses use PADDLE_FAULTS)."""
    inj = install(FaultInjector(kind, **kw))
    try:
        yield inj
    finally:
        with _lock:
            if inj in _injectors:
                _injectors.remove(inj)
