"""Sharded (multi-host/multi-device) checkpointing over orbax —
SURVEY.md §5's prescribed TPU mapping for the reference's checkpoint
subsystem ("orbax-style sharded checkpoint"): each device writes its
own parameter shards, restore re-lays arrays out on the live mesh.
Complements fluid.io save/load_persistables (single-host, whole arrays,
reference io.py:598/902 semantics) for the SPMD trainer path
(parallel/transformer.py) where params are sharded over a Mesh and
gathering them to one host would not scale.

API mirrors the fleet checkpoint idiom (numbered steps + retention,
incubate/fleet/collective/__init__.py:155-341 in the reference):

    mgr = ShardedCheckpointManager(dir, max_to_keep=3)
    mgr.save(step, {"params": params, "opt": opt_state})
    tree = mgr.restore(template={"params": params, "opt": opt_state})
"""
from __future__ import annotations

import os
from typing import Any, Optional


class ShardedCheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))

    def save(self, step: int, pytree: Any, wait: bool = True) -> None:
        """Write `pytree` (arbitrarily nested dict/list of jax arrays,
        sharded or not) as checkpoint `step`; retention prunes old
        steps past max_to_keep."""
        import orbax.checkpoint as ocp

        self._mgr.save(int(step), args=ocp.args.StandardSave(pytree))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def restore(self, step: Optional[int] = None,
                template: Any = None) -> Any:
        """Read checkpoint `step` (default: latest). With `template`
        (a pytree of arrays or ShapeDtypeStructs carrying shardings),
        restored arrays land DIRECTLY in that layout on the live mesh —
        no host gather.

        Crash safety: a mid-save kill can leave a partial/truncated step
        dir that still LOOKS published. When `step` is not given, the
        newest step is validated by actually restoring it; on failure we
        warn and fall back to the next-newest INTACT step (a resumed run
        repeats a few steps instead of dying — or worse, training from
        scratch). An explicitly requested `step` never falls back.

        A template that mismatches the on-disk schema (resized layer,
        different mesh) fails EVERY step the same way; the final error
        chains the newest failure — read it before suspecting disk
        corruption.

        Multi-host caveat: validation is per-process. If only ONE
        host's shard of the newest step is corrupt, hosts could pick
        different steps (or stall inside the sharded restore); on
        multi-host topologies, agree on the step first (e.g. min over
        an allreduce of each host's newest-intact step) and pass it
        explicitly (ROADMAP "Open items")."""
        steps = sorted(self.all_steps(), reverse=True)
        if step is not None:
            return self._restore_step(int(step), template)
        if not steps:
            raise FileNotFoundError(
                "no checkpoints under %s" % self._dir)
        last_err: Optional[BaseException] = None
        for s in steps:
            try:
                return self._restore_step(int(s), template)
            except Exception as e:  # noqa: BLE001 - corrupt/partial step
                last_err = e
                import logging

                logging.getLogger("paddle_tpu.checkpoint").warning(
                    "checkpoint step %d under %s is corrupt or "
                    "incomplete (%s: %s); falling back to the previous "
                    "step", s, self._dir, type(e).__name__, e)
        raise RuntimeError(
            "no intact checkpoint under %s (tried steps %s); newest "
            "failure: %s" % (self._dir, steps, last_err)) from last_err

    def _restore_step(self, step: int, template: Any = None) -> Any:
        import jax
        import orbax.checkpoint as ocp

        if template is None:
            return self._mgr.restore(int(step))

        def absify(a):
            if isinstance(a, jax.ShapeDtypeStruct):
                return a
            if not hasattr(a, "shape"):
                return a  # plain python scalar leaf: restore as-is
            return jax.ShapeDtypeStruct(
                a.shape, a.dtype,
                sharding=getattr(a, "sharding", None))

        abstract = jax.tree_util.tree_map(absify, template)
        return self._mgr.restore(
            int(step), args=ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mgr.close()
