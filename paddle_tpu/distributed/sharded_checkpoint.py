"""Sharded (multi-host/multi-device) checkpointing over orbax —
SURVEY.md §5's prescribed TPU mapping for the reference's checkpoint
subsystem ("orbax-style sharded checkpoint"): each device writes its
own parameter shards, restore re-lays arrays out on the live mesh.
Complements fluid.io save/load_persistables (single-host, whole arrays,
reference io.py:598/902 semantics) for the SPMD trainer path
(parallel/transformer.py) where params are sharded over a Mesh and
gathering them to one host would not scale.

API mirrors the fleet checkpoint idiom (numbered steps + retention,
incubate/fleet/collective/__init__.py:155-341 in the reference):

    mgr = ShardedCheckpointManager(dir, max_to_keep=3)
    mgr.save(step, {"params": params, "opt": opt_state})
    tree = mgr.restore(template={"params": params, "opt": opt_state})
"""
from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

_AGREE_GROUP = None  # lazily built once per process (PADDLE_CKPT_AGREE)


def _env_agree_group():
    """The process-wide host-collective group used for checkpoint-step
    agreement, built once from the PADDLE_* launch env when
    PADDLE_CKPT_AGREE=1 (opt-in: creating a second store client inside
    an arbitrary single-purpose process must not be able to wedge it).
    None on single-host launches."""
    global _AGREE_GROUP
    if os.environ.get("PADDLE_CKPT_AGREE", "0") != "1":
        return None
    if _AGREE_GROUP is None:
        from .host_collectives import group_from_env

        _AGREE_GROUP = group_from_env()
    return _AGREE_GROUP


def agree_newest_intact(candidates, try_load, group, what="checkpoint",
                        fatal=()):
    """Cross-rank agreement on the newest checkpoint step EVERY rank can
    restore (ROADMAP open item: one corrupt shard must not silently
    diverge replicas). Protocol, per round:

      1. allreduce-MIN over each rank's newest remaining candidate —
         a rank that never saw step s cannot be out-voted into it;
      2. every rank that has the agreed step tries to load it;
      3. allreduce-MIN over the per-rank success bit — only a
         unanimously intact step wins; otherwise everyone discards
         candidates >= s and the next round starts.

    `candidates`: this rank's step numbers, NEWEST FIRST (empty is
    allowed: the rank contributes -1 and the whole group fails loudly
    and consistently instead of one rank silently training from
    scratch). `try_load`: callable(step) -> loaded result (raises on a
    corrupt/partial step). `fatal`: exception types that mean the
    PROGRAM disagrees with the on-disk schema — every older step is
    equally doomed, so after the lockstep ok-vote (which keeps the
    other ranks out of a blocked gather) the error re-raises instead
    of grinding through every fallback. Returns (step, result). Raises
    RuntimeError when no step is intact on every rank."""
    remaining = sorted(set(int(c) for c in candidates), reverse=True)
    fatal = tuple(fatal)
    last_err = None
    while True:
        my = remaining[0] if remaining else -1
        s = int(group.all_reduce(
            np.asarray([my], np.int64), op="min")[0])
        if s < 0:
            raise RuntimeError(
                "no %s step is intact on every rank (rank %d tried %s)"
                % (what, group.rank, sorted(set(candidates),
                                            reverse=True))) from last_err
        ok, result, fatal_err = 0, None, None
        if s in remaining:
            try:
                result = try_load(s)
                ok = 1
            except fatal as e:  # empty tuple catches nothing
                fatal_err = e
            except Exception as e:  # noqa: BLE001 - corrupt/partial step
                last_err = e
        ok_all = int(group.all_reduce(
            np.asarray([ok], np.int64), op="min")[0])
        if fatal_err is not None:
            raise fatal_err
        if ok_all:
            return s, result
        import logging

        logging.getLogger("paddle_tpu.checkpoint").warning(
            "%s step %d rejected by cross-rank agreement (intact here: "
            "%s); falling back past it", what, s, bool(ok))
        remaining = [c for c in remaining if c < s]


class ShardedCheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))

    def save(self, step: int, pytree: Any, wait: bool = True) -> None:
        """Write `pytree` (arbitrarily nested dict/list of jax arrays,
        sharded or not) as checkpoint `step`; retention prunes old
        steps past max_to_keep."""
        import orbax.checkpoint as ocp

        self._mgr.save(int(step), args=ocp.args.StandardSave(pytree))
        # injection point for the preemption-mid-save tests: orbax
        # commits asynchronously (save() returns with the step still an
        # uncommitted *.orbax-checkpoint-tmp-* dir), so a PADDLE_FAULTS
        # kill here deterministically leaves a half-written step that
        # all_steps()/restore() must never surface
        from . import faults

        faults.on_message("ckpt", "write", method="sharded_save")
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return list(self._mgr.all_steps())

    def restore(self, step: Optional[int] = None,
                template: Any = None, group: Any = None) -> Any:
        """Read checkpoint `step` (default: latest). With `template`
        (a pytree of arrays or ShapeDtypeStructs carrying shardings),
        restored arrays land DIRECTLY in that layout on the live mesh —
        no host gather.

        Crash safety: a mid-save kill can leave a partial/truncated step
        dir that still LOOKS published. When `step` is not given, the
        newest step is validated by actually restoring it; on failure we
        warn and fall back to the next-newest INTACT step (a resumed run
        repeats a few steps instead of dying — or worse, training from
        scratch). An explicitly requested `step` never falls back.

        A template that mismatches the on-disk schema (resized layer,
        different mesh) fails EVERY step the same way; the final error
        chains the newest failure — read it before suspecting disk
        corruption.

        Multi-host: per-process validation alone could pick DIFFERENT
        steps per host when only one host's shard of the newest step is
        corrupt. Pass a `group` (distributed.host_collectives
        HostCollectiveGroup) — or launch with PADDLE_CKPT_AGREE=1 to
        build one from the PADDLE_* env — and the ranks agree on the
        newest step EVERY rank can restore (allreduce-min protocol,
        `agree_newest_intact`) before any rank trains on."""
        steps = sorted(self.all_steps(), reverse=True)
        if step is not None:
            return self._restore_step(int(step), template)
        if group is None:
            group = _env_agree_group()
        if group is not None:
            # an empty-dir rank still joins the protocol (see
            # agree_newest_intact): all-empty raises consistently
            # everywhere; some-empty fails loudly on every rank rather
            # than deadlocking the others in the store gather
            newest = steps[0] if steps else -1
            global_newest = int(group.all_reduce(
                np.asarray([newest], np.int64), op="max")[0])
            if global_newest < 0:
                raise FileNotFoundError(
                    "no checkpoints under %s (on any rank)" % self._dir)
            _, result = agree_newest_intact(
                steps, lambda s: self._restore_step(int(s), template),
                group, what="sharded checkpoint")
            return result
        if not steps:
            raise FileNotFoundError(
                "no checkpoints under %s" % self._dir)
        last_err: Optional[BaseException] = None
        for s in steps:
            try:
                return self._restore_step(int(s), template)
            except Exception as e:  # noqa: BLE001 - corrupt/partial step
                last_err = e
                import logging

                logging.getLogger("paddle_tpu.checkpoint").warning(
                    "checkpoint step %d under %s is corrupt or "
                    "incomplete (%s: %s); falling back to the previous "
                    "step", s, self._dir, type(e).__name__, e)
        raise RuntimeError(
            "no intact checkpoint under %s (tried steps %s); newest "
            "failure: %s" % (self._dir, steps, last_err)) from last_err

    def _restore_step(self, step: int, template: Any = None) -> Any:
        import jax
        import orbax.checkpoint as ocp

        if template is None:
            return self._mgr.restore(int(step))

        def absify(a):
            if isinstance(a, jax.ShapeDtypeStruct):
                return a
            if not hasattr(a, "shape"):
                return a  # plain python scalar leaf: restore as-is
            return jax.ShapeDtypeStruct(
                a.shape, a.dtype,
                sharding=getattr(a, "sharding", None))

        abstract = jax.tree_util.tree_map(absify, template)
        return self._mgr.restore(
            int(step), args=ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mgr.close()
