"""Cluster launcher (reference: `python/paddle/distributed/launch.py:193`,
env contract set at `distributed/utils.py:356-360`).

On GPU the launcher spawns one process per device. On TPU one process
drives all local chips (SPMD over the mesh), so the launcher spawns one
process per HOST, keeping the same PADDLE_* env contract:
  PADDLE_TRAINER_ID, PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINERS_NUM,
  PADDLE_TRAINER_ENDPOINTS.

Supervision (pod-scale preemption is the common case, not the
exception):
  - FAIL FAST: the first worker that exits non-zero terminates the rest
    of the cohort — a half-dead cohort otherwise hangs in collectives
    until the full store timeout;
  - the launcher exits with the FIRST non-zero return code (lowest
    trainer id among the failures observed in a poll cycle),
    deterministically, not the last one seen;
  - `--max_restarts N` restarts the whole cohort up to N times after a
    failure; composed with the elastic checkpoint-resume path
    (fleet.DistributedStrategy.elastic), a preempted run resumes from
    the latest intact checkpoint. PADDLE_RESTART_NUM carries the attempt
    number into the workers. Log files reopen in append mode across
    restarts so no attempt's output is lost;
  - `--min_ranks M` makes those restarts ELASTIC: when a worker dies
    for good, the surviving cohort relaunches at the SMALLER world size
    N' (>= M) instead of requiring all N back — failed endpoints drop
    out, survivors get contiguous ranks 0..N'-1, and the rendezvous
    (host-collective store on endpoints[0] port+1, PS barriers, device
    mesh) rebuilds from the fresh PADDLE_* env. Restore then re-shards
    everything laid out P(dp) over N: checkpoints hold LOGICAL shapes
    (parallel/sharded_update.unshard_scope_value), so the resumed
    cohort's executor re-pads/re-shards ZeRO-1 moments, ZeRO-2 bucket
    plans and AMP fp32 masters for N' (bit-identical to a replicated
    update at any world size), and reader.resharding recomputes the
    per-rank sample assignment. Each transition lands an
    `elastic_transition` telemetry event (old/new world, reassignment
    map, recovery wall time) in <telemetry_dir>/telemetry.supervisor.jsonl.
    Elastic shrink needs the supervisor to own the whole cohort (the
    all-localhost multi-endpoint mode); per-host launchers fall back to
    fixed-world restarts. With `--num_pods K` (or PADDLE_NUM_PODS) the
    ranks partition into K contiguous pods (PADDLE_POD_ID exported;
    hybrid DCN+ICI meshes and the comm-lane telemetry read the
    topology) and the shrink is POD-AWARE: pods stay rectangular
    (every pod lost the same rank count) or the next cohort falls back
    to a flat single-pod world keeping every survivor — the
    elastic_transition event names which (`pod_topology`:
    "rectangular" | "flat_fallback") — never a lopsided topology that
    wedges the hybrid-mesh rendezvous;
  - SIGINT and SIGTERM both tear the cohort down (exit 128+signum);
  - supervised workers default PADDLE_CKPT_AGREE=1: multi-host
    checkpoint restore agrees cross-rank on the newest step EVERY rank
    can read (allreduce-min), so a restarted cohort never diverges on
    one rank's corrupt shard. Export PADDLE_CKPT_AGREE=0 to opt out.

Usage: python -m paddle_tpu.distributed.launch --hosts h1:port,h2:port
       [--max_restarts N] train.py [args...]
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


class ParallelEnvArgs:
    def __init__(self):
        self.cluster_node_ips = None
        self.node_ip = None
        self.use_paddlecloud = False
        self.started_port = None
        self.print_config = True
        self.selected_devices = None


def _parse_args(argv):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--hosts", type=str, default="127.0.0.1:6170",
                   help="comma-separated host:port endpoints (one per host)")
    p.add_argument("--host_id", type=int, default=None,
                   help="index of this host in --hosts (default: derive "
                        "from matching local address or 0)")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--max_restarts", type=int, default=0,
                   help="restart the whole cohort up to N times after a "
                        "worker failure (composes with elastic "
                        "checkpoint-resume)")
    p.add_argument("--min_ranks", type=int, default=0,
                   help="elastic world-size policy: a restart may drop "
                        "dead workers and relaunch the survivors at any "
                        "world size >= M (0 = fixed world: all N must "
                        "come back)")
    p.add_argument("--hang_timeout", type=float, default=None,
                   help="runtime hang escalation: export "
                        "FLAGS_tpu_hang_timeout_s=S to the workers "
                        "(arming their in-process watchdogs) and watch "
                        "their telemetry streams for `hang` events / "
                        "heartbeat silence; an alive-but-wedged cohort "
                        "is dumped, killed and routed through the "
                        "--min_ranks elastic restart with the desync "
                        "verdict attached. Default: the "
                        "PADDLE_HANG_TIMEOUT_S env, else 0 (off)")
    p.add_argument("--num_pods", type=int, default=0,
                   help="multi-pod topology: partition the ranks into K "
                        "contiguous pods (PADDLE_NUM_PODS/PADDLE_POD_ID "
                        "exported to workers; hybrid DCN+ICI meshes and "
                        "the comm-lane telemetry read them). 0 = the "
                        "PADDLE_NUM_PODS env, else flat. Elastic "
                        "shrink keeps pods RECTANGULAR (equal-size) or "
                        "falls back to a flat world — never a wedged "
                        "rendezvous")
    p.add_argument("--mp_degree", type=int, default=0,
                   help="tensor (model) parallel degree: factor each "
                        "worker's intra-pod device tier into (replica, "
                        "model) — PADDLE_MP_DEGREE exported to workers; "
                        "hybrid (dcn, replica, model) meshes "
                        "(parallel/env.create_hybrid_mesh) and the "
                        "comm-lane telemetry read it. 0 = the "
                        "PADDLE_MP_DEGREE env, else 1 (no model axis). "
                        "Must divide each worker's local device count "
                        "or the worker falls back to a flat mesh with "
                        "a warning")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _launch_num_pods(args, world):
    """The effective pod count for a cohort of `world` ranks:
    --num_pods, else PADDLE_NUM_PODS, else 1 (flat). A count that does
    not divide the world cannot form rectangular pods — warn and run
    flat rather than hand the workers a lopsided topology."""
    npods = args.num_pods
    if not npods:
        try:
            npods = int(os.environ.get("PADDLE_NUM_PODS", "1") or 1)
        except ValueError:
            npods = 1
    if npods <= 1:
        return 1
    if world % npods:
        sys.stderr.write(
            "paddle_tpu.launch: %d rank(s) not divisible into %d "
            "pods; running a flat (single-pod) world\n"
            % (world, npods))
        return 1
    return npods


def _launch_mp_degree(args):
    """The effective model-parallel degree: --mp_degree, else
    PADDLE_MP_DEGREE, else 1 (no model axis). Divisibility against each
    worker's LOCAL device count is the worker's own check
    (parallel/env.create_hybrid_mesh warns and runs flat) — the
    launcher only resolves and exports the knob."""
    mp = getattr(args, "mp_degree", 0)
    if not mp:
        try:
            mp = int(os.environ.get("PADDLE_MP_DEGREE", "1") or 1)
        except ValueError:
            mp = 1
    return mp if mp > 1 else 1


def _pod_shrink(endpoints, failed_tids, npods):
    """Pod-aware elastic shrink decision. Returns (survivor_endpoints,
    new_npods, pod_event_fields): the surviving endpoints in rank
    order, the pod count of the NEXT cohort, and the fields the
    elastic_transition event carries. Pods stay RECTANGULAR — every
    pod the same size, the invariant a hybrid (dcn, ici) mesh needs —
    when each pod lost the same number of ranks; otherwise the next
    cohort falls back to a flat (npods=1) world with every survivor,
    and the event names the fallback. Never returns a lopsided
    topology (the wedged-rendezvous failure mode)."""
    failed = set(failed_tids)
    survivors = [ep for tid, ep in enumerate(endpoints)
                 if tid not in failed]
    if npods <= 1:
        return survivors, 1, {}
    per_pod = len(endpoints) // npods
    counts = [0] * npods
    for tid in range(len(endpoints)):
        if tid not in failed:
            counts[tid // per_pod] += 1
    rectangular = len(set(counts)) == 1 and counts[0] > 0
    if rectangular:
        return survivors, npods, {
            "pods_old": npods, "pods_new": npods,
            "pod_topology": "rectangular",
            "ranks_per_pod": counts[0]}
    return survivors, 1, {
        "pods_old": npods, "pods_new": 1,
        "pod_topology": "flat_fallback",
        "pod_survivor_counts": counts}


def _worker_env(endpoints, tid, restart_no, base_env=None,
                telemetry_dir=None, npods=1, hang_timeout_s=0.0,
                compile_cache_dir=None, mp_degree=1):
    """The PADDLE_* contract for one supervised worker. Cross-rank
    checkpoint-step agreement (PADDLE_CKPT_AGREE, see
    distributed/sharded_checkpoint.agree_newest_intact) is ON by
    default for supervised cohorts — a restarted cohort must not let
    one rank's corrupt newest shard silently diverge the replicas; the
    protocol is fault-injection tested and a no-op for single-worker
    cohorts (group_from_env returns None at world size 1). An explicit
    PADDLE_CKPT_AGREE=0 in the launcher's environment is respected.

    `telemetry_dir` (derived from --log_dir unless the launcher's own
    env already sets FLAGS_tpu_telemetry_dir) turns on each worker's
    observability sink + flight recorder, so a failed cohort leaves
    per-rank postmortems the supervisor can collect."""
    env = dict(os.environ if base_env is None else base_env)
    env.setdefault("PADDLE_CKPT_AGREE", "1")
    if telemetry_dir:
        env.setdefault("FLAGS_tpu_telemetry_dir", telemetry_dir)
    if compile_cache_dir:
        # persistent compilation cache shared across the cohort AND
        # across restarts/elastic transitions: a relaunched worker
        # deserializes its XLA executables instead of recompiling, so
        # recovery is coordination-bound, not compile-bound
        env.setdefault("FLAGS_tpu_compile_cache_dir", compile_cache_dir)
    if hang_timeout_s and hang_timeout_s > 0:
        # one knob arms both tiers: the workers' in-process watchdogs
        # (stack + in-flight dumps, `hang`/`heartbeat` events) and the
        # supervisor's escalation watch. An explicit value in the
        # launcher's env wins.
        env.setdefault("FLAGS_tpu_hang_timeout_s",
                       repr(float(hang_timeout_s)))
    env.update({
        "PADDLE_TRAINER_ID": str(tid),
        "PADDLE_CURRENT_ENDPOINT": endpoints[tid],
        "PADDLE_TRAINERS_NUM": str(len(endpoints)),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_RESTART_NUM": str(restart_no),
    })
    if npods > 1:
        # multi-pod topology: contiguous rank blocks per pod. Workers
        # read these into hybrid (dcn, ici) meshes
        # (parallel/env.dcn_replicas) and the comm-lane telemetry
        env.update({
            "PADDLE_NUM_PODS": str(npods),
            "PADDLE_POD_ID": str(tid // (len(endpoints) // npods)),
        })
    else:
        # an elastic flat fallback must not leak the OLD topology into
        # the shrunk cohort through the inherited environment
        env.pop("PADDLE_NUM_PODS", None)
        env.pop("PADDLE_POD_ID", None)
    if mp_degree > 1:
        # model-parallel degree: each worker factors its intra-pod
        # device tier into (replica, model) —
        # parallel/env.create_hybrid_mesh and the comm-lane telemetry
        # read it (same contract as the pod vars above)
        env["PADDLE_MP_DEGREE"] = str(mp_degree)
    else:
        env.pop("PADDLE_MP_DEGREE", None)
    return env


def _telemetry_dir_for(args):
    """Where the workers' observability sink + flight dumps live: an
    explicit FLAGS_tpu_telemetry_dir in the launcher env wins;
    otherwise <log_dir>/telemetry; None without either (workers then
    run with telemetry off, dumps land in their CWD on a fault kill)."""
    explicit = os.environ.get("FLAGS_tpu_telemetry_dir")
    if explicit:
        return explicit
    if args.log_dir:
        return os.path.join(args.log_dir, "telemetry")
    return None


def _compile_cache_dir_for(args):
    """Where the workers' persistent compilation cache lives: an
    explicit FLAGS_tpu_compile_cache_dir in the launcher env wins;
    otherwise <log_dir>/compile_cache; None without either (workers
    then run with the persistent tier off). NOT collected into
    postmortem/ between attempts — surviving restarts is its entire
    point."""
    explicit = os.environ.get("FLAGS_tpu_compile_cache_dir")
    if explicit:
        return explicit
    if args.log_dir:
        return os.path.join(args.log_dir, "compile_cache")
    return None


def _collect_flight_dumps(args, attempt):
    """Before a cohort restart (and after a final failure), move every
    per-rank flight-recorder dump AND telemetry JSONL stream into
    <log_dir>/postmortem/attempt<K>/ — the restart's fresh workers
    overwrite flightrec.rank<R>.json and would otherwise APPEND
    attempt K+1's step records (with a reset step counter) into
    attempt K's telemetry.rank<R>.jsonl, silently mixing two training
    attempts in one stream. The next attempt starts with a clean dir;
    run tools/perf_analysis.py --stragglers against the postmortem
    subdir to analyze a failed attempt."""
    import shutil

    tdir = _telemetry_dir_for(args)
    if not tdir or not os.path.isdir(tdir):
        return []
    dest_root = args.log_dir or tdir
    dest = os.path.join(dest_root, "postmortem", "attempt%d" % attempt)
    collected = []
    for fname in sorted(os.listdir(tdir)):
        is_dump = fname.startswith("flightrec.rank") and \
            fname.endswith(".json")
        is_jsonl = fname.startswith("telemetry.rank") and \
            fname.endswith(".jsonl")
        # preempt markers ride along: launch() has already read them
        # by the time dumps are collected, and a restarted attempt
        # must start marker-clean
        is_marker = fname.startswith("preempted.rank") and \
            fname.endswith(".json")
        if not (is_dump or is_jsonl or is_marker):
            continue
        os.makedirs(dest, exist_ok=True)
        try:
            shutil.move(os.path.join(tdir, fname),
                        os.path.join(dest, fname))
            if is_dump:
                collected.append(os.path.join(dest, fname))
        except OSError:
            pass
    if collected:
        sys.stderr.write(
            "paddle_tpu.launch: collected %d flight-recorder dump(s) "
            "into %s\n" % (len(collected), dest))
    _write_postmortem_index(os.path.join(dest_root, "postmortem"))
    return collected


def _preempt_marker_ranks(tdir):
    """Ranks with a preempt marker (preempted.rank<R>.json) in the
    telemetry dir: they left on a preemption notice — possibly with
    exit 0 — and must be treated as lost by the restart shrink."""
    if not tdir:
        return []
    from .preemption import read_preempt_markers

    return sorted({int(m["rank"]) for m in read_preempt_markers(tdir)})


def _write_postmortem_index(pm_root):
    """Refresh <log_dir>/postmortem/index.json: one entry per per-rank
    flight dump across EVERY attempt (attempt, rank, reason, fatal
    event, last recorded step), newest attempt first — so a
    multi-restart failure is triaged from one file instead of N x K
    dumps (ROADMAP carried-over observability item). Written atomically;
    unreadable dumps get an "error" entry rather than poisoning the
    index."""
    import json
    import re

    if not os.path.isdir(pm_root):
        return None
    att_re = re.compile(r"^attempt(\d+)$")
    dump_re = re.compile(r"^flightrec\.rank(\d+)\.json$")
    dumps = []
    for aname in sorted(os.listdir(pm_root)):
        m = att_re.match(aname)
        if not m:
            continue
        attempt = int(m.group(1))
        adir = os.path.join(pm_root, aname)
        for fname in sorted(os.listdir(adir)):
            dm = dump_re.match(fname)
            if not dm:
                continue
            entry = {"attempt": attempt, "rank": int(dm.group(1)),
                     "path": os.path.join(aname, fname)}
            try:
                with open(os.path.join(adir, fname)) as f:
                    doc = json.load(f)
                entry["reason"] = doc.get("reason")
                entry["fatal_event"] = doc.get("fatal_event")
                entry["n_steps"] = doc.get("n_steps")
                steps = doc.get("steps") or []
                entry["last_step"] = steps[-1].get("step") if steps \
                    else None
            except (OSError, ValueError) as e:
                entry["error"] = "%s: %s" % (type(e).__name__, e)
            dumps.append(entry)
    dumps.sort(key=lambda d: (-d["attempt"], d["rank"]))
    index = {"attempts": 1 + max((d["attempt"] for d in dumps),
                                 default=-1),
             "dumps": dumps}
    path = os.path.join(pm_root, "index.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(index, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def _supervisor_event(args, etype, **fields):
    """Append one telemetry event record to the supervisor's OWN stream
    (<telemetry_dir>/telemetry.supervisor.jsonl, same "event" schema as
    the workers' registry sink — tools/telemetry_schema.json). Written
    directly rather than through observability.registry: the supervisor
    must stay a subprocess babysitter and not import the jax stack. The
    stream is NOT collected into postmortem/ between attempts — it is
    the one place the whole run's elastic seams live."""
    import json

    tdir = _telemetry_dir_for(args)
    if not tdir:
        return None
    rec = {"kind": "event", "event": str(etype), "rank": -1, "step": 0,
           "ts": time.time()}
    rec.update(fields)
    try:
        os.makedirs(tdir, exist_ok=True)
        with open(os.path.join(tdir, "telemetry.supervisor.jsonl"),
                  "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
    except OSError:
        return None
    return rec


class _TransitionWatch:
    """Defers one elastic_transition event until the respawned cohort's
    FIRST step records land in the workers' telemetry streams, so
    `recovery_s` splits into its two real components:

      coordination_s  failure detection -> shrunk cohort respawned
                      (the supervisor's own work: teardown, rank
                      reassignment, rendezvous env rebuild)
      compile_s       the new cohort's first-step compile (max over
                      ranks of the first step record's compile_ms) —
                      the part the persistent compilation cache
                      (FLAGS_tpu_compile_cache_dir) collapses from
                      minutes to ~0

    recovery_s = coordination_s + compile_s. Workers that emit no
    telemetry (plain scripts) leave compile_s absent and recovery_s =
    coordination_s — exactly the event shape shipped before the split.
    The event is emitted ONCE: when every rank's first step arrived,
    or at flush() (cohort exit / next failure / supervisor teardown),
    whichever comes first."""

    def __init__(self, telemetry_dir, fields, world, emit,
                 poll_every_s=0.25):
        self.dir = telemetry_dir
        self.fields = dict(fields)
        self.world = int(world)
        self._emit = emit
        self._poll_every = float(poll_every_s)
        self._last_poll = 0.0
        self._offsets = {}
        self._first_compile_ms = {}  # rank -> first step's compile_ms
        self.done = False
        if not telemetry_dir:
            self.flush()

    def poll(self):
        if self.done:
            return
        now = time.monotonic()
        if now - self._last_poll < self._poll_every:
            return
        self._last_poll = now
        import json

        try:
            fnames = [f for f in sorted(os.listdir(self.dir))
                      if f.startswith("telemetry.rank")
                      and f.endswith(".jsonl")]
        except OSError:
            return
        for fname in fnames:
            path = os.path.join(self.dir, fname)
            off = self._offsets.get(fname, 0)
            try:
                size = os.path.getsize(path)
                if size <= off:
                    continue
                with open(path) as f:
                    f.seek(off)
                    chunk = f.read(size - off)
            except OSError:
                continue
            consumed = chunk.rfind("\n") + 1
            self._offsets[fname] = off + consumed
            for line in chunk[:consumed].splitlines():
                if '"kind": "step"' not in line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                rank = int(rec.get("rank", -1))
                if rank in self._first_compile_ms:
                    continue
                self._first_compile_ms[rank] = float(
                    rec.get("compile_ms", 0.0))
        if len(self._first_compile_ms) >= self.world:
            self.flush()

    def flush(self):
        """Emit with whatever arrived (all exit paths call this —
        the seam event must never be lost to a fast-exiting or
        telemetry-less cohort)."""
        if self.done:
            return
        self.done = True
        fields = dict(self.fields)
        coord = float(fields.get("coordination_s", 0.0))
        if self._first_compile_ms:
            fields["compile_s"] = round(
                max(self._first_compile_ms.values()) / 1e3, 4)
            fields["recovery_s"] = round(coord + fields["compile_s"], 4)
        else:
            fields["recovery_s"] = round(coord, 4)
        self._emit(fields)


class _HangWatch:
    """Supervisor-side hang detection over the workers' telemetry
    streams — plain file tailing, no jax imports, no RPC to the wedged
    cohort.

    Primary signal: a worker watchdog (FLAGS_tpu_hang_timeout_s, armed
    by --hang_timeout) publishes a `hang` event into its JSONL sink
    the moment a collective is stuck past the timeout; this watch
    tails `telemetry.rank*.jsonl` incrementally and fires on the first
    one. Fallback: every stream silent (no bytes appended — armed
    watchdogs heartbeat, so silence means the PROCESS is wedged before
    its watchdog could arm, or telemetry died with it) for
    4x the timeout after at least one record was seen."""

    STALE_FACTOR = 4.0

    def __init__(self, telemetry_dir, timeout_s, poll_every_s=0.5):
        self.dir = telemetry_dir
        self.timeout_s = float(timeout_s)
        self._poll_every = float(poll_every_s)
        self._last_poll = 0.0
        self._offsets = {}        # fname -> bytes already scanned
        self._last_growth = None  # monotonic ts of last appended byte
        self._seen_any = False
        self._hang_events = []    # parsed worker hang event records

    def _rank_files(self):
        try:
            return [f for f in sorted(os.listdir(self.dir))
                    if f.startswith("telemetry.rank")
                    and f.endswith(".jsonl")]
        except OSError:
            return []

    def poll(self):
        """None, or a detection dict {"via": "hang-event"|"silence",
        "ranks": [ranks that reported], "events": [...]}."""
        now = time.monotonic()
        if now - self._last_poll < self._poll_every:
            return None
        self._last_poll = now
        if self._last_growth is None:
            self._last_growth = now
        import json

        grew = False
        for fname in self._rank_files():
            path = os.path.join(self.dir, fname)
            off = self._offsets.get(fname, 0)
            try:
                size = os.path.getsize(path)
                if size < off:
                    # rotation: the active file was os.replace'd to a
                    # .gN generation and restarted at 0 — a stale
                    # offset would both hide new hang events and let
                    # the silence fallback kill a healthy cohort
                    off = self._offsets[fname] = 0
                if size <= off:
                    continue
                with open(path) as f:
                    f.seek(off)
                    chunk = f.read(size - off)
            except OSError:
                continue
            # only complete lines; a torn tail re-reads next poll
            consumed = chunk.rfind("\n") + 1
            self._offsets[fname] = off + consumed
            grew = grew or consumed > 0
            self._seen_any = self._seen_any or consumed > 0
            for line in chunk[:consumed].splitlines():
                if '"event": "hang"' not in line:
                    continue
                try:
                    self._hang_events.append(json.loads(line))
                except ValueError:
                    continue
        if grew:
            self._last_growth = now
        if self._hang_events:
            return {"via": "hang-event",
                    "ranks": sorted({int(e.get("rank", -1))
                                     for e in self._hang_events}),
                    "events": list(self._hang_events)}
        if self._seen_any and \
                now - self._last_growth > self.STALE_FACTOR \
                * self.timeout_s:
            return {"via": "silence", "ranks": [], "events": []}
        return None


def _hang_verdict(telemetry_dir):
    """Cross-rank desync verdict over the worker watchdogs' flight
    dumps (observability/watchdog.py's pure-JSON analyzer — the same
    code `perf_analysis --hang-report` runs, so supervisor and offline
    tooling can never disagree). Returns the verdict dict, or None
    when the dumps are unreadable/absent."""
    try:
        from ..observability.watchdog import (analyze_hang,
                                              load_hang_bundle)

        docs = load_hang_bundle(telemetry_dir)
        if not docs:
            return None
        return analyze_hang(docs)
    except Exception as e:  # noqa: BLE001 - escalation must proceed
        sys.stderr.write("paddle_tpu.launch: hang verdict failed: "
                         "%s\n" % e)
        return None


def _wait_for_hang_dumps(telemetry_dir, world, grace_s):
    """Give every rank's watchdog a beat to land its flight dump
    before the cohort is killed (they all fire within ~a tick of each
    other; the kill itself would suppress nothing — the dump is
    written first — but collecting a complete bundle beats a partial
    one)."""
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        try:
            dumps = [f for f in os.listdir(telemetry_dir)
                     if f.startswith("flightrec.rank")
                     and f.endswith(".json")]
        except OSError:
            dumps = []
        if len(dumps) >= world:
            return True
        time.sleep(0.1)
    return False


def _hang_timeout_for(args):
    """--hang_timeout, else PADDLE_HANG_TIMEOUT_S, else 0 (off)."""
    if args.hang_timeout is not None:
        return max(0.0, float(args.hang_timeout))
    try:
        return max(0.0, float(
            os.environ.get("PADDLE_HANG_TIMEOUT_S", "0") or 0))
    except ValueError:
        return 0.0


def _spawn_cohort(args, endpoints, local_ids, restart_no, npods=1):
    procs, logs = [], []
    tdir = _telemetry_dir_for(args)
    if tdir:
        os.makedirs(tdir, exist_ok=True)
    ccdir = _compile_cache_dir_for(args)
    if ccdir:
        os.makedirs(ccdir, exist_ok=True)
    for tid in local_ids:
        env = _worker_env(endpoints, tid, restart_no,
                          telemetry_dir=tdir, npods=npods,
                          hang_timeout_s=_hang_timeout_for(args),
                          compile_cache_dir=ccdir,
                          mp_degree=_launch_mp_degree(args))
        cmd = [sys.executable, "-u", args.training_script] \
            + args.training_script_args
        out = None
        if args.log_dir:
            # append across restarts: attempt 0's tail is the evidence
            # for WHY the cohort restarted
            out = open(os.path.join(args.log_dir, "workerlog.%d" % tid),
                       "a" if restart_no else "w")
        logs.append(out)
        procs.append(subprocess.Popen(cmd, env=env, stdout=out,
                                      stderr=subprocess.STDOUT
                                      if out else None))
    return procs, logs


def _terminate_all(procs, grace_s=10.0):
    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + grace_s
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()


#: conventional exit code for a hang-escalated cohort kill (the shell
#: `timeout` convention; distinguishes "wedged, supervisor killed it"
#: from a worker's own failure in logs and restart accounting)
HANG_RC = 124


def _supervise(procs, local_ids, stop_sig, hang_watch=None,
               trans_watch=None):
    """Poll until all workers exit or one fails. Returns (rc,
    failed_tids, hang): rc is the first non-zero return code (lowest
    trainer id among the failures seen in the poll cycle that detected
    the fault), 0 on clean completion; failed_tids names the workers
    that died ON THEIR OWN in that cycle — the elastic policy treats
    them as lost machines (survivors are terminated by the fail-fast
    teardown and are NOT in the list). `hang` is None, or the
    _HangWatch detection dict for an alive-but-wedged cohort (rc is
    HANG_RC there; the guilty rank comes from the desync verdict over
    the collected dumps, not from this loop)."""
    while True:
        if trans_watch is not None and not trans_watch.done:
            # the pending elastic_transition is waiting for the new
            # cohort's first step records (its compile_s half)
            trans_watch.poll()
        if stop_sig["sig"] is not None:
            _terminate_all(procs)
            return 128 + stop_sig["sig"], [], None
        failed = [(tid, p.returncode) for tid, p in zip(local_ids, procs)
                  if p.poll() is not None and p.returncode != 0]
        if failed:
            # fail fast: a half-dead cohort hangs in collectives.
            # Popen reports a signal death as -N; exit statuses are
            # 0..255, so surface it as the conventional 128+N
            bad_tid, bad_rc = failed[0]
            if bad_rc < 0:
                bad_rc = 128 - bad_rc
            # DEGRADE_RC = a SURVIVOR whose live-resize seam failed,
            # loudly requesting the cohort-restart fallback — its
            # machine is healthy, so it must NOT be dropped by the
            # shrink (the preempt markers name who actually left)
            from .preemption import DEGRADE_RC

            lost = [tid for tid, rc_ in failed if rc_ != DEGRADE_RC]
            degraded = [tid for tid, rc_ in failed if rc_ == DEGRADE_RC]
            sys.stderr.write(
                "paddle_tpu.launch: worker %d exited with %d%s; "
                "terminating cohort\n"
                % (bad_tid, bad_rc,
                   " (live-resize degrade from worker(s) %s)"
                   % degraded if degraded else ""))
            _terminate_all(procs)
            return bad_rc, lost, None
        if all(p.poll() is not None for p in procs):
            return 0, [], None
        if hang_watch is not None:
            hang = hang_watch.poll()
            if hang is not None:
                sys.stderr.write(
                    "paddle_tpu.launch: cohort alive but wedged "
                    "(detected via %s%s); collecting dumps and "
                    "terminating\n"
                    % (hang["via"],
                       ", hang reported by rank(s) %s" % hang["ranks"]
                       if hang["ranks"] else ""))
                # let every rank's watchdog land its stack + in-flight
                # dump before the kill (they fire within ~a tick of
                # each other); SIGTERM dumps are once-suppressed after
                # a watchdog dump, so what's on disk IS the evidence
                _wait_for_hang_dumps(
                    hang_watch.dir, len(procs),
                    grace_s=min(10.0, max(
                        1.0, hang_watch.timeout_s)))
                # re-poll after the grace: the first detection froze
                # `ranks` at whichever rank's event landed first, and
                # the fallback blame must not punish ranks for losing
                # a reporting-order race
                hang_watch._last_poll = 0.0
                hang = hang_watch.poll() or hang
                _terminate_all(procs)
                return HANG_RC, [], hang
        time.sleep(0.1)


def _owns_whole_cohort(args, endpoints):
    """True when THIS launcher supervises every worker (the
    all-localhost multi-endpoint test/dev mode) — the precondition for
    elastic world-size shrink: a per-host launcher only sees its own
    workers and cannot reassign the global rank set."""
    return args.host_id is None and len(endpoints) > 1 and all(
        e.split(":")[0] in ("127.0.0.1", "localhost") for e in endpoints)


def launch(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    endpoints = args.hosts.split(",")
    host_id = args.host_id if args.host_id is not None else 0

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    stop_sig = {"sig": None}
    live_procs = []

    def _sig(signum, frame):
        stop_sig["sig"] = signum
        for p in live_procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)

    if args.min_ranks > 0 and not _owns_whole_cohort(args, endpoints):
        sys.stderr.write(
            "paddle_tpu.launch: --min_ranks needs the supervisor to own "
            "the whole cohort (all-localhost endpoints, no --host_id); "
            "falling back to fixed-world restarts\n")
    if _hang_timeout_for(args) > 0 and not _telemetry_dir_for(args):
        sys.stderr.write(
            "paddle_tpu.launch: --hang_timeout needs a telemetry dir "
            "(--log_dir or FLAGS_tpu_telemetry_dir) for supervisor-"
            "side detection; workers still arm their in-process "
            "watchdogs (dumps land in their CWD) but hang ESCALATION "
            "is off\n")

    max_r = max(args.max_restarts, 0)
    rc = 0
    pending_evt, t_fail = None, None
    npods = _launch_num_pods(args, len(endpoints))
    for attempt in range(max_r + 1):
        # On a single-host invocation with multiple endpoints we spawn
        # them all locally (test/dev mode, mirrors
        # multi-process-on-localhost testing — SURVEY.md §4.5). On real
        # clusters each host runs launch with its --host_id.
        # Recomputed per attempt: an elastic shrink changes the world.
        local_ids = list(range(len(endpoints))) \
            if _owns_whole_cohort(args, endpoints) else [host_id]
        procs, logs = _spawn_cohort(args, endpoints, local_ids, attempt,
                                    npods=npods)
        tdir = _telemetry_dir_for(args)
        trans_watch = None
        if pending_evt is not None:
            # coordination wall time = failure detection -> shrunk
            # cohort respawned. The event itself is DEFERRED until the
            # new cohort's first step records land, so it can report
            # compile_s (the recompile the persistent compilation
            # cache is supposed to collapse) separately — see
            # _TransitionWatch; a telemetry-less cohort emits
            # immediately with coordination time only
            pending_evt["coordination_s"] = round(
                time.monotonic() - t_fail, 4)
            trans_watch = _TransitionWatch(
                tdir, pending_evt, len(endpoints),
                emit=lambda fields: _supervisor_event(
                    args, "elastic_transition", **fields))
            pending_evt = None
        live_procs[:] = procs
        hang_timeout = _hang_timeout_for(args)
        hang_watch = (_HangWatch(tdir, hang_timeout)
                      if hang_timeout > 0 and tdir else None)
        try:
            rc, failed_tids, hang = _supervise(procs, local_ids,
                                               stop_sig, hang_watch,
                                               trans_watch)
        finally:
            if trans_watch is not None and not trans_watch.done:
                # cohort ended (clean exit, failure, or signal) before
                # every rank's first step arrived: tail once more, then
                # emit with what there is — the seam event must land
                # before the telemetry files move to postmortem/
                trans_watch._last_poll = 0.0
                trans_watch.poll()
                trans_watch.flush()
            for f in logs:
                if f:
                    f.close()
        if rc == 0 or stop_sig["sig"] is not None:
            break
        t_fail = time.monotonic()
        hang_fields = {}
        if hang is not None:
            # name the guilty rank BEFORE the dumps move: the desync
            # verdict over the per-rank in-flight tables (the same
            # analyzer perf_analysis --hang-report runs offline)
            verdict = _hang_verdict(tdir)
            guilty = list((verdict or {}).get("guilty_ranks") or [])
            if verdict is None and hang["ranks"]:
                # NO verdict at all (dumps missing/torn): fall back to
                # blaming the ranks that never published a hang event
                # — a fully wedged process (stuck before its watchdog
                # armed) can't report. A verdict that EXISTS but names
                # nobody ("indeterminate": every rank arrived, the
                # store/wire itself wedged) is respected: no machine
                # is dropped on a guess.
                reporters = set(hang["ranks"])
                guilty = [tid for tid in local_ids
                          if tid not in reporters]
            failed_tids = guilty
            hang_fields = {
                "hang": True,
                "hang_via": hang["via"],
                "hang_collective": (verdict or {}).get("collective"),
                "hang_op": (verdict or {}).get("op"),
                "hang_verdict": (verdict or {}).get("verdict"),
                "hang_guilty_ranks": guilty,
            }
            _supervisor_event(
                args, "hang",
                stalled_s=max([float(e.get("stalled_s", 0.0))
                               for e in hang["events"]] or [0.0]),
                inflight_n=max([int(e.get("inflight_n", 0))
                                for e in hang["events"]] or [0]),
                via=hang["via"], attempt=attempt,
                collective=hang_fields["hang_collective"] or "",
                verdict=hang_fields["hang_verdict"] or "",
                guilty_ranks=guilty)
            sys.stderr.write(
                "paddle_tpu.launch: hang verdict: %s (collective %s, "
                "guilty rank(s) %s)\n"
                % (hang_fields["hang_verdict"],
                   hang_fields["hang_collective"], guilty or "none"))
        # preempt markers: ranks that left via a preemption notice
        # (live seam, or the doomed half of a degraded one) exited 0 —
        # the restart shrink must drop them exactly like crashed ranks
        # (distributed/preemption.py writes the marker FIRST in the
        # seam, so it survives any later seam failure)
        preempt_ranks = _preempt_marker_ranks(tdir)
        if preempt_ranks:
            failed_tids = sorted(set(failed_tids) | set(preempt_ranks))
            sys.stderr.write(
                "paddle_tpu.launch: preempt marker(s) for rank(s) %s — "
                "included in the shrink\n" % preempt_ranks)
        # secure this attempt's per-rank flight-recorder dumps before
        # the restarted cohort overwrites them (and keep the final
        # failed attempt's evidence too when restarts are exhausted)
        _collect_flight_dumps(args, attempt)
        if attempt >= max_r:
            break
        if args.min_ranks > 0 and failed_tids \
                and _owns_whole_cohort(args, endpoints):
            survivors, new_npods, pod_fields = _pod_shrink(
                endpoints, failed_tids, npods)
            if len(survivors) < args.min_ranks:
                sys.stderr.write(
                    "paddle_tpu.launch: only %d endpoint(s) left after "
                    "dropping ranks %s — below --min_ranks %d; giving "
                    "up\n" % (len(survivors), sorted(failed_tids),
                              args.min_ranks))
                break
            if len(survivors) < len(endpoints):
                reassignment = {
                    old: new for new, old in enumerate(
                        tid for tid in range(len(endpoints))
                        if tid not in set(failed_tids))}
                from .preemption import DEGRADE_RC as _DEGRADE_RC

                degrade_fields = {}
                if preempt_ranks:
                    degrade_fields["preempted_ranks"] = preempt_ranks
                if rc == _DEGRADE_RC:
                    # the live seam failed mid-recovery and a survivor
                    # demanded this restart — record the degradation so
                    # perf_analysis --elastic shows live-vs-restart
                    # honestly
                    degrade_fields["degraded_from_live"] = True
                pending_evt = dict(
                    old_world=len(endpoints),
                    new_world=len(survivors),
                    mode="restart",
                    failed_ranks=sorted(failed_tids),
                    reassignment={str(o): n
                                  for o, n in reassignment.items()},
                    attempt=attempt + 1, **pod_fields,
                    **degrade_fields,
                    # a hang-escalated shrink carries its desync
                    # verdict: WHY this rank was dropped, stitched to
                    # the postmortem bundle the dumps moved into
                    **hang_fields)
                sys.stderr.write(
                    "paddle_tpu.launch: elastic shrink %d -> %d ranks "
                    "(dropped %s; reassignment %s%s)\n"
                    % (len(endpoints), len(survivors),
                       sorted(failed_tids),
                       {o: n for o, n in sorted(reassignment.items())},
                       ("; pods %d -> %d (%s)" % (
                           npods, new_npods,
                           pod_fields.get("pod_topology"))
                        if npods > 1 else "")))
                endpoints = survivors
                npods = new_npods
        sys.stderr.write(
            "paddle_tpu.launch: cohort failed (rc=%d); restart "
            "%d/%d\n" % (rc, attempt + 1, args.max_restarts))
    sys.exit(rc)


if __name__ == "__main__":
    launch()
