"""Cluster launcher (reference: `python/paddle/distributed/launch.py:193`,
env contract set at `distributed/utils.py:356-360`).

On GPU the launcher spawns one process per device. On TPU one process
drives all local chips (SPMD over the mesh), so the launcher spawns one
process per HOST, keeping the same PADDLE_* env contract:
  PADDLE_TRAINER_ID, PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINERS_NUM,
  PADDLE_TRAINER_ENDPOINTS.

Usage: python -m paddle_tpu.distributed.launch --hosts h1:port,h2:port
       train.py [args...]
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys


class ParallelEnvArgs:
    def __init__(self):
        self.cluster_node_ips = None
        self.node_ip = None
        self.use_paddlecloud = False
        self.started_port = None
        self.print_config = True
        self.selected_devices = None


def _parse_args(argv):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--hosts", type=str, default="127.0.0.1:6170",
                   help="comma-separated host:port endpoints (one per host)")
    p.add_argument("--host_id", type=int, default=None,
                   help="index of this host in --hosts (default: derive "
                        "from matching local address or 0)")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    endpoints = args.hosts.split(",")
    nhosts = len(endpoints)
    host_id = args.host_id if args.host_id is not None else 0

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    procs = []
    # On a single-host invocation with multiple endpoints we spawn them all
    # locally (test/dev mode, mirrors multi-process-on-localhost testing —
    # SURVEY.md §4.5). On real clusters each host runs launch with its
    # --host_id.
    local_ids = range(nhosts) if args.host_id is None and nhosts > 1 and \
        all(e.split(":")[0] in ("127.0.0.1", "localhost")
            for e in endpoints) else [host_id]

    for tid in local_ids:
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(tid),
            "PADDLE_CURRENT_ENDPOINT": endpoints[tid],
            "PADDLE_TRAINERS_NUM": str(nhosts),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        })
        cmd = [sys.executable, "-u", args.training_script] \
            + args.training_script_args
        if args.log_dir:
            out = open(os.path.join(args.log_dir,
                                    "workerlog.%d" % tid), "w")
        else:
            out = None
        procs.append(subprocess.Popen(cmd, env=env, stdout=out,
                                      stderr=subprocess.STDOUT
                                      if out else None))

    def _term(signum, frame):
        for p in procs:
            p.terminate()

    signal.signal(signal.SIGTERM, _term)
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    sys.exit(rc)


if __name__ == "__main__":
    launch()
