"""Cluster launcher (reference: `python/paddle/distributed/launch.py:193`,
env contract set at `distributed/utils.py:356-360`).

On GPU the launcher spawns one process per device. On TPU one process
drives all local chips (SPMD over the mesh), so the launcher spawns one
process per HOST, keeping the same PADDLE_* env contract:
  PADDLE_TRAINER_ID, PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINERS_NUM,
  PADDLE_TRAINER_ENDPOINTS.

Supervision (pod-scale preemption is the common case, not the
exception):
  - FAIL FAST: the first worker that exits non-zero terminates the rest
    of the cohort — a half-dead cohort otherwise hangs in collectives
    until the full store timeout;
  - the launcher exits with the FIRST non-zero return code (lowest
    trainer id among the failures observed in a poll cycle),
    deterministically, not the last one seen;
  - `--max_restarts N` restarts the whole cohort up to N times after a
    failure; composed with the elastic checkpoint-resume path
    (fleet.DistributedStrategy.elastic), a preempted run resumes from
    the latest intact checkpoint. PADDLE_RESTART_NUM carries the attempt
    number into the workers. Log files reopen in append mode across
    restarts so no attempt's output is lost;
  - SIGINT and SIGTERM both tear the cohort down (exit 128+signum);
  - supervised workers default PADDLE_CKPT_AGREE=1: multi-host
    checkpoint restore agrees cross-rank on the newest step EVERY rank
    can read (allreduce-min), so a restarted cohort never diverges on
    one rank's corrupt shard. Export PADDLE_CKPT_AGREE=0 to opt out.

Usage: python -m paddle_tpu.distributed.launch --hosts h1:port,h2:port
       [--max_restarts N] train.py [args...]
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


class ParallelEnvArgs:
    def __init__(self):
        self.cluster_node_ips = None
        self.node_ip = None
        self.use_paddlecloud = False
        self.started_port = None
        self.print_config = True
        self.selected_devices = None


def _parse_args(argv):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--hosts", type=str, default="127.0.0.1:6170",
                   help="comma-separated host:port endpoints (one per host)")
    p.add_argument("--host_id", type=int, default=None,
                   help="index of this host in --hosts (default: derive "
                        "from matching local address or 0)")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--max_restarts", type=int, default=0,
                   help="restart the whole cohort up to N times after a "
                        "worker failure (composes with elastic "
                        "checkpoint-resume)")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(endpoints, tid, restart_no, base_env=None,
                telemetry_dir=None):
    """The PADDLE_* contract for one supervised worker. Cross-rank
    checkpoint-step agreement (PADDLE_CKPT_AGREE, see
    distributed/sharded_checkpoint.agree_newest_intact) is ON by
    default for supervised cohorts — a restarted cohort must not let
    one rank's corrupt newest shard silently diverge the replicas; the
    protocol is fault-injection tested and a no-op for single-worker
    cohorts (group_from_env returns None at world size 1). An explicit
    PADDLE_CKPT_AGREE=0 in the launcher's environment is respected.

    `telemetry_dir` (derived from --log_dir unless the launcher's own
    env already sets FLAGS_tpu_telemetry_dir) turns on each worker's
    observability sink + flight recorder, so a failed cohort leaves
    per-rank postmortems the supervisor can collect."""
    env = dict(os.environ if base_env is None else base_env)
    env.setdefault("PADDLE_CKPT_AGREE", "1")
    if telemetry_dir:
        env.setdefault("FLAGS_tpu_telemetry_dir", telemetry_dir)
    env.update({
        "PADDLE_TRAINER_ID": str(tid),
        "PADDLE_CURRENT_ENDPOINT": endpoints[tid],
        "PADDLE_TRAINERS_NUM": str(len(endpoints)),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        "PADDLE_RESTART_NUM": str(restart_no),
    })
    return env


def _telemetry_dir_for(args):
    """Where the workers' observability sink + flight dumps live: an
    explicit FLAGS_tpu_telemetry_dir in the launcher env wins;
    otherwise <log_dir>/telemetry; None without either (workers then
    run with telemetry off, dumps land in their CWD on a fault kill)."""
    explicit = os.environ.get("FLAGS_tpu_telemetry_dir")
    if explicit:
        return explicit
    if args.log_dir:
        return os.path.join(args.log_dir, "telemetry")
    return None


def _collect_flight_dumps(args, attempt):
    """Before a cohort restart (and after a final failure), move every
    per-rank flight-recorder dump AND telemetry JSONL stream into
    <log_dir>/postmortem/attempt<K>/ — the restart's fresh workers
    overwrite flightrec.rank<R>.json and would otherwise APPEND
    attempt K+1's step records (with a reset step counter) into
    attempt K's telemetry.rank<R>.jsonl, silently mixing two training
    attempts in one stream. The next attempt starts with a clean dir;
    run tools/perf_analysis.py --stragglers against the postmortem
    subdir to analyze a failed attempt."""
    import shutil

    tdir = _telemetry_dir_for(args)
    if not tdir or not os.path.isdir(tdir):
        return []
    dest_root = args.log_dir or tdir
    dest = os.path.join(dest_root, "postmortem", "attempt%d" % attempt)
    collected = []
    for fname in sorted(os.listdir(tdir)):
        is_dump = fname.startswith("flightrec.rank") and \
            fname.endswith(".json")
        is_jsonl = fname.startswith("telemetry.rank") and \
            fname.endswith(".jsonl")
        if not (is_dump or is_jsonl):
            continue
        os.makedirs(dest, exist_ok=True)
        try:
            shutil.move(os.path.join(tdir, fname),
                        os.path.join(dest, fname))
            if is_dump:
                collected.append(os.path.join(dest, fname))
        except OSError:
            pass
    if collected:
        sys.stderr.write(
            "paddle_tpu.launch: collected %d flight-recorder dump(s) "
            "into %s\n" % (len(collected), dest))
    return collected


def _spawn_cohort(args, endpoints, local_ids, restart_no):
    procs, logs = [], []
    tdir = _telemetry_dir_for(args)
    if tdir:
        os.makedirs(tdir, exist_ok=True)
    for tid in local_ids:
        env = _worker_env(endpoints, tid, restart_no,
                          telemetry_dir=tdir)
        cmd = [sys.executable, "-u", args.training_script] \
            + args.training_script_args
        out = None
        if args.log_dir:
            # append across restarts: attempt 0's tail is the evidence
            # for WHY the cohort restarted
            out = open(os.path.join(args.log_dir, "workerlog.%d" % tid),
                       "a" if restart_no else "w")
        logs.append(out)
        procs.append(subprocess.Popen(cmd, env=env, stdout=out,
                                      stderr=subprocess.STDOUT
                                      if out else None))
    return procs, logs


def _terminate_all(procs, grace_s=10.0):
    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + grace_s
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait()


def _supervise(procs, local_ids, stop_sig):
    """Poll until all workers exit or one fails. Returns the first
    non-zero return code (lowest trainer id among the failures seen in
    the poll cycle that detected the fault), or 0."""
    while True:
        if stop_sig["sig"] is not None:
            _terminate_all(procs)
            return 128 + stop_sig["sig"]
        failed = [(tid, p.returncode) for tid, p in zip(local_ids, procs)
                  if p.poll() is not None and p.returncode != 0]
        if failed:
            # fail fast: a half-dead cohort hangs in collectives.
            # Popen reports a signal death as -N; exit statuses are
            # 0..255, so surface it as the conventional 128+N
            bad_tid, bad_rc = failed[0]
            if bad_rc < 0:
                bad_rc = 128 - bad_rc
            sys.stderr.write(
                "paddle_tpu.launch: worker %d exited with %d; "
                "terminating cohort\n" % (bad_tid, bad_rc))
            _terminate_all(procs)
            return bad_rc
        if all(p.poll() is not None for p in procs):
            return 0
        time.sleep(0.1)


def launch(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    endpoints = args.hosts.split(",")
    nhosts = len(endpoints)
    host_id = args.host_id if args.host_id is not None else 0

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    # On a single-host invocation with multiple endpoints we spawn them all
    # locally (test/dev mode, mirrors multi-process-on-localhost testing —
    # SURVEY.md §4.5). On real clusters each host runs launch with its
    # --host_id.
    local_ids = list(range(nhosts)) if args.host_id is None and \
        nhosts > 1 and all(e.split(":")[0] in ("127.0.0.1", "localhost")
                           for e in endpoints) else [host_id]

    stop_sig = {"sig": None}
    live_procs = []

    def _sig(signum, frame):
        stop_sig["sig"] = signum
        for p in live_procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass

    signal.signal(signal.SIGTERM, _sig)
    signal.signal(signal.SIGINT, _sig)

    rc = 0
    for attempt in range(max(args.max_restarts, 0) + 1):
        procs, logs = _spawn_cohort(args, endpoints, local_ids, attempt)
        live_procs[:] = procs
        try:
            rc = _supervise(procs, local_ids, stop_sig)
        finally:
            for f in logs:
                if f:
                    f.close()
        if rc == 0 or stop_sig["sig"] is not None:
            break
        # secure this attempt's per-rank flight-recorder dumps before
        # the restarted cohort overwrites them (and keep the final
        # failed attempt's evidence too when restarts are exhausted)
        _collect_flight_dumps(args, attempt)
        if attempt < max(args.max_restarts, 0):
            sys.stderr.write(
                "paddle_tpu.launch: cohort failed (rc=%d); restart "
                "%d/%d\n" % (rc, attempt + 1, args.max_restarts))
    sys.exit(rc)


if __name__ == "__main__":
    launch()
