"""paddle_tpu.distributed — launch + eager collective API (reference:
`python/paddle/distributed/launch.py` and env contract
`distributed/utils.py:356-360`).

Multi-host bootstrap: `init_parallel_env` calls `jax.distributed.initialize`
over DCN (replacing the rank-0 TCP exchange of ncclUniqueId,
`imperative/nccl_context.cc:21-63`); within a host, all local TPU chips form
the default mesh.
"""
from __future__ import annotations

import os

import numpy as np

from ..parallel import env as penv


def get_rank():
    return penv.trainer_id()


def get_world_size():
    n = penv.trainer_num()
    return n


def init_parallel_env(backend="xla"):
    """Build the global 1-D data-parallel mesh over all visible devices.
    For multi-host (PADDLE_TRAINERS_NUM>1) also brings up jax.distributed
    over the endpoint list."""
    import jax

    nhosts = penv.trainer_num()
    if nhosts > 1 and penv.trainer_endpoints():
        coord = penv.trainer_endpoints()[0]
        try:
            # CPU backend: cross-process collectives (multihost
            # device_put, psum over DCN) need the gloo transport; the
            # default CPU backend refuses multiprocess computations.
            # Read the platform from config/env only — probing the
            # backend here would initialize it BEFORE distributed init.
            platforms = (getattr(jax.config, "jax_platforms", None)
                         or os.environ.get("JAX_PLATFORMS", ""))
            if platforms and "cpu" in str(platforms):
                try:
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo")
                except Exception:  # noqa: BLE001 - knob absent: ignore
                    pass
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=nhosts,
                process_id=penv.trainer_id())
        except Exception:
            pass  # already initialized or single-host fallback
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("dp",))
    penv.set_global_mesh(mesh)
    penv.register_ring(0, "dp", devs.size)
    from ..fluid.dygraph.parallel import ParallelEnv

    return ParallelEnv()


def _mesh_or_none():
    return penv.global_mesh()


def _eager_collective(x, fn_name, **kw):
    """Apply a collective to a global array sharded over the dp mesh."""
    import jax

    mesh = _mesh_or_none()
    val = x._value() if hasattr(x, "_value") else x
    if mesh is None:
        return x
    from jax.sharding import PartitionSpec as P

    axes = {a: mesh.shape[a] for a in mesh.axis_names}

    def inner(v):
        with penv.collective_scope(axes):
            from .. import ops as ops_lib

            out = ops_lib.run_op(fn_name, {"X": [v]}, kw)
            return out["Out"][0]

    from ..parallel.env import shard_map_compat

    smapped = shard_map_compat(inner, mesh=mesh, in_specs=P("dp"),
                               out_specs=P("dp"), check_vma=False)
    out = jax.jit(smapped)(val)
    if hasattr(x, "_assign_raw"):
        x._assign_raw(out)
        return x
    return out


def all_reduce(tensor, op="sum", group=0):
    return _eager_collective(tensor, "c_allreduce_" + op, ring_id=group)


def broadcast(tensor, src=0, group=0):
    return _eager_collective(tensor, "c_broadcast", ring_id=group, root=src)


def all_gather(tensor_list, tensor, group=0):
    out = _eager_collective(tensor, "c_allgather", ring_id=group)
    tensor_list.append(out)
    return tensor_list


def reduce_scatter(tensor, group=0):
    return _eager_collective(tensor, "c_reducescatter", ring_id=group)


def barrier(group=0):
    pass


from . import faults  # noqa: F401,E402
from . import launch  # noqa: F401,E402
from .launch import ParallelEnvArgs  # noqa: F401,E402
from .sharded_checkpoint import ShardedCheckpointManager  # noqa: F401,E402


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Single-host multi-chip needs no process spawn on TPU (one process
    drives all local chips through the mesh); run func once."""
    init_parallel_env()
    func(*args)
