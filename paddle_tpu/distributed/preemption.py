"""Preemption notices + zero-downtime live mesh resize.

Preemptible TPU capacity delivers an advance *notice* (SIGTERM with a
grace window, or a scheduler RPC) before reclaiming a host. Every fault
path before this module was restart-shaped: the launch supervisor
killed the whole cohort and relaunched at N' (launch.py, PR 9), paying
process teardown + rendezvous even though the compile half of recovery
is already ~free (pre-warmed N' executables). This module treats the
notice as a LIVE event instead:

- notice delivery: `install_sigterm()` turns the FIRST SIGTERM into a
  pending notice (the second one falls through to the previous handler
  — the flight recorder's dump-then-die); `post_notice()` delivers the
  same thing over the PR 1 RPC envelope via the host-collective store;
  `faults.py` kind "preempt" injects one deterministically at rank R /
  step K.
- group agreement: `ElasticWorld.sync()` runs at step boundaries — it
  polls the store for RPC notices and allreduce-maxes a doomed-rank
  bitmap so every rank agrees on WHO leaves at the SAME step.
- the seam: `ElasticWorld.resize()` — the doomed rank writes an atomic
  preempt marker (the degrade-to-restart breadcrumb), the group takes
  its snapshot callback (checkpoint-on-signal), barriers, then the old
  store is drained; the doomed rank flight-dumps and exits 0 (exit 0
  is NOT a failure to the supervisor — survivors keep running) while
  survivors rebuild a fresh HostCollectiveGroup over the shrunk
  endpoint list on a generation-bumped store port and re-export the
  PADDLE_* env so every downstream consumer (mesh build, reader
  resharding, checkpoint manager) sees the new world.
- degrade loudly: any failure inside the seam raises LiveResizeError;
  the runner exits with DEGRADE_RC, which the supervisor treats as
  "survivor requesting cohort restart" — the PR 9 path — never a hang.

The device-tier half (unshard + mesh swap + re-shard in place) lives in
`Executor.live_resize`; this module owns the host-coordination half.
See distributed/README.md ("Live resize") for the runbook.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = [
    "DEGRADE_RC", "PREEMPT_MARKER_FMT",
    "PreemptNotice", "LiveResizeError",
    "default_grace_s", "deliver_notice", "pending_notice",
    "clear_notice", "install_sigterm", "post_notice",
    "write_preempt_marker", "read_preempt_markers",
    "ElasticWorld",
]

# a survivor that failed the live seam exits with this rc to request a
# cohort restart (launch.py treats it as degrade, not as the guilty
# rank); distinct from HANG_RC (124) and real crashes
DEGRADE_RC = 98

# atomic per-rank breadcrumb in the telemetry dir: written by the
# doomed rank BEFORE the seam can fail, read by the launch supervisor
# on the degraded path so the restart shrink drops the preempted rank
# even when it exited 0
PREEMPT_MARKER_FMT = "preempted.rank%d.json"

# store key carrying an RPC-delivered notice for rank R
_NOTICE_KEY_FMT = "preempt/%d"


def default_grace_s() -> float:
    """The grace window (seconds) between notice and reclaim;
    PADDLE_PREEMPT_GRACE_S env, default 30 — the order of real TPU
    preemption notices."""
    try:
        return float(os.environ.get("PADDLE_PREEMPT_GRACE_S", 30.0))
    except ValueError:
        return 30.0


class PreemptNotice:
    """One delivered preemption notice: this process must be gone by
    `deadline` (monotonic epoch seconds)."""

    __slots__ = ("rank", "grace_s", "source", "ts")

    def __init__(self, rank, grace_s, source, ts=None):
        self.rank = int(rank)
        self.grace_s = float(grace_s)
        self.source = str(source)  # "sigterm" | "rpc" | "fault"
        self.ts = float(ts if ts is not None else time.time())

    @property
    def deadline(self) -> float:
        return self.ts + self.grace_s

    def remaining_s(self) -> float:
        return max(0.0, self.deadline - time.time())

    def as_dict(self) -> dict:
        return {"rank": self.rank, "grace_s": self.grace_s,
                "source": self.source, "ts": self.ts}

    def __repr__(self):
        return ("PreemptNotice(rank=%d, grace_s=%g, source=%r, "
                "remaining=%.1fs)" % (self.rank, self.grace_s,
                                      self.source, self.remaining_s()))


class LiveResizeError(RuntimeError):
    """The live seam failed (second fault mid-recovery, rendezvous
    timeout). The runner must exit DEGRADE_RC so the supervisor falls
    back to the cohort-restart path instead of hanging."""


_lock = threading.Lock()
_pending: Optional[PreemptNotice] = None


def deliver_notice(grace_s=None, source="rpc",
                   rank=None) -> PreemptNotice:
    """Record a preemption notice for THIS process (first notice wins —
    a SIGTERM racing an RPC notice must not shorten or extend the
    already-armed grace window) and publish the `preempt_notice`
    telemetry event. Never kills anything: consumption happens at the
    next step boundary via ElasticWorld.sync()."""
    global _pending
    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    notice = PreemptNotice(
        rank, default_grace_s() if grace_s is None else grace_s, source)
    with _lock:
        if _pending is not None:
            return _pending
        _pending = notice
    try:
        from ..observability.registry import registry

        registry().event("preempt_notice", grace_s=notice.grace_s,
                         source=notice.source)
    except Exception:  # noqa: BLE001 - telemetry never gates the notice
        pass
    return notice


def pending_notice() -> Optional[PreemptNotice]:
    with _lock:
        return _pending


def clear_notice() -> None:
    global _pending
    with _lock:
        _pending = None


_prev_sigterm = None
_sigterm_installed = False


def install_sigterm(grace_s=None) -> bool:
    """Arm SIGTERM-as-notice: the first SIGTERM records a pending
    notice and returns (the process keeps training toward the seam);
    a second SIGTERM chains to the previously-installed handler — the
    flight recorder's dump-then-redeliver — so an impatient reclaimer
    still gets a postmortem and a dead process. Main thread only
    (signal module constraint); returns False when it can't install."""
    global _prev_sigterm, _sigterm_installed
    if _sigterm_installed:
        return True

    def _handler(signum, frame):
        if pending_notice() is None:
            deliver_notice(grace_s=grace_s, source="sigterm")
            return
        if callable(_prev_sigterm):
            _prev_sigterm(signum, frame)
        else:  # SIG_DFL: restore and re-deliver
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    try:
        _prev_sigterm = signal.signal(signal.SIGTERM, _handler)
    except ValueError:  # not the main thread
        return False
    _sigterm_installed = True
    return True


def post_notice(store_endpoint, target_rank, grace_s=None) -> None:
    """Deliver a preemption notice to `target_rank` over the PR 1 RPC
    envelope: drop a grace-window blob under the rank's notice key on
    the host-collective store. The target's next ElasticWorld.sync()
    peek picks it up. Usable from any process that can reach the store
    (an external scheduler shim, a test)."""
    from .rpc import RpcClient

    grace = default_grace_s() if grace_s is None else float(grace_s)
    client = RpcClient(store_endpoint)
    try:
        client.call("hc_put", _NOTICE_KEY_FMT % int(target_rank),
                    np.asarray([grace], np.float64))
    finally:
        client.close()


# -- degrade-to-restart breadcrumbs -------------------------------------


def _telemetry_dir() -> str:
    try:
        from ..utils.flags import get_flag

        base = str(get_flag("FLAGS_tpu_telemetry_dir", "") or "")
    except Exception:  # noqa: BLE001
        base = ""
    return base or os.getcwd()


def write_preempt_marker(rank, step=None, grace_s=None, source=None,
                         extra=None) -> Optional[str]:
    """Atomically write the doomed rank's preempt marker into the
    telemetry dir (tmp + fsync + rename, same discipline as the flight
    recorder). Written FIRST in the seam so the supervisor can tell
    'preempted, exited 0' from 'healthy, exited 0' even when the live
    path degrades right after. Returns the path, or None on IO failure
    (best-effort: a dying rank must never raise here)."""
    doc = {"rank": int(rank), "ts": time.time()}
    if step is not None:
        doc["step"] = int(step)
    if grace_s is not None:
        doc["grace_s"] = float(grace_s)
    if source is not None:
        doc["source"] = str(source)
    if extra:
        doc.update(extra)
    try:
        path = os.path.join(_telemetry_dir(),
                            PREEMPT_MARKER_FMT % int(rank))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 - breadcrumb, best effort
        return None


def read_preempt_markers(dirpath) -> List[dict]:
    """All preempt markers in `dirpath`, sorted by rank. Unreadable or
    malformed markers are skipped (a half-written tmp never matches the
    marker name, so rename atomicity keeps this clean)."""
    out = []
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return out
    for name in names:
        if not (name.startswith("preempted.rank")
                and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(dirpath, name)) as f:
                doc = json.load(f)
            if isinstance(doc, dict) and "rank" in doc:
                out.append(doc)
        except Exception:  # noqa: BLE001
            continue
    out.sort(key=lambda d: int(d.get("rank", 0)))
    return out


# -- the live seam ------------------------------------------------------


class ElasticWorld:
    """Host-coordination state machine for live shrink.

    Owns the HostCollectiveGroup across resizes: `sync()` at every step
    boundary turns per-rank notices into a group-agreed doomed set;
    `resize()` executes the seam. The registry's rank (telemetry stream
    identity) deliberately stays the ORIGINAL launch rank across a
    resize — only the collective rank moves."""

    def __init__(self, group, endpoints, generation=0):
        self.group = group
        self.endpoints = [str(e) for e in endpoints]
        self.generation = int(generation)
        # the rank THIS process was launched as: the supervisor's tid
        # space — preempt markers must speak it, not the post-resize
        # contiguous rank
        self.launch_rank = int(os.environ.get("PADDLE_LAUNCH_RANK",
                                              group.rank))
        os.environ.setdefault("PADDLE_LAUNCH_RANK",
                              str(self.launch_rank))
        if len(self.endpoints) != group.world:
            raise ValueError(
                "endpoints (%d) != group world (%d)"
                % (len(self.endpoints), group.world))

    @property
    def rank(self) -> int:
        return self.group.rank

    @property
    def world(self) -> int:
        return self.group.world

    @classmethod
    def from_env(cls) -> Optional["ElasticWorld"]:
        """Build from the PADDLE_* launch env; None for world <= 1
        (a solo process has nobody to agree a seam with)."""
        from .host_collectives import group_from_env

        group = group_from_env()
        if group is None:
            return None
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        return cls(group, eps)

    # -- agreement -------------------------------------------------------
    def poll_notice(self) -> Optional[PreemptNotice]:
        """Local-first notice check: an already-delivered notice
        (SIGTERM / fault injection), else a store peek for an
        RPC-delivered one. Non-blocking."""
        notice = pending_notice()
        if notice is not None:
            return notice
        try:
            val = self.group.peek(_NOTICE_KEY_FMT % self.rank)
        except Exception:  # noqa: BLE001 - store may be resizing
            val = None
        if val is None:
            return None
        return deliver_notice(grace_s=float(np.asarray(val).ravel()[0]),
                              source="rpc", rank=self.rank)

    def sync(self) -> List[int]:
        """Step-boundary agreement: allreduce-max a doomed-rank bitmap
        so every rank leaves the SAME step with the SAME doomed set
        (possibly empty). Costs one small host allreduce per step."""
        bitmap = np.zeros((self.world,), np.int8)
        if self.poll_notice() is not None:
            bitmap[self.rank] = 1
        agreed = self.group.all_reduce(bitmap, op="max")
        return [r for r in range(self.world) if int(agreed[r]) > 0]

    # -- the seam --------------------------------------------------------
    def resize(self, doomed: Sequence[int],
               snapshot: Optional[Callable[[List[int]], None]] = None,
               step: Optional[int] = None) -> dict:
        """Execute the live seam for an agreed doomed set.

        Every rank: doomed ranks drop their preempt markers first (the
        degrade breadcrumb must exist before anything can fail), the
        `snapshot` callback runs (group-agreed checkpoint-on-signal —
        reuse the ShardedCheckpointManager's intact-step protocol
        here), a barrier proves it landed everywhere, then the old
        group is torn down (old rank 0 drains the store; everyone else
        leaves cleanly).

        Doomed ranks flight-dump ("preempt") and get role="doomed"
        back — the caller must exit 0 within the grace window (exit 0
        keeps the supervisor's fail-fast from killing survivors).

        Survivors rebuild: new endpoint list minus the doomed ranks,
        new contiguous rank, a fresh store on a generation-bumped port
        (old port + 1 + generation — never collides with a store still
        draining), a rendezvous barrier, and the PADDLE_* env
        re-exported for downstream consumers. Returns the seam report
        (role, new rank/world, span timings) and publishes the
        `live_resize` + `elastic_transition(mode=live)` events.

        Any failure raises LiveResizeError — exit DEGRADE_RC then.
        """
        doomed = sorted(set(int(r) for r in doomed))
        if not doomed:
            raise ValueError("resize with an empty doomed set")
        if len(doomed) >= self.world:
            raise LiveResizeError("all %d ranks doomed" % self.world)
        t0 = time.monotonic()
        notice = pending_notice()
        notice_s = (max(0.0, time.time() - notice.ts)
                    if notice is not None else 0.0)
        old_world, old_rank = self.world, self.rank
        am_doomed = old_rank in doomed
        try:
            if am_doomed:
                write_preempt_marker(
                    self.launch_rank, step=step,
                    grace_s=notice.grace_s if notice else None,
                    source=notice.source if notice else None,
                    extra={"group_rank": old_rank})
            if snapshot is not None:
                snapshot(list(doomed))
            t_snap = time.monotonic()
            # the barrier is the group's agreement that every rank's
            # snapshot part is durably on disk — after it, survivors
            # may proceed even if the doomed rank is reclaimed early
            self.group.barrier()
            if am_doomed:
                try:
                    from ..observability import flight as _flight

                    _flight.dump("preempt", fatal_event={
                        "notice": notice.as_dict() if notice else None,
                        "step": step, "doomed": doomed})
                except Exception:  # noqa: BLE001 - forensics only
                    pass
                if old_rank == 0:
                    self.group.shutdown()
                else:
                    self.group.leave()
                report = {"role": "doomed", "old_world": old_world,
                          "new_world": old_world - len(doomed),
                          "old_rank": old_rank, "doomed": doomed,
                          "step": step}
                clear_notice()
                return report
            # ---- survivor path ----
            if old_rank == 0:
                self.group.shutdown()  # drains: waits for leaves
            else:
                self.group.leave()
            t_down = time.monotonic()
            new_eps = [ep for r, ep in enumerate(self.endpoints)
                       if r not in doomed]
            new_rank = new_eps.index(self.endpoints[old_rank])
            new_world = len(new_eps)
            self.generation += 1
            host, port = new_eps[0].rsplit(":", 1)
            store_ep = "%s:%d" % (host,
                                  int(port) + 1 + self.generation)
            from .host_collectives import HostCollectiveGroup

            group = HostCollectiveGroup(new_rank, new_world, store_ep,
                                        generation=self.generation)
            # rendezvous proof: the first post-seam collective must
            # complete before we declare the seam done (the RPC
            # client's reconnect backoff absorbs survivors racing the
            # new store's bind)
            group.barrier()
            t_up = time.monotonic()
            self.group = group
            self.endpoints = new_eps
            os.environ["PADDLE_TRAINER_ID"] = str(new_rank)
            os.environ["PADDLE_TRAINERS_NUM"] = str(new_world)
            os.environ["PADDLE_TRAINER_ENDPOINTS"] = ",".join(new_eps)
            report = {
                "role": "survivor", "old_world": old_world,
                "new_world": new_world, "old_rank": old_rank,
                "new_rank": new_rank, "doomed": doomed, "step": step,
                "generation": self.generation,
                "notice_s": round(notice_s, 6),
                "snapshot_s": round(t_snap - t0, 6),
                "rebuild_s": round(t_up - t_snap, 6),
                "teardown_s": round(t_down - t_snap, 6),
                "coordination_s": round(t_up - t0, 6),
            }
            self._emit(report)
            clear_notice()
            return report
        except LiveResizeError:
            raise
        except Exception as e:
            try:
                from ..observability.registry import registry

                registry().event(
                    "live_resize", old_world=old_world,
                    new_world=old_world - len(doomed),
                    coordination_s=round(time.monotonic() - t0, 6),
                    mode="live", status="degraded", error=repr(e))
            except Exception:  # noqa: BLE001
                pass
            raise LiveResizeError(
                "live seam failed (%s: %s) — degrade to cohort "
                "restart (exit %d)" % (type(e).__name__, e,
                                       DEGRADE_RC)) from e

    def _emit(self, report) -> None:
        try:
            from ..observability.registry import registry

            reg = registry()
            reg.event(
                "live_resize", old_world=report["old_world"],
                new_world=report["new_world"], mode="live",
                status="ok", generation=report["generation"],
                notice_s=report["notice_s"],
                snapshot_s=report["snapshot_s"],
                rebuild_s=report["rebuild_s"],
                coordination_s=report["coordination_s"])
            reg.event(
                "elastic_transition", old_world=report["old_world"],
                new_world=report["new_world"], mode="live",
                coordination_s=report["coordination_s"])
        except Exception:  # noqa: BLE001 - telemetry only
            pass

    def shutdown(self) -> None:
        self.group.shutdown()
