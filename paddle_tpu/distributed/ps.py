"""Parameter-server runtime: Communicator (trainer side) and
listen_and_serv (server side).

Reference parity:
- `operators/distributed/communicator.h:176-395` — Async/HalfAsync/Sync/
  GeoSgd Communicator background send/recv machinery on the trainer;
- `operators/distributed_ops/listen_and_serv_op.cc:336` — the pserver
  main loop (sync loop at :112) executing per-param optimizer blocks;
- `operators/distributed/parameter_send.cc / parameter_recv.cc`.

TPU-native shape: the accelerator runs fwd+bwd as one jitted computation
that also yields the param grads; the Communicator then pushes grads /
pulls params over the host TCP RPC (distributed/rpc.py). The pserver
applies updates by executing the transpiled update program through the
normal fluid Executor (REAL optimizer ops, not a re-implementation), with
sync mode aggregating all trainers' grads behind a barrier whose action
runs the update exactly once per global step.

Fault tolerance: the RPC layer (distributed/rpc.py) reconnects dropped
client connections and dedups retried requests per (client_id, seq), so
a retried `send_grads_batch`/`sparse_push` after a mid-stream drop is
applied to the tables exactly once, and a retried `send_barrier` never
double-arrives at the sync barrier. The barrier itself is bounded by
PADDLE_PS_BARRIER_TIMEOUT_S and reports heartbeat-lost trainers instead
of hanging forever on a dead worker.

Server-role checkpoint/restore (PADDLE_PS_CKPT_DIR; the trainer role
got this in PR 1): with a checkpoint dir set, the server persists its
tables + pending (un-applied) grads + per-client applied-seq dedup
markers ATOMICALLY after every PADDLE_PS_CKPT_EVERY-th state mutation,
and `listen_and_serv` restores the newest intact snapshot on startup.
Because the marker for a request is persisted in the same atomic write
as the mutation it acknowledges — and BEFORE the response leaves the
server — a trainer's retry after a server death+restart is answered
from the restored marker instead of being re-applied: exactly-once
survives the server role dying, not just the wire dropping. The launch
supervisors (launch_ps --max_restarts) restart a dead pserver in place
while the trainers' RPC clients retry with jittered backoff.
PADDLE_PS_CKPT_EVERY > 1 trades that exactness for less write traffic
(a crash may then replay up to N-1 mutations).
"""
from __future__ import annotations

import os
import pickle
import threading
from typing import Dict, List, Optional

import numpy as np

from .rpc import (RpcClient, RpcServer, _Stop, current_request_ctx,
                  decode as _rpc_decode, encode as _rpc_encode)


class PSCommunicator:
    """Trainer-side push/pull around each executor step."""

    def __init__(self, ps_cfg):
        self.cfg = ps_cfg
        self.mode = ps_cfg["mode"]
        self.tid = int(ps_cfg["trainer_id"])
        self._clients: Dict[str, RpcClient] = {}
        self._geo_step = 0
        self._geo_snapshots: Dict[str, np.ndarray] = {}
        # half-async state (reference: communicator.h:299
        # HalfAsyncCommunicator's send queues + background send thread)
        self._ha_lock = threading.Lock()
        self._ha_pending: Dict[str, list] = {}  # pname -> [sum, count]
        self._ha_round = 0        # rounds enqueued by the trainer
        self._ha_done_round = 0   # rounds fully pushed+pulled
        self._ha_cv = threading.Condition(self._ha_lock)
        self._ha_wake = threading.Event()
        self._ha_stop = threading.Event()
        self._ha_thread = None
        self._ha_err: list = []
        self._ha_scope = None
        # bounded staleness (the "half" in half-async; reference:
        # communicator.h max_merge_var_num): at most this many unsent
        # steps may pile up before the trainer waits for a flush. The
        # default of 1 pipelines each round's push/pull behind the next
        # step's compute without compounding stale updates.
        self._ha_max_merge = int(ps_cfg.get("half_async_max_merge", 1))

    def _client(self, ep) -> RpcClient:
        if ep not in self._clients:
            self._clients[ep] = RpcClient(ep)
        return self._clients[ep]

    # -- batched dense RPC (one call per SERVER per step, not per table:
    # VERDICT r2 weak #8; reference Communicator merges per-endpoint) ----
    def _groups(self):
        pe = self.cfg["param_endpoint"]
        groups: Dict[str, list] = {}
        for pname in sorted(pe):
            groups.setdefault(pe[pname], []).append(pname)
        return groups

    def _push_batched(self, grads, clients=None):
        client = clients or self._client
        pe = self.cfg["param_endpoint"]
        by_ep: Dict[str, list] = {}
        for pname, g in grads.items():
            by_ep.setdefault(pe[pname], []).append((pname, g))
        for ep, items in sorted(by_ep.items()):
            flat = []
            for pname, g in items:
                flat += [pname, np.asarray(g)]
            client(ep).call("send_grads_batch", self.tid,
                            len(items), *flat)

    def _pull_batched(self, scope, clients=None):
        client = clients or self._client
        for ep, names in sorted(self._groups().items()):
            c = client(ep)
            vals = c.call("get_params_batch", *names)
            for pname, val in zip(names, vals):
                scope.set_var(pname, val)
            # acked-release: the params-sized reply is applied — free
            # the server's retained dedup blob now instead of pinning
            # it until this trainer's next RPC (next step's push)
            c.ack_last()

    def init_params(self, scope):
        """Seed the pserver tables with this trainer's initial params
        (first write wins server-side). Replaces the reference's
        trainer->pserver initial broadcast so both tiers start from the
        SAME values regardless of each process's RNG stream."""
        targets = dict(self.cfg["param_endpoint"])
        for w, meta in self.cfg.get("sparse_tables", {}).items():
            targets[w] = meta["endpoint"]
        for pname, ep in targets.items():
            val = scope.find_var(pname)
            if val is not None:
                self._client(ep).call("init_param", pname,
                                      np.asarray(val))
                if self.mode == "geo":
                    # geo deltas are measured from the seed values; a
                    # lazy first snapshot at push time would make the
                    # first delta zero and then overwrite local progress
                    # with the server's seed
                    self._geo_snapshots[pname] = np.asarray(val).copy()

    # -- distributed_lookup_table prefetch (reference:
    # distributed/parameter_prefetch.cc) --------------------------------
    def prefetch(self, feed_arrays, scope):
        """Before the jitted step: fetch this batch's unique embedding
        rows from the pserver into the fixed-size @PREFETCH feed and the
        host-remapped ids into @REMAP."""
        self._last_uniq = {}
        for wname, meta in self.cfg.get("sparse_tables", {}).items():
            ids = np.asarray(feed_arrays[meta["ids_feed"]])
            flat = ids.reshape(-1).astype(np.int64)
            uniq, inverse = np.unique(flat, return_inverse=True)
            n = int(flat.size)
            uniq_p = np.zeros((n,), np.int64)
            uniq_p[:len(uniq)] = uniq
            (rows,) = self._client(meta["endpoint"]).call(
                "lookup_rows", wname, uniq_p)
            feed_arrays[meta["prefetch"]] = np.asarray(rows)
            feed_arrays[meta["remap"]] = inverse.reshape(
                ids.shape).astype(np.int64)
            self._last_uniq[wname] = uniq_p

    def push_sparse(self, sparse_grads):
        """Push SelectedRows-shaped (rows, values) grads of the
        prefetched rows back to the hosting pserver."""
        for wname, gvals in sparse_grads.items():
            meta = self.cfg["sparse_tables"][wname]
            rows = self._last_uniq[wname]
            self._client(meta["endpoint"]).call(
                "sparse_push", wname, rows,
                np.asarray(gvals, dtype=np.float32), self.tid)

    def _beat_all(self):
        eps = set(self.cfg["param_endpoint"].values())
        eps |= {m["endpoint"]
                for m in self.cfg.get("sparse_tables", {}).values()}
        for ep in eps:
            try:
                self._client(ep).call("heartbeat", self.tid)
            except Exception:  # noqa: BLE001 - liveness only
                pass

    # -- half-async background sender --------------------------------------
    def _ha_loop(self):
        """Merge-and-send loop: drains the pending grad queue, batch-sends
        the AVERAGED grads per server, pulls params back — all off the
        training thread, overlapping the next accelerator step (reference:
        HalfAsyncCommunicator's SendThread, communicator.h:299)."""
        clients: Dict[str, RpcClient] = {}

        def client(ep):
            if ep not in clients:
                clients[ep] = RpcClient(ep)  # thread-local sockets
            return clients[ep]

        try:
            while not self._ha_stop.is_set():
                self._ha_wake.wait(timeout=0.05)
                self._ha_wake.clear()
                self._ha_flush(client)
            self._ha_flush(client)  # final drain
        except Exception as e:  # noqa: BLE001 - surfaced on next step
            self._ha_err.append(e)
        finally:
            for c in clients.values():
                c.close()

    def _ha_flush(self, client):
        with self._ha_lock:
            pending, self._ha_pending = self._ha_pending, {}
            snap_round = self._ha_round  # rounds covered by this snapshot
        if pending:
            merged = {p: s / max(n, 1) for p, (s, n) in pending.items()}
            self._push_batched(merged, clients=client)
            scope = self._ha_scope
            if scope is not None:
                self._pull_batched(scope, clients=client)
        with self._ha_cv:
            # generation counter, not an event: an event set by a flush
            # whose snapshot predated this step's enqueue would release
            # the staleness wait without having sent this round
            if snap_round > self._ha_done_round:
                self._ha_done_round = snap_round
            self._ha_cv.notify_all()

    def _ha_step(self, grads, scope):
        self._ha_scope = scope
        if self._ha_err:
            raise self._ha_err[0]
        with self._ha_lock:
            for pname, g in grads.items():
                ent = self._ha_pending.get(pname)
                if ent is None:
                    self._ha_pending[pname] = [
                        np.asarray(g, np.float32).copy(), 1]
                else:
                    ent[0] += np.asarray(g, np.float32)
                    ent[1] += 1
            self._ha_round += 1
            my_round = self._ha_round
        if self._ha_thread is None:
            self._ha_thread = threading.Thread(
                target=self._ha_loop, daemon=True,
                name="paddle_tpu-ps-halfasync-sender")
            self._ha_thread.start()
        self._ha_wake.set()
        with self._ha_cv:
            # bounded staleness: at most max_merge rounds may be unsent.
            # A stalled sender must be an ERROR, not a silent fallback
            # to unbounded staleness.
            deadline = 60.0
            while (my_round - self._ha_done_round > self._ha_max_merge
                   and not self._ha_err and deadline > 0):
                self._ha_cv.wait(timeout=0.5)
                deadline -= 0.5
            stalled = (my_round - self._ha_done_round
                       > self._ha_max_merge)
        if self._ha_err:
            raise self._ha_err[0]
        if stalled:
            raise RuntimeError(
                "half-async sender stalled: round %d still unsent after "
                "60s (done=%d, max_merge=%d) — pserver unreachable?"
                % (my_round, self._ha_done_round, self._ha_max_merge))

    # -- dense sync/async --------------------------------------------------
    def step(self, grads: Dict[str, np.ndarray], scope):
        """grads: param name -> grad value for this step."""
        self._beat_all()
        pe = self.cfg["param_endpoint"]
        if self.mode == "half_async":
            self._ha_step(grads, scope)
        elif self.mode in ("sync", "async"):
            self._push_batched(grads)
            if self.mode == "sync":
                eps = sorted(set(pe.values()))
                # barrier releases once every trainer reported; its action
                # applies the aggregated update exactly once
                for ep in eps:
                    self._client(ep).call("send_barrier", self.tid)
            self._pull_batched(scope)
        elif self.mode == "geo":
            self._geo_step += 1
            if self._geo_step % max(self.cfg["geo_push_every"], 1):
                return
            for pname in pe:
                cur = np.asarray(scope.find_var(pname))
                snap = self._geo_snapshots.get(pname)
                if snap is None:  # init_params not called (no local var)
                    self._geo_snapshots[pname] = cur.copy()
                    continue
                delta = cur - snap
                (merged,) = self._client(pe[pname]).call(
                    "geo_delta", pname, delta.astype(np.float32))
                scope.set_var(pname, merged)
                self._geo_snapshots[pname] = np.asarray(merged).copy()

    def complete(self):
        # a completed communicator is dead: its sender thread is joined
        # and its clients closed — consumers (the Executor cache) must
        # build a fresh one rather than step this instance again
        self._completed = True
        if self._ha_thread is not None:
            # flush pending half-async grads, then stop the sender
            self._ha_stop.set()
            self._ha_wake.set()
            self._ha_thread.join(timeout=30.0)
            if self._ha_err:
                raise self._ha_err[0]
        eps = set(self.cfg["param_endpoint"].values())
        eps |= {m["endpoint"]
                for m in self.cfg.get("sparse_tables", {}).values()}
        for ep in sorted(eps):
            try:
                self._client(ep).call("complete", self.tid)
            except Exception:  # noqa: BLE001 - server may already be down
                pass
        for c in self._clients.values():
            c.close()


class HeartBeatMonitor:
    """Lost-worker detection (reference:
    `operators/distributed/heart_beat_monitor.h:54` — the pserver-side
    LostWorkerMonitor thread watching per-worker update timestamps)."""

    def __init__(self, trainers, timeout_s=60.0, on_lost=None):
        import time

        self.trainers = int(trainers)
        self.timeout_s = float(timeout_s)
        self._clock = time.monotonic
        # pre-seed every expected worker so one that dies BEFORE its
        # first RPC is still detected
        now = self._clock()
        self._last_beat: Dict[int, float] = {
            tid: now for tid in range(self.trainers)}
        self._lost: set = set()
        self._on_lost = on_lost
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._beat_lock = threading.Lock()

    def beat(self, tid: int):
        now = self._clock()
        with self._beat_lock:
            self._last_beat[int(tid)] = now
            self._lost.discard(int(tid))

    def lost_workers(self):
        now = self._clock()
        with self._beat_lock:
            items = list(self._last_beat.items())
        for tid, t in items:
            if now - t > self.timeout_s and tid not in self._lost:
                self._lost.add(tid)
                if self._on_lost:
                    self._on_lost(tid)
                else:
                    import logging

                    logging.getLogger("paddle_tpu.ps").warning(
                        "trainer %d lost (no heartbeat for %.0fs)",
                        tid, now - t)
        return sorted(self._lost)

    def start(self, interval_s=10.0):
        def loop():
            while not self._stop.wait(interval_s):
                self.lost_workers()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="ps-heartbeat-monitor")
        self._thread.start()

    def stop(self):
        self._stop.set()


class ParameterServer:
    """listen_and_serv state: tables + aggregation + update execution.

    With `ckpt_dir` set, every state mutation (or every `ckpt_every`-th
    one) atomically persists tables + pending grads + applied-seq dedup
    markers, and a restarted server restores the newest intact snapshot
    — see the module docstring for the exactly-once argument."""

    def __init__(self, pserver_prog, startup_prog, trainers, mode,
                 ckpt_dir=None, ckpt_every=1):
        from ..core.scope import Scope
        from ..fluid.executor import Executor
        from ..fluid.framework import CPUPlace

        self.prog = pserver_prog
        self.mode = mode
        self.trainers = int(trainers)
        self.scope = Scope()
        self.exe = Executor(CPUPlace())
        if startup_prog is not None and startup_prog.global_block().ops:
            self.exe.run(startup_prog, scope=self.scope)
        self.grad_of = dict(getattr(pserver_prog, "_ps_grad_of", {}))
        self.hosted = list(getattr(pserver_prog, "_ps_hosted_params", []))
        self._pending: Dict[str, Dict[int, np.ndarray]] = {}
        self._pending_sparse: Dict[str, Dict[int, tuple]] = {}
        self._sparse_lr = dict(getattr(pserver_prog, "_ps_sparse", {}))
        self._inited: set = set()
        self._lock = threading.Lock()
        self.heartbeat = HeartBeatMonitor(self.trainers)
        self.heartbeat.start()
        # per-param update programs (reference: listen_and_serv per-param
        # optimize sub-blocks) — async mode applies one grad at a time
        from ..fluid import framework as fw

        self._per_param_prog: Dict[str, object] = {}
        src_blk = pserver_prog.global_block()
        for op in src_blk.ops:
            if "Param" not in op.input_names or not op.input_names["Param"]:
                continue
            pname = op.input_names["Param"][0]
            prog = fw.Program()
            blk = prog.global_block()
            for n in sorted(set(op.input_arg_names)
                            | set(op.output_arg_names)):
                v = src_blk._find_var_recursive(n)
                if v is not None:
                    blk.create_var(name=n, shape=v.shape, dtype=v.dtype,
                                   persistable=v.persistable,
                                   stop_gradient=True)
            blk.append_op(
                type=op.type,
                inputs={s: list(ns) for s, ns in op.input_names.items()},
                outputs={s: list(ns) for s, ns in op.output_names.items()},
                attrs=dict(op.attrs))
            self._per_param_prog[pname] = prog
        self._completed: set = set()
        self._barrier = threading.Barrier(self.trainers,
                                          action=self._apply_sync)
        # sync barrier must not hang forever on a dead trainer: bound
        # the wait and report lost workers (heartbeat monitor) instead
        import os

        self._barrier_timeout_s = float(
            os.environ.get("PADDLE_PS_BARRIER_TIMEOUT_S", 600))
        self._barrier_reset_lock = threading.Lock()
        # trainers that reached the CURRENT barrier round: the break
        # diagnostic names who never arrived. Heartbeat ages can't
        # attribute the break — waiters stop beating while blocked, so
        # by break time every healthy waiter looks stale too.
        self._barrier_arrived: set = set()
        self._barrier_last_missing: list = []
        self._barrier_action_failed = False
        # -- server-role checkpoint state (PADDLE_PS_CKPT_DIR) --------
        self._ckpt_dir = ckpt_dir or None
        self._ckpt_every = max(int(ckpt_every or 1), 1)
        self._mutations = 0
        # cid -> (seq, wire-resp fields) of the newest APPLIED
        # side-effecting request per client, maintained under the same
        # lock as the mutation it marks — the persisted form of the RPC
        # dedup table (read-only methods never enter: they are safe to
        # re-execute after a restore)
        self._applied: Dict[str, tuple] = {}
        # tid -> (cid, seq) of trainers blocked in the CURRENT sync
        # barrier round: the barrier ACTION persists all of them in one
        # atomic write (once the aggregated update ran, every waiter's
        # send_barrier is applied, whether or not its response ever
        # reaches the trainer)
        self._barrier_inflight: Dict[int, tuple] = {}

    # -- server-role checkpoint/restore ---------------------------------
    _CKPT_PREFIX = "ps_state"
    _CKPT_KEEP = 2

    def _record_applied(self, resp_fields=(), stop=False):
        """Mark the request the current handler thread is executing as
        APPLIED (call while holding the lock that guards the mutation
        it acknowledges), then maybe persist. `resp_fields` is what the
        retried request should be answered with after a restore — the
        wire form is ["ok", *resp_fields]. `stop=True` (the final
        `complete`) makes the restored dedup replay ALSO stop the
        reborn server, so a trainer retrying it doesn't leave the
        server serving forever."""
        ctx = current_request_ctx()
        if ctx is not None:
            cid, seq = ctx
            self._applied[cid] = (int(seq),
                                  ["ok"] + [np.asarray(f) if
                                            isinstance(f, np.ndarray)
                                            else f
                                            for f in resp_fields],
                                  bool(stop))
        self._maybe_persist()

    def _maybe_persist(self):
        if not self._ckpt_dir:
            return
        self._mutations += 1
        if self._mutations % self._ckpt_every:
            return
        self._persist()

    def _snapshot_state(self) -> dict:
        tables = {}
        for name in self.scope.local_var_names():
            v = self.scope.find_var(name)
            if v is None:
                continue
            try:
                tables[name] = np.asarray(v)
            except Exception:  # noqa: BLE001 - non-array metadata var
                continue
        return {
            "version": 1,
            "tables": tables,
            "pending": {p: dict(t) for p, t in self._pending.items()},
            "pending_sparse": {p: dict(t) for p, t in
                               self._pending_sparse.items()},
            "inited": sorted(self._inited),
            "completed": sorted(self._completed),
            # wire-encode resp fields (body only, no frame length: the
            # restore side feeds rpc.decode directly) so the pickle
            # holds flat bytes
            "applied": {cid: (int(seq), _rpc_encode(resp)[8:], stop)
                        for cid, (seq, resp, stop)
                        in self._applied.items()},
        }

    def _persist(self):
        """One atomic numbered snapshot (tmp + os.replace — a kill
        mid-write can never leave a corrupt newest snapshot), retention
        pruning past _CKPT_KEEP. Caller holds the lock guarding the
        mutation being acknowledged."""
        os.makedirs(self._ckpt_dir, exist_ok=True)
        nos = self._ckpt_nos(self._ckpt_dir)
        n = (max(nos) if nos else -1) + 1
        path = os.path.join(self._ckpt_dir,
                            "%s.%d.pkl" % (self._CKPT_PREFIX, n))
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._snapshot_state(), f,
                        protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        for old in nos:
            if old <= n - self._CKPT_KEEP:
                try:
                    os.remove(os.path.join(
                        self._ckpt_dir,
                        "%s.%d.pkl" % (self._CKPT_PREFIX, old)))
                except OSError:
                    pass
        try:
            from ..observability.registry import registry

            registry().event("checkpoint", action="save", role="pserver",
                             path=path, step_no=n)
        except Exception:  # noqa: BLE001 - telemetry only
            pass

    @classmethod
    def _ckpt_nos(cls, directory) -> List[int]:
        out = []
        try:
            names = os.listdir(directory)
        except OSError:
            return out
        for nm in names:
            parts = nm.split(".")
            if len(parts) != 3 or parts[0] != cls._CKPT_PREFIX \
                    or parts[2] != "pkl":
                continue
            try:
                out.append(int(parts[1]))
            except ValueError:
                continue
        return out

    def restore_from_checkpoint(self):
        """Load the newest INTACT snapshot under ckpt_dir into tables /
        pending / markers; returns the {cid: [seq, resp_bytes]} dedup
        snapshot for RpcServer.dedup_restore, or None when there is
        nothing (or no dir). Corrupt/partial newest snapshots (a kill
        mid-write before the atomic replace is impossible, but disk
        faults are not) fall back to the previous one, matching the
        trainer-side newest-intact restore semantics."""
        if not self._ckpt_dir:
            return None
        last_err = None
        for n in sorted(self._ckpt_nos(self._ckpt_dir), reverse=True):
            path = os.path.join(self._ckpt_dir,
                                "%s.%d.pkl" % (self._CKPT_PREFIX, n))
            try:
                with open(path, "rb") as f:
                    state = pickle.load(f)
                if state.get("version") != 1:
                    raise ValueError("unknown ps snapshot version %r"
                                     % state.get("version"))
            except Exception as e:  # noqa: BLE001 - corrupt snapshot
                last_err = e
                import logging

                logging.getLogger("paddle_tpu.ps").warning(
                    "pserver snapshot %s unreadable (%s: %s); falling "
                    "back", path, type(e).__name__, e)
                continue
            with self._lock:
                for name, val in state["tables"].items():
                    self.scope.set_var(name, val)
                self._pending = {p: dict(t)
                                 for p, t in state["pending"].items()}
                self._pending_sparse = {
                    p: dict(t)
                    for p, t in state["pending_sparse"].items()}
                self._inited = set(state["inited"])
                self._completed = set(state["completed"])
                # carry the markers forward: the NEXT snapshot must
                # still contain them, or a second restart would lose
                # exactly-once for requests applied before the first
                self._applied = {
                    cid: (seq, _rpc_decode(bytes(resp_bytes)),
                          bool(stop))
                    for cid, (seq, resp_bytes, stop)
                    in state["applied"].items()}
            try:
                from ..observability.registry import registry

                registry().event("checkpoint", action="restore",
                                 role="pserver", path=path, step_no=n)
            except Exception:  # noqa: BLE001 - telemetry only
                pass
            return {cid: [seq, resp_bytes, bool(stop)]
                    for cid, (seq, resp_bytes, stop)
                    in state["applied"].items()}
        if last_err is not None:
            raise RuntimeError(
                "no intact pserver snapshot under %r" % self._ckpt_dir
            ) from last_err
        return None

    # sync: barrier action runs in exactly one thread
    def _apply_sync(self):
        try:
            self._apply_sync_inner()
        except BaseException:
            # the flag — not an empty missing-set, which a straggler
            # arriving mid-break can also produce — is what marks this
            # round as an action failure for the other waiters
            with self._barrier_reset_lock:
                self._barrier_action_failed = True
            raise
        with self._barrier_reset_lock:
            self._barrier_arrived.clear()
            # a successful round also clears any stale failure flag
            # (world=1: an action failure propagates to the sole waiter
            # without entering the BrokenBarrierError handler that
            # normally consumes the flag, so the retry's handler reads
            # it once; it must not outlive that)
            self._barrier_action_failed = False

    def _apply_sync_inner(self):
        with self._lock:
            feed = {}
            for gname, pname in self.grad_of.items():
                per_t = self._pending.pop(pname, {})
                if not per_t:
                    continue
                agg = np.sum(list(per_t.values()), axis=0) / self.trainers
                feed[gname] = agg
            if feed:
                self.exe.run(self.prog, feed=feed, fetch_list=[],
                             scope=self.scope)
            for pname, per_t in list(self._pending_sparse.items()):
                if not per_t:
                    continue
                self._pending_sparse[pname] = {}
                self._apply_sparse(
                    pname,
                    np.concatenate([rv[0] for rv in per_t.values()]),
                    np.concatenate([rv[1] for rv in per_t.values()])
                    / self.trainers)
            # once the aggregated update ran, EVERY waiter's
            # send_barrier is applied — persist all their markers in
            # the same atomic snapshot as the updated tables, so a
            # server death after this point answers retried barriers
            # from the marker instead of re-forming a half-round
            self._record_barrier_applied()

    def _record_barrier_applied(self):
        """Mark every trainer blocked in the current barrier round as
        applied (called from the barrier action, self._lock held)."""
        with self._barrier_reset_lock:
            inflight = dict(self._barrier_inflight)
            self._barrier_inflight.clear()
        for _tid, (cid, seq) in inflight.items():
            self._applied[cid] = (int(seq), ["ok"], False)
        self._maybe_persist()

    def _apply_sparse(self, pname, rows, values):
        # sparse SGD row update (reference: sgd_op.h SelectedRows branch)
        lr = float(self._sparse_lr.get(pname, 1.0))
        table = np.asarray(self.scope.find_var(pname)).copy()
        np.subtract.at(table, rows, lr * values.astype(table.dtype))
        self.scope.set_var(pname, table)

    def _apply_one(self, pname, grad):
        gname = next(g for g, p in self.grad_of.items() if p == pname)
        self.exe.run(self._per_param_prog[pname], feed={gname: grad},
                     fetch_list=[], scope=self.scope)

    def handle(self, method, args):
        if method == "init_param":
            pname, val = args[0], args[1]
            with self._lock:
                if pname not in self._inited:
                    self.scope.set_var(pname, val)
                    self._inited.add(pname)
                self._record_applied()
            return []
        if method == "heartbeat":
            self.heartbeat.beat(int(args[0]))
            return []
        if method == "send_grad":
            pname, grad, tid = args[0], args[1], int(args[2])
            self.heartbeat.beat(tid)
            if self.mode in ("async", "half_async"):
                with self._lock:
                    self._apply_one(pname, grad)
                    self._record_applied()
            else:
                with self._lock:
                    self._pending.setdefault(pname, {})[tid] = grad
                    self._record_applied()
            return []
        if method == "send_grads_batch":
            # one RPC carrying every table this server hosts (VERDICT r2
            # weak #8; reference Communicator batches per endpoint):
            # args = [tid, n, name1, grad1, ..., nameN, gradN]
            tid, n = int(args[0]), int(args[1])
            self.heartbeat.beat(tid)
            pairs = [(args[2 + 2 * i], args[3 + 2 * i]) for i in range(n)]
            with self._lock:
                for pname, grad in pairs:
                    if self.mode in ("async", "half_async"):
                        self._apply_one(pname, grad)
                    else:
                        self._pending.setdefault(pname, {})[tid] = grad
                self._record_applied()
            return []
        if method == "get_params_batch":
            with self._lock:
                return [np.asarray(self.scope.find_var(p))
                        for p in args]
        if method == "send_barrier":
            tid = int(args[0])
            self.heartbeat.beat(tid)
            with self._barrier_reset_lock:
                self._barrier_arrived.add(tid)
                # the barrier ACTION persists this marker once the
                # aggregated update has run (_record_barrier_applied)
                ctx = current_request_ctx()
                if ctx is not None:
                    self._barrier_inflight[tid] = ctx
            try:
                self._barrier.wait(timeout=self._barrier_timeout_s)
            except threading.BrokenBarrierError:
                # reset so later steps can still synchronize once the
                # straggler returns — a broken Barrier otherwise rejects
                # every future wait() for the rest of the run. Reset
                # exactly ONCE per broken round (every waiter lands
                # here; a late second reset() would break a fresh round
                # a recovering trainer already re-entered), and capture
                # the never-arrived set before clearing it.
                with self._barrier_reset_lock:
                    if self._barrier.broken:
                        self._barrier_last_missing = sorted(
                            set(range(self.trainers))
                            - self._barrier_arrived)
                        self._barrier_arrived.clear()
                        self._barrier_inflight.clear()
                        self._barrier.reset()
                    missing = list(self._barrier_last_missing)
                    action_failed = self._barrier_action_failed
                    self._barrier_action_failed = False
                if action_failed:
                    # the thread that ran the action got the real error
                    raise RuntimeError(
                        "sync barrier broken: the aggregated update "
                        "failed — see the pserver log / the co-trainer "
                        "that received the original error")
                if missing:
                    raise RuntimeError(
                        "sync barrier timed out after %.0fs: trainers "
                        "%s never arrived"
                        % (self._barrier_timeout_s, missing))
                raise RuntimeError(
                    "sync barrier broken while this trainer was "
                    "arriving (another round timed out concurrently); "
                    "retry the step")
            return []
        if method == "get_param":
            with self._lock:
                return [np.asarray(self.scope.find_var(args[0]))]
        if method == "lookup_rows":
            pname, rows = args[0], np.asarray(args[1]).astype(np.int64)
            with self._lock:
                table = np.asarray(self.scope.find_var(pname))
            return [table[rows]]
        if method == "sparse_push":
            pname, rows, values, tid = (args[0],
                                        np.asarray(args[1]),
                                        np.asarray(args[2]),
                                        int(args[3]))
            self.heartbeat.beat(tid)
            if self.mode in ("async", "half_async"):
                with self._lock:
                    self._apply_sparse(pname, rows, values)
                    self._record_applied()
            else:
                with self._lock:
                    self._pending_sparse.setdefault(pname, {})[tid] = (
                        rows, values)
                    self._record_applied()
            return []
        if method == "write_rows":
            # exact row write (embedding cold-tier demotion: an evicted
            # row's current device value + moments land back in the
            # authoritative table). Rides the RPC envelope's
            # (client_id, seq) dedup via _record_applied, so a server
            # death between the write and its ack can never double-
            # apply a retried demotion — exactly-once, the same
            # contract as every other mutation here. Every target —
            # including the moment side-tables `name#slot`, which
            # have no program var — must be seeded via init_param
            # first (RowCache.seed_ps does); a row write cannot
            # invent the table's full shape.
            pname, rows, values, tid = (args[0],
                                        np.asarray(args[1]).astype(
                                            np.int64),
                                        np.asarray(args[2]),
                                        int(args[3]))
            self.heartbeat.beat(tid)
            with self._lock:
                cur = self.scope.find_var(pname)
                if cur is None:
                    raise ValueError(
                        "write_rows: table %r was never initialized "
                        "(seed it with init_param first)" % pname)
                table = np.asarray(cur).copy()
                table[rows] = values.astype(table.dtype)
                self.scope.set_var(pname, table)
                self._record_applied()
            return []
        if method == "sparse_grad_sgd":
            # direct sparse SGD row update (reference: sgd_op.h sparse
            # SelectedRows path; avoids densifying the whole table)
            pname, rows, values, lr = (args[0],
                                       np.asarray(args[1]).astype(np.int64),
                                       np.asarray(args[2]), float(args[3]))
            with self._lock:
                table = np.asarray(self.scope.find_var(pname)).copy()
                np.subtract.at(table, rows, lr * values)
                self.scope.set_var(pname, table)
                self._record_applied()
            return []
        if method == "geo_delta":
            pname, delta = args[0], args[1]
            with self._lock:
                table = np.asarray(self.scope.find_var(pname)) + delta
                self.scope.set_var(pname, table)
                # a retried geo_delta after a restore must get the SAME
                # merged table back, not a re-merge of its delta
                self._record_applied([table])
                return [table]
        if method == "complete":
            with self._lock:
                self._completed.add(int(args[0]))
                stop = len(self._completed) >= self.trainers
                # the final complete's marker carries stop=True: a
                # server killed between this persist and the response
                # must STOP again when the trainer's retry replays it
                self._record_applied(stop=stop)
            if stop:
                raise _Stop()
            return []
        raise ValueError("unknown rpc method %r" % method)


def listen_and_serv(pserver_prog, pserver_startup=None,
                    endpoint="127.0.0.1:0", trainers=1, mode="sync",
                    ckpt_dir=None, ckpt_every=None):
    """Run the pserver loop until every trainer calls complete().
    Returns after serving (reference: listen_and_serv_op.cc:336).

    `ckpt_dir` (default: PADDLE_PS_CKPT_DIR env) turns on server-role
    checkpointing: tables + pending grads + dedup markers persist
    atomically every `ckpt_every` mutations (PADDLE_PS_CKPT_EVERY,
    default 1 = exactly-once across a server death), and a restarted
    server restores the newest intact snapshot — including the
    per-client applied-seq markers, so trainers' retried requests are
    never double-applied."""
    host, port = endpoint.rsplit(":", 1)
    if ckpt_dir is None:
        ckpt_dir = os.environ.get("PADDLE_PS_CKPT_DIR") or None
    if ckpt_every is None:
        ckpt_every = int(os.environ.get("PADDLE_PS_CKPT_EVERY", "1"))
    server_state = ParameterServer(pserver_prog, pserver_startup,
                                   trainers, mode, ckpt_dir=ckpt_dir,
                                   ckpt_every=ckpt_every)
    dedup = server_state.restore_from_checkpoint()
    srv = RpcServer(host, int(port), server_state.handle)
    if dedup:
        srv.dedup_restore(dedup)
    srv.start()
    if len(server_state._completed) >= server_state.trainers:
        # the old server died after the LAST trainer's complete was
        # applied+persisted: every trainer already has (or is retrying,
        # and its retry's responses are swallowed best-effort) its
        # answer — don't serve forever waiting for completes that will
        # never come
        srv._stop_evt.set()
    try:
        server_state.served_port = srv.port
        srv.wait_stopped()
    finally:
        server_state.heartbeat.stop()
        srv.shutdown()
    return server_state
