"""Parameter-server launcher (reference:
`python/paddle/distributed/launch_ps.py`): spawns N pserver + M trainer
processes of the user script on this host with the reference PS env
contract — TRAINING_ROLE, PADDLE_PSERVERS_IP_PORT_LIST,
PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ID, POD_IP/PADDLE_PORT — which
fleet.PaddleCloudRoleMaker(is_collective=False) reads.

Usage: python -m paddle_tpu.distributed.launch_ps \
           --server_num 2 --worker_num 2 train.py [args...]
       (or explicit --servers host:port,host:port --workers ...)

Server-role supervision (`--max_restarts N`, composing with the pserver
checkpoint/restore in distributed/ps.py): a pserver that dies mid-run
is restarted IN PLACE on its original endpoint up to N times while the
trainers keep running — their RPC clients retry with jittered backoff
and reconnect to the reborn server, whose tables + dedup markers come
back from the newest intact snapshot (PADDLE_PS_CKPT_DIR, exported
per-server as <--ps_ckpt_dir>/server<i>), so retried requests are never
double-applied. PADDLE_RESTART_NUM carries the server's attempt number.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _parse_args(argv):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch_ps")
    p.add_argument("--server_num", type=int, default=None)
    p.add_argument("--worker_num", type=int, default=None)
    p.add_argument("--servers", type=str, default="",
                   help="comma-separated pserver host:port list")
    p.add_argument("--workers", type=str, default="",
                   help="comma-separated trainer host:port list")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--max_restarts", type=int, default=0,
                   help="restart a dead pserver in place up to N times "
                        "(trainers keep running; composes with "
                        "--ps_ckpt_dir table/dedup restore)")
    p.add_argument("--ps_ckpt_dir", type=str, default=None,
                   help="root for per-server state snapshots; exported "
                        "as PADDLE_PS_CKPT_DIR=<dir>/server<i>")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    servers = [e for e in args.servers.split(",") if e]
    workers = [e for e in args.workers.split(",") if e]
    if not servers:
        servers = ["127.0.0.1:%d" % _free_port()
                   for _ in range(args.server_num or 2)]
    if not workers:
        workers = ["127.0.0.1:%d" % _free_port()
                   for _ in range(args.worker_num or 2)]

    if args.max_restarts > 0 and not args.ps_ckpt_dir \
            and not os.environ.get("PADDLE_PS_CKPT_DIR"):
        # a restarted stateless pserver reboots with EMPTY tables and a
        # fresh dedup table while the trainers keep running — silent
        # state loss. Restart supervision without snapshots is almost
        # certainly a mistake; refuse to be quiet about it.
        sys.stderr.write(
            "paddle_tpu.launch_ps: WARNING --max_restarts without "
            "--ps_ckpt_dir/PADDLE_PS_CKPT_DIR: a restarted pserver "
            "loses its tables, pending grads and dedup markers\n")

    base = dict(os.environ)
    base["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(servers)
    base["PADDLE_TRAINERS_NUM"] = str(len(workers))
    base["PADDLE_TRAINER_ENDPOINTS"] = ",".join(workers)

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    def out(tag):
        if args.log_dir:
            return open(os.path.join(args.log_dir, tag + ".log"), "w")
        return None

    procs = []
    cmd = [sys.executable, args.training_script] \
        + args.training_script_args

    def server_env(i, ep, restart_no=0):
        env = dict(base)
        env["TRAINING_ROLE"] = "PSERVER"
        ip, port = ep.rsplit(":", 1)
        env["POD_IP"] = ip
        env["PADDLE_PORT"] = port
        env["PADDLE_CURRENT_ENDPOINT"] = ep
        env["PADDLE_RESTART_NUM"] = str(restart_no)
        if args.ps_ckpt_dir:
            env["PADDLE_PS_CKPT_DIR"] = os.path.join(
                args.ps_ckpt_dir, "server%d" % i)
        return env

    def spawn_server(i, ep, restart_no=0):
        # append across restarts: attempt 0's tail is the evidence for
        # WHY the server restarted
        f = out("serverlog.%d" % i) if restart_no == 0 else (
            open(os.path.join(args.log_dir, "serverlog.%d.log" % i),
                 "a") if args.log_dir else None)
        return (subprocess.Popen(cmd, env=server_env(i, ep, restart_no),
                                 stdout=f, stderr=f), f)

    for i, ep in enumerate(servers):
        procs.append(spawn_server(i, ep))
    for i, ep in enumerate(workers):
        env = dict(base)
        env["TRAINING_ROLE"] = "TRAINER"
        env["PADDLE_TRAINER_ID"] = str(i)
        env["PADDLE_CURRENT_ENDPOINT"] = ep
        f = out("workerlog.%d" % i)
        procs.append((subprocess.Popen(cmd, env=env, stdout=f,
                                       stderr=f), f))

    restarts_left = [max(args.max_restarts, 0)] * len(servers)
    rc = 0
    try:
        # trainers finishing ends the job; pservers are then reaped.
        # While trainers run, a pserver that dies is restarted in place
        # (same endpoint, bumped PADDLE_RESTART_NUM) while the trainer
        # RPC clients retry against the endpoint with jittered backoff.
        import time as _time

        trainer_procs = [p for p, _ in procs[len(servers):]]
        while any(p.poll() is None for p in trainer_procs):
            for i in range(len(servers)):
                p, f = procs[i]
                if p.poll() is None or p.returncode == 0 \
                        or restarts_left[i] <= 0:
                    continue
                restarts_left[i] -= 1
                attempt = max(args.max_restarts, 0) - restarts_left[i]
                sys.stderr.write(
                    "paddle_tpu.launch_ps: pserver %d exited with %d; "
                    "restart %d/%d\n" % (i, p.returncode, attempt,
                                         max(args.max_restarts, 0)))
                if f:
                    f.close()
                procs[i] = spawn_server(i, servers[i],
                                        restart_no=attempt)
            _time.sleep(0.1)
        for p in trainer_procs:
            rc = p.wait() or rc
    finally:
        # grace window before reaping: a pserver that is already
        # exiting cleanly (trainers sent complete(), or a short probe
        # script still flushing its log) must not be terminated
        # mid-write, which truncates its log and discards its rc
        import time

        from .launch import _terminate_all

        deadline = time.monotonic() + 5.0
        for p, _ in procs:
            remaining = deadline - time.monotonic()
            if p.poll() is None and remaining > 0:
                try:
                    p.wait(timeout=remaining)
                except subprocess.TimeoutExpired:
                    pass
        # terminate (then kill) whatever is still running
        _terminate_all([p for p, _ in procs], grace_s=5.0)
        for _, f in procs:
            if f:
                f.close()
    return rc


if __name__ == "__main__":
    sys.exit(launch())
