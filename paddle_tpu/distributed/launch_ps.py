"""Parameter-server launcher (reference:
`python/paddle/distributed/launch_ps.py`): spawns N pserver + M trainer
processes of the user script on this host with the reference PS env
contract — TRAINING_ROLE, PADDLE_PSERVERS_IP_PORT_LIST,
PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ID, POD_IP/PADDLE_PORT — which
fleet.PaddleCloudRoleMaker(is_collective=False) reads.

Usage: python -m paddle_tpu.distributed.launch_ps \
           --server_num 2 --worker_num 2 train.py [args...]
       (or explicit --servers host:port,host:port --workers ...)
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _parse_args(argv):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch_ps")
    p.add_argument("--server_num", type=int, default=None)
    p.add_argument("--worker_num", type=int, default=None)
    p.add_argument("--servers", type=str, default="",
                   help="comma-separated pserver host:port list")
    p.add_argument("--workers", type=str, default="",
                   help="comma-separated trainer host:port list")
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    servers = [e for e in args.servers.split(",") if e]
    workers = [e for e in args.workers.split(",") if e]
    if not servers:
        servers = ["127.0.0.1:%d" % _free_port()
                   for _ in range(args.server_num or 2)]
    if not workers:
        workers = ["127.0.0.1:%d" % _free_port()
                   for _ in range(args.worker_num or 2)]

    base = dict(os.environ)
    base["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(servers)
    base["PADDLE_TRAINERS_NUM"] = str(len(workers))
    base["PADDLE_TRAINER_ENDPOINTS"] = ",".join(workers)

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    def out(tag):
        if args.log_dir:
            return open(os.path.join(args.log_dir, tag + ".log"), "w")
        return None

    procs = []
    cmd = [sys.executable, args.training_script] \
        + args.training_script_args
    for i, ep in enumerate(servers):
        env = dict(base)
        env["TRAINING_ROLE"] = "PSERVER"
        ip, port = ep.rsplit(":", 1)
        env["POD_IP"] = ip
        env["PADDLE_PORT"] = port
        env["PADDLE_CURRENT_ENDPOINT"] = ep
        f = out("serverlog.%d" % i)
        procs.append((subprocess.Popen(cmd, env=env, stdout=f,
                                       stderr=f), f))
    for i, ep in enumerate(workers):
        env = dict(base)
        env["TRAINING_ROLE"] = "TRAINER"
        env["PADDLE_TRAINER_ID"] = str(i)
        env["PADDLE_CURRENT_ENDPOINT"] = ep
        f = out("workerlog.%d" % i)
        procs.append((subprocess.Popen(cmd, env=env, stdout=f,
                                       stderr=f), f))

    rc = 0
    try:
        # trainers finishing ends the job; pservers are then reaped
        for p, _ in procs[len(servers):]:
            rc = p.wait() or rc
    finally:
        # grace window before reaping: a pserver that is already
        # exiting cleanly (trainers sent complete(), or a short probe
        # script still flushing its log) must not be terminated
        # mid-write, which truncates its log and discards its rc
        import time

        from .launch import _terminate_all

        deadline = time.monotonic() + 5.0
        for p, _ in procs:
            remaining = deadline - time.monotonic()
            if p.poll() is None and remaining > 0:
                try:
                    p.wait(timeout=remaining)
                except subprocess.TimeoutExpired:
                    pass
        # terminate (then kill) whatever is still running
        _terminate_all([p for p, _ in procs], grace_s=5.0)
        for _, f in procs:
            if f:
                f.close()
    return rc


if __name__ == "__main__":
    sys.exit(launch())
