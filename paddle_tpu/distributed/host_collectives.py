"""Host-side (CPU) collectives: barrier / allreduce / allgather /
broadcast over TCP.

Reference parity: `paddle/fluid/framework/fleet/gloo_wrapper.h:106` —
GlooWrapper's Barrier (:146) and AllReduce (:157) used by dataset global
shuffle and the GeneralRoleMaker, with an HdfsStore rendezvous (:45).
TPU-native scope: device collectives ride ICI via XLA; this tier exists
for HOST coordination (dataset shuffle, role-maker barriers) where the
accelerator isn't involved. Rendezvous is rank-0-hosts-a-store over the
same binary RPC as the PS tier (distributed/rpc.py) instead of HDFS.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .rpc import RpcClient, RpcServer, _Stop


class _StoreState:
    """Rank-0 store: keyed blobs + counting barriers. Wait timeout is
    configurable (PADDLE_HC_TIMEOUT_S env or ctor arg) — dataset-sized
    collectives legitimately wait minutes for slow ranks."""

    def __init__(self, world_size, timeout_s=None):
        import os

        self.world = int(world_size)
        self.timeout_s = float(
            timeout_s if timeout_s is not None
            else os.environ.get("PADDLE_HC_TIMEOUT_S", 600))
        self._kv: Dict[str, object] = {}
        self._counts: Dict[str, int] = {}
        self._cv = threading.Condition()

    def handle(self, method, args):
        if method == "hc_put":
            key, val = args[0], args[1]
            with self._cv:
                self._kv[key] = val
                self._counts[key] = self._counts.get(key, 0) + 1
                self._cv.notify_all()
            return []
        if method == "hc_get":
            key, need = args[0], int(args[1])
            with self._cv:
                self._cv.wait_for(
                    lambda: self._counts.get(key, 0) >= need,
                    timeout=self.timeout_s)
                if self._counts.get(key, 0) < need:
                    raise TimeoutError("hc_get %s: %d/%d contributions"
                                       % (key, self._counts.get(key, 0),
                                          need))
                return [self._kv[key]]
        if method == "hc_take":
            # blocking fetch that REMOVES the blob: point-to-point
            # exchange keys pass through the store exactly once, so the
            # store's peak memory stays bounded by in-flight data
            key = args[0]
            with self._cv:
                self._cv.wait_for(lambda: key in self._kv,
                                  timeout=self.timeout_s)
                if key not in self._kv:
                    raise TimeoutError("hc_take %s" % key)
                val = self._kv.pop(key)
                self._counts.pop(key, None)
                return [val]
        if method == "hc_put_part":
            key, rank, val = args[0], int(args[1]), args[2]
            with self._cv:
                self._kv["%s/%d" % (key, rank)] = val
                self._counts[key] = self._counts.get(key, 0) + 1
                self._cv.notify_all()
            return []
        if method == "hc_gather":
            key = args[0]
            with self._cv:
                self._cv.wait_for(
                    lambda: self._counts.get(key, 0) >= self.world,
                    timeout=self.timeout_s)
                if self._counts.get(key, 0) < self.world:
                    raise TimeoutError("hc_gather %s" % key)
                return [self._kv["%s/%d" % (key, r)]
                        for r in range(self.world)]
        if method == "hc_shutdown":
            raise _Stop()
        raise ValueError("unknown host-collective method %r" % method)


class HostCollectiveGroup:
    """Gloo-equivalent group. rank 0 hosts the store; everyone (incl.
    rank 0) talks to it through the same client path."""

    def __init__(self, rank, world_size, store_endpoint,
                 timeout_s=None):
        self.rank = int(rank)
        self.world = int(world_size)
        self._seq = 0
        self._server: Optional[RpcServer] = None
        host, port = store_endpoint.rsplit(":", 1)
        if self.rank == 0:
            state = _StoreState(world_size, timeout_s=timeout_s)
            self._server = RpcServer(host, int(port), state.handle)
            self._server.start()
            port = self._server.port
        self._client = RpcClient("%s:%s" % (host, port))

    def _key(self, tag):
        self._seq += 1
        return "%s#%d" % (tag, self._seq)

    def barrier(self):
        key = self._key("barrier")
        self._client.call("hc_put_part", key, self.rank,
                          np.zeros((1,), np.int8))
        self._client.call("hc_gather", key)

    def all_reduce(self, array, op="sum"):
        key = self._key("allreduce")
        self._client.call("hc_put_part", key, self.rank,
                          np.ascontiguousarray(array))
        parts = self._client.call("hc_gather", key)
        stack = np.stack([np.asarray(p) for p in parts])
        if op == "sum":
            return stack.sum(axis=0)
        if op == "max":
            return stack.max(axis=0)
        if op == "min":
            return stack.min(axis=0)
        if op in ("mean", "avg"):
            return stack.mean(axis=0)
        raise ValueError(op)

    def all_gather(self, array) -> List[np.ndarray]:
        key = self._key("allgather")
        self._client.call("hc_put_part", key, self.rank,
                          np.ascontiguousarray(array))
        return [np.asarray(p) for p in
                self._client.call("hc_gather", key)]

    def put(self, key, array):
        """Point-to-point send half (paired with take)."""
        self._client.call("hc_put", key, np.ascontiguousarray(array))

    def take(self, key) -> np.ndarray:
        """Blocking receive that removes the blob from the store."""
        (val,) = self._client.call("hc_take", key)
        return np.asarray(val)

    def broadcast(self, array, root=0):
        key = self._key("bcast")
        if self.rank == root:
            self._client.call("hc_put", key, np.ascontiguousarray(array))
        (val,) = self._client.call("hc_get", key, 1)
        return np.asarray(val)

    def shutdown(self):
        try:
            if self.rank == 0 and self._server is not None:
                self._client.call("hc_shutdown")
        except Exception:  # noqa: BLE001
            pass
        self._client.close()
        if self._server is not None:
            self._server.shutdown()


def group_from_env() -> Optional[HostCollectiveGroup]:
    """Build the group from the PADDLE_* launch env (role-maker path);
    the store binds on trainer 0's endpoint port + 1."""
    import os

    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if n <= 1 or not eps:
        return None
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    host, port = eps.split(",")[0].rsplit(":", 1)
    return HostCollectiveGroup(rank, n, "%s:%d" % (host, int(port) + 1))
