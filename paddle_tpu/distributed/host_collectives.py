"""Host-side (CPU) collectives: barrier / allreduce / allgather /
broadcast over TCP.

Reference parity: `paddle/fluid/framework/fleet/gloo_wrapper.h:106` —
GlooWrapper's Barrier (:146) and AllReduce (:157) used by dataset global
shuffle and the GeneralRoleMaker, with an HdfsStore rendezvous (:45).
TPU-native scope: device collectives ride ICI via XLA; this tier exists
for HOST coordination (dataset shuffle, role-maker barriers) where the
accelerator isn't involved. Rendezvous is rank-0-hosts-a-store over the
same binary RPC as the PS tier (distributed/rpc.py) instead of HDFS.

Fault tolerance (see distributed/README.md for the env knobs):

- every rank heartbeats the rank-0 store (own socket, so a minutes-long
  blocked gather on the main client never starves liveness); a blocked
  `hc_gather`/`hc_get` fails FAST with "waiting on ranks {3,5} (last
  heartbeat 42s ago)" once a waited-on rank misses its liveness window,
  instead of hanging to the full PADDLE_HC_TIMEOUT_S;
- the store RELEASES each collective's blobs once every rank has
  fetched them, so long runs with per-step barriers/allreduces stay
  bounded (the seed leaked every contributed blob for the run's life);
- the RPC layer underneath retries dropped connections with idempotent
  request dedup, so a mid-collective TCP drop is invisible here.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .rpc import RpcClient, RpcServer, _Stop


def _env_f(name, default):
    return float(os.environ.get(name, default))


class _StoreState:
    """Rank-0 store: keyed blobs + counting barriers. Wait timeout is
    configurable (PADDLE_HC_TIMEOUT_S env or ctor arg) — dataset-sized
    collectives legitimately wait minutes for slow ranks. Liveness is
    separate: a rank that stops heartbeating for PADDLE_HC_LIVENESS_S
    fails waiters immediately."""

    def __init__(self, world_size, timeout_s=None, heartbeat_s=None,
                 liveness_s=None):
        self.world = int(world_size)
        self.timeout_s = float(
            timeout_s if timeout_s is not None
            else _env_f("PADDLE_HC_TIMEOUT_S", 600))
        self.heartbeat_s = float(
            heartbeat_s if heartbeat_s is not None
            else _env_f("PADDLE_HC_HEARTBEAT_S", 2.0))
        self.liveness_s = float(
            liveness_s if liveness_s is not None
            else _env_f("PADDLE_HC_LIVENESS_S",
                        max(15.0, 5 * self.heartbeat_s)))
        # a rank that has NEVER beaten is judged against the (longer)
        # join window, not liveness_s: cold jax imports / container
        # start skew legitimately delay the first heartbeat well past
        # the steady-state liveness window
        self.join_s = _env_f("PADDLE_HC_JOIN_S",
                             max(120.0, 4 * self.liveness_s))
        self._kv: Dict[str, object] = {}
        self._counts: Dict[str, int] = {}
        # key -> ranks that have fetched this collective's result; the
        # last fetch releases the blobs (fix for the seed's unbounded
        # _kv growth across barriers/allreduces)
        self._fetched: Dict[str, set] = {}
        # rank -> last heartbeat (pre-seeded so a rank that dies before
        # its FIRST beat is still detected — via join_s, not liveness_s)
        now = time.monotonic()
        self._beats: Dict[int, float] = {
            r: now for r in range(self.world)}
        self._seen: set = set()  # ranks that have actually beaten
        # ranks that LEFT cleanly (group shutdown): instantly dead for
        # a wait that NAMES them (a gather part, a broadcast root), and
        # excluded from anonymous waits (hc_take / generic hc_get)
        # unless no possible sender remains
        self._left: set = set()
        self._cv = threading.Condition()

    # -- liveness --------------------------------------------------------
    def _wait_or_fail(self, pred, desc_fn, waiting_ranks_fn):
        """Wait (under self._cv) until pred(); fail fast if any rank we
        are waiting on misses its liveness window; TimeoutError at the
        full timeout_s as before. desc_fn is CALLED at raise time so
        the message carries the contribution count as of the failure,
        not as of wait entry."""
        deadline = time.monotonic() + self.timeout_s
        while not pred():
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(desc_fn())
            if self.heartbeat_s > 0:
                waiting = waiting_ranks_fn()
                now = time.monotonic()
                dead = sorted(
                    r for r in waiting
                    if r in self._left
                    or now - self._beats.get(r, now)
                    > (self.liveness_s if r in self._seen
                       else self.join_s))
                if dead:
                    stale = max(now - self._beats[r] for r in dead)
                    raise RuntimeError(
                        "%s: waiting on ranks {%s} (last heartbeat "
                        "%.0fs ago)" % (desc_fn(),
                                        ",".join(map(str, dead)), stale))
            self._cv.wait(timeout=min(0.5, remaining))

    def _release_after_fetch(self, key, rank, blob_keys):
        """Record that `rank` fetched collective `key`; the last rank's
        fetch drops the blobs + bookkeeping. Exactly-once per rank: the
        RPC dedup layer never re-invokes the handler for a retried
        request, so the count can't be inflated by reconnects."""
        got = self._fetched.setdefault(key, set())
        got.add(int(rank))
        if len(got) >= self.world:
            for bk in blob_keys:
                self._kv.pop(bk, None)
            self._counts.pop(key, None)
            self._fetched.pop(key, None)

    def _stale_ranks(self):
        """Ranks presumed DEAD (crashed): stale heartbeat and no clean
        leave. Used as the waiting set where the actual waited-on rank
        is unknown (hc_take, generic hc_get) — a rank that finished and
        shut down cleanly must not poison unrelated waits there."""
        now = time.monotonic()
        return [r for r in range(self.world)
                if r not in self._left
                and now - self._beats.get(r, now) > self.liveness_s]

    # -- dispatch --------------------------------------------------------
    def handle(self, method, args):
        if method == "hc_beat":
            with self._cv:
                r = int(args[0])
                self._beats[r] = time.monotonic()
                self._seen.add(r)
            return []
        if method == "hc_leave":
            with self._cv:
                self._left.add(int(args[0]))
                self._cv.notify_all()  # waiters re-check liveness
            return []
        if method == "hc_put":
            key, val = args[0], args[1]
            with self._cv:
                self._kv[key] = val
                self._counts[key] = self._counts.get(key, 0) + 1
                self._cv.notify_all()
            return []
        if method == "hc_get":
            # optional 3rd arg: calling rank — enables blob release once
            # all ranks have fetched; optional 4th arg: the rank whose
            # put this get is waiting on (broadcast root), so the
            # fast-fail names the actual straggler instead of blaming
            # any stale rank
            key, need = args[0], int(args[1])
            rank = int(args[2]) if len(args) > 2 else None
            src = int(args[3]) if len(args) > 3 else None
            with self._cv:
                self._wait_or_fail(
                    lambda: self._counts.get(key, 0) >= need,
                    lambda: "hc_get %s (%d/%d contributions)"
                    % (key, self._counts.get(key, 0), need),
                    (lambda: [src]) if src is not None
                    else self._stale_ranks)
                val = self._kv[key]
                if rank is not None:
                    self._release_after_fetch(key, rank, [key])
                return [val]
        if method == "hc_peek":
            # non-blocking probe: [val] when the key is present, []
            # otherwise. The preemption notice path polls this at step
            # boundaries — a poll must never wait on anything.
            key = args[0]
            with self._cv:
                if key in self._kv:
                    return [self._kv[key]]
                return []
        if method == "hc_take":
            # blocking fetch that REMOVES the blob: point-to-point
            # exchange keys pass through the store exactly once, so the
            # store's peak memory stays bounded by in-flight data
            key = args[0]
            with self._cv:
                # the intended sender is unknown; fail fast on crashed
                # ranks, and on cleanly-left ranks only once every
                # OTHER rank has left (the caller is the sole survivor,
                # so nobody can ever put this key)
                self._wait_or_fail(
                    lambda: key in self._kv,
                    lambda: "hc_take %s" % key,
                    lambda: (sorted(self._left)
                             if len(self._left) >= self.world - 1
                             else self._stale_ranks()))
                val = self._kv.pop(key)
                self._counts.pop(key, None)
                return [val]
        if method == "hc_put_part":
            key, rank, val = args[0], int(args[1]), args[2]
            with self._cv:
                self._kv["%s/%d" % (key, rank)] = val
                self._counts[key] = self._counts.get(key, 0) + 1
                self._beats[rank] = time.monotonic()
                self._seen.add(rank)
                self._cv.notify_all()
            return []
        if method == "hc_gather":
            key = args[0]
            rank = int(args[1]) if len(args) > 1 else None
            part_keys = ["%s/%d" % (key, r) for r in range(self.world)]
            with self._cv:
                self._wait_or_fail(
                    lambda: self._counts.get(key, 0) >= self.world,
                    lambda: "hc_gather %s (%d/%d contributions)"
                    % (key, self._counts.get(key, 0), self.world),
                    lambda: [r for r in range(self.world)
                             if part_keys[r] not in self._kv])
                out = [self._kv[pk] for pk in part_keys]
                if rank is not None:
                    self._release_after_fetch(key, rank, part_keys)
                return out
        if method == "hc_stats":
            # introspection for tests/debugging: live blob + key counts
            with self._cv:
                return [len(self._kv), len(self._counts),
                        len(self._fetched)]
        if method == "hc_shutdown":
            # don't tear the store down under ranks still DRAINING
            # their last collective: a rank whose response was dropped
            # mid-read (injected or real) retries the fetch, and the
            # dedup replay needs the store alive — rank 0 finishing
            # first must not turn that retry into ConnectionRefused.
            # Wait (bounded) until every rank left cleanly or went
            # heartbeat-stale; crashed ranks never hold shutdown
            # hostage.
            with self._cv:
                deadline = time.monotonic() + min(10.0, self.timeout_s)

                def _drained():
                    now = time.monotonic()
                    return all(
                        r in self._left
                        or now - self._beats.get(r, now)
                        > (self.liveness_s if r in self._seen
                           else self.join_s)
                        for r in range(self.world))

                while not _drained() and time.monotonic() < deadline:
                    self._cv.wait(timeout=0.2)
            raise _Stop()
        raise ValueError("unknown host-collective method %r" % method)


class HostCollectiveGroup:
    """Gloo-equivalent group. rank 0 hosts the store; everyone (incl.
    rank 0) talks to it through the same client path. A background
    heartbeat thread (own socket — the main client can legitimately
    block for minutes inside a gather) keeps this rank live in the
    store; set PADDLE_HC_HEARTBEAT_S=0 to disable."""

    def __init__(self, rank, world_size, store_endpoint,
                 timeout_s=None, heartbeat_s=None, generation=0):
        self.rank = int(rank)
        self.world = int(world_size)
        # elastic generation: bumped by a live mesh resize
        # (distributed/preemption.py). Tags collective schedule keys so
        # the desync analyzer never aliases a pre-resize barrier with a
        # post-resize one that happens to share (op, world, seq).
        self.generation = int(generation)
        self._seq = 0
        self._server: Optional[RpcServer] = None
        self._heartbeat_s = float(
            heartbeat_s if heartbeat_s is not None
            else _env_f("PADDLE_HC_HEARTBEAT_S", 2.0))
        host, port = store_endpoint.rsplit(":", 1)
        if self.rank == 0:
            state = _StoreState(world_size, timeout_s=timeout_s,
                                heartbeat_s=self._heartbeat_s)
            self._server = RpcServer(host, int(port), state.handle)
            self._server.start()
            port = self._server.port
        self._client = RpcClient("%s:%s" % (host, port))
        self._hb_stop = threading.Event()
        self._hb_client: Optional[RpcClient] = None
        self._hb_thread: Optional[threading.Thread] = None
        if self._heartbeat_s > 0:
            # liveness-only traffic: one retry, never the full cycle —
            # a dead store must not wedge each 2s tick for ~45s
            self._hb_client = RpcClient("%s:%s" % (host, port),
                                        call_retries=1)
            try:
                self._hb_client.call("hc_beat", self.rank)
            except Exception:  # noqa: BLE001 - liveness only
                pass
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True,
                name="paddle_tpu-hc-heartbeat-%d" % self.rank)
            self._hb_thread.start()

    def _hb_loop(self):
        while not self._hb_stop.wait(self._heartbeat_s):
            try:
                self._hb_client.call("hc_beat", self.rank)
            except Exception:  # noqa: BLE001 - store may be shutting down
                pass

    def _key(self, tag):
        self._seq += 1
        return "%s#%d" % (tag, self._seq)

    def _comm_lane(self):
        """"dcn" | "ici" | "mp" lane of this group's collectives on a
        multi-pod / model-parallel launch, or None when no hierarchy is
        declared (FLAGS_tpu_dcn_replicas / PADDLE_NUM_PODS and
        PADDLE_MP_DEGREE unset/1 — the flat pre-hybrid reading, no
        extra counters). Pod of rank r = r // (global_world /
        num_pods), the launcher's contiguous-block assignment; a group
        spanning two pods coordinates over the slow DCN link, one
        confined to a single pod stays "ici" — unless the model axis
        is live and the group stays inside one aligned mp-block (all
        ranks share r // mp: same pod, same replica — model is
        INNERMOST in the (dcn, replica, model) factorization), which
        is tensor-parallel coordination: lane "mp". Today's full-world
        groups therefore classify as "dcn" whenever pods > 1 —
        cross-rank host coordination IS cross-pod traffic there."""
        lane = getattr(self, "_comm_lane_cached", False)
        if lane is not False:
            return lane
        from ..parallel import env as penv

        npods = penv.dcn_replicas()
        mp = penv.model_parallel_degree()
        if (npods <= 1 and mp <= 1) or self.world <= 1:
            lane = None
        else:
            # pod size derives from the GLOBAL launch world (this
            # group may span a subset of it), never less than 1
            try:
                gw = int(os.environ.get("PADDLE_TRAINERS_NUM", "0")
                         or 0) or self.world
            except ValueError:
                gw = self.world
            per_pod = max(1, gw // max(npods, 1))
            ranks = range(self.world)
            pods = {r // per_pod for r in ranks}
            if len(pods) > 1:
                lane = "dcn"
            elif mp > 1 and len({r // mp for r in ranks}) == 1:
                lane = "mp"
            else:
                lane = "ici"
        self._comm_lane_cached = lane
        return lane

    @contextlib.contextmanager
    def _comm_phase(self, op=None, key=None, payload=None):
        """Account host-collective wall time to the profiler's `comm`
        step phase (the executor keeps `host` disjoint from it), so a
        step blocked on cross-rank coordination shows as comm, not as
        anonymous host time. A completed collective also lands a
        telemetry "collective" event carrying its cross-rank `key`
        (ranks issue collectives in lockstep, so key N completes at
        ~the same wall instant everywhere — tools/timeline.py uses
        these as clock-sync anchors when merging per-rank JSONL).

        Yields an in-flight trace token (observability/watchdog.py —
        the NCCL-flight-recorder idiom): enqueue is recorded here, the
        collective body marks `arrived()` once this rank's part landed
        in the store, and completion/failure is recorded on exit. The
        hang watchdog and the offline desync analyzer read that table;
        a wedged rank's token still in state "inflight" is the one
        that never arrived."""
        from ..fluid import profiler as _prof

        tok = None
        if op is not None:
            try:
                from ..observability import watchdog as _wd

                _wd.maybe_install()
                tok = _wd.trace().begin(
                    op, key, tier="host", world=self.world,
                    rank=self.rank,
                    dtype=None if payload is None else payload.dtype,
                    shape=None if payload is None else payload.shape,
                    nbytes=None if payload is None else payload.nbytes,
                    region=("gen%d" % self.generation
                            if self.generation else None))
            except Exception:  # noqa: BLE001 - tracing never gates comm
                tok = None
        t0 = time.perf_counter()
        ok = False
        try:
            yield tok
            ok = True
        finally:
            if tok is not None:
                try:
                    tok.done(ok)
                except Exception:  # noqa: BLE001
                    pass
            dt = time.perf_counter() - t0
            _prof.record_step_phase("comm", dt, t0)
            # multi-pod launches (PADDLE_NUM_PODS > 1): break the comm
            # phase down by interconnect lane — a group whose rank set
            # spans two pods coordinates over the slow DCN link; a
            # within-pod group stays on the fast tier. Counter-only
            # (no second trace span — it is the SAME wall time).
            lane = self._comm_lane()
            if lane is not None:
                _prof.record_step_phase("comm_" + lane, dt)
            if ok and op is not None:
                try:
                    from ..observability.registry import registry

                    registry().event("collective", op=op, key=key,
                                     dur_ms=round(dt * 1e3, 4))
                except Exception:  # noqa: BLE001 - telemetry only
                    pass

    def barrier(self):
        key = self._key("barrier")
        with self._comm_phase("barrier", key) as tok:
            self._client.call("hc_put_part", key, self.rank,
                              np.zeros((1,), np.int8))
            if tok is not None:
                tok.arrived()
            self._client.call("hc_gather", key, self.rank)

    def all_reduce(self, array, op="sum"):
        key = self._key("allreduce")
        buf = np.ascontiguousarray(array)
        with self._comm_phase("allreduce", key, payload=buf) as tok:
            self._client.call("hc_put_part", key, self.rank, buf)
            if tok is not None:
                tok.arrived()
            parts = self._client.call("hc_gather", key, self.rank)
        stack = np.stack([np.asarray(p) for p in parts])
        if op == "sum":
            return stack.sum(axis=0)
        if op == "max":
            return stack.max(axis=0)
        if op == "min":
            return stack.min(axis=0)
        if op in ("mean", "avg"):
            return stack.mean(axis=0)
        raise ValueError(op)

    def all_gather(self, array) -> List[np.ndarray]:
        key = self._key("allgather")
        buf = np.ascontiguousarray(array)
        with self._comm_phase("allgather", key, payload=buf) as tok:
            self._client.call("hc_put_part", key, self.rank, buf)
            if tok is not None:
                tok.arrived()
            parts = self._client.call("hc_gather", key, self.rank)
        return [np.asarray(p) for p in parts]

    def put(self, key, array):
        """Point-to-point send half (paired with take)."""
        self._client.call("hc_put", key, np.ascontiguousarray(array))

    def take(self, key) -> np.ndarray:
        """Blocking receive that removes the blob from the store."""
        (val,) = self._client.call("hc_take", key)
        return np.asarray(val)

    def broadcast(self, array, root=0):
        key = self._key("bcast")
        buf = np.ascontiguousarray(array)
        with self._comm_phase("broadcast", key, payload=buf) as tok:
            if self.rank == root:
                self._client.call("hc_put", key, buf)
            if tok is not None:
                # the root's contribution is its put; a non-root has
                # nothing to contribute — only the blocking get remains
                tok.arrived()
            (val,) = self._client.call("hc_get", key, 1, self.rank,
                                       root)
        return np.asarray(val)

    def peek(self, key) -> Optional[np.ndarray]:
        """Non-blocking probe: the blob under `key`, or None. Leaves
        the blob in the store (take() is the consuming read)."""
        vals = self._client.call("hc_peek", key)
        if not vals:
            return None
        return np.asarray(vals[0])

    def store_stats(self):
        """(n_blobs, n_counts, n_pending_fetch) on the rank-0 store —
        lets tests assert the leak fix holds."""
        return tuple(int(x) for x in self._client.call("hc_stats"))

    def _detach(self):
        self._hb_stop.set()
        # teardown is best-effort: don't let the full retry cycle
        # stall shutdown when the store host is already gone
        self._client._call_retries = min(self._client._call_retries, 1)
        try:
            # clean leave: this rank stops heartbeating but must not be
            # mistaken for a crash by waits that don't involve it
            self._client.call("hc_leave", self.rank)
        except Exception:  # noqa: BLE001 - store may already be down
            pass

    def _close_clients(self):
        self._client.close()
        if self._hb_client is not None:
            self._hb_client.close()

    def leave(self):
        """Detach this rank from the group WITHOUT tearing the store
        down: stop heartbeating, mark a clean leave, close sockets.
        The live-resize seam (distributed/preemption.py) uses this on
        survivors — the old rank-0 store must stay up until every old
        member has left, then rank 0's shutdown() drains it."""
        self._detach()
        self._close_clients()

    def shutdown(self):
        self._detach()
        try:
            if self.rank == 0 and self._server is not None:
                self._client.call("hc_shutdown")
        except Exception:  # noqa: BLE001
            pass
        self._close_clients()
        if self._server is not None:
            self._server.shutdown()


def group_from_env() -> Optional[HostCollectiveGroup]:
    """Build the group from the PADDLE_* launch env (role-maker path);
    the store binds on trainer 0's endpoint port + 1."""
    import os

    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if n <= 1 or not eps:
        return None
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    host, port = eps.split(",")[0].rsplit(":", 1)
    return HostCollectiveGroup(rank, n, "%s:%d" % (host, int(port) + 1))
