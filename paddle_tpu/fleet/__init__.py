"""Fleet — unified distributed training API.

Reference parity: `python/paddle/fleet/base/fleet_base.py:25-233` (2.0 API)
and `python/paddle/fluid/incubate/fleet/collective/__init__.py:64-468`
(CollectiveOptimizer + transpiler flow, SURVEY.md §3C):

  fleet.init(role_maker) ; opt = fleet.distributed_optimizer(opt, strategy)
  opt.minimize(loss) ; exe.run(...)

TPU-native: `minimize` runs the normal backward+optimizer build, then the
collective transpiler marks the program data-parallel over the device mesh,
scales the loss cotangent by 1/nranks (reference: transpiler/collective.py
:190 scale op) and inserts `c_allreduce_sum` on every gradient (reference:
:209-260); lowering executes them as `lax.psum` over ICI inside one
shard_map'd XLA program. `c_gen_nccl_id`/`c_comm_init` collapse into mesh
construction (ring 0 -> axis "dp").
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..fluid import framework
from ..fluid.framework import Operator
from ..parallel import env as penv
from .role_maker import (  # noqa: F401
    RoleMakerBase, PaddleCloudRoleMaker, UserDefinedRoleMaker, Role,
)
from . import metrics  # noqa: F401  (fleet.metrics.* helpers)
from . import util  # noqa: F401  (fleet.util collective helpers)


class DistributedStrategy:
    """Strategy knobs (reference: `framework/distributed_strategy.proto:25`
    backing `fleet/base/distributed_strategy.py:57`). Knobs that exist to
    work around GPU limits (fuse_all_reduce, nccl_comm_num) are accepted
    but XLA's collective scheduler owns them.
    `use_hierarchical_allreduce` + `hierarchical_allreduce_inter_nranks`
    are REAL now: they set `FLAGS_tpu_dcn_replicas` (unless already
    set), factoring the dp world into a hybrid (dcn, ici) mesh whose
    grad syncs lower hierarchically — see parallel/README.md
    "Hierarchical collectives"."""

    def __init__(self):
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.pipeline = False
        self.pipeline_configs = {"micro_batch": 1}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1}
        self.dgc = False
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.lars = False
        self.lamb = False
        self.sync_nccl_allreduce = True
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 1
        self.sync_batch_norm = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.elastic = False
        self.elastic_configs = {"checkpoint_dir": "", "save_steps": 100,
                                "max_checkpoints": 3}
        self.auto = False
        self.auto_configs = {}
        self.a_sync = False
        self.a_sync_configs = {}

    # fluid-era aliases (incubate DistributedStrategy fields)
    @property
    def forward_recompute(self):
        return self.recompute

    @forward_recompute.setter
    def forward_recompute(self, v):
        self.recompute = v

    # -- serialization (reference: distributed_strategy.proto text
    # format via save_to_prototxt/load_from_prototxt,
    # fleet/base/distributed_strategy.py:57) ----------------------------
    def _fields(self):
        return {k: v for k, v in vars(self).items()
                if not k.startswith("_")}

    @staticmethod
    def _proto_scalar(v):
        """One scalar in protobuf TEXT format (lowercase bools,
        double-quoted strings with C escapes, plain numbers) — the
        format the reference's protobuf-backed strategy writes
        (distributed_strategy.proto:25-81)."""
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, str):
            return '"%s"' % (v.replace("\\", "\\\\").replace('"', '\\"')
                             .replace("\n", "\\n"))
        return repr(v)

    def save_to_prototxt(self, path):
        """Real protobuf text format: scalar knobs as `name: value`,
        lists as REPEATED `name: value` lines, config dicts as nested
        `name { ... }` blocks. A prototxt written here parses with
        protobuf's own text_format against the reference's
        DistributedStrategy message field set, and vice versa."""
        lines = []
        for k, v in sorted(self._fields().items()):
            if isinstance(v, dict):
                lines.append("%s {" % k)
                for ck, cv in sorted(v.items()):
                    if isinstance(cv, (list, tuple)):
                        for item in cv:
                            lines.append("  %s: %s"
                                         % (ck, self._proto_scalar(item)))
                    else:
                        lines.append("  %s: %s"
                                     % (ck, self._proto_scalar(cv)))
                lines.append("}")
            elif isinstance(v, (list, tuple)):
                for item in v:
                    lines.append("%s: %s" % (k, self._proto_scalar(item)))
            else:
                lines.append("%s: %s" % (k, self._proto_scalar(v)))
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")

    @staticmethod
    def _parse_scalar(tok):
        """Protobuf text scalar -> python; legacy round-2 files wrote
        Python reprs (True, 'str'), still accepted as a fallback."""
        import ast as _ast

        tok = tok.strip()
        if tok == "true":
            return True
        if tok == "false":
            return False
        if tok.startswith('"') and tok.endswith('"'):
            body = tok[1:-1]
            # left-to-right unescape: replace-chains corrupt strings
            # holding a literal backslash before 'n' (code-review r4)
            out, i = [], 0
            esc = {"n": "\n", '"': '"', "\\": "\\", "t": "\t"}
            while i < len(body):
                if body[i] == "\\" and i + 1 < len(body):
                    out.append(esc.get(body[i + 1],
                                       "\\" + body[i + 1]))
                    i += 2
                else:
                    out.append(body[i])
                    i += 1
            return "".join(out)
        try:
            return _ast.literal_eval(tok)
        except (ValueError, SyntaxError):
            return tok  # bare enum-style token

    def load_from_prototxt(self, path):
        with open(path) as f:
            lines = [ln.rstrip() for ln in f
                     if ln.strip() and not ln.strip().startswith("#")]
        i = 0
        seen_scalars = set()
        while i < len(lines):
            ln = lines[i].strip()
            if ln.endswith("{"):
                name = ln[:-1].strip()
                # merge into the default config dict: keys absent from
                # the file keep their defaults (proto unset-field
                # semantics), and a key whose DEFAULT is a list stays a
                # list even with one occurrence (repeated field)
                base = getattr(self, name, None)
                block = dict(base) if isinstance(base, dict) else {}
                repeated = {k for k, v in block.items()
                            if isinstance(v, list)}
                seen_block = set()
                i += 1
                while i < len(lines) and lines[i].strip() != "}":
                    ck, cv = lines[i].strip().split(":", 1)
                    ck = ck.strip()
                    val = self._parse_scalar(cv)
                    if ck in seen_block:
                        prev = block[ck]
                        block[ck] = (prev if isinstance(prev, list)
                                     else [prev]) + [val]
                    else:
                        # legacy repr files already encode lists as one
                        # token; never double-wrap them
                        block[ck] = (val if isinstance(val, list)
                                     else [val] if ck in repeated
                                     else val)
                        seen_block.add(ck)
                    i += 1
                setattr(self, name, block)
            else:
                k, v = ln.split(":", 1)
                k = k.strip()
                val = self._parse_scalar(v)
                if k in seen_scalars:
                    prev = getattr(self, k)
                    setattr(self, k, (prev if isinstance(prev, list)
                                      else [prev]) + [val])
                elif isinstance(getattr(self, k, None), list):
                    setattr(self, k,
                            val if isinstance(val, list) else [val])
                    seen_scalars.add(k)
                else:
                    setattr(self, k, val)
                    seen_scalars.add(k)
            i += 1
        return self


class _Fleet:
    def __init__(self):
        self._role_maker = None
        self._is_collective = False
        self._inited = False
        self._strategy = None

    # -- init / topology ---------------------------------------------------
    def init(self, role_maker=None, is_collective=True):
        self._role_maker = role_maker or PaddleCloudRoleMaker(
            is_collective=is_collective)
        self._is_collective = is_collective
        self._inited = True
        # a re-init starts a fresh topology: drop any PS transpile
        # stashed by a previous minimize so stale server state cannot
        # leak across runs
        self._ps_transpiler = None
        self._pserver_prog = None
        # wire fleet.util to this topology (reference: UtilFactory
        # _set_role_maker at fleet init) — without it get_file_shard/
        # print_on_rank silently behave single-worker
        util._util._set_role_maker(self._role_maker)
        # multi-host bootstrap over DCN (replaces nccl-id TCP exchange)
        # — collective mode only: PS processes must NOT join a jax
        # distributed rendezvous (under launch_ps every role sees
        # PADDLE_TRAINER_ENDPOINTS and pservers would deadlock in
        # jax.distributed.initialize)
        if is_collective and self.worker_num() > 1:
            from ..distributed import init_parallel_env

            init_parallel_env()
        return self

    def is_first_worker(self):
        return self.worker_index() == 0

    def worker_index(self):
        return self._role_maker.worker_index() if self._role_maker else 0

    def worker_num(self):
        return self._role_maker.worker_num() if self._role_maker else 1

    def is_worker(self):
        if self._role_maker is not None:
            return self._role_maker.is_worker()
        return True

    def worker_endpoints(self, to_string=False):
        eps = penv.trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def is_server(self):
        return (self._role_maker.is_server()
                if self._role_maker is not None else False)

    def server_num(self):
        return (self._role_maker.server_num()
                if self._role_maker is not None else 0)

    def server_index(self):
        return (self._role_maker.server_index()
                if self._role_maker is not None else 0)

    def server_endpoints(self, to_string=False):
        eps = (self._role_maker.get_pserver_endpoints()
               if self._role_maker is not None else [])
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        pass

    def stop_worker(self):
        pass

    # -- optimizer ---------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._strategy = strategy or DistributedStrategy()
        util._util._set_strategy(self._strategy)
        return CollectiveOptimizer(optimizer, self._strategy)

    # -- checkpoint (reference: fleet/collective/__init__.py:236,294) ------
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from ..fluid import io

        return io.save_inference_model(dirname, feeded_var_names,
                                       target_vars, executor, main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from ..fluid import io

        return io.save_persistables(executor, dirname, main_program)

    def init_worker(self, timeout=120.0):
        """PS mode: block until every pserver port accepts connections
        (reference: fleet_base init_worker -> wait_server_ready). A
        real wait — relying on the RPC client's fixed 15s first-step
        retry loses the race on slow hosts."""
        if getattr(self, "_ps_transpiler", None) is None:
            return
        import socket
        import time as _time

        eps = (self._role_maker.get_pserver_endpoints()
               if self._role_maker else [])
        deadline = _time.monotonic() + timeout
        for ep in eps:
            host, port = ep.rsplit(":", 1)
            while True:
                try:
                    with socket.create_connection((host, int(port)),
                                                  timeout=2.0):
                        break
                except OSError:
                    if _time.monotonic() > deadline:
                        raise TimeoutError(
                            "init_worker: pserver %s not reachable "
                            "within %.0fs" % (ep, timeout))
                    _time.sleep(0.25)

    def init_server(self, *a, **k):
        """PS mode: build this server's program pair from the transpile
        stored by distributed_optimizer().minimize()."""
        t = getattr(self, "_ps_transpiler", None)
        if t is None or not self.is_server():
            return
        ep = self._ps_my_endpoint
        self._pserver_prog = t.get_pserver_program(ep)
        self._pserver_startup = t.get_startup_program(
            ep, self._pserver_prog)

    def run_server(self):
        """PS mode: serve until every trainer sent its completion
        barrier (reference: listen_and_serv_op.cc:336 main loop)."""
        if getattr(self, "_pserver_prog", None) is None \
                or not self.is_server():
            return
        from ..distributed.ps import listen_and_serv

        listen_and_serv(self._pserver_prog, self._pserver_startup,
                        endpoint=self._ps_my_endpoint,
                        trainers=self._ps_n_trainers,
                        mode=self._ps_mode)


fleet = _Fleet()

# module-level 2.0-style API
init = fleet.init
distributed_optimizer = fleet.distributed_optimizer
worker_index = fleet.worker_index
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker


class CollectiveOptimizer:
    """Reference: CollectiveOptimizer (incubate/fleet/collective:393) +
    GradAllReduce transpiler (transpiler/collective.py:178)."""

    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy or DistributedStrategy()

    def __getattr__(self, item):
        return getattr(self._optimizer, item)

    def backward(self, *a, **k):
        return self._optimizer.backward(*a, **k)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        import warnings

        st = self._strategy
        # fleet 2.0 meta-optimizer composition (reference:
        # fleet/base/strategy_compiler.py + meta_optimizers/): each knob
        # maps to a wrapper; unimplementable knobs warn loudly
        from .meta_optimizers import compose

        inner, self._applied_meta_list = compose(st, self._optimizer)
        optimize_ops, params_grads = inner.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        pcfg = getattr(loss.block.program, "_pipeline_cfg", None)
        if st.pipeline or pcfg is not None:
            # dp x pp composition: the pipeline engine owns the mesh;
            # fleet contributes the data-parallel degree (devices not
            # consumed by pipeline stages become replicas of the whole
            # pipeline — reference: fleet pipeline+collective mode,
            # optimizer.py:3634 + transpiler/collective.py:178)
            if pcfg is None:
                warnings.warn("strategy.pipeline is set but the inner "
                              "optimizer is not a PipelineOptimizer; no "
                              "pipeline cut to replicate.")
            else:
                import jax

                from ..parallel.pipeline import n_pipeline_stages

                n_stages = n_pipeline_stages(loss.block.program)
                n_dev = len(jax.devices())
                dp = max(1, n_dev // n_stages)
                if dp * n_stages != n_dev:
                    warnings.warn(
                        "pipeline dp x pp: %d devices not divisible by %d "
                        "stages; using dp=%d over the first %d devices"
                        % (n_dev, n_stages, dp, dp * n_stages))
                pcfg["dp"] = dp
        elif getattr(st, "a_sync", False) and self._transpile_ps(
                loss, startup_program, st):
            pass  # PS transpile done; programs rewritten in place
        elif getattr(st, "auto", False):
            # auto-parallel: no collective-op rewrite — mark the program
            # and let lowering run the dp x tp GSPMD sharding search
            # (parallel/auto_parallel.py; reference reserves the knob at
            # distributed_strategy.proto:401 but never implements it)
            loss.block.program._auto_parallel = dict(
                getattr(st, "auto_configs", {}) or {})
        else:
            dgc_cfg = None
            if getattr(st, "dgc", False):
                cfgs = getattr(st, "dgc_configs", {}) or {}
                from ..fluid.optimizer import normalize_dgc_cfg

                dgc_cfg = normalize_dgc_cfg(
                    getattr(self._optimizer, "_momentum", 0.9),
                    cfgs.get("sparsity", 0.75),
                    cfgs.get("rampup_begin_step", 0))
            if getattr(st, "use_hierarchical_allreduce", False) and \
                    int(getattr(st,
                                "hierarchical_allreduce_inter_nranks",
                                1) or 1) > 1:
                # the reference's GPU hierarchical-allreduce knob maps
                # onto the REAL hybrid (dcn, ici) mesh now:
                # inter_nranks = the cross-pod (dcn) degree. Same
                # precedence as the launcher env — an explicit
                # FLAGS_tpu_dcn_replicas wins.
                from ..utils.flags import get_flag, set_flags

                if not int(get_flag("FLAGS_tpu_dcn_replicas", 0) or 0):
                    set_flags({"FLAGS_tpu_dcn_replicas": int(
                        st.hierarchical_allreduce_inter_nranks)})
            transpile_collective(
                loss.block.program,
                k_steps_localsgd=(st.localsgd_configs["k_steps"]
                                  if st.localsgd else 0),
                dgc_cfg=dgc_cfg,
                sync_batch_norm=getattr(st, "sync_batch_norm", False))
        if getattr(st, "elastic", False):
            # preemption checkpoint/auto-resume every save_steps
            # (reference: elastic reserved at
            # distributed_strategy.proto:301; machinery:
            # fluid/checkpoint.py numbered dirs + TrainStatus)
            loss.block.program._elastic_cfg = dict(
                getattr(st, "elastic_configs", {}) or {})
        return optimize_ops, params_grads

    def _transpile_ps(self, loss, startup_program, st):
        """Fleet 2.0 parameter-server mode (strategy.a_sync; reference:
        fleet parameter_server runtime over the DistributeTranspiler):
        rewrite the trainer program for PS training and stash the
        transpile on the fleet singleton so init_server/run_server/
        init_worker drive the existing PS tier (distributed/ps.py).
        a_sync_configs: k_steps>0 selects geo-SGD with that push
        interval; half_async=True the bounded-staleness mode; else pure
        async. Returns False (caller falls back to collective) when no
        pserver endpoints are configured."""
        import warnings

        from ..fluid import framework as fw
        from ..fluid.transpiler import (DistributeTranspiler,
                                        DistributeTranspilerConfig)

        rm = fleet._role_maker
        eps = rm.get_pserver_endpoints() if rm is not None else []
        if not eps:
            warnings.warn(
                "DistributedStrategy.a_sync is set but no pserver "
                "endpoints are configured (fleet.init with a PS role "
                "maker, or PADDLE_PSERVERS_IP_PORT_LIST); running "
                "collective (sync) instead.")
            return False

        cfg_map = dict(getattr(st, "a_sync_configs", {}) or {})
        k_steps = int(cfg_map.get("k_steps", 0) or 0)
        cfg = DistributeTranspilerConfig()
        mode = "async"
        if k_steps > 0:
            cfg.geo_sgd_mode = True
            cfg.geo_sgd_need_push_nums = k_steps
            mode = "geo"
        elif cfg_map.get("half_async"):
            cfg.half_async = True
            mode = "half_async"
        t = DistributeTranspiler(config=cfg)
        n_trainers = rm.worker_num()
        tid = rm.worker_index() if rm.is_worker() else 0
        t.transpile(tid, program=loss.block.program,
                    pservers=",".join(eps), trainers=n_trainers,
                    sync_mode=False,
                    startup_program=(startup_program
                                     or fw.default_startup_program()))
        fleet._ps_transpiler = t
        fleet._ps_mode = mode
        fleet._ps_n_trainers = n_trainers
        fleet._ps_my_endpoint = (eps[rm.server_index()]
                                 if rm.is_server() else None)
        return True


def transpile_collective(program, nranks=None, k_steps_localsgd=0,
                         dgc_cfg=None, sync_batch_norm=False):
    """GradAllReduce program rewrite (reference: transpiler/collective.py:
    178-268). Marks the program DP over the local mesh, scales the loss
    cotangent 1/nranks, inserts c_allreduce_sum per gradient.
    sync_batch_norm: rewrite batch_norm ops to the sync variant whose
    moments pmean over the dp axis (reference sync_batch_norm_op.cu via
    ncclAllReduce; here jax.vjp through lax.pmean gives the matching
    synchronized backward for free)."""
    import jax

    if nranks is None:
        nranks = len(jax.devices())
    if nranks <= 1:
        return program
    from jax.sharding import Mesh

    # hybrid multi-pod factorization (FLAGS_tpu_dcn_replicas /
    # PADDLE_NUM_PODS > 1): the dp world becomes a (dcn, ici) mesh and
    # ring 0 spans the axis PAIR — grad c_allreduce_sum ops lower
    # hierarchically through the sharded-update plan (reduce-scatter
    # over ici, cross-pod psum over dcn) or, unplanned, as a psum over
    # both axes. Flat default unchanged byte-for-byte.
    mesh = penv.create_hybrid_mesh(nranks=nranks)
    if mesh is not None:
        program._dp_axis = penv.ICI_AXIS
        program._dcn_axis = penv.DCN_AXIS
        penv.register_ring(0, (penv.DCN_AXIS, penv.ICI_AXIS), nranks)
    else:
        mesh = Mesh(np.array(jax.devices()[:nranks]), ("dp",))
        program._dp_axis = "dp"
        penv.register_ring(0, "dp", nranks)
    program._data_parallel = True
    program._mesh = mesh
    penv.set_global_mesh(mesh)

    if sync_batch_norm:
        # the moments must sync over the WHOLE dp world: on a hybrid
        # mesh that is the (dcn, ici) axis pair — "dp" would be an
        # unbound axis name inside the shard_map and crash the step
        bn_axis = (penv.DCN_AXIS, penv.ICI_AXIS) \
            if program._dp_axis == penv.ICI_AXIS else program._dp_axis
        n_swapped = 0
        for bi in range(program.num_blocks):
            for op in program.block(bi).ops:
                if op.type == "batch_norm":
                    op.type = "sync_batch_norm"
                    op.attrs["axis_name"] = bn_axis
                    n_swapped += 1
        if n_swapped:
            program._version += 1

    block = program.global_block()
    bwd_idx = None
    for i, op in enumerate(block.ops):
        if op.type == "backward":
            bwd_idx = i
            break
    if bwd_idx is None:
        return program
    bop = block.ops[bwd_idx]
    # loss-grad scaling (reference: transpiler/collective.py:190)
    bop.attrs["loss_scale"] = bop.attrs.get("loss_scale", 1.0) / nranks

    grad_names = list(bop.output_names.get("Grad", []))
    dgc_cfg = dgc_cfg or getattr(program, "_dgc_cfg", None)
    ar_ops = []
    for g in grad_names:
        if dgc_cfg is not None:
            _insert_dgc(program, block, g, dgc_cfg, ar_ops)
        op = Operator(block, "c_allreduce_sum",
                      inputs={"X": [g]}, outputs={"Out": [g]},
                      attrs={"ring_id": 0, "use_calc_stream": True})
        ar_ops.append(op)
    block.ops[bwd_idx + 1:bwd_idx + 1] = ar_ops
    program._version += 1
    return program


def _insert_dgc(program, block, grad_name, cfg, ops_out):
    """Plant the dgc op (momentum-corrected top-k sparsification,
    reference `operators/dgc_op.cc`) before the grad's allreduce, with
    persistable U/V residual accumulators and a step counter."""
    gvar = block._find_var_recursive(grad_name)
    shape = tuple(gvar.shape) if gvar is not None else None
    from ..core.scope import global_scope
    import jax.numpy as jnp

    def state(name, sshape, value=0.0):
        if name not in block.vars:
            v = block.create_var(name=name, shape=sshape,
                                 dtype="float32", persistable=True)
            v.stop_gradient = True
        if global_scope().find_var(name) is None:
            global_scope().set_var(
                name, jnp.full(sshape, value, jnp.float32))
        return name

    u = state(grad_name + "@DGC_U", shape)
    v = state(grad_name + "@DGC_V", shape)
    step = state(grad_name + "@DGC_STEP", (1,))
    ops_out.append(Operator(
        block, "dgc",
        inputs={"Grad": [grad_name], "U": [u], "V": [v],
                "Step": [step]},
        outputs={"UOut": [u], "VOut": [v], "EncodeGrad": [grad_name],
                 "StepOut": [step]},
        attrs={"momentum": cfg.get("momentum", 0.9),
               "sparsity": cfg.get("sparsity", 0.75),
               "rampup_begin_step": cfg.get("rampup_begin_step", 0)}))
