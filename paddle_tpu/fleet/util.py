"""fleet.util — cross-worker utility collectives (reference:
`python/paddle/fleet/base/util_factory.py:31` UtilBase, whose methods
are all commented-out WIP there; here they WORK, over the host
collective tier of `distributed/host_collectives.py` when a multi-host
group is up, degrading to single-process identities otherwise)."""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class UtilBase:
    def __init__(self):
        self.role_maker = None
        self.dist_strategy = None

    def _set_strategy(self, dist_strategy):
        self.dist_strategy = dist_strategy

    def _set_role_maker(self, role_maker):
        self.role_maker = role_maker

    # -- collectives over the host tier --------------------------------
    def _group(self):
        from ..distributed.host_collectives import group_from_env

        return group_from_env()

    def barrier(self):
        g = self._group()
        if g is not None:
            g.barrier()

    def all_reduce(self, input, mode="sum"):
        """Elementwise allreduce of a numpy array across workers
        (sum/max/min); identity on a single process."""
        a = np.asarray(input)
        g = self._group()
        if g is None:
            return a
        return g.all_reduce(a, op=mode)

    def all_gather(self, input) -> List[np.ndarray]:
        a = np.asarray(input)
        g = self._group()
        if g is None:
            return [a]
        return g.all_gather(a)

    def broadcast(self, input, root=0):
        a = np.asarray(input)
        g = self._group()
        if g is None:
            return a
        return g.broadcast(a, root=root)

    # -- sharding helpers ----------------------------------------------
    def get_file_shard(self, files) -> List[str]:
        """This worker's contiguous slice of `files` (reference
        contract: remainder spread over the first workers)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file names")
        rm = self.role_maker
        n = rm.worker_num() if rm is not None else 1
        idx = rm.worker_index() if rm is not None else 0
        per, rem = divmod(len(files), n)
        start = per * idx + min(idx, rem)
        return files[start:start + per + (1 if idx < rem else 0)]

    def print_on_rank(self, message, rank_id=0):
        rm = self.role_maker
        myrank = rm.worker_index() if rm is not None else 0
        if myrank == int(rank_id):
            print(message, flush=True)


_util = UtilBase()


def __getattr__(name):  # pragma: no cover - module-attr convenience
    return getattr(_util, name)
