"""Fleet 2.0 meta-optimizer composition.

Reference parity: `python/paddle/fleet/meta_optimizers/` +
`fleet/base/strategy_compiler.py` — each DistributedStrategy knob maps to
a meta-optimizer that wraps the user optimizer; the StrategyCompiler
resolves which apply and in what order. TPU-native: the wrappers reuse
the real fluid machinery (RecomputeOptimizer -> jax.checkpoint segments,
GradientMergeOptimizer -> lax.cond accumulation, PipelineOptimizer ->
shard_map GPipe engine, AMP -> bf16 cast insertion), so composition is
pure configuration, not new execution paths.
"""
from __future__ import annotations

import warnings
from typing import List


class MetaOptimizerBase:
    """One strategy knob -> one wrapper (reference:
    meta_optimizers/meta_optimizer_base.py)."""

    name = "base"

    def can_apply(self, strategy, optimizer) -> bool:
        raise NotImplementedError

    def apply(self, strategy, optimizer):
        raise NotImplementedError


class RecomputeMetaOptimizer(MetaOptimizerBase):
    name = "recompute"

    def can_apply(self, strategy, optimizer):
        return strategy.recompute and \
            strategy.recompute_configs.get("checkpoints")

    def apply(self, strategy, optimizer):
        from ..fluid.optimizer import RecomputeOptimizer

        inner = RecomputeOptimizer(optimizer)
        inner._set_checkpoints(
            strategy.recompute_configs["checkpoints"])
        return inner


class GradientMergeMetaOptimizer(MetaOptimizerBase):
    name = "gradient_merge"

    def can_apply(self, strategy, optimizer):
        if strategy.gradient_merge and strategy.pipeline:
            warnings.warn("gradient_merge + pipeline both set; pipeline's "
                          "own microbatching wins, gradient_merge "
                          "ignored.")
            return False
        return strategy.gradient_merge

    def apply(self, strategy, optimizer):
        from ..fluid.optimizer import GradientMergeOptimizer

        cfg = strategy.gradient_merge_configs
        return GradientMergeOptimizer(
            optimizer, k_steps=int(cfg.get("k_steps", 1)),
            avg=bool(cfg.get("avg", True)))


class PipelineMetaOptimizer(MetaOptimizerBase):
    name = "pipeline"

    def can_apply(self, strategy, optimizer):
        return strategy.pipeline

    def apply(self, strategy, optimizer):
        from ..fluid.optimizer import PipelineOptimizer

        cfg = strategy.pipeline_configs
        return PipelineOptimizer(
            optimizer, cut_list=cfg.get("cut_list"),
            num_microbatches=int(cfg.get("micro_batch", 1)))


class AMPMetaOptimizer(MetaOptimizerBase):
    name = "amp"

    def can_apply(self, strategy, optimizer):
        return strategy.amp

    def apply(self, strategy, optimizer):
        from ..fluid.contrib import mixed_precision

        return mixed_precision.decorate(optimizer,
                                        **strategy.amp_configs)


class LambMetaOptimizer(MetaOptimizerBase):
    name = "lamb"

    def can_apply(self, strategy, optimizer):
        return strategy.lamb and \
            not type(optimizer).__name__.startswith("Lamb")

    def apply(self, strategy, optimizer):
        from ..fluid.optimizer import AdamOptimizer, LambOptimizer

        kw = {}
        if isinstance(optimizer, AdamOptimizer):
            kw = {"beta1": optimizer._beta1, "beta2": optimizer._beta2,
                  "epsilon": optimizer._epsilon}
        return LambOptimizer(
            learning_rate=optimizer._learning_rate,
            regularization=getattr(optimizer, "regularization", None),
            grad_clip=getattr(optimizer, "_grad_clip", None), **kw)


class LarsMetaOptimizer(MetaOptimizerBase):
    name = "lars"

    def can_apply(self, strategy, optimizer):
        return strategy.lars and \
            type(optimizer).__name__.startswith("Momentum")

    def apply(self, strategy, optimizer):
        from ..fluid.optimizer import LarsMomentumOptimizer

        return LarsMomentumOptimizer(
            learning_rate=optimizer._learning_rate,
            momentum=getattr(optimizer, "_momentum", 0.9),
            regularization=getattr(optimizer, "regularization", None),
            grad_clip=getattr(optimizer, "_grad_clip", None))


class _WarnOnlyMeta(MetaOptimizerBase):
    def __init__(self, knob, message):
        self.name = knob
        self._message = message

    def can_apply(self, strategy, optimizer):
        if getattr(strategy, self.name, False):
            warnings.warn(self._message)
        return False

    def apply(self, strategy, optimizer):  # pragma: no cover
        return optimizer


# every knob is now either implemented or redirected with a loud
# warning at its use site (a_sync falls back in fleet._transpile_ps
# when no pserver endpoints exist); the list stays for future knobs
_WARN_ONLY: List[MetaOptimizerBase] = []

# application order matters: optimizer swaps first, then recompute /
# gradient_merge wrap, pipeline cuts the program, AMP decorates last so
# the cast policy sees the final graph (reference: strategy_compiler
# ordering)
_META_ORDER: List[MetaOptimizerBase] = _WARN_ONLY + [
    LambMetaOptimizer(), LarsMetaOptimizer(), RecomputeMetaOptimizer(),
    GradientMergeMetaOptimizer(), PipelineMetaOptimizer(),
    AMPMetaOptimizer(),
]

# conflict table (reference: each meta-optimizer's _disable_strategy
# zeroes knobs it cannot coexist with): winner knob -> knobs it
# disables, with the why for the warning
_CONFLICTS = [
    ("pipeline", "sync_batch_norm",
     "the pipeline engine's minimize branch owns the program rewrite; "
     "BN-stat synchronization over dp replicas of a pipeline is not "
     "wired — stats stay per-replica"),
    ("pipeline", "a_sync",
     "pipeline training is collective-mode; the parameter-server "
     "rewrite cannot compose with the stage cut"),
    ("lamb", "lars",
     "lamb replaces the base optimizer; lars (a Momentum wrapper) "
     "cannot also apply"),
    ("localsgd", "dgc",
     "localsgd averages parameters every k steps; dgc's sparse "
     "momentum-corrected grads assume per-step dense allreduce"),
    ("pipeline", "recompute",
     "the GPipe engine owns the per-stage computation; recompute "
     "checkpoints are not segmented across pipeline cuts yet"),
    ("pipeline", "localsgd",
     "pipeline grads psum over the ring every step; k-step parameter "
     "averaging would diverge the stages"),
]


def resolve_conflicts(strategy):
    """StrategyCompiler._disable_strategy pass: mutate the strategy so
    conflicting knobs are turned off LOUDLY; returns disabled names."""
    disabled = []
    for winner, loser, why in _CONFLICTS:
        if getattr(strategy, winner, False) and \
                getattr(strategy, loser, False):
            warnings.warn("DistributedStrategy: %s disabled because %s "
                          "is set (%s)" % (loser, winner, why))
            setattr(strategy, loser, False)
            disabled.append(loser)
    return disabled


def compose(strategy, optimizer):
    """StrategyCompiler: resolve knob conflicts, then fold the
    applicable meta-optimizers over the user optimizer; returns
    (wrapped_optimizer, applied_names)."""
    resolve_conflicts(strategy)
    applied = []
    for meta in _META_ORDER:
        if meta.can_apply(strategy, optimizer):
            optimizer = meta.apply(strategy, optimizer)
            applied.append(meta.name)
    return optimizer, applied
