"""Fleet distributed metrics: allreduce-aggregated metric helpers.

Reference parity: `python/paddle/fleet/metrics/metric.py:1` (an empty
placeholder in the reference snapshot — the working implementation it
fronts is `fluid/incubate/fleet/utils/fleet_util.py:186` get_global_auc
and `:1268` get_global_metrics, whose MPI allreduce semantics these
helpers reproduce). TPU-native: aggregation rides the host TCP
collective tier (`distributed/host_collectives.py`, the Gloo
equivalent) — these are HOST metrics over locally-accumulated metric
vars; device reductions stay on ICI.

Each helper takes a numpy array, a Variable, or a var name (resolved in
`scope`), allreduce-sums it across trainers through `util` (a
HostCollectiveGroup; defaults to the env-configured group, or local
identity when running single-process), and returns the global value.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "mse", "acc"]

_builtin_sum, _builtin_max, _builtin_min = sum, max, min


_env_group_cache = [None, False]  # [group, resolved?]


def _group(util):
    if util is not None:
        return util
    # the env-derived group binds a real TCP store: build it ONCE and
    # reuse it (a second group_from_env on rank 0 would EADDRINUSE on
    # the store port; non-zero ranks would leak a client per call)
    if not _env_group_cache[1]:
        from ..distributed.host_collectives import group_from_env

        _env_group_cache[0] = group_from_env()
        _env_group_cache[1] = True
    return _env_group_cache[0]


def _value(input_, scope) -> np.ndarray:
    if isinstance(input_, np.ndarray):
        return input_
    name = getattr(input_, "name", input_)
    if scope is None:
        from ..core.scope import global_scope

        scope = global_scope()
    v = scope.find_var(str(name))
    if v is None:
        raise ValueError("fleet.metrics: var %r absent from the scope"
                         % name)
    return np.asarray(v)


def _all_reduce(arr, util, op="sum"):
    g = _group(util)
    if g is None:
        return np.asarray(arr, np.float64)
    return np.asarray(g.all_reduce(np.asarray(arr, np.float64), op=op))


def sum(input_, scope=None, util=None):  # noqa: A001 - reference name
    """Global sum (reference: fleet.metrics.sum)."""
    return _all_reduce(_value(input_, scope), util, "sum")


def max(input_, scope=None, util=None):  # noqa: A001
    return _all_reduce(_value(input_, scope), util, "max")


def min(input_, scope=None, util=None):  # noqa: A001
    return _all_reduce(_value(input_, scope), util, "min")


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Global AUC from the auc op's pos/neg threshold buckets
    (reference: fleet_util.py:186 get_global_auc — trapezoid area over
    buckets walked from the highest threshold down)."""
    pos = _all_reduce(_value(stat_pos, scope).reshape(-1), util)
    neg = _all_reduce(_value(stat_neg, scope).reshape(-1), util)
    num_bucket = pos.shape[0]
    area = 0.0
    p = n = 0.0
    total = 0.0
    for i in range(num_bucket):
        index = num_bucket - 1 - i
        new_p = p + pos[index]
        new_n = n + neg[index]
        total += pos[index] + neg[index]
        area += (new_n - n) * (p + new_p) / 2.0
        p, n = new_p, new_n
    if p * n == 0 or total == 0:
        return 0.5
    return float(area / (p * n))


def _reduced_scalar(x, scope, util):
    return float(np.asarray(_all_reduce(
        _value(x, scope).reshape(-1)[:1], util)).reshape(-1)[0])


def mae(abserr, total_ins_num, scope=None, util=None):
    """Global mean absolute error (reference: get_global_metrics mae =
    sum(abserr) / sum(total_ins_num))."""
    err = _reduced_scalar(abserr, scope, util)
    n = _reduced_scalar(total_ins_num, scope, util)
    return err / _builtin_max(n, 1.0)


def mse(sqrerr, total_ins_num, scope=None, util=None):
    err = _reduced_scalar(sqrerr, scope, util)
    n = _reduced_scalar(total_ins_num, scope, util)
    return err / _builtin_max(n, 1.0)


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    return math.sqrt(mse(sqrerr, total_ins_num, scope, util))


def acc(correct, total, scope=None, util=None):
    """Global accuracy = sum(correct) / sum(total)."""
    c = _reduced_scalar(correct, scope, util)
    n = _reduced_scalar(total, scope, util)
    return c / _builtin_max(n, 1.0)
