"""Role makers (reference:
`python/paddle/fluid/incubate/fleet/base/role_maker.py:68-988`):
PaddleCloud env-based (:477), user-defined, MPI-symmetric (rendezvous only).

TPU-native: the worker set is the PADDLE_* env contract (one process per
HOST, chips within a host are mesh-local); Gloo/HDFS rendezvous is replaced
by jax.distributed's coordination service.
"""
from __future__ import annotations

import os


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role = Role.WORKER
        self._current_id = 0

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def generate_role(self):
        pass

    def barrier_worker(self):
        pass

    def barrier_all(self):
        pass


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-var driven (reference: role_maker.py:477): PADDLE_TRAINER_ID,
    PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ENDPOINTS[, PADDLE_PORT/IP for PS
    mode]."""

    def __init__(self, is_collective=True):
        super().__init__()
        self._is_collective = is_collective
        self.generate_role()

    def generate_role(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = eps.split(",") if eps else []
        ps_eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = ps_eps.split(",") if ps_eps else []
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        if role == "PSERVER" and not self._is_collective:
            # reference role_maker.py:477: a pserver identifies itself
            # by POD_IP:PADDLE_PORT within the server list
            self._role = Role.SERVER
            me = "%s:%s" % (os.environ.get("POD_IP", "127.0.0.1"),
                            os.environ.get("PADDLE_PORT", "0"))
            self._current_id = (self._server_endpoints.index(me)
                                if me in self._server_endpoints else 0)
        else:
            self._role = Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID",
                                                  "0"))

    def worker_num(self):
        return int(os.environ.get(
            "PADDLE_TRAINERS_NUM",
            str(max(len(self._worker_endpoints), 1))))


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_num = worker_num
        self._server_endpoints = server_endpoints or []

    def worker_num(self):
        return self._worker_num


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._worker_endpoints = worker_endpoints or []
