"""Checkpoint filesystem abstraction (reference:
`python/paddle/fluid/incubate/fleet/utils/fs.py` FS/LocalFS +
`hdfs.py` HDFSClient). TPU-native scope: pods checkpoint to
local/NFS/GCS-fuse paths, so LocalFS is the real implementation;
HDFSClient keeps the reference surface by shelling out to a `hadoop`
binary when one exists and failing loudly otherwise (this build ships
no Hadoop)."""
from __future__ import annotations

import os
import shutil
import subprocess
from pathlib import Path

__all__ = ["FS", "LocalFS", "HDFSClient", "ExecuteError",
           "FSFileExistsError", "FSFileNotExistsError", "FSTimeOut"]


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError


class LocalFS(FS):
    def ls_dir(self, fs_path):
        return list(os.listdir(fs_path))

    def mkdirs(self, fs_path):
        if os.path.isfile(fs_path):
            raise FSFileExistsError("%s is already a file" % fs_path)
        os.makedirs(fs_path, exist_ok=True)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isfile(fs_path):
            os.remove(fs_path)
        else:
            shutil.rmtree(fs_path)

    def need_upload_download(self):
        return False

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path):
        Path(fs_path).touch()

    def mv(self, src_path, dst_path):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        self.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        if not self.is_exist(fs_path):
            return []
        return [d for d in os.listdir(fs_path)
                if os.path.isdir(os.path.join(fs_path, d))]

    # local fs: upload/download degenerate to copies
    def upload(self, local_path, fs_path):
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path)
        else:
            shutil.copy2(local_path, fs_path)

    download = upload


class HDFSClient(FS):
    """Shells out to `hadoop fs` (reference: hdfs.py HDFSClient's
    java-client subprocess pattern). Constructing without a hadoop
    binary on PATH raises immediately rather than failing at first
    use."""

    def __init__(self, hadoop_home=None, configs=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        self._hadoop = (os.path.join(hadoop_home, "bin", "hadoop")
                        if hadoop_home else shutil.which("hadoop"))
        if not self._hadoop or not os.path.exists(self._hadoop):
            raise ExecuteError(
                "no `hadoop` binary found (this build ships no Hadoop); "
                "checkpoint to a local/NFS/GCS-fuse path with LocalFS "
                "instead")
        self._configs = configs or {}
        self._timeout = time_out / 1000.0

    def _run(self, *args):
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", "%s=%s" % (k, v)]
        cmd += list(args)
        p = subprocess.run(cmd, stdout=subprocess.PIPE,
                           stderr=subprocess.STDOUT, text=True,
                           timeout=self._timeout)
        if p.returncode != 0:
            raise ExecuteError("%r failed: %s" % (args, p.stdout[-500:]))
        return p.stdout

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        return [ln.split()[-1].rsplit("/", 1)[-1]
                for ln in out.splitlines() if ln.startswith(("-", "d"))]

    def list_dirs(self, fs_path):
        out = self._run("-ls", fs_path)
        return [ln.split()[-1].rsplit("/", 1)[-1]
                for ln in out.splitlines() if ln.startswith("d")]

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        if self.is_exist(fs_path):
            self._run("-rm", "-r", fs_path)

    def mv(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path)

    rename = mv

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def need_upload_download(self):
        return True
