from .fs import (  # noqa: F401
    FS, LocalFS, HDFSClient, ExecuteError, FSFileExistsError,
    FSFileNotExistsError, FSTimeOut,
)
