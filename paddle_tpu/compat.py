"""paddle.compat (reference: `python/paddle/compat.py`): py2/py3 text
shims kept for API compatibility."""
from __future__ import annotations

__all__ = ["to_text", "to_bytes", "round", "floor_division",
           "get_exception_message"]

import math as _math


def to_text(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, (list, set, tuple)):
        t = type(obj)
        return t(to_text(o, encoding) for o in obj)
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    return str(obj) if not isinstance(obj, str) else obj


def to_bytes(obj, encoding="utf-8", inplace=False):
    if isinstance(obj, (list, set, tuple)):
        t = type(obj)
        return t(to_bytes(o, encoding) for o in obj)
    if isinstance(obj, str):
        return obj.encode(encoding)
    return bytes(obj) if not isinstance(obj, bytes) else obj


def round(x, d=0):
    """py2-style half-away-from-zero rounding returning float
    (reference compat.py round)."""
    p = 10 ** d
    if x > 0:
        return float(_math.floor((x * p) + 0.5)) / p
    if x < 0:
        return float(_math.ceil((x * p) - 0.5)) / p
    return 0.0


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
