"""Device-side input prefetch: overlap host->HBM transfer with compute.

The host-tier double buffering in `fluid/reader.py` (and the feeder
thread in `fluid/trainer.py`) stops at the host channel — batch N+1 is
parsed and collated while step N computes, but it never reaches HBM
until `Executor.run` blocks on a synchronous `jax.device_put`.
`prefetch_to_device` closes that gap: a background thread issues
non-blocking `jax.device_put` calls against the program's mesh/sharding
so the H2D DMA for batch N+1 rides under step N's compute, and the
executor's on-device fast path consumes the arrays without re-putting
them ("Exploring the limits of Concurrency in ML Training on Google
TPUs" attributes a large fraction of achievable throughput to exactly
this infeed/compute overlap; reference analogue:
`operators/reader/buffered_reader.cc`, whose double buffer owns the
device-side copy stream).

Contract notes:
- depth is bounded (`FLAGS_tpu_prefetch_depth`, default 2): at most
  `size` batches occupy HBM ahead of the consumer;
- producer errors surface at the consumer's `next()` — never a
  silently truncated epoch;
- `close()` (also via context-manager exit, iterator GC, or an early
  `break`) stops the producer, drains queued device buffers, and joins
  the thread;
- prefetched buffers are *donatable*: the consumer (the executor's
  jitted step, `FLAGS_tpu_donate_feed_buffers`) may alias them for
  scratch; the prefetcher never hands the same buffer out twice.
"""
from __future__ import annotations

import queue as _queue
import threading
import weakref
from typing import Iterable, Iterator, Optional

import numpy as np

_END = object()

# Device arrays the prefetcher itself put: single-consumer by contract,
# so the executor may donate their buffers into the jitted step
# (FLAGS_tpu_donate_feed_buffers). Keyed by id() with a weakref
# GC-callback (jax Arrays are weak-referenceable but NOT hashable, so a
# WeakSet cannot hold them); the `ref() is x` check guards against id
# reuse after collection.
_DONATABLE = {}


def mark_donatable(x):
    """Register a device array as single-consumer: the executor may
    donate its buffer. Returns False when `x` is not weak-referenceable
    (then it is treated as caller-owned and never donated)."""
    try:
        key = id(x)
        _DONATABLE[key] = weakref.ref(
            x, lambda _r, _k=key: _DONATABLE.pop(_k, None))
        return True
    except TypeError:
        return False


def is_donatable(x) -> bool:
    r = _DONATABLE.get(id(x))
    return r is not None and r() is x


class _ProducerError:
    def __init__(self, exc):
        self.exc = exc


def _default_depth() -> int:
    from ..utils.flags import get_flag

    return max(1, int(get_flag("FLAGS_tpu_prefetch_depth", 2) or 2))


def _device_put(value, sharding):
    """Non-blocking H2D issue of one batch (dict / list / array).

    `sharding` may be None (default device), a jax Sharding applied to
    every array, or a dict name->Sharding for dict batches (names
    absent from the dict fall back to the default device).
    """
    import jax

    def put_one(name, a):
        if sharding is None:
            s = None
        elif isinstance(sharding, dict):
            s = sharding.get(name)
        else:
            s = sharding
        if s is None:
            out = jax.device_put(a)
        else:
            try:
                out = jax.device_put(a, s)
            except ValueError:
                # uneven tail batch (rows not divisible by the mesh):
                # land it unsharded and let the executor handle it —
                # tail bucketing replicates rows to a cached divisible
                # batch before sharding, same as the host-fed path
                out = jax.device_put(a)
        if out is not a:  # a fresh buffer this prefetcher owns
            mark_donatable(out)
        return out

    if isinstance(value, dict):
        return {k: put_one(k, v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return type(value)(put_one(None, v) for v in value)
    return put_one(None, value)


class DevicePrefetcher:
    """Iterator wrapper: a producer thread pulls batches from `iterator`
    and issues async `jax.device_put`s, keeping at most `size` batches
    in flight ahead of the consumer."""

    def __init__(self, iterator: Iterable, size: Optional[int] = None,
                 sharding=None):
        self._size = size if size and size > 0 else _default_depth()
        self._sharding = sharding
        self._q = _queue.Queue(maxsize=self._size)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, args=(iter(iterator),), daemon=True,
            name="paddle_tpu-device-prefetch")
        self._thread.start()

    # -- producer ----------------------------------------------------------
    def _produce(self, it):
        try:
            for item in it:
                if self._stop.is_set():
                    return
                dev = _device_put(item, self._sharding)
                # bounded-depth handoff that stays responsive to close()
                while not self._stop.is_set():
                    try:
                        self._q.put(dev, timeout=0.2)
                        break
                    except _queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 - re-raised by consumer
            while not self._stop.is_set():
                try:
                    self._q.put(_ProducerError(e), timeout=0.2)
                    break
                except _queue.Full:
                    continue
        finally:
            # end marker must not be dropped on a full queue (the
            # consumer would hang at end-of-data); bail only on close()
            while not self._stop.is_set():
                try:
                    self._q.put(_END, timeout=0.2)
                    break
                except _queue.Full:
                    continue

    # -- consumer ----------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._q.get()
        if item is _END:
            self._stop.set()
            self._thread.join(timeout=5.0)
            raise StopIteration
        if isinstance(item, _ProducerError):
            self.close()
            # re-raise the ORIGINAL exception (type intact, traceback
            # from the producer thread attached): callers with typed
            # except clauses around their loop keep working, matching
            # the old trainer feeder's `raise feeder_err[0]` contract
            raise item.exc
        return item

    def close(self):
        """Stop the producer and drain queued device buffers so their
        HBM is released promptly (early loop exit / error paths)."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except _queue.Empty:
                break
        self._thread.join(timeout=5.0)
        # a put in flight during the first drain can land one more item
        # before the producer observes stop — drain again after join
        while True:
            try:
                self._q.get_nowait()
            except _queue.Empty:
                break

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def prefetch_to_device(iterator: Iterable, size: Optional[int] = None,
                       sharding=None) -> DevicePrefetcher:
    """Wrap `iterator` (yielding dicts / lists / arrays of numpy
    batches) so batches arrive already on device, `size` deep
    (default `FLAGS_tpu_prefetch_depth`). `sharding`: None, a jax
    Sharding, or a dict name->Sharding (data-parallel feeds use the
    program's mesh — see `Executor.feed_sharding`)."""
    return DevicePrefetcher(iterator, size=size, sharding=sharding)


def device_put_batch(value, sharding=None):
    """Issue one non-blocking host->device transfer of a batch (dict /
    list / array), marking fresh buffers donatable — the prefetcher's
    own put path exposed for single-batch producers (the serving
    engine's request-ingress packing: the packed prefill/decode bucket
    is uploaded while the previous step's compute is still in flight,
    and the jitted step may reuse its HBM)."""
    return _device_put(value, sharding)


def is_on_device(value) -> bool:
    """True when `value` is a jax Array already resident on device (the
    executor's feed fast path skips device_put for these). numpy arrays
    and python scalars return False without importing jax eagerly."""
    if isinstance(value, (np.ndarray, np.generic, int, float, bool,
                          list, tuple, dict)) or value is None:
        return False
    try:
        import jax

        return isinstance(value, jax.Array)
    except Exception:  # noqa: BLE001 - jax not importable
        return False
