"""Elastic per-rank sample assignment — the data half of an elastic
world-size restart (distributed/launch.py --min_ranks).

A data-parallel cohort at world size N consumes one GLOBAL batch per
step, each rank training on a deterministic slice of it. When a restart
comes back at N' != N, the assignment must be recomputed from the same
global sample stream so that **no sample is dropped or double-trained**
across the seam:

- the resume point is a GLOBAL step count (checkpoint TrainStatus
  step_no) — world-size independent, because every world consumes
  exactly `global_batch` samples per step. `resume_sample_offset`
  converts it to the global sample cursor;
- `rank_slice`/`shard_batch` re-derive each rank's slice of every
  global batch for the NEW (rank, world). The split is contiguous and
  balanced (the remainder spreads over the first ranks), so for
  divisible batches the mean-of-per-rank-means equals the global-batch
  mean and the host-tier grad allreduce stays exact at any world size;
- `shard_batches` applies it to a global-batch iterator, and
  `skip_steps` (host-side, before any H2D transfer — same rule the
  trainer resume path uses) drops the already-trained prefix.

The ZeRO/AMP state half of the same seam lives in
parallel/sharded_update.to_sharded_global (re-pad/re-shard for N');
see distributed/README.md "Elastic restarts" for the full runbook.
"""
from __future__ import annotations

import itertools
from typing import Dict, Iterable, Iterator, Tuple

import numpy as np

__all__ = ["rank_slice", "shard_batch", "shard_batches",
           "resume_sample_offset", "skip_steps", "survivor_rank"]


def survivor_rank(old_rank: int, doomed) -> int:
    """This rank's NEW contiguous rank after the doomed ranks leave a
    live resize (distributed/preemption.ElasticWorld), or -1 for a
    doomed rank. Survivors keep their relative order — the same
    reassignment rule as the launch supervisor's restart shrink, so
    `shard_batch(batch, survivor_rank(r, doomed), world - len(doomed))`
    continues the global sample stream with no sample dropped or
    double-trained across the seam (mid-epoch data continuity: the
    resume cursor is a GLOBAL step count, unchanged by the seam)."""
    old_rank = int(old_rank)
    doomed = {int(r) for r in doomed}
    if old_rank in doomed:
        return -1
    return old_rank - sum(1 for r in doomed if r < old_rank)


def rank_slice(n: int, rank: int, world: int) -> Tuple[int, int]:
    """[lo, hi) of global-batch rows assigned to `rank` of `world`:
    contiguous, balanced, remainder on the first ranks. Every row is
    assigned to exactly one rank for ANY world size — the invariant an
    elastic re-shard relies on."""
    n, rank, world = int(n), int(rank), int(world)
    if world <= 0:
        raise ValueError("world must be positive, got %d" % world)
    if not 0 <= rank < world:
        raise ValueError("rank %d outside [0, %d)" % (rank, world))
    base, rem = divmod(n, world)
    lo = rank * base + min(rank, rem)
    return lo, lo + base + (1 if rank < rem else 0)


def shard_batch(batch, rank: int, world: int):
    """This rank's slice of one GLOBAL batch (dict of arrays, sequence
    of arrays, or one array — sliced along axis 0). Dict/sequence
    entries must share the leading (batch) dimension."""
    if isinstance(batch, dict):
        sizes = {k: len(v) for k, v in batch.items()}
        if len(set(sizes.values())) > 1:
            raise ValueError(
                "global batch entries disagree on the batch dim: %s"
                % sizes)
        n = next(iter(sizes.values())) if sizes else 0
        lo, hi = rank_slice(n, rank, world)
        return {k: v[lo:hi] for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        ns = {len(v) for v in batch}
        if len(ns) > 1:
            raise ValueError(
                "global batch entries disagree on the batch dim: %s"
                % sorted(ns))
        n = ns.pop() if ns else 0
        lo, hi = rank_slice(n, rank, world)
        return type(batch)(v[lo:hi] for v in batch)
    arr = np.asarray(batch)
    lo, hi = rank_slice(arr.shape[0], rank, world)
    return arr[lo:hi]


def shard_batches(batches: Iterable, rank: int,
                  world: int) -> Iterator:
    """Per-rank view of a GLOBAL batch iterator (the elastic-safe
    feeder: rebuild with the new (rank, world) after a shrink and the
    sample->rank map recomputes itself)."""
    for b in batches:
        yield shard_batch(b, rank, world)


def resume_sample_offset(step_no: int, global_batch: int) -> int:
    """Global sample cursor after `step_no` completed GLOBAL steps.
    World-size independent: a cohort at any N consumes global_batch
    samples per step, so a checkpoint taken at N resumes at the same
    cursor when restored at N'."""
    return max(int(step_no), 0) * int(global_batch)


def skip_steps(batches: Iterable, start_step: int) -> Iterator:
    """Drop the first `start_step` GLOBAL batches host-side (before the
    prefetcher — paying an H2D transfer per discarded batch would be
    pure waste; same rule as trainer.train_from_dataset's resume)."""
    return itertools.islice(iter(batches), max(int(start_step), 0),
                            None)
