"""Classic reader decorators (reference:
`python/paddle/reader/decorator.py`): composable generators feeding the
data pipeline. Host-side pure python — identical semantics."""
from __future__ import annotations

import itertools
import random as _random
from queue import Queue
from threading import Thread

from .prefetcher import (  # noqa: F401
    DevicePrefetcher, is_on_device, prefetch_to_device,
)
from .resharding import (  # noqa: F401
    rank_slice, resume_sample_offset, shard_batch, shard_batches,
    skip_steps,
)

__all__ = [
    "cache", "map_readers", "buffered", "compose", "chain", "shuffle",
    "firstn", "xmap_readers", "multiprocess_reader",
    "prefetch_to_device", "DevicePrefetcher", "is_on_device",
    "rank_slice", "shard_batch", "shard_batches",
    "resume_sample_offset", "skip_steps",
]


def cache(reader):
    """Materialize the reader once; replay from memory afterwards."""
    all_data = tuple(reader())

    def __impl__():
        for item in all_data:
            yield item

    return __impl__


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            _random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    def reader():
        for r in readers:
            for e in r():
                yield e

    return reader


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                yield sum((make_tuple(o) for o in outputs
                           if o is not None), ())

    return reader


def buffered(reader, size):
    """Prefetch up to `size` items on a background thread."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = Queue(maxsize=size)
        err = []

        def feed():
            try:
                for d in r:
                    q.put(d)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                err.append(e)
            finally:
                q.put(_End)

        t = Thread(target=feed)
        t.daemon = True
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e
        if err:
            raise err[0]

    return data_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads (reference
    xmap_readers; threads, not processes — the mappers here are numpy
    transforms that release the GIL)."""

    class _End:
        pass

    def data_reader():
        in_q = Queue(buffer_size)
        out_q = Queue(buffer_size)

        errs = []

        def feed():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errs.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(_End)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is _End:
                        break
                    i, d = item
                    out_q.put((i, mapper(d)))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errs.append(e)
            finally:
                out_q.put(_End)

        Thread(target=feed, daemon=True).start()
        workers = [Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is _End:
                finished += 1
                continue
            i, d = item
            if not order:
                yield d
            else:
                pending[i] = d
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        for i in sorted(pending):
            yield pending[i]
        if errs:
            raise errs[0]

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Round-robin merge of multiple readers on threads (the reference
    forks processes; mappers here are IO/numpy-bound so threads match
    throughput without fork hazards under a live TPU client)."""

    def reader():
        its = [r() for r in readers]
        alive = [True] * len(its)
        while any(alive):
            for i, it in enumerate(its):
                if not alive[i]:
                    continue
                try:
                    yield next(it)
                except StopIteration:
                    alive[i] = False

    return reader
