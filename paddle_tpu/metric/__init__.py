"""paddle.metric 2.0 namespace (reference:
`python/paddle/metric/`): streaming metric classes shared with
fluid.metrics plus the hapi metric protocol."""
from ..fluid.metrics import (  # noqa: F401
    MetricBase, Accuracy, Precision, Recall, Auc, CompositeMetric,
    ChunkEvaluator, EditDistance,
)
from ..hapi.metrics import Metric  # noqa: F401


def accuracy(input, label, k=1):
    """Functional accuracy (reference metric/metrics.py accuracy)."""
    from ..fluid.layers import nn as N

    return N.accuracy(input, label, k=k)
