"""Incubating APIs (reference: `python/paddle/incubate/`)."""
from .. import hapi  # noqa: F401
from . import complex  # noqa: F401
