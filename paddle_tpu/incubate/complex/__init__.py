"""paddle.incubate.complex (reference:
`python/paddle/incubate/complex/` — ComplexVariable in helper.py plus
the tensor ops in tensor/math.py / manipulation.py / linalg.py).

TPU-native design: the reference carries (real, imag) as two tensors
through pairs of real ops; XLA supports complex64/128 natively, so
ComplexVariable wraps ONE complex jax array and every op is a single
complex primitive — half the HBM traffic and fusion-friendly. The
public contract (construct from real/imag, .real/.imag accessors, the
same function names) is unchanged."""
from __future__ import annotations

import numpy as np

__all__ = [
    "ComplexVariable", "to_complex",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "matmul", "kron", "reshape", "transpose", "sum",
    "trace",
]


class ComplexVariable:
    """A complex tensor (reference helper.py ComplexVariable)."""

    def __init__(self, real, imag=None):
        import jax.numpy as jnp

        if imag is None:
            self._data = jnp.asarray(real)
            if not jnp.iscomplexobj(self._data):
                wide = self._data.dtype == jnp.float64
                self._data = self._data.astype(
                    jnp.complex128 if wide else jnp.complex64)
        else:
            r = jnp.asarray(real)
            i = jnp.asarray(imag)
            wide = (r.dtype == jnp.float64 or i.dtype == jnp.float64)
            self._data = (r + 1j * i).astype(
                jnp.complex128 if wide else jnp.complex64)

    @property
    def real(self):
        return self._data.real

    @property
    def imag(self):
        return self._data.imag

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return str(self._data.dtype)

    def numpy(self):
        return np.asarray(self._data)

    def __repr__(self):
        return "ComplexVariable(shape=%s)\n%s" % (self.shape,
                                                  np.asarray(self._data))

    # operator sugar
    def __add__(self, other):
        return elementwise_add(self, other)

    def __sub__(self, other):
        return elementwise_sub(self, other)

    def __mul__(self, other):
        return elementwise_mul(self, other)

    def __truediv__(self, other):
        return elementwise_div(self, other)

    def __matmul__(self, other):
        return matmul(self, other)


def to_complex(x):
    return x._data if isinstance(x, ComplexVariable) else x


def _wrap(v):
    return ComplexVariable(v)


def elementwise_add(x, y, axis=-1, name=None):
    return _wrap(to_complex(x) + to_complex(y))


def elementwise_sub(x, y, axis=-1, name=None):
    return _wrap(to_complex(x) - to_complex(y))


def elementwise_mul(x, y, axis=-1, name=None):
    return _wrap(to_complex(x) * to_complex(y))


def elementwise_div(x, y, axis=-1, name=None):
    return _wrap(to_complex(x) / to_complex(y))


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    import jax.numpy as jnp

    a, b = to_complex(x), to_complex(y)
    if transpose_x:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_y:
        b = jnp.swapaxes(b, -1, -2)
    return _wrap(alpha * (a @ b))


def kron(x, y, name=None):
    import jax.numpy as jnp

    return _wrap(jnp.kron(to_complex(x), to_complex(y)))


def reshape(x, shape, inplace=False, name=None):
    return _wrap(to_complex(x).reshape(shape))


def transpose(x, perm, name=None):
    import jax.numpy as jnp

    return _wrap(jnp.transpose(to_complex(x), perm))


def sum(input, dim=None, keep_dim=False, name=None):
    import jax.numpy as jnp

    return _wrap(jnp.sum(to_complex(input),
                         axis=tuple(dim) if isinstance(dim, (list, tuple))
                         else dim, keepdims=keep_dim))


def trace(input, offset=0, dim1=0, dim2=1, name=None):
    import jax.numpy as jnp

    return _wrap(jnp.trace(to_complex(input), offset=offset, axis1=dim1,
                           axis2=dim2))
