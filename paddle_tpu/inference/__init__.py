"""Inference engine: an AnalysisPredictor-shaped API over compiled XLA
executables.

Reference: `paddle/fluid/inference/api/analysis_predictor.cc`
(CreatePaddlePredictor:1012, PrepareProgram:184, Run:289,
OptimizeInferenceProgram:498) and `paddle_inference_api.h` (Config /
Predictor / Tensor zero-copy surface).

TPU-native: the reference's analysis passes (fusions, TRT/Lite subgraph
capture) are XLA's job — the loaded program lowers to ONE compiled
computation cached by input shapes; "zero-copy" tensors hold numpy on the
host side and jax device arrays after run. MKLDNN/TensorRT/GPU knobs are
accepted as no-ops so reference configs port unchanged.

This is the per-call, load-and-run surface. For PERSISTENT serving —
continuous batching across concurrent requests, a paged KV cache, and
AOT-warmed decode-step buckets — see ``paddle_tpu.serving``
(serving/README.md); `Predictor.warmup(shapes=...)` pre-compiles this
predictor's own input-shape buckets through the same persistent
compile cache (FLAGS_tpu_compile_cache_dir) so a serving process
restart answers its first request without paying XLA compilation.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

__all__ = [
    "Config", "AnalysisConfig", "Predictor", "Tensor",
    "create_predictor", "create_paddle_predictor", "PlaceType",
]


class PlaceType:
    kHost = CPU = 0
    kGPU = GPU = 1
    kTPU = TPU = 2


class Config:
    """Reference: AnalysisConfig (inference/api/paddle_analysis_config.h).

    Accepts both the dir form ``Config(model_dir)`` and the two-file form
    ``Config(prog_file, params_file)``.
    """

    def __init__(self, model_dir: Optional[str] = None,
                 params_path: Optional[str] = None):
        if model_dir is not None and params_path is not None:
            self._model_dir = os.path.dirname(model_dir) or "."
            self._prog_file = os.path.basename(model_dir)
            # keep the full params path: it may live in a different
            # directory than the program file (os.path.join in the loader
            # respects an absolute second component)
            self._params_file = os.path.abspath(params_path)
        else:
            self._model_dir = model_dir
            self._prog_file = None
            self._params_file = None
        self._use_tpu = True
        self._ir_optim = True
        self._enable_memory_optim = True
        self._cpu_math_threads = 1

    # -- model location ----------------------------------------------------
    def set_model(self, model_dir: str, params_path: Optional[str] = None):
        # only update the model location (reference AnalysisConfig.SetModel);
        # previously configured knobs (ir_optim, ...) must survive
        if params_path is not None:
            self._model_dir = None
            self._prog_file = model_dir
            self._params_file = params_path
        else:
            self._model_dir = model_dir
            self._prog_file = None
            self._params_file = None

    def model_dir(self) -> Optional[str]:
        return self._model_dir

    def prog_file(self) -> Optional[str]:
        return self._prog_file

    def params_file(self) -> Optional[str]:
        return self._params_file

    # -- device / optimization knobs (reference API kept; XLA makes most
    # of them no-ops on TPU) ----------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        pass

    def disable_gpu(self):
        pass

    def use_gpu(self) -> bool:
        return False

    def enable_xpu(self, *a, **k):
        pass

    def switch_ir_optim(self, x: bool = True):
        self._ir_optim = x

    def ir_optim(self) -> bool:
        return self._ir_optim

    def switch_use_feed_fetch_ops(self, x: bool = False):
        pass

    def switch_specify_input_names(self, x: bool = True):
        pass

    def enable_memory_optim(self):
        self._enable_memory_optim = True

    def enable_mkldnn(self):
        pass

    def enable_tensorrt_engine(self, *a, **k):
        pass

    def tensorrt_engine_enabled(self) -> bool:
        return False

    def set_cpu_math_library_num_threads(self, n: int):
        self._cpu_math_threads = n

    def pass_builder(self):
        """Analysis pass control (reference:
        analysis_predictor.cc:498 + pass_builder.h PaddlePassBuilder).
        TPU-native: graph fusion/layout passes belong to XLA, so the
        builder lists the LOGICAL pipeline stages this runtime applies
        around the compiler; deleting a pass disables the matching
        stage where one exists (ir_optim gates XLA optimization
        itself via switch_ir_optim)."""
        if not hasattr(self, "_pass_builder"):
            self._pass_builder = PassStrategy()
        return self._pass_builder

    def enable_profile(self):
        pass

    def disable_glog_info(self):
        pass


AnalysisConfig = Config  # legacy name (reference: paddle_analysis_config.h)


class PassStrategy:
    """Reference: pass_builder.h — an ordered, editable pass list.
    Stages marked (xla) are owned by the compiler (they run iff
    ir_optim is on — switch_ir_optim is the real toggle for them).
    Two passes have REAL individual delete semantics:
    `memory_optimize_pass` (disables buffer donation) and
    `conv_bn_fuse_pass` (disables the load-time weight fold). Deleting
    any other (compiler-owned) pass warns that it has no individual
    effect."""

    _RUNTIME = {"memory_optimize_pass", "conv_bn_fuse_pass"}
    _DEFAULT = [
        "infer_clean_graph_pass",          # feed/fetch pruning (load)
        "conv_bn_fuse_pass",               # weight fold (load; real)
        "constant_folding_pass",           # (xla)
        "common_subexpression_elimination",  # (xla)
        "operator_fusion_pass",            # (xla)
        "layout_assignment_pass",          # (xla)
        "memory_optimize_pass",            # buffer donation (runtime)
    ]

    def __init__(self):
        self._passes = list(self._DEFAULT)

    def all_passes(self):
        return list(self._passes)

    def delete_pass(self, name):
        if name in self._passes and name not in self._RUNTIME:
            import warnings

            warnings.warn(
                "pass %r is owned by the XLA pipeline (or applied at "
                "model load); deleting it only edits the report — use "
                "switch_ir_optim(False) to disable compiler "
                "optimization as a whole" % (name,))
        self._passes = [p for p in self._passes if p != name]

    def insert_pass(self, idx, name):
        self._passes.insert(int(idx), str(name))

    def append_pass(self, name):
        self._passes.append(str(name))

    def memory_optim_enabled(self):
        return "memory_optimize_pass" in self._passes


class Tensor:
    """Zero-copy input/output handle (reference: ZeroCopyTensor,
    inference/api/details/zero_copy_tensor.cc)."""

    def __init__(self, name: str, predictor: "Predictor", is_input: bool):
        self._name = name
        self._pred = predictor
        self._is_input = is_input

    def name(self) -> str:
        return self._name

    def reshape(self, shape) -> None:
        if not self._is_input:
            raise RuntimeError("cannot reshape an output tensor")
        cur = self._pred._inputs.get(self._name)
        self._pred._inputs[self._name] = (
            np.zeros(shape, cur.dtype if cur is not None else "float32"))

    def copy_from_cpu(self, data: np.ndarray) -> None:
        if not self._is_input:
            raise RuntimeError("cannot write an output tensor")
        self._pred._inputs[self._name] = np.ascontiguousarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        out = self._pred._outputs.get(self._name)
        if out is None:
            raise RuntimeError(
                "output %r not available — call run() first" % self._name)
        return np.asarray(out)

    def shape(self) -> List[int]:
        if self._is_input:
            a = self._pred._inputs.get(self._name)
        else:
            a = self._pred._outputs.get(self._name)
        return list(a.shape) if a is not None else []

    # paddle-2.x tensor handle aliases
    def copy_from_cpu_bind(self, data):
        self.copy_from_cpu(data)


class Predictor:
    """Reference: AnalysisPredictor. Loads the saved inference program,
    lowers it through the same block compiler as the Executor, and caches
    the XLA executable per input-shape signature."""

    def __init__(self, config: Config):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import framework

        self._config = config
        self._exe = fluid.Executor()
        # load under a private scope so predictors don't collide
        from paddle_tpu.core.scope import Scope, scope_guard

        self._scope = Scope()
        with scope_guard(self._scope):
            prog, feed_names, fetch_targets = fluid.io.load_inference_model(
                config.model_dir(), self._exe,
                model_filename=config.prog_file(),
                params_filename=config.params_file())
        self._program = prog
        self._conv_bn_fused = 0
        if config.ir_optim() and "conv_bn_fuse_pass" in \
                config.pass_builder().all_passes():
            from .passes import conv_bn_fuse

            self._conv_bn_fused = conv_bn_fuse(
                prog, self._scope,
                keep_names=[t.name for t in fetch_targets])
        self._feed_names = list(feed_names)
        self._fetch_targets = fetch_targets
        self._fetch_names = [t.name for t in fetch_targets]
        self._inputs: Dict[str, np.ndarray] = {}
        self._outputs: Dict[str, np.ndarray] = {}

    # -- reference Predictor surface --------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name: str) -> Tensor:
        if name not in self._feed_names:
            raise KeyError(name)
        return Tensor(name, self, is_input=True)

    def get_output_handle(self, name: str) -> Tensor:
        if name not in self._fetch_names:
            raise KeyError(name)
        return Tensor(name, self, is_input=False)

    # legacy ZeroCopy names
    get_input_tensor = get_input_handle
    get_output_tensor = get_output_handle

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """With ``inputs``: positional legacy mode, returns outputs list.
        Without: zero-copy mode over the bound input handles."""
        if inputs is not None:
            for name, arr in zip(self._feed_names, inputs):
                self._inputs[name] = np.asarray(arr)
        missing = [n for n in self._feed_names if n not in self._inputs]
        if missing:
            raise RuntimeError("inputs %s not set" % missing)
        from paddle_tpu.core.scope import scope_guard

        import contextlib

        import jax

        from paddle_tpu.utils.flags import get_flags, set_flags

        # switch_ir_optim(False): run unoptimized — op-by-op eager
        # dispatch instead of one fused XLA executable (the reference's
        # no-IR-passes NaiveExecutor path, analysis_predictor.cc:498)
        no_opt = (jax.disable_jit() if not self._config.ir_optim()
                  else contextlib.nullcontext())
        # memory_optimize_pass deleted (or memory optim disabled):
        # buffer donation off for this predictor's compilations
        donate_off = (
            not self._config.pass_builder().memory_optim_enabled()
            or not getattr(self._config, "_enable_memory_optim", True))
        flag = "FLAGS_tpu_donate_buffers"
        prev = get_flags([flag])[flag]
        try:
            if donate_off:
                set_flags({flag: False})
            with scope_guard(self._scope), no_opt:
                outs = self._exe.run(self._program,
                                     feed=dict(self._inputs),
                                     fetch_list=self._fetch_names)
        finally:
            if donate_off:
                set_flags({flag: prev})
        self._outputs = dict(zip(self._fetch_names,
                                 [np.asarray(o) for o in outs]))
        if inputs is not None:
            return [self._outputs[n] for n in self._fetch_names]
        return True

    # legacy alias
    zero_copy_run = run

    def warmup(self, shapes, meshes=None, background=False):
        """AOT-compile this predictor's program for the given
        input-shape buckets BEFORE traffic (PR 13 machinery:
        `Executor.warmup` + the FLAGS_tpu_compile_cache_dir persistent
        tier). `shapes` is a list of dicts mapping input name ->
        concrete shape tuple / example array / ShapeDtypeStruct; each
        bucket executes one discarded run on state copies, so the
        first real request of that shape dispatches with
        compile_ms ~ 0 — and a RESTARTED serving process warms
        all-hit from the persistent tier. Returns the warmup report
        ({"compiled": [...], "cached": [...], "skipped": [...]}), or
        the background Thread when background=True."""
        from paddle_tpu.core.scope import scope_guard

        with scope_guard(self._scope):
            return self._exe.warmup(
                self._program, shapes, meshes=meshes,
                fetch_list=self._fetch_targets, scope=self._scope,
                background=background)

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass

    def get_optimization_report(self) -> Dict:
        """Analysis report (reference: the AnalysisConfig summary +
        argument dump, analysis_predictor.cc:498): what the pipeline
        will do to this program and how big it is."""
        block = self._program.global_block()
        op_types: Dict[str, int] = {}
        for op in block.ops:
            op_types[op.type] = op_types.get(op.type, 0) + 1
        return {
            "num_ops": len(block.ops),
            "op_types": op_types,
            "num_feeds": len(self._feed_names),
            "num_fetches": len(self._fetch_names),
            "ir_optim": self._config.ir_optim(),
            "conv_bn_fused": self._conv_bn_fused,
            "passes": self._config.pass_builder().all_passes(),
            "memory_optim": getattr(self._config,
                                    "_enable_memory_optim", False),
            "compiler": "xla",
        }


def create_predictor(config: Config) -> Predictor:
    """Reference: paddle_infer::CreatePredictor."""
    return Predictor(config)


def create_paddle_predictor(config: Config) -> Predictor:
    """Reference: CreatePaddlePredictor<AnalysisConfig>
    (analysis_predictor.cc:1012)."""
    return Predictor(config)
