"""Load-time inference optimization passes that need parameter VALUES
(reference: `framework/ir/conv_bn_fuse_pass.cc`). These cannot be
XLA-owned: to the compiler, parameters are runtime inputs, so the
algebraic fold of a frozen batch_norm into conv weights is invisible to
it — the fold must happen once at model-load with the scope in hand.
"""
from __future__ import annotations

import numpy as np


def conv_bn_fuse(program, scope, keep_names=()) -> int:
    """Fold every frozen (is_test) batch_norm that solely consumes a
    bias-free conv2d's output into the conv's weights + one channel
    bias: w' = w * (gamma/sqrt(var+eps)) per out-channel,
    b' = beta - mean * gamma/sqrt(var+eps). Removes the BN's
    normalize/affine arithmetic from every inference step. Returns the
    number of BN ops folded.

    keep_names: externally observed vars (the predictor's fetch
    targets) — a conv output or BN side output fetched by name must not
    be rescaled/dropped, so those pairs are skipped."""
    import jax.numpy as jnp

    from ..fluid import lowering
    from ..fluid.framework import Operator

    block = program.global_block()
    ops = list(block.ops)
    keep = set(keep_names)
    # recursive read analysis: a conv output also read inside a
    # while/cond/scan body must count as a second consumer, or its
    # weights get rescaled in scope while the sub-block still reads the
    # pre-BN-fold activation (ADVICE r4)
    consumers = {}
    for i, op in enumerate(ops):
        reads, _ = lowering._op_reads_writes(op)
        for n in set(reads):
            consumers.setdefault(n, []).append(i)

    fused = 0
    for i, op in enumerate(ops):
        if op.type != "conv2d":
            continue
        out = op.output_names["Output"][0]
        if out in keep:
            continue  # fetched pre-BN activation: fold would rescale it
        cons = consumers.get(out, [])
        if len(cons) != 1:
            continue
        # a weight-tied filter (shared by another conv) must not be
        # rescaled in scope
        if len(consumers.get(op.input_names["Filter"][0], [])) != 1:
            continue
        bn = ops[cons[0]]
        if bn.type != "batch_norm":
            continue
        if bn.attrs.get("fused_act"):
            # a fuse_bn_act-folded relu rides on this BN: replacing it
            # with a bias add would silently drop the activation
            continue
        if not (bn.attrs.get("is_test")
                or bn.attrs.get("use_global_stats")):
            continue
        if bn.input_names["X"][0] != out:
            continue
        # only the normalized output may have consumers — MeanOut-style
        # side outputs must be dead or the rewrite would drop them.
        # (MeanOut aliases the Mean INPUT var, so the BN op itself
        # appears as a consumer — exclude it.)
        bn_idx = cons[0]
        side_names = [n for slot, names in bn.output_names.items()
                      if slot != "Y" for n in names]
        if any(c != bn_idx for n in side_names
               for c in consumers.get(n, [])):
            continue
        if any(n in keep for n in side_names):
            continue

        w_name = op.input_names["Filter"][0]
        vals = {}
        missing = False
        for slot in ("Scale", "Bias", "Mean", "Variance"):
            v = scope.find_var(bn.input_names[slot][0])
            if v is None:
                missing = True
                break
            vals[slot] = np.asarray(v)
        w_dev = scope.find_var(w_name)
        if missing or w_dev is None:
            continue
        w = np.asarray(w_dev)
        eps = float(bn.attrs.get("epsilon", 1e-5))
        inv = vals["Scale"] / np.sqrt(vals["Variance"] + eps)
        scope.set_var(w_name, jnp.asarray(
            (w * inv[:, None, None, None]).astype(w.dtype)))
        b_folded = (vals["Bias"] - vals["Mean"] * inv).astype("float32")
        bias_name = w_name + "@bn_folded_bias"
        bias_var = block.create_var(name=bias_name,
                                    shape=(int(b_folded.shape[0]),),
                                    dtype="float32")
        bias_var.persistable = True
        scope.set_var(bias_name, jnp.asarray(b_folded))

        y_var = block._find_var_recursive(bn.output_names["Y"][0])
        conv_out_var = block._find_var_recursive(out)
        ops[cons[0]] = Operator(
            block, "elementwise_add",
            inputs={"X": [conv_out_var], "Y": [bias_var]},
            outputs={"Out": [y_var]}, attrs={"axis": 1})
        fused += 1

    if fused:
        block.ops = ops
        program._version += 1
    return fused
