"""paddle.tensor manipulation ops (reference:
`python/paddle/tensor/manipulation.py`)."""
from __future__ import annotations

from ..fluid.layer_helper import apply_op
from ..fluid.layers import nn as _nn
from ..fluid.layers import tensor as _t


def reshape(x, shape, name=None):
    return _t.reshape(x, shape)


def transpose(x, perm, name=None):
    return _t.transpose(x, perm)


def concat(x, axis=0, name=None):
    return _t.concat(x, axis)


def stack(x, axis=0, name=None):
    return _nn.stack(x, axis)


def unstack(x, axis=0, num=None):
    return _nn.unstack(x, axis, num)


def split(x, num_or_sections, axis=0, name=None):
    return _nn.split(x, num_or_sections, dim=axis)


def chunk(x, chunks, axis=0, name=None):
    return _nn.split(x, chunks, dim=axis)


def squeeze(x, axis=None, name=None):
    axes = [] if axis is None else (
        list(axis) if isinstance(axis, (list, tuple)) else [axis])
    return _nn.squeeze(x, axes)


def unsqueeze(x, axis, name=None):
    axes = list(axis) if isinstance(axis, (list, tuple)) else [axis]
    return _nn.unsqueeze(x, axes)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return apply_op("flatten_contiguous_range",
                    "flatten_contiguous_range", {"X": [x]},
                    {"start_axis": start_axis, "stop_axis": stop_axis},
                    ["Out"], out_dtype=getattr(x, "dtype", "float32"))[0]


def flip(x, axis, name=None):
    axes = list(axis) if isinstance(axis, (list, tuple)) else [axis]
    return apply_op("flip", "flip", {"X": [x]}, {"axis": axes}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


def roll(x, shifts, axis=None, name=None):
    shifts = list(shifts) if isinstance(shifts, (list, tuple)) \
        else [shifts]
    axes = ([] if axis is None else
            list(axis) if isinstance(axis, (list, tuple)) else [axis])
    return apply_op("roll", "roll", {"X": [x]},
                    {"shifts": shifts, "axis": axes}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


def tile(x, repeat_times, name=None):
    return apply_op("tile", "tile", {"X": [x]},
                    {"repeat_times": list(repeat_times)}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


def expand(x, shape, name=None):
    return apply_op("expand_v2", "expand_v2", {"X": [x]},
                    {"shape": list(shape)}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


broadcast_to = expand


def expand_as(x, y, name=None):
    return _nn.expand_as(x, y)


def gather(x, index, axis=None, name=None):
    return _nn.gather(x, index)


def gather_nd(x, index, name=None):
    return _nn.gather_nd(x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    return _nn.scatter(x, index, updates, overwrite=overwrite)


def scatter_nd_add(x, index, updates, name=None):
    return apply_op("scatter_nd_add", "scatter_nd_add",
                    {"X": [x], "Index": [index], "Updates": [updates]},
                    {}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


def slice(x, axes, starts, ends):
    return _nn.slice(x, axes, starts, ends)


def strided_slice(x, axes, starts, ends, strides, name=None):
    return _nn.strided_slice(x, axes, starts, ends, strides)


def cast(x, dtype):
    return _t.cast(x, dtype)


def unique(x, return_index=False, return_inverse=False,
           return_counts=False, axis=None, dtype="int64", name=None):
    """paddle.unique: Out [, first-occurrence Indices][, Inverse]
    [, Counts] (reference: python/paddle/tensor/manipulation.py unique)."""
    if axis is not None:
        raise NotImplementedError("unique(axis=...) is not supported yet")
    outs = apply_op("unique", "unique", {"X": [x]}, {},
                    ["Out", "Index", "Indices", "Counts"],
                    out_dtype=getattr(x, "dtype", "float32"))
    out, inverse, first_idx, counts = outs
    result = [out]
    if return_index:
        result.append(first_idx)
    if return_inverse:
        result.append(inverse)
    if return_counts:
        result.append(counts)
    return tuple(result) if len(result) > 1 else out


def take_along_axis(x, indices, axis, name=None):
    return apply_op("take_along_axis", "take_along_axis",
                    {"Input": [x], "Index": [indices]}, {"Axis": axis},
                    ["Result"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]
