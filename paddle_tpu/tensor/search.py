"""paddle.tensor search/sort ops (reference:
`python/paddle/tensor/search.py`)."""
from __future__ import annotations

from ..fluid.layer_helper import apply_op
from ..fluid.layers import nn as _nn
from ..fluid.layers import tensor as _t


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _t.argmax(x, axis=-1 if axis is None else axis)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _t.argmin(x, axis=-1 if axis is None else axis)


def argsort(x, axis=-1, descending=False, name=None):
    outs = apply_op("argsort", "argsort", {"X": [x]},
                    {"axis": axis, "descending": descending},
                    ["Out", "Indices"],
                    out_dtype=getattr(x, "dtype", "float32"))
    return outs[1]


def sort(x, axis=-1, descending=False, name=None):
    outs = apply_op("argsort", "argsort", {"X": [x]},
                    {"axis": axis, "descending": descending},
                    ["Out", "Indices"],
                    out_dtype=getattr(x, "dtype", "float32"))
    return outs[0]


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    outs = apply_op("top_k_v2", "top_k_v2", {"X": [x]},
                    {"k": int(k), "axis": -1 if axis is None else axis,
                     "largest": largest},
                    ["Out", "Indices"],
                    out_dtype=getattr(x, "dtype", "float32"))
    return outs[0], outs[1]


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return _nn.where(condition, x, y)


def nonzero(x, as_tuple=False):
    out = apply_op("where_index", "where_index", {"Condition": [x]}, {},
                   ["Out"], out_dtype="int64")[0]
    if as_tuple:
        ndim = len(getattr(x, "shape", ())) or 1
        return tuple(_nn.slice(out, axes=[1], starts=[i], ends=[i + 1])
                     for i in range(ndim))
    return out


def index_select(x, index, axis=0, name=None):
    return apply_op("index_select", "index_select",
                    {"X": [x], "Index": [index]}, {"dim": axis}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


def masked_select(x, mask, name=None):
    return apply_op("masked_select", "masked_select",
                    {"X": [x], "Mask": [mask]}, {}, ["Y"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]
