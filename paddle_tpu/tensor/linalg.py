"""paddle.tensor linalg ops (reference:
`python/paddle/tensor/linalg.py`)."""
from __future__ import annotations

from ..fluid.layer_helper import apply_op
from ..fluid.layers import nn as _nn


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _nn.matmul(x, y, transpose_x, transpose_y)


def bmm(x, y, name=None):
    return _nn.matmul(x, y)


def dot(x, y, name=None):
    prod = _nn.elementwise_mul(x, y)
    ndim = len(getattr(prod, "shape", ())) or 1
    return _nn.reduce_sum(prod, dim=ndim - 1, keep_dim=False)


def norm(x, p=2, axis=None, keepdim=False, name=None):
    if p in ("fro", 2) and axis is None:
        sq = _nn.reduce_sum(_nn.square(x))
        return _nn.sqrt(sq)
    axis = -1 if axis is None else axis
    return apply_op("p_norm", "p_norm", {"X": [x]},
                    {"porder": float(p), "axis": int(axis),
                     "keepdim": keepdim}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


def t(x, name=None):
    from ..fluid.layers import tensor as _t

    ndim = len(getattr(x, "shape", ()))
    if ndim <= 1:
        return x
    return _t.transpose(x, [1, 0])


def transpose(x, perm, name=None):
    from ..fluid.layers import tensor as _t

    return _t.transpose(x, perm)


def dist(x, y, p=2, name=None):
    diff = _nn.elementwise_sub(x, y)
    if p == 2:
        return _nn.sqrt(_nn.reduce_sum(_nn.square(diff)))
    return apply_op("p_norm", "p_norm", {"X": [diff]},
                    {"porder": float(p), "axis": -1, "keepdim": False},
                    ["Out"], out_dtype=getattr(x, "dtype", "float32"))[0]
