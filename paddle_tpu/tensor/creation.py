"""paddle.tensor creation ops (reference:
`python/paddle/tensor/creation.py`)."""
from __future__ import annotations

import numpy as np

from ..core.types import normalize_dtype
from ..fluid.layer_helper import apply_op
from ..fluid.layers import tensor as _t


def zeros(shape, dtype="float32", name=None):
    return _t.zeros(shape, dtype)


def ones(shape, dtype="float32", name=None):
    return _t.ones(shape, dtype)


def full(shape, fill_value, dtype="float32", name=None):
    return _t.fill_constant(list(shape), dtype, fill_value)


def zeros_like(x, dtype=None, name=None):
    return _t.zeros_like(x)


def ones_like(x, dtype=None, name=None):
    return _t.ones_like(x)


def full_like(x, fill_value, dtype=None, name=None):
    return apply_op("fill_any_like", "fill_any_like", {"X": [x]},
                    {"value": float(fill_value),
                     "dtype": normalize_dtype(dtype) if dtype else None},
                    ["Out"],
                    out_dtype=normalize_dtype(dtype) if dtype
                    else getattr(x, "dtype", "float32"))[0]


def arange(start=0, end=None, step=1, dtype="int64", name=None):
    if end is None:
        start, end = 0, start
    return apply_op("range", "range", {}, {
        "start": float(start), "end": float(end), "step": float(step),
        "dtype": normalize_dtype(dtype)}, ["Out"],
        out_dtype=normalize_dtype(dtype))[0]


def linspace(start, stop, num, dtype="float32", name=None):
    return _t.linspace(start, stop, num, dtype)


def eye(num_rows, num_columns=None, dtype="float32", name=None):
    return _t.eye(num_rows, num_columns, dtype=dtype)


def diag(x, offset=0, padding_value=0, name=None):
    return apply_op("diag_v2", "diag_v2", {"X": [x]},
                    {"offset": offset, "padding_value": padding_value},
                    ["Out"], out_dtype=getattr(x, "dtype", "float32"))[0]


def meshgrid(*args, **kwargs):
    inputs = list(args[0]) if len(args) == 1 and \
        isinstance(args[0], (list, tuple)) else list(args)
    return apply_op("meshgrid", "meshgrid", {"X": inputs}, {},
                    {"Out": len(inputs)},
                    out_dtype=getattr(inputs[0], "dtype", "float32"))


def tril(x, diagonal=0, name=None):
    return _t.tril(x, diagonal)


def triu(x, diagonal=0, name=None):
    return _t.triu(x, diagonal)


def assign(x, output=None):
    return _t.assign(np.asarray(x) if not hasattr(x, "dtype") else x,
                     output=output)


def clone(x, name=None):
    return _t.assign(x)


def empty(shape, dtype="float32", name=None):
    return zeros(shape, dtype)


def numel(x, name=None):
    n = 1
    for s in getattr(x, "shape", ()):
        n *= int(s)
    return full([1], n, dtype="int64")
