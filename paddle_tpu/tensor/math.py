"""paddle.tensor math ops (reference: `python/paddle/tensor/math.py`) —
thin mode-polymorphic wrappers over the op registry."""
from __future__ import annotations

from ..fluid.layer_helper import apply_op
from ..fluid.layers import nn as _nn
from ..fluid.layers import tensor as _t


def _unary(op_type, x, attrs=None):
    return apply_op(op_type, op_type, {"X": [x]}, attrs or {}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


def add(x, y, name=None):
    return _nn.elementwise_add(x, y)


def subtract(x, y, name=None):
    return _nn.elementwise_sub(x, y)


def multiply(x, y, name=None):
    return _nn.elementwise_mul(x, y)


def divide(x, y, name=None):
    return _nn.elementwise_div(x, y)


def floor_divide(x, y, name=None):
    return _nn.elementwise_floordiv(x, y)


def mod(x, y, name=None):
    return _nn.elementwise_mod(x, y)


remainder = mod


def pow(x, y, name=None):
    if isinstance(y, (int, float)):
        return apply_op("pow", "pow", {"X": [x]}, {"factor": float(y)},
                        ["Out"], out_dtype=getattr(x, "dtype",
                                                   "float32"))[0]
    return _nn.elementwise_pow(x, y)


def maximum(x, y, name=None):
    return _nn.maximum(x, y)


def minimum(x, y, name=None):
    return _nn.minimum(x, y)


def sqrt(x, name=None):
    return _nn.sqrt(x)


def rsqrt(x, name=None):
    return _unary("rsqrt", x)


def square(x, name=None):
    return _nn.square(x)


def abs(x, name=None):
    return _nn.abs(x)


def sign(x, name=None):
    return _unary("sign", x)


def ceil(x, name=None):
    return _nn.ceil(x)


def floor(x, name=None):
    return _nn.floor(x)


def round(x, name=None):
    return _nn.round(x)


def reciprocal(x, name=None):
    return _nn.reciprocal(x)


def exp(x, name=None):
    return _nn.exp(x)


def log(x, name=None):
    return _nn.log(x)


def log2(x, name=None):
    return _unary("log2", x)


def log10(x, name=None):
    return _unary("log10", x)


def log1p(x, name=None):
    return _unary("log1p", x)


def sin(x, name=None):
    return _nn.sin(x)


def cos(x, name=None):
    return _nn.cos(x)


def tan(x, name=None):
    return divide(sin(x), cos(x))


def asin(x, name=None):
    return _unary("asin", x)


def acos(x, name=None):
    return _unary("acos", x)


def atan(x, name=None):
    return _unary("atan", x)


def sinh(x, name=None):
    return _unary("sinh", x)


def cosh(x, name=None):
    return _unary("cosh", x)


def tanh(x, name=None):
    return apply_op("tanh", "tanh", {"X": [x]}, {}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


def erf(x, name=None):
    return _nn.erf(x)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    return _nn.reduce_sum(x, dim=axis, keep_dim=keepdim)


def mean(x, axis=None, keepdim=False, name=None):
    return _nn.reduce_mean(x, dim=axis, keep_dim=keepdim)


def max(x, axis=None, keepdim=False, name=None):
    return _nn.reduce_max(x, dim=axis, keep_dim=keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return _nn.reduce_min(x, dim=axis, keep_dim=keepdim)


def prod(x, axis=None, keepdim=False, name=None):
    return _nn.reduce_prod(x, dim=axis, keep_dim=keepdim)


def all(x, axis=None, keepdim=False, name=None):
    return _nn.reduce_all(x, dim=axis, keep_dim=keepdim)


def any(x, axis=None, keepdim=False, name=None):
    return _nn.reduce_any(x, dim=axis, keep_dim=keepdim)


def cumsum(x, axis=None, dtype=None, name=None):
    return _t.cumsum(x, axis=axis)


def clip(x, min=None, max=None, name=None):
    import numpy as np

    lo = -np.inf if min is None else float(min)
    hi = np.inf if max is None else float(max)
    return _nn.clip(x, lo, hi)


def isnan(x, name=None):
    return apply_op("isnan_v2", "isnan_v2", {"X": [x]}, {}, ["Out"],
                    out_dtype="bool")[0]


def isinf(x, name=None):
    return apply_op("isinf_v2", "isinf_v2", {"X": [x]}, {}, ["Out"],
                    out_dtype="bool")[0]


def isfinite(x, name=None):
    return apply_op("isfinite_v2", "isfinite_v2", {"X": [x]}, {}, ["Out"],
                    out_dtype="bool")[0]


def add_n(inputs, name=None):
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    return apply_op("sum", "sum", {"X": list(inputs)}, {}, ["Out"],
                    out_dtype=getattr(inputs[0], "dtype", "float32"))[0]


def increment(x, value=1.0, name=None):
    return apply_op("increment", "increment", {"X": [x]},
                    {"step": float(value)}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    return _t.scale(x, scale, bias)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _unary("stanh", x, {"scale_a": scale_a, "scale_b": scale_b})


def kron(x, y, name=None):
    raise NotImplementedError("kron: not yet implemented on TPU build")
