"""paddle.tensor logic ops (reference:
`python/paddle/tensor/logic.py`)."""
from __future__ import annotations

from ..fluid.layer_helper import apply_op
from ..fluid.layers import nn as _nn


def _cmp(op_type, x, y):
    return apply_op(op_type, op_type, {"X": [x], "Y": [y]}, {}, ["Out"],
                    out_dtype="bool")[0]


def equal(x, y, name=None):
    return _cmp("equal", x, y)


def not_equal(x, y, name=None):
    return _cmp("not_equal", x, y)


def less_than(x, y, name=None):
    return _cmp("less_than", x, y)


def less_equal(x, y, name=None):
    return _cmp("less_equal", x, y)


def greater_than(x, y, name=None):
    return _cmp("greater_than", x, y)


def greater_equal(x, y, name=None):
    return _cmp("greater_equal", x, y)


def logical_and(x, y, out=None, name=None):
    return _nn.logical_and(x, y)


def logical_or(x, y, out=None, name=None):
    return _nn.logical_or(x, y)


def logical_xor(x, y, out=None, name=None):
    return _nn.logical_xor(x, y)


def logical_not(x, out=None, name=None):
    return _nn.logical_not(x)


def equal_all(x, y, name=None):
    return _nn.reduce_all(equal(x, y))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    from ..fluid.layers import tensor as _t

    diff = _nn.abs(_nn.elementwise_sub(x, y))
    tol = _t.scale(_nn.abs(y), float(rtol), float(atol))  # atol + rtol*|y|
    return _nn.reduce_all(less_equal(diff, tol))
