"""paddle.tensor stat ops (reference: `python/paddle/tensor/stat.py`)."""
from __future__ import annotations

from ..fluid.layers import nn as _nn


def mean(x, axis=None, keepdim=False, name=None):
    return _nn.reduce_mean(x, dim=axis, keep_dim=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    m = _nn.reduce_mean(x, dim=axis, keep_dim=True)
    sq = _nn.square(_nn.elementwise_sub(x, m))
    v = _nn.reduce_mean(sq, dim=axis, keep_dim=keepdim)
    if unbiased:
        shape = getattr(x, "shape", ())
        if axis is None:
            n = 1
            for s in shape:
                n *= int(s)
        elif isinstance(axis, (list, tuple)):
            n = 1
            for a in axis:
                n *= int(shape[a])
        else:
            n = int(shape[axis])
        if n > 1:
            from ..fluid.layers import tensor as _t

            v = _t.scale(v, float(n) / (n - 1), 0.0)
    return v


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _nn.sqrt(var(x, axis=axis, unbiased=unbiased,
                        keepdim=keepdim))


def numel(x, name=None):
    from .creation import numel as _numel

    return _numel(x)
