"""paddle.tensor 2.0-style namespace (reference: `python/paddle/tensor/`)
— math/manipulation/creation re-exports over fluid.layers."""
from ..fluid.layers.nn import (  # noqa: F401
    matmul, elementwise_add as add, elementwise_sub as subtract,
    elementwise_mul as multiply, elementwise_div as divide,
    reduce_sum as sum, reduce_mean as mean, reduce_max as max,
    reduce_min as min, reduce_prod as prod, clip, topk, squeeze, unsqueeze,
    stack, split, gather, gather_nd, scatter, where, expand,
    maximum, minimum, sqrt, square, exp, log, abs, sin, cos,
)
from ..fluid.layers.tensor import (  # noqa: F401
    cast, concat, reshape, transpose, zeros, ones, zeros_like, ones_like,
    argmax, argmin, argsort, cumsum, linspace, eye, tril, triu, fill_constant,
)
