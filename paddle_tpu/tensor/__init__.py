"""paddle.tensor 2.0-style namespace (reference: `python/paddle/tensor/`)
— math/linalg/manipulation/creation/search/stat/random/logic over the
mode-polymorphic fluid layer builders."""
from . import (  # noqa: F401
    creation, linalg, logic, manipulation, math, random, search, stat,
)
from .creation import (  # noqa: F401
    zeros, ones, full, zeros_like, ones_like, full_like, arange, linspace,
    eye, diag, meshgrid, tril, triu, assign, clone, empty, numel,
)
from .linalg import (  # noqa: F401
    matmul, bmm, dot, norm, t, dist,
)
from .logic import (  # noqa: F401
    equal, not_equal, less_than, less_equal, greater_than, greater_equal,
    logical_and, logical_or, logical_xor, logical_not, equal_all, allclose,
)
from .manipulation import (  # noqa: F401
    reshape, transpose, concat, stack, unstack, split, chunk, squeeze,
    unsqueeze, flatten, flip, roll, tile, expand, broadcast_to, expand_as,
    gather, gather_nd, scatter, scatter_nd_add, slice, strided_slice,
    cast, unique, take_along_axis,
)
from .math import (  # noqa: F401
    add, subtract, multiply, divide, floor_divide, mod, remainder, pow,
    maximum, minimum, sqrt, rsqrt, square, abs, sign, ceil, floor, round,
    reciprocal, exp, log, log2, log10, log1p, sin, cos, tan, asin, acos,
    atan, sinh, cosh, tanh, erf, sum, max, min, prod,
    all, any, cumsum, clip, isnan, isinf, isfinite, add_n, increment,
    scale, stanh,
)
from .search import (  # noqa: F401
    argmax, argmin, argsort, sort, topk, where, nonzero, index_select,
    masked_select,
)
from .stat import mean, var, std  # noqa: F401
from ..fluid.layers.tensor import fill_constant  # noqa: F401
