"""paddle.tensor random ops (reference:
`python/paddle/tensor/random.py`). All sample through the seeded
stateless op registry (uniform_random/gaussian_random/...)."""
from __future__ import annotations

from ..core.types import normalize_dtype
from ..fluid.layer_helper import apply_op


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    return apply_op("uniform_random", "uniform_random", {}, {
        "shape": list(shape), "min": float(min), "max": float(max),
        "seed": seed, "dtype": normalize_dtype(dtype)}, ["Out"],
        out_dtype=normalize_dtype(dtype))[0]


def rand(shape, dtype="float32", name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    return apply_op("gaussian_random", "gaussian_random", {}, {
        "shape": list(shape), "mean": float(mean), "std": float(std),
        "seed": 0, "dtype": "float32"}, ["Out"], out_dtype="float32")[0]


def randn(shape, dtype="float32", name=None):
    return normal(0.0, 1.0, shape)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return apply_op("randint", "randint", {}, {
        "low": int(low), "high": int(high), "shape": list(shape),
        "seed": 0, "dtype": normalize_dtype(dtype)}, ["Out"],
        out_dtype=normalize_dtype(dtype))[0]


def randperm(n, dtype="int64", name=None):
    return apply_op("randperm", "randperm", {}, {
        "n": int(n), "seed": 0, "dtype": normalize_dtype(dtype)},
        ["Out"], out_dtype=normalize_dtype(dtype))[0]


def bernoulli(x, name=None):
    return apply_op("bernoulli", "bernoulli", {"X": [x]}, {}, ["Out"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]


def multinomial(x, num_samples=1, replacement=False, name=None):
    return apply_op("multinomial", "multinomial", {"X": [x]},
                    {"num_samples": int(num_samples),
                     "replacement": replacement}, ["Out"],
                    out_dtype="int64")[0]
