"""paddle.nn layer classes, second tranche (reference:
`python/paddle/nn/layer/` common/conv/loss/norm/extension): the 2.0
class surface over the functional builders."""
from __future__ import annotations

from ..fluid.dygraph.layers import Layer
from ..fluid.initializer import ConstantInitializer, NormalInitializer
from . import functional as F

__all__ = [
    "BCELoss", "NLLLoss", "HSigmoid", "LogSoftmax", "Pad2D", "UpSample",
    "Conv3D", "Conv3DTranspose", "RowConv", "SpectralNorm",
    "BilinearTensorProduct", "InstanceNorm",
]


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean"):
        super().__init__()
        self._weight = weight
        self._reduction = reduction

    def forward(self, input, label):
        from ..fluid.layer_helper import apply_op
        from .. import tensor as T

        out = apply_op("bce_loss", "bce_loss",
                       {"X": [input], "Label": [label]}, {}, ["Out"],
                       out_dtype="float32")[0]
        if self._weight is not None:
            out = out * self._weight
        if self._reduction == "mean":
            return T.mean(out)
        if self._reduction == "sum":
            return T.sum(out)
        return out


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean"):
        super().__init__()
        self._weight = weight
        self._ignore = ignore_index
        self._reduction = reduction

    def forward(self, input, label):
        from ..fluid.layer_helper import apply_op

        ins = {"X": [input], "Label": [label]}
        if self._weight is not None:
            ins["Weight"] = [self._weight]
        return apply_op("nll_loss", "nll_loss", ins,
                        {"reduction": self._reduction,
                         "ignore_index": self._ignore},
                        ["Out", "Total_weight"],
                        out_dtype="float32")[0]


class HSigmoid(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self._num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], attr=weight_attr,
            default_initializer=NormalInitializer(scale=0.01))
        self.bias = self.create_parameter(
            [num_classes - 1], attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        from ..fluid.layer_helper import apply_op

        return apply_op("hsigmoid", "hsigmoid",
                        {"X": [input], "W": [self.weight],
                         "Label": [label], "Bias": [self.bias]},
                        {"num_classes": self._num_classes},
                        ["Out", "PreOut"], out_dtype="float32")[0]


class LogSoftmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self._axis)


class Pad2D(Layer):
    def __init__(self, paddings=0, mode="constant", pad_value=0.0,
                 data_format="NCHW"):
        super().__init__()
        self._pad = ([paddings] * 4 if isinstance(paddings, int)
                     else list(paddings))
        self._mode = mode
        self._value = pad_value

    def forward(self, x):
        return F.pad2d(x, paddings=self._pad, mode=self._mode,
                       pad_value=self._value)


class UpSample(Layer):
    def __init__(self, out_shape=None, scale=None, resample="BILINEAR",
                 actual_shape=None, align_corners=True, align_mode=1,
                 data_format="NCHW"):
        super().__init__()
        self._args = (out_shape, scale, resample, align_corners,
                      align_mode, data_format)

    def forward(self, x):
        out_shape, scale, resample, ac, am, fmt = self._args
        return F.interpolate(x, out_shape=out_shape, scale=scale,
                             resample=resample, align_corners=ac,
                             align_mode=am, data_format=fmt)


class _ConvNd(Layer):
    _op = "conv3d"
    _transpose = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        nd = 3
        k = [kernel_size] * nd if isinstance(kernel_size, int) \
            else list(kernel_size)
        if self._transpose:
            w_shape = [in_channels, out_channels // groups] + k
        else:
            w_shape = [out_channels, in_channels // groups] + k
        self.weight = self.create_parameter(w_shape, attr=weight_attr)
        self.bias = (self.create_parameter([out_channels], attr=bias_attr,
                                           is_bias=True)
                     if bias_attr is not False else None)
        self._attrs = {"strides": [stride] * nd if isinstance(stride, int)
                       else list(stride),
                       "paddings": [padding] * nd
                       if isinstance(padding, int) else list(padding),
                       "dilations": [dilation] * nd
                       if isinstance(dilation, int) else list(dilation),
                       "groups": groups}

    def forward(self, x):
        from ..fluid.layer_helper import apply_op

        out = apply_op(self._op, self._op,
                       {"Input": [x], "Filter": [self.weight]},
                       self._attrs, ["Output"],
                       out_dtype=getattr(x, "dtype", "float32"))[0]
        if self.bias is not None:
            out = apply_op("elementwise_add", "elementwise_add",
                           {"X": [out], "Y": [self.bias]}, {"axis": 1},
                           ["Out"],
                           out_dtype=getattr(x, "dtype", "float32"))[0]
        return out


class Conv3D(_ConvNd):
    _op = "conv3d"


class Conv3DTranspose(_ConvNd):
    _op = "conv3d_transpose"
    _transpose = True


class RowConv(Layer):
    def __init__(self, num_channels, future_context_size,
                 param_attr=None, act=None):
        super().__init__()
        self.weight = self.create_parameter(
            [future_context_size + 1, num_channels], attr=param_attr)
        self._act = act

    def forward(self, x):
        from ..fluid.layer_helper import apply_op

        out = apply_op("row_conv", "row_conv",
                       {"X": [x], "Filter": [self.weight]}, {}, ["Out"],
                       out_dtype=getattr(x, "dtype", "float32"))[0]
        if self._act == "relu":
            out = F.relu(out)
        return out


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12):
        super().__init__()
        import numpy as np

        h = weight_shape[dim]
        w_dim = int(np.prod(weight_shape)) // h
        self._attrs = {"dim": dim, "power_iters": power_iters, "eps": eps}
        self.weight_u = self.create_parameter(
            [h], default_initializer=NormalInitializer())
        self.weight_v = self.create_parameter(
            [w_dim], default_initializer=NormalInitializer())

    def forward(self, weight):
        from ..fluid.layer_helper import apply_op

        return apply_op("spectral_norm", "spectral_norm",
                        {"Weight": [weight], "U": [self.weight_u],
                         "V": [self.weight_v]}, self._attrs, ["Out"],
                        out_dtype=getattr(weight, "dtype", "float32"))[0]


class BilinearTensorProduct(Layer):
    def __init__(self, input1_dim, input2_dim, output_dim,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim], attr=weight_attr)
        self.bias = self.create_parameter([1, output_dim], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        from ..fluid.layer_helper import apply_op

        return apply_op("bilinear_tensor_product",
                        "bilinear_tensor_product",
                        {"X": [x1], "Y": [x2], "Weight": [self.weight],
                         "Bias": [self.bias]}, {}, ["Out"],
                        out_dtype=getattr(x1, "dtype", "float32"))[0]


class InstanceNorm(Layer):
    def __init__(self, num_features, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        self._eps = epsilon
        self.scale = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        from ..fluid.layer_helper import apply_op

        return apply_op("instance_norm", "instance_norm",
                        {"X": [x], "Scale": [self.scale],
                         "Bias": [self.bias]},
                        {"epsilon": self._eps}, ["Y"],
                        out_dtype="float32")[0]
