"""paddle.nn.functional (reference: `python/paddle/nn/functional/`) — the
mode-polymorphic layer functions re-exported plus 2.0-only entry
points."""
from ..fluid.layers.nn import (  # noqa: F401
    relu, sigmoid, tanh, gelu, leaky_relu, elu, relu6, softplus, softsign,
    swish, hard_sigmoid, hard_swish, logsigmoid, erf, softmax, log_softmax,
    dropout, matmul, one_hot, pad, pad2d, clip,
)
from ..fluid.layers.loss import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy,
    sigmoid_cross_entropy_with_logits, square_error_cost, mse_loss,
    kldiv_loss,
)
from ..fluid.layer_helper import apply_op as _apply_op
from ..fluid.layers import nn as _nn


def linear(x, weight, bias=None, name=None):
    out = _nn.matmul(x, weight)
    if bias is not None:
        ndim = len(getattr(out, "shape", ())) or 1
        out = _apply_op("elementwise_add", "elementwise_add",
                        {"X": [out], "Y": [bias]}, {"axis": ndim - 1},
                        ["Out"],
                        out_dtype=getattr(x, "dtype", "float32"))[0]
    return out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
           groups=1, name=None):
    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    out = _apply_op("conv2d", "conv2d",
                    {"Input": [x], "Filter": [weight]},
                    {"strides": _pair(stride), "paddings": _pair(padding),
                     "dilations": _pair(dilation), "groups": groups},
                    ["Output"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]
    if bias is not None:
        out = _apply_op("elementwise_add", "elementwise_add",
                        {"X": [out], "Y": [bias]}, {"axis": 1}, ["Out"],
                        out_dtype=getattr(x, "dtype", "float32"))[0]
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, name=None):
    return _nn.pool2d(x, pool_size=kernel_size, pool_type="max",
                      pool_stride=stride or kernel_size,
                      pool_padding=padding)


def avg_pool2d(x, kernel_size, stride=None, padding=0, name=None):
    return _nn.pool2d(x, pool_size=kernel_size, pool_type="avg",
                      pool_stride=stride or kernel_size,
                      pool_padding=padding)


def adaptive_avg_pool2d(x, output_size, name=None):
    return _nn.adaptive_pool2d(x, output_size, pool_type="avg")


def embedding(x, weight, padding_idx=None, name=None):
    return _apply_op("lookup_table_v2", "lookup_table_v2",
                     {"Ids": [x], "W": [weight]},
                     {"padding_idx": -1 if padding_idx is None
                      else padding_idx}, ["Out"],
                     out_dtype=getattr(weight, "dtype", "float32"))[0]


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    return _nn.l2_normalize(x, axis=axis, epsilon=epsilon)


def binary_cross_entropy_with_logits(logit, label, reduction="mean",
                                     name=None):
    out = sigmoid_cross_entropy_with_logits(logit, label)
    if reduction == "mean":
        return _nn.mean(out)
    if reduction == "sum":
        return _nn.reduce_sum(out)
    return out


def l1_loss(input, label, reduction="mean", name=None):
    out = _nn.abs(_nn.elementwise_sub(input, label))
    if reduction == "mean":
        return _nn.mean(out)
    if reduction == "sum":
        return _nn.reduce_sum(out)
    return out


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    out = _apply_op("huber_loss", "huber_loss",
                    {"X": [input], "Y": [label]}, {"delta": delta},
                    ["Out"], out_dtype="float32")[0]
    if reduction == "mean":
        return _nn.mean(out)
    if reduction == "sum":
        return _nn.reduce_sum(out)
    return out


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Fused attention over [B, H, S, D] heads; lowers to the Pallas
    flash kernel on TPU (ops/nn_ops.py scaled_dot_product_attention)."""
    ins = {"Q": [query], "K": [key], "V": [value]}
    if attn_mask is not None:
        # paddle 2.x semantics: attn_mask is ALWAYS a full additive (or
        # bool keep-) mask broadcastable to [B, H, Sq, Sk] — routed down
        # the op's unfused XLA path. A [batch, seq_k] KEY bias (which
        # rides the fused/flash path) is a different parameter: use
        # fluid.layers.scaled_dot_product_attention(key_bias=...) —
        # shape-guessing between the two here silently mis-broadcasts.
        ins["Mask"] = [attn_mask]
    return _apply_op("scaled_dot_product_attention",
                     "scaled_dot_product_attention", ins,
                     {"causal": is_causal,
                      "attn_dropout_prob": dropout_p,
                      "is_test": not training}, ["Out"])[0]


# -- reference functional/__init__.py alias surface (DEFINE_ALIAS names) ----
# everything below re-exports the fluid.layers builders under the 2.0
# namespace (reference: python/paddle/nn/functional/__init__.py).
from ..fluid.layers.nn import (  # noqa: F401,E402
    l2_normalize, label_smooth, pool2d, adaptive_pool2d,
    elementwise_add,
)
from ..fluid.layers.nn_extra import (  # noqa: F401,E402
    brelu, hard_shrink, maxout,
)
from ..fluid.layers.nn_extra import (  # noqa: F401,E402
    interpolate, resize_bilinear, resize_trilinear, resize_bicubic,
    image_resize_short, pool3d, adaptive_pool3d, grid_sampler,
    affine_grid, affine_channel, lrn, unfold, space_to_depth,
    shuffle_channel, temporal_shift, pixel_shuffle, selu, softshrink,
    tanh_shrink, soft_relu, thresholded_relu, row_conv, fsp_matrix, hash,
    add_position_encoding, similarity_focus, random_crop,
    pad_constant_like, continuous_value_model, filter_by_instag,
    warpctc, hsigmoid, sampled_softmax_with_cross_entropy,
    dice_loss, log_loss, npair_loss, rank_loss, margin_rank_loss,
    bpr_loss, center_loss, teacher_student_sigmoid_loss, cos_sim,
    deformable_conv, unpool, conv3d, conv3d_transpose,
)
from ..fluid.layers.nn import (  # noqa: F401,E402
    image_resize, resize_nearest,
)
from ..fluid.layers.detection import (  # noqa: F401,E402
    anchor_generator, bipartite_match, box_clip, box_coder,
    box_decoder_and_assign, collect_fpn_proposals, density_prior_box,
    detection_output, distribute_fpn_proposals,
    deformable_roi_pooling, generate_proposal_labels,
    generate_proposals, iou_similarity, multiclass_nms,
    polygon_box_transform, prior_box, prroi_pool, psroi_pool,
    retinanet_detection_output, retinanet_target_assign, roi_align,
    roi_pool, roi_perspective_transform, rpn_target_assign,
    sigmoid_focal_loss, ssd_loss, target_assign, yolo_box, yolov3_loss,
)
from ..fluid.layers.loss import (  # noqa: F401,E402
    huber_loss, smooth_l1,
)
from ..fluid.layers.learning_rate_scheduler import (  # noqa: F401,E402
    cosine_decay, exponential_decay, inverse_time_decay, natural_exp_decay,
    noam_decay, piecewise_decay, polynomial_decay,
)
from ..fluid.layers.learning_rate_scheduler import (  # noqa: F401,E402
    linear_lr_warmup,
)
from ..fluid.layers.tensor import assign  # noqa: F401,E402


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    return _apply_op("diag_embed", "diag_embed", {"Input": [input]},
                     {"offset": offset, "dim1": dim1, "dim2": dim2},
                     ["Out"],
                     out_dtype=getattr(input, "dtype", "float32"))[0]


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     dilation=1, groups=1, output_size=None, name=None):
    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    out = _apply_op("conv2d_transpose", "conv2d_transpose",
                    {"Input": [x], "Filter": [weight]},
                    {"strides": _pair(stride), "paddings": _pair(padding),
                     "dilations": _pair(dilation), "groups": groups},
                    ["Output"],
                    out_dtype=getattr(x, "dtype", "float32"))[0]
    if bias is not None:
        out = _apply_op("elementwise_add", "elementwise_add",
                        {"X": [out], "Y": [bias]}, {"axis": 1}, ["Out"],
                        out_dtype=getattr(x, "dtype", "float32"))[0]
    return out
