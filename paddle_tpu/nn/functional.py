"""paddle.nn.functional (reference: `python/paddle/nn/functional/`) — the
mode-polymorphic layer functions re-exported."""
from ..fluid.layers.nn import (  # noqa: F401
    relu, sigmoid, tanh, gelu, leaky_relu, elu, relu6, softplus, softsign,
    swish, hard_sigmoid, hard_swish, logsigmoid, erf, softmax, log_softmax,
    dropout, matmul, one_hot, pad, pad2d, clip,
)
from ..fluid.layers.loss import (  # noqa: F401
    cross_entropy, softmax_with_cross_entropy,
    sigmoid_cross_entropy_with_logits, square_error_cost, mse_loss,
    kldiv_loss,
)
