"""paddle.nn 2.0-style surface (reference: `python/paddle/nn/`) — thin
re-exports over the fluid dygraph layer library."""
from ..fluid.dygraph.layers import (  # noqa: F401
    Layer, Sequential, LayerList, ParameterList,
)
from ..fluid.dygraph.nn import (  # noqa: F401
    Linear, Conv2D, Pool2D, BatchNorm, LayerNorm, Embedding, Dropout,
)
from . import functional  # noqa: F401


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class GELU(Layer):
    def __init__(self, approximate=False):
        super().__init__()
        self._approx = approximate

    def forward(self, x):
        return functional.gelu(x, approximate=self._approx)


class Sigmoid(Layer):
    def forward(self, x):
        return functional.sigmoid(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return functional.softmax(x, axis=self._axis)


class Tanh(Layer):
    def forward(self, x):
        return functional.tanh(x)


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, reduction="mean", ignore_index=-100,
                 soft_label=False):
        super().__init__()
        self._reduction = reduction
        self._ignore_index = ignore_index
        self._soft_label = soft_label

    def forward(self, input, label):
        from ..fluid.layers import loss as L
        from ..fluid.layers import nn as N

        out = L.softmax_with_cross_entropy(
            input, label, soft_label=self._soft_label,
            ignore_index=self._ignore_index)
        if self._reduction == "mean":
            return N.mean(out)
        if self._reduction == "sum":
            return N.reduce_sum(out)
        return out


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        from ..fluid.layers import loss as L
        from ..fluid.layers import nn as N

        out = L.square_error_cost(input, label)
        if self._reduction == "mean":
            return N.mean(out)
        if self._reduction == "sum":
            return N.reduce_sum(out)
        return out
