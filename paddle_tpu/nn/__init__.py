"""paddle.nn 2.0-style surface (reference: `python/paddle/nn/`) — thin
re-exports over the fluid dygraph layer library."""
from ..fluid.initializer import ConstantInitializer
from ..fluid.dygraph.layers import (  # noqa: F401
    Layer, Sequential, LayerList, ParameterList,
)
from ..fluid.dygraph.nn import (  # noqa: F401
    Linear, Conv2D, Pool2D, BatchNorm, LayerNorm, Embedding, Dropout,
)
from . import functional  # noqa: F401


class ReLU(Layer):
    def forward(self, x):
        return functional.relu(x)


class GELU(Layer):
    def __init__(self, approximate=False):
        super().__init__()
        self._approx = approximate

    def forward(self, x):
        return functional.gelu(x, approximate=self._approx)


class Sigmoid(Layer):
    def forward(self, x):
        return functional.sigmoid(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return functional.softmax(x, axis=self._axis)


class Tanh(Layer):
    def forward(self, x):
        return functional.tanh(x)


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, reduction="mean", ignore_index=-100,
                 soft_label=False):
        super().__init__()
        self._reduction = reduction
        self._ignore_index = ignore_index
        self._soft_label = soft_label

    def forward(self, input, label):
        from ..fluid.layers import loss as L
        from ..fluid.layers import nn as N

        out = L.softmax_with_cross_entropy(
            input, label, soft_label=self._soft_label,
            ignore_index=self._ignore_index)
        if self._reduction == "mean":
            return N.mean(out)
        if self._reduction == "sum":
            return N.reduce_sum(out)
        return out


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        from ..fluid.layers import loss as L
        from ..fluid.layers import nn as N

        out = L.square_error_cost(input, label)
        if self._reduction == "mean":
            return N.mean(out)
        if self._reduction == "sum":
            return N.reduce_sum(out)
        return out


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        from ..fluid.layers import nn as N

        return N.leaky_relu(x, alpha=self._slope)


class Hardswish(Layer):
    def forward(self, x):
        from ..fluid.layers import nn as N

        return N.hard_swish(x)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25):
        super().__init__()
        self.weight = self.create_parameter(
            [num_parameters],
            default_initializer=ConstantInitializer(init))

    def forward(self, x):
        from .. import tensor as T

        pos = T.maximum(x, T.zeros_like(x))
        neg = T.minimum(x, T.zeros_like(x)) * self.weight
        return pos + neg


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self._start, self._stop = start_axis, stop_axis

    def forward(self, x):
        from ..tensor import manipulation as M

        return M.flatten(x, self._start, self._stop)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        self._k, self._s, self._p = kernel_size, stride or kernel_size, \
            padding

    def forward(self, x):
        from ..fluid.layers import nn as N

        return N.pool2d(x, pool_size=self._k, pool_type="max",
                        pool_stride=self._s, pool_padding=self._p)


class AvgPool2D(MaxPool2D):
    def forward(self, x):
        from ..fluid.layers import nn as N

        return N.pool2d(x, pool_size=self._k, pool_type="avg",
                        pool_stride=self._s, pool_padding=self._p)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size):
        super().__init__()
        self._out = output_size

    def forward(self, x):
        from ..fluid.layers import nn as N

        return N.adaptive_pool2d(x, self._out, pool_type="avg")


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self._groups = num_groups
        self._eps = epsilon
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        from ..fluid.layer_helper import apply_op

        return apply_op("group_norm", "group_norm",
                        {"X": [x], "Scale": [self.weight],
                         "Bias": [self.bias]},
                        {"groups": self._groups, "epsilon": self._eps},
                        ["Y"], out_dtype="float32")[0]


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self._eps = epsilon
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        from ..fluid.layer_helper import apply_op

        return apply_op("instance_norm", "instance_norm",
                        {"X": [x], "Scale": [self.weight],
                         "Bias": [self.bias]},
                        {"epsilon": self._eps}, ["Y"],
                        out_dtype="float32")[0]


class L1Loss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        from .. import tensor as T

        out = T.abs(T.subtract(input, label))
        if self._reduction == "mean":
            return T.mean(out)
        if self._reduction == "sum":
            return T.sum(out)
        return out


class BCEWithLogitsLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, logit, label):
        from ..fluid.layers import loss as L
        from .. import tensor as T

        out = L.sigmoid_cross_entropy_with_logits(logit, label)
        if self._reduction == "mean":
            return T.mean(out)
        if self._reduction == "sum":
            return T.sum(out)
        return out


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        from ..fluid.layers import loss as L
        from .. import tensor as T

        out = L.kldiv_loss(input, label, reduction="none")
        if self._reduction == "mean":
            return T.mean(out)
        if self._reduction == "sum":
            return T.sum(out)
        return out


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0):
        super().__init__()
        self._reduction = reduction
        self._delta = delta

    def forward(self, input, label):
        from ..fluid.layer_helper import apply_op
        from .. import tensor as T

        out = apply_op("huber_loss", "huber_loss",
                       {"X": [input], "Y": [label]},
                       {"delta": self._delta}, ["Out"],
                       out_dtype="float32")[0]
        if self._reduction == "mean":
            return T.mean(out)
        if self._reduction == "sum":
            return T.sum(out)
        return out


class Conv2DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        from ..fluid.dygraph import nn as dnn

        self._impl = dnn.Conv2DTranspose(
            in_channels, out_channels, kernel_size, stride=stride,
            padding=padding, dilation=dilation, groups=groups,
            param_attr=weight_attr, bias_attr=bias_attr)

    def forward(self, x):
        return self._impl(x)


from .rnn import LSTM, GRU  # noqa: F401,E402
from .transformer import (  # noqa: F401,E402
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
)


# -- reference nn/__init__.py surface completion ---------------------------
from .layers_extra import (  # noqa: F401,E402
    BCELoss, NLLLoss, HSigmoid, LogSoftmax, Pad2D, UpSample, Conv3D,
    Conv3DTranspose, RowConv, SpectralNorm, BilinearTensorProduct,
    InstanceNorm,
)
from ..fluid.clip import (  # noqa: F401,E402
    GradientClipByGlobalNorm, GradientClipByNorm, GradientClipByValue,
)
from ..fluid.layers.nn import clip, clip_by_norm  # noqa: F401,E402
from ..fluid.layers.control_flow import (  # noqa: F401,E402
    case, cond, switch_case, while_loop,
)
from ..fluid.layers.rnn_decode import (  # noqa: F401,E402
    BeamSearchDecoder, dynamic_decode,
)
from ..fluid.layers.tensor import data  # noqa: F401,E402
from ..fluid.dygraph.nn import BatchNorm as BatchNorm2D  # noqa: F401,E402
from .. import tensor as _pt_tensor  # noqa: F401,E402
from ..fluid import initializer as initializer  # noqa: F401,E402


from ..fluid.layers import (  # noqa: F401,E402
    beam_search, beam_search_decode, gather_tree,
)
