"""Recurrent layers (reference: `python/paddle/fluid/layers/rnn.py`
LSTMCell/GRUCell/dynamic_rnn + paddle.nn.LSTM/GRU). TPU-native: each
layer-direction is ONE `lstm_seq`/`gru_seq` op, scanned by lax.scan
with the input projection hoisted out of the loop onto the MXU."""
from __future__ import annotations

import numpy as np

from ..fluid.dygraph.layers import Layer
from ..fluid.initializer import UniformInitializer
from ..fluid.layer_helper import apply_op
from ..fluid.layers import tensor as _t


def _uniform(hidden_size):
    k = 1.0 / np.sqrt(hidden_size)
    return UniformInitializer(-k, k)


class _RNNBase(Layer):
    GATES = None  # 4 for LSTM, 3 for GRU

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dtype="float32"):
        super().__init__()
        assert direction in ("forward", "bidirect", "bidirectional")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction != "forward"
        self.time_major = time_major
        self._dirs = 2 if self.bidirectional else 1
        g = self.GATES
        self._weights = []
        for layer in range(num_layers):
            in_dim = input_size if layer == 0 \
                else hidden_size * self._dirs
            for d in range(self._dirs):
                tag = "l%d%s" % (layer, "_rev" if d else "")
                w = {
                    "w_ih": self.create_parameter(
                        [g * hidden_size, in_dim],
                        default_initializer=_uniform(hidden_size)),
                    "w_hh": self.create_parameter(
                        [g * hidden_size, hidden_size],
                        default_initializer=_uniform(hidden_size)),
                }
                for k, v in list(w.items()):
                    self.add_parameter("%s_%s" % (k, tag), v)
                w.update(self._make_biases(g, hidden_size, tag))
                self._weights.append(w)

    def _make_biases(self, g, hidden_size, tag):
        raise NotImplementedError

    def _zeros_state(self, x, batch):
        return _t.fill_constant([batch, self.hidden_size],
                                "float32", 0.0)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs
        if self.time_major:
            x = _t.transpose(x, [1, 0, 2])
        batch = x.shape[0]
        states = self._init_states(x, batch, initial_states)
        outs, last_states = self._run_stack(x, states)
        if self.time_major:
            outs = _t.transpose(outs, [1, 0, 2])
        return outs, last_states


class LSTM(_RNNBase):
    GATES = 4

    def _make_biases(self, g, hidden_size, tag):
        b = self.create_parameter([g * hidden_size], is_bias=True,
                                  default_initializer=_uniform(
                                      hidden_size))
        self.add_parameter("b_%s" % tag, b)
        return {"b": b}

    def _init_states(self, x, batch, initial_states):
        n = self.num_layers * self._dirs
        if initial_states is None:
            zeros = [self._zeros_state(x, batch) for _ in range(n)]
            return list(zip(zeros, [self._zeros_state(x, batch)
                                    for _ in range(n)]))
        h0, c0 = initial_states
        hs = _split_state(h0, n)
        cs = _split_state(c0, n)
        return list(zip(hs, cs))

    def _run_stack(self, x, states):
        idx = 0
        hs, cs = [], []
        for layer in range(self.num_layers):
            dir_outs = []
            for d in range(self._dirs):
                w = self._weights[idx]
                h0, c0 = states[idx]
                out, h, c = apply_op(
                    "lstm_seq", "lstm_seq",
                    {"Input": [x], "WeightIh": [w["w_ih"]],
                     "WeightHh": [w["w_hh"]], "Bias": [w["b"]],
                     "InitH": [h0], "InitC": [c0]},
                    {"is_reverse": bool(d)},
                    ["Out", "LastH", "LastC"], out_dtype="float32")
                dir_outs.append(out)
                hs.append(h)
                cs.append(c)
                idx += 1
            x = dir_outs[0] if len(dir_outs) == 1 else \
                _t.concat(dir_outs, axis=-1)
        return x, (_stack_state(hs), _stack_state(cs))


class GRU(_RNNBase):
    GATES = 3

    def _make_biases(self, g, hidden_size, tag):
        b_ih = self.create_parameter([g * hidden_size], is_bias=True,
                                     default_initializer=_uniform(
                                         hidden_size))
        b_hh = self.create_parameter([g * hidden_size], is_bias=True,
                                     default_initializer=_uniform(
                                         hidden_size))
        self.add_parameter("b_ih_%s" % tag, b_ih)
        self.add_parameter("b_hh_%s" % tag, b_hh)
        return {"b_ih": b_ih, "b_hh": b_hh}

    def _init_states(self, x, batch, initial_states):
        n = self.num_layers * self._dirs
        if initial_states is None:
            return [self._zeros_state(x, batch) for _ in range(n)]
        return _split_state(initial_states, n)

    def _run_stack(self, x, states):
        idx = 0
        hs = []
        for layer in range(self.num_layers):
            dir_outs = []
            for d in range(self._dirs):
                w = self._weights[idx]
                out, h = apply_op(
                    "gru_seq", "gru_seq",
                    {"Input": [x], "WeightIh": [w["w_ih"]],
                     "WeightHh": [w["w_hh"]], "BiasIh": [w["b_ih"]],
                     "BiasHh": [w["b_hh"]], "InitH": [states[idx]]},
                    {"is_reverse": bool(d)},
                    ["Out", "LastH"], out_dtype="float32")
                dir_outs.append(out)
                hs.append(h)
                idx += 1
            x = dir_outs[0] if len(dir_outs) == 1 else \
                _t.concat(dir_outs, axis=-1)
        return x, _stack_state(hs)


def _split_state(state, n):
    """(n, B, H) -> list of n (B, H)."""
    from ..fluid.layers import nn as _nn

    return _nn.unstack(state, axis=0, num=n)


def _stack_state(states):
    from ..fluid.layers import nn as _nn

    return _nn.stack(states, axis=0)
