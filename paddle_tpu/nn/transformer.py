"""paddle.nn transformer layers (reference: `python/paddle/nn/layer/
transformer.py` in 2.0; in this 1.8-era snapshot the equivalent surface
is the incubate transformer models). MXU note: attention and FFN are
plain matmul chains — XLA fuses the bias/activation/dropout elementwise
work into them; on real TPU configs the Pallas flash-attention kernel
(ops/pallas/flash_attention.py) takes over via
functional.scaled_dot_product_attention. With need_weights=True the
unfused path runs instead (the prob matrix must exist to be returned)."""
from __future__ import annotations

import collections
import math

import numpy as np

from ..fluid.dygraph.layers import Layer, LayerList
from ..fluid.dygraph.nn import Linear, LayerNorm, Dropout
from ..fluid.dygraph import base as dy_base
from . import functional as F

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder"]


class MultiHeadAttention(Layer):
    # incremental-decoding caches (paddle 2.0 transformer.py Cache /
    # StaticCache): Cache grows along seq_k each step (self-attention),
    # StaticCache is precomputed once (cross-attention to the encoder)
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim)
        self.k_proj = Linear(kdim or embed_dim, embed_dim)
        self.v_proj = Linear(vdim or embed_dim, embed_dim)
        self.out_proj = Linear(embed_dim, embed_dim)

    def _heads(self, t):
        b, s, _ = t._val.shape
        return dy_base.trace_op(
            "transpose2",
            {"X": [dy_base.trace_op(
                "reshape2", {"X": [t]},
                {"shape": [b, s, self.num_heads, self.head_dim]},
                ["Out", "XShape"])[0]]},
            {"axis": [0, 2, 1, 3]}, ["Out", "XShape"])[0]

    def gen_cache(self, key, value=None, type=None):
        """Build an incremental-decoding cache (paddle 2.0
        MultiHeadAttention.gen_cache contract): type=StaticCache
        projects the encoder output once (cross-attention);
        type=Cache (default) with a value means (key, value) are
        ALREADY-projected head-shaped k/v to seed the cache with;
        without a value an empty growing Cache starts."""
        if type is MultiHeadAttention.StaticCache:
            k = self._heads(self.k_proj(key))
            v = self._heads(self.v_proj(value
                                        if value is not None else key))
            return MultiHeadAttention.StaticCache(k, v)
        if value is not None:
            return MultiHeadAttention.Cache(key, value)
        b = key._val.shape[0]
        zeros = dy_base.to_variable(np.zeros(
            (b, self.num_heads, 0, self.head_dim), "float32"))
        return MultiHeadAttention.Cache(zeros, zeros)

    def _attn_unfused(self, qh, kh, vh, attn_mask):
        """Unfused attention that RETURNS the prob matrix."""
        scores = dy_base.trace_op(
            "matmul", {"X": [qh], "Y": [kh]},
            {"transpose_X": False, "transpose_Y": True,
             "alpha": 1.0 / math.sqrt(self.head_dim)}, ["Out"])[0]
        if attn_mask is not None:
            scores = dy_base.trace_op(
                "elementwise_add", {"X": [scores], "Y": [attn_mask]},
                {}, ["Out"])[0]
        weights = dy_base.trace_op("softmax", {"X": [scores]},
                                   {"axis": -1}, ["Out"])[0]
        if self.dropout and self.training:
            weights = dy_base.trace_op(
                "dropout", {"X": [weights]},
                {"dropout_prob": self.dropout,
                 "dropout_implementation": "upscale_in_train",
                 "is_test": False}, ["Out", "Mask"])[0]
        ctx = dy_base.trace_op("matmul", {"X": [weights], "Y": [vh]},
                               {"transpose_X": False,
                                "transpose_Y": False, "alpha": 1.0},
                               ["Out"])[0]
        return ctx, weights

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self.q_proj(query)
        qh = self._heads(q)

        new_cache = None
        if isinstance(cache, MultiHeadAttention.StaticCache):
            kh, vh = cache.k, cache.v
        else:
            kh = self._heads(self.k_proj(key))
            vh = self._heads(self.v_proj(value))
            if isinstance(cache, MultiHeadAttention.Cache):
                kh = dy_base.trace_op("concat",
                                      {"X": [cache.k, kh]},
                                      {"axis": 2}, ["Out"])[0]
                vh = dy_base.trace_op("concat",
                                      {"X": [cache.v, vh]},
                                      {"axis": 2}, ["Out"])[0]
                new_cache = MultiHeadAttention.Cache(kh, vh)

        if self.need_weights:
            ctx, weights = self._attn_unfused(qh, kh, vh, attn_mask)
        else:
            ctx = F.scaled_dot_product_attention(
                qh, kh, vh, attn_mask=attn_mask,
                dropout_p=self.dropout if self.training else 0.0)
            weights = None
        b, h, s, d = ctx._val.shape
        ctx = dy_base.trace_op("transpose2", {"X": [ctx]},
                               {"axis": [0, 2, 1, 3]},
                               ["Out", "XShape"])[0]
        ctx = dy_base.trace_op("reshape2", {"X": [ctx]},
                               {"shape": [b, s, h * d]},
                               ["Out", "XShape"])[0]
        out = self.out_proj(ctx)
        # paddle 2.0 return contract: out, +weights if requested,
        # +cache if one was passed
        outs = [out]
        if self.need_weights:
            outs.append(weights)
        if cache is not None and new_cache is not None:
            outs.append(new_cache)
        elif isinstance(cache, MultiHeadAttention.StaticCache):
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout
            if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout
                                   if act_dropout is not None else dropout)
        self._act = activation

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        h = self.linear1(src)
        h = F.relu(h) if self._act == "relu" else F.gelu(h)
        h = self.act_dropout(h)
        src = residual + self.dropout2(self.linear2(h))
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [
            copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out
