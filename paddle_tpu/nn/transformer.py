"""paddle.nn transformer layers (reference: `python/paddle/nn/layer/
transformer.py` in 2.0; in this 1.8-era snapshot the equivalent surface
is the incubate transformer models). MXU note: attention and FFN are
plain matmul chains — XLA fuses the bias/activation/dropout elementwise
work into them; on real TPU configs the Pallas flash-attention kernel
(ops/pallas/flash_attention.py) takes over via
functional.scaled_dot_product_attention."""
from __future__ import annotations

import math

import numpy as np

from ..fluid.dygraph.layers import Layer, LayerList
from ..fluid.dygraph.nn import Linear, LayerNorm, Dropout
from ..fluid.dygraph import base as dy_base
from . import functional as F

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer",
           "TransformerEncoder"]


class MultiHeadAttention(Layer):
    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        if need_weights:
            raise NotImplementedError(
                "need_weights=True is not supported: the fused attention "
                "path never materializes the [B,H,Sq,Sk] prob matrix "
                "(that is the point of the flash kernel)")
        self.q_proj = Linear(embed_dim, embed_dim)
        self.k_proj = Linear(kdim or embed_dim, embed_dim)
        self.v_proj = Linear(vdim or embed_dim, embed_dim)
        self.out_proj = Linear(embed_dim, embed_dim)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if cache is not None:
            raise NotImplementedError(
                "incremental decoding cache is not supported by the "
                "fused attention path yet")
        key = query if key is None else key
        value = key if value is None else value
        q = self.q_proj(query)
        k = self.k_proj(key)
        v = self.v_proj(value)

        import jax.numpy as jnp

        def heads(t):
            b, s, _ = t._val.shape
            return dy_base.trace_op(
                "transpose2",
                {"X": [dy_base.trace_op(
                    "reshape2", {"X": [t]},
                    {"shape": [b, s, self.num_heads, self.head_dim]},
                    ["Out", "XShape"])[0]]},
                {"axis": [0, 2, 1, 3]}, ["Out", "XShape"])[0]

        qh, kh, vh = heads(q), heads(k), heads(v)
        ctx = F.scaled_dot_product_attention(
            qh, kh, vh, attn_mask=attn_mask,
            dropout_p=self.dropout if self.training else 0.0)
        b, h, s, d = ctx._val.shape
        ctx = dy_base.trace_op("transpose2", {"X": [ctx]},
                               {"axis": [0, 2, 1, 3]},
                               ["Out", "XShape"])[0]
        ctx = dy_base.trace_op("reshape2", {"X": [ctx]},
                               {"shape": [b, s, h * d]},
                               ["Out", "XShape"])[0]
        return self.out_proj(ctx)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(
            d_model, nhead, dropout=attn_dropout
            if attn_dropout is not None else dropout)
        self.linear1 = Linear(d_model, dim_feedforward)
        self.linear2 = Linear(dim_feedforward, d_model)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout
                                   if act_dropout is not None else dropout)
        self._act = activation

    def forward(self, src, src_mask=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        h = self.linear1(src)
        h = F.relu(h) if self._act == "relu" else F.gelu(h)
        h = self.act_dropout(h)
        src = residual + self.dropout2(self.linear2(h))
        if not self.normalize_before:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([encoder_layer] + [
            copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None):
        out = src
        for layer in self.layers:
            out = layer(out, src_mask=src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out
