"""Typed error/enforce system.

Reference parity: `paddle/fluid/platform/enforce.h:302-355`
(PADDLE_ENFORCE/PADDLE_THROW with typed payloads), `platform/
error_codes.proto` (the error taxonomy), and `framework/op_call_stack.cc`
(python creation-site tracebacks attached to op errors so users see
WHERE in their model code the failing op was built).
"""
from __future__ import annotations

import traceback


class EnforceNotMet(RuntimeError):
    """Base framework error (reference: enforce.h EnforceNotMet)."""

    code = "LEGACY"


class InvalidArgumentError(EnforceNotMet):
    code = "INVALID_ARGUMENT"


class NotFoundError(EnforceNotMet):
    code = "NOT_FOUND"


class OutOfRangeError(EnforceNotMet):
    code = "OUT_OF_RANGE"


class AlreadyExistsError(EnforceNotMet):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(EnforceNotMet):
    code = "RESOURCE_EXHAUSTED"


class PreconditionNotMetError(EnforceNotMet):
    code = "PRECONDITION_NOT_MET"


class PermissionDeniedError(EnforceNotMet):
    code = "PERMISSION_DENIED"


class ExecutionTimeoutError(EnforceNotMet):
    code = "EXECUTION_TIMEOUT"


class UnimplementedError(EnforceNotMet):
    code = "UNIMPLEMENTED"


class UnavailableError(EnforceNotMet):
    code = "UNAVAILABLE"


class FatalError(EnforceNotMet):
    code = "FATAL"


class ExternalError(EnforceNotMet):
    code = "EXTERNAL"


def enforce(condition, message="enforce failed",
            exc=InvalidArgumentError):
    """PADDLE_ENFORCE (reference: enforce.h:314)."""
    if not condition:
        raise exc(message)


def enforce_not_none(value, name="value", exc=NotFoundError):
    if value is None:
        raise exc("%s should not be null" % name)
    return value


# -- op creation-site attribution (reference: op_call_stack.cc) -----------

_FRAMEWORK_MARKERS = ("/paddle_tpu/", "<frozen")


def capture_user_callstack(limit=3):
    """Topmost non-framework frames of the current stack — recorded on
    each Operator at build time, attached to lowering/execution errors.
    Walks raw frames with early stop (no linecache source resolution),
    so BERT-scale program builds pay microseconds per op, not
    extract_stack's full-stack cost."""
    import sys

    frames = []
    f = sys._getframe(2)
    while f is not None and len(frames) < limit:
        fn = f.f_code.co_filename or ""
        if not any(m in fn for m in _FRAMEWORK_MARKERS):
            frames.append("%s:%d in %s" % (fn, f.f_lineno,
                                           f.f_code.co_name))
        f = f.f_back
    return frames


def attach_op_callstack(exc, op):
    """Wrap an exception with the op's creation site (reference:
    InsertCallStackInfo, op_call_stack.cc)."""
    stack = getattr(op, "_creation_stack", None)
    note = "\n  [operator %s error]" % op.type
    if stack:
        note += "\n  op created at:\n    " + "\n    ".join(stack)
    raise type(exc)(str(exc) + note) if isinstance(exc, EnforceNotMet) \
        else RuntimeError(str(exc) + note) from exc
