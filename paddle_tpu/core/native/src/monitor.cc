// Global named-counter registry.
//
// TPU-native counterpart of the reference's runtime stat registry
// (paddle/fluid/platform/monitor.h STAT_ADD / StatRegistry): cheap
// process-wide counters (bytes fed, batches produced, cache hits...)
// readable from python for observability without a profiler session.
#include "capi.h"

#include <atomic>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

namespace {

std::mutex g_mu;
// std::map keeps names sorted for stable ptq_stat_names output
std::map<std::string, std::atomic<int64_t>*> g_stats;

std::atomic<int64_t>* GetOrCreate(const char* name) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_stats.find(name);
  if (it != g_stats.end()) return it->second;
  auto* v = new std::atomic<int64_t>(0);
  g_stats[name] = v;
  return v;
}

}  // namespace

extern "C" {

void ptq_stat_add(const char* name, int64_t delta) {
  GetOrCreate(name)->fetch_add(delta);
}

int64_t ptq_stat_get(const char* name) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_stats.find(name);
  return it == g_stats.end() ? 0 : it->second->load();
}

void ptq_stat_reset(const char* name) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_stats.find(name);
  if (it != g_stats.end()) it->second->store(0);
}

int64_t ptq_stat_names(char* buf, int64_t cap) {
  std::lock_guard<std::mutex> g(g_mu);
  std::string all;
  for (auto& kv : g_stats) {
    if (!all.empty()) all += '\n';
    all += kv.first;
  }
  if (buf && cap > 0) {
    int64_t n = (int64_t)all.size() < cap - 1 ? (int64_t)all.size() : cap - 1;
    memcpy(buf, all.data(), (size_t)n);
    buf[n] = '\0';
  }
  return (int64_t)all.size();
}

}  // extern "C"
