// Auto-growth best-fit caching host allocator.
//
// TPU-native counterpart of the reference's strategy allocator
// (paddle/fluid/memory/allocation/auto_growth_best_fit_allocator.cc and
// allocator_facade.h:32). On TPU the device heap belongs to XLA/PJRT, so
// the native allocator's job is the HOST side: reusable aligned staging
// buffers for feed/fetch and the data pipeline, avoiding malloc churn in
// the hot input loop. Freed blocks go to a size-keyed free list and are
// handed back best-fit (smallest block >= request).
#include "capi.h"

#include <atomic>
#include <cstdlib>
#include <map>
#include <mutex>
#include <unordered_map>

namespace {

struct Block {
  void* raw;        // base pointer returned by aligned alloc
  int64_t size;     // usable size
};

class BestFitAllocator {
 public:
  explicit BestFitAllocator(int64_t alignment)
      : align_(alignment < 64 ? 64 : alignment) {}

  ~BestFitAllocator() {
    ReleaseCache();
    for (auto& kv : in_use_) free(kv.second.raw);  // unfreed allocations
    in_use_.clear();
  }

  void* Malloc(int64_t size) {
    if (size <= 0) size = 1;
    std::lock_guard<std::mutex> g(mu_);
    n_alloc_++;
    // best fit: smallest cached block that can hold `size`
    auto it = free_.lower_bound(size);
    if (it != free_.end()) {
      Block b = it->second;
      free_.erase(it);
      cached_bytes_ -= b.size;
      in_use_[b.raw] = b;
      in_use_bytes_ += b.size;
      n_hit_++;
      return b.raw;
    }
    void* p = nullptr;
    if (posix_memalign(&p, (size_t)align_, (size_t)size) != 0) return nullptr;
    Block b{p, size};
    in_use_[p] = b;
    in_use_bytes_ += size;
    return p;
  }

  void Free(void* p) {
    if (!p) return;
    std::lock_guard<std::mutex> g(mu_);
    auto it = in_use_.find(p);
    if (it == in_use_.end()) return;
    Block b = it->second;
    in_use_.erase(it);
    in_use_bytes_ -= b.size;
    free_.emplace(b.size, b);
    cached_bytes_ += b.size;
  }

  void ReleaseCache() {
    std::lock_guard<std::mutex> g(mu_);
    for (auto& kv : free_) free(kv.second.raw);
    free_.clear();
    cached_bytes_ = 0;
  }

  void Stats(int64_t* s) {
    std::lock_guard<std::mutex> g(mu_);
    s[0] = in_use_bytes_;
    s[1] = cached_bytes_;
    s[2] = n_alloc_;
    s[3] = n_hit_;
  }

 private:
  const int64_t align_;
  std::mutex mu_;
  std::multimap<int64_t, Block> free_;          // size -> block (best fit)
  std::unordered_map<void*, Block> in_use_;
  int64_t in_use_bytes_ = 0, cached_bytes_ = 0;
  int64_t n_alloc_ = 0, n_hit_ = 0;
};

std::mutex g_mu;
std::unordered_map<int64_t, BestFitAllocator*> g_allocs;
std::atomic<int64_t> g_next{1};

BestFitAllocator* Get(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_allocs.find(h);
  return it == g_allocs.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int64_t ptq_alloc_create(int64_t alignment) {
  int64_t id = g_next.fetch_add(1);
  std::lock_guard<std::mutex> g(g_mu);
  g_allocs[id] = new BestFitAllocator(alignment);
  return id;
}

void* ptq_alloc_malloc(int64_t h, int64_t size) {
  BestFitAllocator* a = Get(h);
  return a ? a->Malloc(size) : nullptr;
}

void ptq_alloc_free(int64_t h, void* p) {
  BestFitAllocator* a = Get(h);
  if (a) a->Free(p);
}

void ptq_alloc_stats(int64_t h, int64_t* stats) {
  BestFitAllocator* a = Get(h);
  if (a) a->Stats(stats);
}

void ptq_alloc_release_cache(int64_t h) {
  BestFitAllocator* a = Get(h);
  if (a) a->ReleaseCache();
}

void ptq_alloc_destroy(int64_t h) {
  BestFitAllocator* a = nullptr;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_allocs.find(h);
    if (it != g_allocs.end()) {
      a = it->second;
      g_allocs.erase(it);
    }
  }
  delete a;
}

}  // extern "C"
