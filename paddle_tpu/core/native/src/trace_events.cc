// Native profiler event recorder + chrome-trace exporter.
//
// Reference parity: paddle/fluid/platform/profiler.{h,cc} — RecordEvent
// ring storage, EnableProfiler/DisableProfiler aggregation, and
// tools/timeline.py's chrome://tracing JSON conversion (done here in
// C++ so a million-event trace exports without a python loop).
//
// Model: a global mutex-guarded event store capped at kMaxEvents
// (events beyond the cap are counted but dropped, like the reference's
// bounded profiler storage); events are (name_id, tid, start_us,
// dur_us). Names are interned once. Export writes the standard chrome
// trace "traceEvents" array with "X" (complete) events; stats
// aggregates count/total/max per name.
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

struct Event {
  int32_t name_id;
  int32_t tid;
  int64_t start_us;
  int64_t dur_us;
};

constexpr size_t kMaxEvents = 4u << 20;  // ~100MB worst case

struct TraceStore {
  std::mutex mu;
  std::vector<std::string> names;
  std::map<std::string, int32_t> name_ids;
  std::vector<Event> events;
  int64_t dropped = 0;
  bool enabled = false;
};

TraceStore& store() {
  static TraceStore s;
  return s;
}

}  // namespace

extern "C" {

void ptq_trace_enable(int enabled) {
  TraceStore& s = store();
  std::lock_guard<std::mutex> g(s.mu);
  s.enabled = enabled != 0;
}

int32_t ptq_trace_name_id(const char* name) {
  TraceStore& s = store();
  std::lock_guard<std::mutex> g(s.mu);
  auto it = s.name_ids.find(name);
  if (it != s.name_ids.end()) return it->second;
  int32_t id = static_cast<int32_t>(s.names.size());
  s.names.emplace_back(name);
  s.name_ids.emplace(name, id);
  return id;
}

void ptq_trace_record(int32_t name_id, int32_t tid, int64_t start_us,
                      int64_t dur_us) {
  TraceStore& s = store();
  std::lock_guard<std::mutex> g(s.mu);
  if (!s.enabled) return;
  if (s.events.size() >= kMaxEvents) {
    s.dropped += 1;
    return;
  }
  s.events.push_back(Event{name_id, tid, start_us, dur_us});
}

int64_t ptq_trace_dropped() {
  TraceStore& s = store();
  std::lock_guard<std::mutex> g(s.mu);
  return s.dropped;
}

int64_t ptq_trace_count() {
  TraceStore& s = store();
  std::lock_guard<std::mutex> g(s.mu);
  return static_cast<int64_t>(s.events.size());
}

void ptq_trace_reset() {
  TraceStore& s = store();
  std::lock_guard<std::mutex> g(s.mu);
  s.events.clear();
  s.dropped = 0;
}

// Writes chrome://tracing JSON. Returns 0 on success.
int ptq_trace_export(const char* path, const char* process_name) {
  TraceStore& s = store();
  std::lock_guard<std::mutex> g(s.mu);
  FILE* f = std::fopen(path, "w");
  if (!f) return -1;
  std::fputs("{\"traceEvents\":[\n", f);
  std::fprintf(f,
               "{\"name\":\"process_name\",\"ph\":\"M\",\"pid\":0,"
               "\"args\":{\"name\":\"%s\"}}",
               process_name ? process_name : "paddle_tpu");
  for (const Event& e : s.events) {
    const std::string& name =
        (e.name_id >= 0 &&
         e.name_id < static_cast<int32_t>(s.names.size()))
            ? s.names[e.name_id]
            : "?";
    // escape quotes/backslashes in the name
    std::string esc;
    esc.reserve(name.size());
    for (char c : name) {
      if (c == '"' || c == '\\') esc.push_back('\\');
      esc.push_back(c);
    }
    std::fprintf(f,
                 ",\n{\"name\":\"%s\",\"ph\":\"X\",\"pid\":0,"
                 "\"tid\":%d,\"ts\":%lld,\"dur\":%lld}",
                 esc.c_str(), e.tid,
                 static_cast<long long>(e.start_us),
                 static_cast<long long>(e.dur_us));
  }
  std::fputs("\n]}\n", f);
  std::fclose(f);
  return 0;
}

// Aggregated per-name stats. Caller passes arrays of capacity `cap`;
// returns the number of distinct names. counts/totals/maxes are
// per-name aggregates in name-id order; use ptq_trace_name_at to map
// ids back to strings.
int32_t ptq_trace_stats(int64_t* counts, int64_t* totals, int64_t* maxes,
                        int32_t cap) {
  TraceStore& s = store();
  std::lock_guard<std::mutex> g(s.mu);
  int32_t n = static_cast<int32_t>(s.names.size());
  if (counts == nullptr) return n;
  for (int32_t i = 0; i < n && i < cap; ++i) {
    counts[i] = totals[i] = maxes[i] = 0;
  }
  for (const Event& e : s.events) {
    if (e.name_id < 0 || e.name_id >= cap) continue;
    counts[e.name_id] += 1;
    totals[e.name_id] += e.dur_us;
    if (e.dur_us > maxes[e.name_id]) maxes[e.name_id] = e.dur_us;
  }
  return n;
}

const char* ptq_trace_name_at(int32_t id) {
  TraceStore& s = store();
  std::lock_guard<std::mutex> g(s.mu);
  if (id < 0 || id >= static_cast<int32_t>(s.names.size())) return "";
  return s.names[id].c_str();
}

}  // extern "C"
