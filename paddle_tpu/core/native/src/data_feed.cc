// MultiSlot text data feed: multithreaded parse -> local shuffle ->
// batch -> serialized batches on a blocking channel.
//
// TPU-native counterpart of the reference's C++ ingestion tier
// (paddle/fluid/framework/data_feed.cc:639 MultiSlotDataFeed,
// data_feed.h:108/291; dataset shuffle in data_set.h:111). Same text
// format: one example per line; for each slot in declared order, a count
// followed by that many values. Variable-length slots produce per-batch
// LoD offsets exactly like the reference's LoDTensor batches; python
// decodes the wire format into numpy arrays + lod without touching the
// parse loop.
#include "capi.h"

#include <atomic>
#include <algorithm>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Example {
  // per-slot payload; only one of f/i used depending on slot type
  std::vector<std::vector<float>> f;
  std::vector<std::vector<int64_t>> i;
};

struct Feed {
  std::vector<int32_t> slot_types;  // 0=float32 1=int64
  int64_t batch_size;
  int64_t chan;                     // ptq channel handle of serialized batches
  std::vector<std::string> files;
  std::vector<std::thread> threads;
  std::atomic<int64_t> active{0};
  std::atomic<int64_t> next_file{0};
  std::atomic<int64_t> n_examples{0};
  bool started = false;
};

std::mutex g_mu;
std::unordered_map<int64_t, Feed*> g_feeds;
std::atomic<int64_t> g_next{1};

Feed* Get(int64_t h) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_feeds.find(h);
  return it == g_feeds.end() ? nullptr : it->second;
}

// Parse one line into an example. Returns false on malformed input
// (wrong slot count / non-numeric field) — the caller skips the line,
// matching the reference's tolerant CheckFile behavior.
bool ParseLine(const char* line, size_t line_len,
               const std::vector<int32_t>& types, Example* ex) {
  const char* p = line;
  char* end = nullptr;
  ex->f.assign(types.size(), {});
  ex->i.assign(types.size(), {});
  // a value needs >= 2 chars ("1 "), so any honest count is < line_len;
  // this bound keeps a corrupt count from aborting on reserve()
  const long long max_vals = (long long)line_len;
  for (size_t s = 0; s < types.size(); ++s) {
    long long n = strtoll(p, &end, 10);
    if (end == p || n < 0 || n > max_vals) return false;
    p = end;
    if (types[s] == 0) {
      auto& v = ex->f[s];
      v.reserve(n);
      for (long long k = 0; k < n; ++k) {
        float x = strtof(p, &end);
        if (end == p) return false;
        p = end;
        v.push_back(x);
      }
    } else {
      auto& v = ex->i[s];
      v.reserve(n);
      for (long long k = 0; k < n; ++k) {
        long long x = strtoll(p, &end, 10);
        if (end == p) return false;
        p = end;
        v.push_back((int64_t)x);
      }
    }
  }
  return true;
}

void AppendI64(std::vector<uint8_t>* out, int64_t v) {
  const uint8_t* p = (const uint8_t*)&v;
  out->insert(out->end(), p, p + 8);
}

void AppendI32(std::vector<uint8_t>* out, int32_t v) {
  const uint8_t* p = (const uint8_t*)&v;
  out->insert(out->end(), p, p + 4);
}

// Wire format documented in capi.h: n_slots, then per slot
// type / lod offsets / flat values.
void SerializeBatch(const std::vector<Example>& batch,
                    const std::vector<int32_t>& types,
                    std::vector<uint8_t>* out) {
  out->clear();
  AppendI64(out, (int64_t)types.size());
  for (size_t s = 0; s < types.size(); ++s) {
    AppendI32(out, types[s]);
    std::vector<int64_t> lod{0};
    int64_t total = 0;
    for (auto& ex : batch) {
      total += types[s] == 0 ? (int64_t)ex.f[s].size()
                             : (int64_t)ex.i[s].size();
      lod.push_back(total);
    }
    AppendI64(out, (int64_t)lod.size());
    for (int64_t o : lod) AppendI64(out, o);
    AppendI64(out, total);
    if (types[s] == 0) {
      for (auto& ex : batch) {
        const uint8_t* p = (const uint8_t*)ex.f[s].data();
        out->insert(out->end(), p, p + ex.f[s].size() * sizeof(float));
      }
    } else {
      for (auto& ex : batch) {
        const uint8_t* p = (const uint8_t*)ex.i[s].data();
        out->insert(out->end(), p, p + ex.i[s].size() * sizeof(int64_t));
      }
    }
  }
}

void EmitBatches(Feed* f, std::vector<Example>* buf, bool flush,
                 std::vector<uint8_t>* scratch) {
  size_t i = 0;
  while (buf->size() - i >= (size_t)f->batch_size ||
         (flush && i < buf->size())) {
    size_t n = std::min((size_t)f->batch_size, buf->size() - i);
    std::vector<Example> batch(buf->begin() + i, buf->begin() + i + n);
    i += n;
    SerializeBatch(batch, f->slot_types, scratch);
    ptq_chan_push(f->chan, scratch->data(), (int64_t)scratch->size(), -1);
  }
  buf->erase(buf->begin(), buf->begin() + i);
}

void ParserThread(Feed* f, int32_t shuffle, uint64_t seed, int64_t buf_size,
                  int tid) {
  std::mt19937_64 rng(seed + (uint64_t)tid * 0x9E3779B97F4A7C15ULL);
  std::vector<Example> buf;
  std::vector<uint8_t> scratch;
  char* line = nullptr;
  size_t cap = 0;
  for (;;) {
    int64_t fi = f->next_file.fetch_add(1);
    if (fi >= (int64_t)f->files.size()) break;
    FILE* fp = fopen(f->files[fi].c_str(), "r");
    if (!fp) continue;
    ssize_t got;
    while ((got = getline(&line, &cap, fp)) != -1) {
      if (got <= 1) continue;
      Example ex;
      if (!ParseLine(line, (size_t)got, f->slot_types, &ex)) continue;
      f->n_examples.fetch_add(1);
      buf.push_back(std::move(ex));
      if ((int64_t)buf.size() >= (shuffle ? buf_size : f->batch_size)) {
        if (shuffle) std::shuffle(buf.begin(), buf.end(), rng);
        EmitBatches(f, &buf, /*flush=*/false, &scratch);
      }
    }
    fclose(fp);
  }
  if (shuffle) std::shuffle(buf.begin(), buf.end(), rng);
  EmitBatches(f, &buf, /*flush=*/true, &scratch);
  free(line);
  // last parser out closes the channel so consumers see end-of-data
  if (f->active.fetch_sub(1) == 1) ptq_chan_close(f->chan);
}

}  // namespace

extern "C" {

int64_t ptq_feed_create(int32_t n_slots, const int32_t* slot_types,
                        int64_t batch_size, int64_t queue_capacity) {
  if (n_slots <= 0 || batch_size <= 0) return -1;
  Feed* f = new Feed();
  f->slot_types.assign(slot_types, slot_types + n_slots);
  f->batch_size = batch_size;
  f->chan = ptq_chan_create(queue_capacity < 2 ? 2 : queue_capacity);
  int64_t id = g_next.fetch_add(1);
  std::lock_guard<std::mutex> g(g_mu);
  g_feeds[id] = f;
  return id;
}

int ptq_feed_set_files(int64_t h, const char* paths_nl_joined) {
  Feed* f = Get(h);
  if (!f || f->started) return PTQ_ERR;
  f->files.clear();
  std::string s(paths_nl_joined ? paths_nl_joined : "");
  size_t pos = 0;
  while (pos < s.size()) {
    size_t nl = s.find('\n', pos);
    if (nl == std::string::npos) nl = s.size();
    if (nl > pos) f->files.push_back(s.substr(pos, nl - pos));
    pos = nl + 1;
  }
  return PTQ_OK;
}

int ptq_feed_start(int64_t h, int32_t n_threads, int32_t shuffle,
                   uint64_t seed, int64_t buffer_size) {
  Feed* f = Get(h);
  if (!f || f->started || f->files.empty()) return PTQ_ERR;
  f->started = true;
  if (n_threads < 1) n_threads = 1;
  if (buffer_size < f->batch_size) buffer_size = f->batch_size * 16;
  f->active.store(n_threads);
  for (int t = 0; t < n_threads; ++t)
    f->threads.emplace_back(ParserThread, f, shuffle, seed, buffer_size, t);
  return PTQ_OK;
}

int ptq_feed_next(int64_t h, uint8_t** out, int64_t* out_len,
                  int64_t timeout_ms) {
  Feed* f = Get(h);
  if (!f) return PTQ_ERR;
  return ptq_chan_pop(f->chan, out, out_len, timeout_ms);
}

int64_t ptq_feed_examples(int64_t h) {
  Feed* f = Get(h);
  return f ? f->n_examples.load() : -1;
}

void ptq_feed_join(int64_t h) {
  Feed* f = Get(h);
  if (!f) return;
  for (auto& t : f->threads)
    if (t.joinable()) t.join();
  f->threads.clear();
}

void ptq_feed_destroy(int64_t h) {
  Feed* f = nullptr;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_feeds.find(h);
    if (it != g_feeds.end()) {
      f = it->second;
      g_feeds.erase(it);
    }
  }
  if (!f) return;
  ptq_chan_close(f->chan);
  for (auto& t : f->threads)
    if (t.joinable()) t.join();
  ptq_chan_destroy(f->chan);
  delete f;
}

}  // extern "C"
