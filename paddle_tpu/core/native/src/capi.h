// C API surface of the paddle_tpu native runtime.
//
// TPU-native counterpart of the reference's native runtime plumbing:
//   - blocking byte-buffer channel   (ref: paddle/fluid/operators/reader/
//     lod_tensor_blocking_queue.h, framework/channel.h)
//   - auto-growth best-fit host allocator (ref: paddle/fluid/memory/
//     allocation/auto_growth_best_fit_allocator.cc)
//   - MultiSlot text data feed        (ref: paddle/fluid/framework/
//     data_feed.cc:639 MultiSlotDataFeed)
//   - global stats monitor            (ref: paddle/fluid/platform/monitor.h)
//
// Everything is extern "C" and loaded from python via ctypes (no pybind11
// in this image). Handles are opaque int64 ids; buffers returned by the
// library are owned by the library and freed with ptq_buf_free.
#pragma once
#include <stdint.h>
#include <stddef.h>

extern "C" {

// ---- error codes ----
enum {
  PTQ_OK = 0,
  PTQ_CLOSED = -1,   // channel closed and drained
  PTQ_TIMEOUT = -2,
  PTQ_ERR = -3,
};

// ---- blocking channel of byte buffers ----
int64_t ptq_chan_create(int64_t capacity);
// Copies buf[0:len] into the channel. Blocks while full (up to timeout_ms;
// timeout_ms < 0 means wait forever).
int ptq_chan_push(int64_t h, const uint8_t* buf, int64_t len,
                  int64_t timeout_ms);
// On PTQ_OK, *out is a library-owned buffer of *out_len bytes; free it
// with ptq_buf_free.
int ptq_chan_pop(int64_t h, uint8_t** out, int64_t* out_len,
                 int64_t timeout_ms);
void ptq_chan_close(int64_t h);   // wakes all waiters; pops drain then CLOSED
void ptq_chan_reopen(int64_t h);
int64_t ptq_chan_size(int64_t h);
void ptq_chan_destroy(int64_t h);
void ptq_buf_free(uint8_t* buf);

// ---- auto-growth best-fit host allocator ----
int64_t ptq_alloc_create(int64_t alignment);
void* ptq_alloc_malloc(int64_t h, int64_t size);
void ptq_alloc_free(int64_t h, void* p);
// stats[0]=bytes_in_use stats[1]=bytes_cached stats[2]=n_alloc
// stats[3]=n_cache_hit
void ptq_alloc_stats(int64_t h, int64_t* stats);
void ptq_alloc_release_cache(int64_t h);
void ptq_alloc_destroy(int64_t h);

// ---- MultiSlot data feed ----
// Slot types: 0 = float32, 1 = int64.
// Text format (one example per line, same as the reference MultiSlot
// format): for each slot in order, "<n> v_1 ... v_n" fields separated by
// whitespace.
int64_t ptq_feed_create(int32_t n_slots, const int32_t* slot_types,
                        int64_t batch_size, int64_t queue_capacity);
int ptq_feed_set_files(int64_t h, const char* paths_nl_joined);
// Starts n_threads parser threads. shuffle: 0 = none, 1 = within-buffer
// local shuffle with the given seed and buffer_size examples.
int ptq_feed_start(int64_t h, int32_t n_threads, int32_t shuffle,
                   uint64_t seed, int64_t buffer_size);
// Pops one serialized batch (wire format below). PTQ_CLOSED at end of data.
// Wire format: [i64 n_slots] then per slot:
//   [i32 type][i64 n_lod][i64 lod_0..lod_n][i64 n_vals][vals...]
// lod offsets are per-batch cumulative example offsets (lod_0 == 0,
// lod_{n-1} == n_vals for var-length slots).
int ptq_feed_next(int64_t h, uint8_t** out, int64_t* out_len,
                  int64_t timeout_ms);
// number of examples parsed so far (for progress/metrics)
int64_t ptq_feed_examples(int64_t h);
void ptq_feed_join(int64_t h);   // wait for parser threads to finish
void ptq_feed_destroy(int64_t h);

// ---- global stats monitor ----
void ptq_stat_add(const char* name, int64_t delta);
int64_t ptq_stat_get(const char* name);
void ptq_stat_reset(const char* name);
// Writes '\n'-joined stat names into buf (truncated to cap); returns the
// full length needed.
int64_t ptq_stat_names(char* buf, int64_t cap);

}  // extern "C"

// ---- profiler trace events (trace_events.cc; ref: platform/profiler.h
// + tools/timeline.py chrome-trace conversion) ----
void ptq_trace_enable(int enabled);
int32_t ptq_trace_name_id(const char* name);
void ptq_trace_record(int32_t name_id, int32_t tid, int64_t start_us,
                      int64_t dur_us);
int64_t ptq_trace_count(void);
int64_t ptq_trace_dropped(void);
void ptq_trace_reset(void);
int ptq_trace_export(const char* path, const char* process_name);
int32_t ptq_trace_stats(int64_t* counts, int64_t* totals, int64_t* maxes,
                        int32_t cap);
const char* ptq_trace_name_at(int32_t id);

// ---- ragged <-> padded batching (ragged.cc; ref:
// operators/math/sequence_padding.cc) ----
int64_t ptq_ragged_pad(const uint8_t* values, const int64_t* lengths,
                       int64_t batch, int64_t max_len, int64_t width,
                       int64_t elem_size, uint8_t* out);
int64_t ptq_ragged_unpad(const uint8_t* padded, const int64_t* lengths,
                         int64_t batch, int64_t max_len, int64_t width,
                         int64_t elem_size, uint8_t* out);
void ptq_lod_to_lengths(const int64_t* lod, int64_t batch,
                        int64_t* lengths);

// ---- model-file encryption (crypto.cc; ref:
// framework/io/crypto/aes_cipher.h:48, pybind/crypto.cc) ----
// AES-256-CTR + HMAC-SHA256 encrypt-then-MAC. Sealed format:
// "PTQE" | ver u8 | iv[16] | ciphertext | tag[32]. Buffers returned in
// *out are library-owned; free with ptq_buf_free. decrypt returns -1
// (bad tag) on wrong key or corruption.
int ptq_crypto_gen_key(uint8_t* out, int64_t len);
int ptq_crypto_encrypt(const uint8_t* key, int64_t keylen,
                       const uint8_t* plain, int64_t len,
                       uint8_t** out, int64_t* out_len);
int ptq_crypto_decrypt(const uint8_t* key, int64_t keylen,
                       const uint8_t* sealed, int64_t len,
                       uint8_t** out, int64_t* out_len);
int ptq_crypto_selftest(void);
