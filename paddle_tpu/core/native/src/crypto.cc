// Model-file encryption for checkpoint/inference artifacts.
//
// TPU-native counterpart of the reference's crypto tier
// (paddle/fluid/framework/io/crypto/aes_cipher.h:48, cipher.h:24,
// cipher_utils.h:23, bound to python in pybind/crypto.cc). The
// reference wraps Crypto++ AES-GCM; this image has no crypto library,
// so the primitives are implemented here from the public FIPS-197 /
// FIPS-180-4 specs: AES-256 in CTR mode with an HMAC-SHA256
// encrypt-then-MAC tag (equivalent confidentiality+integrity contract
// to GCM, simpler to implement correctly without carry-less multiply).
//
// Wire format of a sealed buffer:
//   magic "PTQE" | version u8=1 | iv[16] | ciphertext | hmac_tag[32]
// The HMAC covers magic..ciphertext with a key derived from the user
// key (HMAC key = SHA256(key || "ptq-mac")), so the encryption and MAC
// keys differ even though the user supplies one key blob.
#include <stdint.h>
#include <string.h>
#include <stdio.h>

#include <stdlib.h>

extern "C" {
enum { PTQC_OK = 0, PTQC_BAD_TAG = -1, PTQC_ERR = -3 };
void ptq_buf_free(uint8_t* buf);  // shared with capi (channel.cc)
}

namespace {

// ---------------- SHA-256 (FIPS 180-4) ----------------

struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t fill = 0;

  Sha256() {
    static const uint32_t init[8] = {
        0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
        0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u};
    memcpy(h, init, sizeof(h));
  }

  static uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void block(const uint8_t* p) {
    static const uint32_t K[64] = {
        0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
        0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
        0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
        0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
        0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
        0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
        0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
        0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
        0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
        0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
        0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
        0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
        0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    len += n;
    while (n) {
      size_t take = 64 - fill < n ? 64 - fill : n;
      memcpy(buf + fill, p, take);
      fill += take; p += take; n -= take;
      if (fill == 64) { block(buf); fill = 0; }
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t z = 0;
    while (fill != 56) update(&z, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; ++i) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    update(lenb, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

void sha256(const uint8_t* p, size_t n, uint8_t out[32]) {
  Sha256 s;
  s.update(p, n);
  s.final(out);
}

// HMAC-SHA256 (FIPS 198-1)
void hmac_sha256(const uint8_t* key, size_t keylen, const uint8_t* msg,
                 size_t msglen, uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (keylen > 64) {
    sha256(key, keylen, k);  // fold long keys, per spec
  } else {
    memcpy(k, key, keylen);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; ++i) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 si;
  si.update(ipad, 64);
  si.update(msg, msglen);
  si.final(inner);
  Sha256 so;
  so.update(opad, 64);
  so.update(inner, 32);
  so.final(out);
}

// ---------------- AES-256 (FIPS 197), encrypt direction only ----------------
// CTR mode needs only the forward cipher on the counter block.

const uint8_t kSbox[256] = {
    0x63, 0x7c, 0x77, 0x7b, 0xf2, 0x6b, 0x6f, 0xc5, 0x30, 0x01, 0x67, 0x2b,
    0xfe, 0xd7, 0xab, 0x76, 0xca, 0x82, 0xc9, 0x7d, 0xfa, 0x59, 0x47, 0xf0,
    0xad, 0xd4, 0xa2, 0xaf, 0x9c, 0xa4, 0x72, 0xc0, 0xb7, 0xfd, 0x93, 0x26,
    0x36, 0x3f, 0xf7, 0xcc, 0x34, 0xa5, 0xe5, 0xf1, 0x71, 0xd8, 0x31, 0x15,
    0x04, 0xc7, 0x23, 0xc3, 0x18, 0x96, 0x05, 0x9a, 0x07, 0x12, 0x80, 0xe2,
    0xeb, 0x27, 0xb2, 0x75, 0x09, 0x83, 0x2c, 0x1a, 0x1b, 0x6e, 0x5a, 0xa0,
    0x52, 0x3b, 0xd6, 0xb3, 0x29, 0xe3, 0x2f, 0x84, 0x53, 0xd1, 0x00, 0xed,
    0x20, 0xfc, 0xb1, 0x5b, 0x6a, 0xcb, 0xbe, 0x39, 0x4a, 0x4c, 0x58, 0xcf,
    0xd0, 0xef, 0xaa, 0xfb, 0x43, 0x4d, 0x33, 0x85, 0x45, 0xf9, 0x02, 0x7f,
    0x50, 0x3c, 0x9f, 0xa8, 0x51, 0xa3, 0x40, 0x8f, 0x92, 0x9d, 0x38, 0xf5,
    0xbc, 0xb6, 0xda, 0x21, 0x10, 0xff, 0xf3, 0xd2, 0xcd, 0x0c, 0x13, 0xec,
    0x5f, 0x97, 0x44, 0x17, 0xc4, 0xa7, 0x7e, 0x3d, 0x64, 0x5d, 0x19, 0x73,
    0x60, 0x81, 0x4f, 0xdc, 0x22, 0x2a, 0x90, 0x88, 0x46, 0xee, 0xb8, 0x14,
    0xde, 0x5e, 0x0b, 0xdb, 0xe0, 0x32, 0x3a, 0x0a, 0x49, 0x06, 0x24, 0x5c,
    0xc2, 0xd3, 0xac, 0x62, 0x91, 0x95, 0xe4, 0x79, 0xe7, 0xc8, 0x37, 0x6d,
    0x8d, 0xd5, 0x4e, 0xa9, 0x6c, 0x56, 0xf4, 0xea, 0x65, 0x7a, 0xae, 0x08,
    0xba, 0x78, 0x25, 0x2e, 0x1c, 0xa6, 0xb4, 0xc6, 0xe8, 0xdd, 0x74, 0x1f,
    0x4b, 0xbd, 0x8b, 0x8a, 0x70, 0x3e, 0xb5, 0x66, 0x48, 0x03, 0xf6, 0x0e,
    0x61, 0x35, 0x57, 0xb9, 0x86, 0xc1, 0x1d, 0x9e, 0xe1, 0xf8, 0x98, 0x11,
    0x69, 0xd9, 0x8e, 0x94, 0x9b, 0x1e, 0x87, 0xe9, 0xce, 0x55, 0x28, 0xdf,
    0x8c, 0xa1, 0x89, 0x0d, 0xbf, 0xe6, 0x42, 0x68, 0x41, 0x99, 0x2d, 0x0f,
    0xb0, 0x54, 0xbb, 0x16};

uint8_t xtime(uint8_t x) {
  return uint8_t((x << 1) ^ ((x >> 7) * 0x1b));
}

struct Aes256 {
  // 15 round keys of 16 bytes (Nr=14 for 256-bit keys)
  uint8_t rk[15][16];

  explicit Aes256(const uint8_t key[32]) {
    // key expansion: Nk=8 words, 60 words total
    uint8_t w[60][4];
    memcpy(w, key, 32);
    uint8_t rcon = 1;
    for (int i = 8; i < 60; ++i) {
      uint8_t t[4];
      memcpy(t, w[i - 1], 4);
      if (i % 8 == 0) {
        // RotWord + SubWord + Rcon
        uint8_t tmp = t[0];
        t[0] = uint8_t(kSbox[t[1]] ^ rcon);
        t[1] = kSbox[t[2]];
        t[2] = kSbox[t[3]];
        t[3] = kSbox[tmp];
        rcon = xtime(rcon);
      } else if (i % 8 == 4) {
        for (int j = 0; j < 4; ++j) t[j] = kSbox[t[j]];
      }
      for (int j = 0; j < 4; ++j) w[i][j] = uint8_t(w[i - 8][j] ^ t[j]);
    }
    memcpy(rk, w, sizeof(rk));
  }

  void encrypt_block(const uint8_t in[16], uint8_t out[16]) const {
    uint8_t s[16];
    for (int i = 0; i < 16; ++i) s[i] = uint8_t(in[i] ^ rk[0][i]);
    for (int round = 1; round <= 14; ++round) {
      // SubBytes
      for (int i = 0; i < 16; ++i) s[i] = kSbox[s[i]];
      // ShiftRows (state is column-major: s[4c+r] is row r, col c)
      uint8_t t[16];
      for (int c = 0; c < 4; ++c)
        for (int r = 0; r < 4; ++r)
          t[4 * c + r] = s[4 * ((c + r) % 4) + r];
      memcpy(s, t, 16);
      if (round != 14) {
        // MixColumns
        for (int c = 0; c < 4; ++c) {
          uint8_t* col = s + 4 * c;
          uint8_t a0 = col[0], a1 = col[1], a2 = col[2], a3 = col[3];
          uint8_t all = uint8_t(a0 ^ a1 ^ a2 ^ a3);
          uint8_t b0 = uint8_t(a0 ^ all ^ xtime(uint8_t(a0 ^ a1)));
          uint8_t b1 = uint8_t(a1 ^ all ^ xtime(uint8_t(a1 ^ a2)));
          uint8_t b2 = uint8_t(a2 ^ all ^ xtime(uint8_t(a2 ^ a3)));
          uint8_t b3 = uint8_t(a3 ^ all ^ xtime(uint8_t(a3 ^ a0)));
          col[0] = b0; col[1] = b1; col[2] = b2; col[3] = b3;
        }
      }
      for (int i = 0; i < 16; ++i) s[i] = uint8_t(s[i] ^ rk[round][i]);
    }
    memcpy(out, s, 16);
  }
};

// CTR keystream: counter block = iv[0:12] || big-endian u32 counter.
void aes256_ctr_xor(const Aes256& aes, const uint8_t iv[16],
                    const uint8_t* in, uint8_t* out, size_t n) {
  uint8_t ctr[16], ks[16];
  memcpy(ctr, iv, 16);
  uint32_t counter = (uint32_t(iv[12]) << 24) | (uint32_t(iv[13]) << 16) |
                     (uint32_t(iv[14]) << 8) | uint32_t(iv[15]);
  for (size_t off = 0; off < n; off += 16) {
    ctr[12] = uint8_t(counter >> 24);
    ctr[13] = uint8_t(counter >> 16);
    ctr[14] = uint8_t(counter >> 8);
    ctr[15] = uint8_t(counter);
    aes.encrypt_block(ctr, ks);
    size_t take = n - off < 16 ? n - off : 16;
    for (size_t i = 0; i < take; ++i) out[off + i] = uint8_t(in[off + i] ^ ks[i]);
    ++counter;
  }
}

// MAC key differs from the cipher key: SHA256(key || "ptq-mac").
void derive_mac_key(const uint8_t* key, size_t keylen, uint8_t out[32]) {
  Sha256 s;
  s.update(key, keylen);
  const char* suffix = "ptq-mac";
  s.update(reinterpret_cast<const uint8_t*>(suffix), 7);
  s.final(out);
}

// Cipher key is always folded to 256 bits: SHA256(key || "ptq-enc").
// This lets callers pass any key length (the reference supports 128/192/
// 256-bit AES keys; folding keeps one code path with full entropy use).
void derive_enc_key(const uint8_t* key, size_t keylen, uint8_t out[32]) {
  Sha256 s;
  s.update(key, keylen);
  const char* suffix = "ptq-enc";
  s.update(reinterpret_cast<const uint8_t*>(suffix), 7);
  s.final(out);
}

const uint8_t kMagic[4] = {'P', 'T', 'Q', 'E'};
const size_t kHeader = 5;   // magic + version byte
const size_t kIv = 16;
const size_t kTag = 32;

bool fill_random(uint8_t* out, size_t n) {
  FILE* f = fopen("/dev/urandom", "rb");
  if (!f) return false;
  size_t got = fread(out, 1, n, f);
  fclose(f);
  return got == n;
}

int ct_memcmp(const uint8_t* a, const uint8_t* b, size_t n) {
  // constant-time compare: tag checks must not leak a prefix length
  uint8_t d = 0;
  for (size_t i = 0; i < n; ++i) d = uint8_t(d | (a[i] ^ b[i]));
  return d != 0;
}

}  // namespace

extern "C" {

int ptq_crypto_gen_key(uint8_t* out, int64_t len) {
  if (len <= 0) return PTQC_ERR;
  return fill_random(out, size_t(len)) ? PTQC_OK : PTQC_ERR;
}

// Seals plain[0:len]; *out is a library-owned buffer (free with
// ptq_buf_free) of *out_len = kHeader + 16 + len + 32 bytes.
int ptq_crypto_encrypt(const uint8_t* key, int64_t keylen,
                       const uint8_t* plain, int64_t len,
                       uint8_t** out, int64_t* out_len) {
  if (!key || keylen <= 0 || len < 0 || !out || !out_len) return PTQC_ERR;
  // CTR counter is 32 bits over 16-byte blocks: past 64 GiB the
  // keystream would repeat, silently destroying confidentiality
  if (uint64_t(len) >= (uint64_t(1) << 36)) return PTQC_ERR;
  size_t total = kHeader + kIv + size_t(len) + kTag;
  uint8_t* buf = static_cast<uint8_t*>(malloc(total));
  if (!buf) return PTQC_ERR;
  memcpy(buf, kMagic, 4);
  buf[4] = 1;  // version
  uint8_t* iv = buf + kHeader;
  if (!fill_random(iv, 12)) { free(buf); return PTQC_ERR; }
  memset(iv + 12, 0, 4);  // counter starts at 0
  uint8_t ek[32];
  derive_enc_key(key, size_t(keylen), ek);
  Aes256 aes(ek);
  aes256_ctr_xor(aes, iv, plain, buf + kHeader + kIv, size_t(len));
  uint8_t mk[32];
  derive_mac_key(key, size_t(keylen), mk);
  hmac_sha256(mk, 32, buf, kHeader + kIv + size_t(len),
              buf + kHeader + kIv + size_t(len));
  *out = buf;
  *out_len = int64_t(total);
  return PTQC_OK;
}

// Opens a sealed buffer; returns PTQC_BAD_TAG on wrong key/corruption.
int ptq_crypto_decrypt(const uint8_t* key, int64_t keylen,
                       const uint8_t* sealed, int64_t len,
                       uint8_t** out, int64_t* out_len) {
  if (!key || keylen <= 0 || !sealed || !out || !out_len) return PTQC_ERR;
  // structural damage (truncation, bad magic/version) is reported the
  // same way as a bad tag: "this is not an intact sealed buffer"
  if (len < 0 || size_t(len) < kHeader + kIv + kTag) return PTQC_BAD_TAG;
  if (memcmp(sealed, kMagic, 4) != 0 || sealed[4] != 1) return PTQC_BAD_TAG;
  size_t clen = size_t(len) - kHeader - kIv - kTag;
  uint8_t mk[32], want[32];
  derive_mac_key(key, size_t(keylen), mk);
  hmac_sha256(mk, 32, sealed, kHeader + kIv + clen, want);
  if (ct_memcmp(want, sealed + kHeader + kIv + clen, kTag))
    return PTQC_BAD_TAG;
  uint8_t* buf = static_cast<uint8_t*>(malloc(clen ? clen : 1));
  if (!buf) return PTQC_ERR;
  uint8_t ek[32];
  derive_enc_key(key, size_t(keylen), ek);
  Aes256 aes(ek);
  aes256_ctr_xor(aes, sealed + kHeader, sealed + kHeader + kIv, buf, clen);
  *out = buf;
  *out_len = int64_t(clen);
  return PTQC_OK;
}

// Self-check against a FIPS-197 appendix C.3 vector (AES-256 raw block,
// exercised by tests through this hook rather than exposing internals).
int ptq_crypto_selftest() {
  const uint8_t key[32] = {
      0x00, 0x01, 0x02, 0x03, 0x04, 0x05, 0x06, 0x07,
      0x08, 0x09, 0x0a, 0x0b, 0x0c, 0x0d, 0x0e, 0x0f,
      0x10, 0x11, 0x12, 0x13, 0x14, 0x15, 0x16, 0x17,
      0x18, 0x19, 0x1a, 0x1b, 0x1c, 0x1d, 0x1e, 0x1f};
  const uint8_t pt[16] = {0x00, 0x11, 0x22, 0x33, 0x44, 0x55, 0x66, 0x77,
                          0x88, 0x99, 0xaa, 0xbb, 0xcc, 0xdd, 0xee, 0xff};
  const uint8_t want[16] = {0x8e, 0xa2, 0xb7, 0xca, 0x51, 0x67, 0x45, 0xbf,
                            0xea, 0xfc, 0x49, 0x90, 0x4b, 0x49, 0x60, 0x89};
  Aes256 aes(key);
  uint8_t got[16];
  aes.encrypt_block(pt, got);
  if (memcmp(got, want, 16) != 0) return PTQC_ERR;
  // SHA-256 of "abc" (FIPS 180-4 appendix B.1)
  const uint8_t sha_want[32] = {
      0xba, 0x78, 0x16, 0xbf, 0x8f, 0x01, 0xcf, 0xea,
      0x41, 0x41, 0x40, 0xde, 0x5d, 0xae, 0x22, 0x23,
      0xb0, 0x03, 0x61, 0xa3, 0x96, 0x17, 0x7a, 0x9c,
      0xb4, 0x10, 0xff, 0x61, 0xf2, 0x00, 0x15, 0xad};
  uint8_t sha_got[32];
  sha256(reinterpret_cast<const uint8_t*>("abc"), 3, sha_got);
  if (memcmp(sha_got, sha_want, 32) != 0) return PTQC_ERR;
  return PTQC_OK;
}

}  // extern "C"
