// Ragged <-> padded batching kernels for the host data path.
//
// Reference parity: LoDTensor ragged batching (framework/lod_tensor.h:52
// nested offsets) and the sequence-padding kernels
// (operators/math/sequence_padding.cc PaddingLoDTensorFunctor /
// UnpaddingLoDTensorFunctor). The TPU representation is dense padding +
// explicit lengths (SURVEY.md §7 hard part (a)); these kernels do the
// concatenated-rows -> [B, T_max, D] scatter (and the inverse gather)
// in one memcpy pass per row instead of a python loop per element.
#include <algorithm>
#include <cstdint>
#include <cstring>

extern "C" {

// values: concatenated rows, total_rows x width elements of elem_size
// bytes. lengths[b] rows belong to batch item b. out must hold
// batch x max_len x width elements; it is zero-filled first (pad value
// 0). Returns the max length actually seen (<= max_len used).
int64_t ptq_ragged_pad(const uint8_t* values, const int64_t* lengths,
                       int64_t batch, int64_t max_len, int64_t width,
                       int64_t elem_size, uint8_t* out) {
  const int64_t row_bytes = width * elem_size;
  std::memset(out, 0, static_cast<size_t>(batch * max_len * row_bytes));
  int64_t offset_rows = 0;
  int64_t seen_max = 0;
  for (int64_t b = 0; b < batch; ++b) {
    const int64_t len = std::min<int64_t>(lengths[b], max_len);
    seen_max = std::max(seen_max, lengths[b]);
    std::memcpy(out + b * max_len * row_bytes,
                values + offset_rows * row_bytes,
                static_cast<size_t>(len * row_bytes));
    offset_rows += lengths[b];
  }
  return seen_max;
}

// Inverse: gather the first lengths[b] rows of each padded batch item
// back into a concatenated buffer. Returns total rows written.
int64_t ptq_ragged_unpad(const uint8_t* padded, const int64_t* lengths,
                         int64_t batch, int64_t max_len, int64_t width,
                         int64_t elem_size, uint8_t* out) {
  const int64_t row_bytes = width * elem_size;
  int64_t offset_rows = 0;
  for (int64_t b = 0; b < batch; ++b) {
    const int64_t len = std::min<int64_t>(lengths[b], max_len);
    std::memcpy(out + offset_rows * row_bytes,
                padded + b * max_len * row_bytes,
                static_cast<size_t>(len * row_bytes));
    offset_rows += len;
  }
  return offset_rows;
}

// LoD offsets -> per-item lengths (reference lod_tensor.h level-0
// offsets [0, n1, n1+n2, ...]).
void ptq_lod_to_lengths(const int64_t* lod, int64_t batch,
                        int64_t* lengths) {
  for (int64_t b = 0; b < batch; ++b) {
    lengths[b] = lod[b + 1] - lod[b];
  }
}

}  // extern "C"
