// Bounded MPMC blocking channel of byte buffers.
//
// TPU-native stand-in for the reference's reader queue
// (paddle/fluid/operators/reader/lod_tensor_blocking_queue.h:30 and
// framework/blocking_queue.h): python feeder threads push serialized
// batches, the device-prefetch consumer pops them. Close() wakes all
// waiters and lets pops drain remaining items before reporting CLOSED —
// the same drain semantics the reference queue has.
#include "capi.h"

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

struct Buf {
  uint8_t* data;
  int64_t len;
};

class Channel {
 public:
  explicit Channel(int64_t cap) : cap_(cap < 1 ? 1 : cap) {}

  ~Channel() {
    std::lock_guard<std::mutex> g(mu_);
    for (auto& b : q_) free(b.data);
    q_.clear();
  }

  int Push(const uint8_t* buf, int64_t len, int64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    if (!Wait(lk, not_full_, timeout_ms,
              [&] { return closed_ || (int64_t)q_.size() < cap_; }))
      return PTQ_TIMEOUT;
    if (closed_) return PTQ_CLOSED;
    Buf b;
    b.data = (uint8_t*)malloc(len > 0 ? len : 1);
    if (!b.data) return PTQ_ERR;
    if (len > 0) memcpy(b.data, buf, len);
    b.len = len;
    q_.push_back(b);
    not_empty_.notify_one();
    return PTQ_OK;
  }

  int Pop(uint8_t** out, int64_t* out_len, int64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    if (!Wait(lk, not_empty_, timeout_ms,
              [&] { return closed_ || !q_.empty(); }))
      return PTQ_TIMEOUT;
    if (q_.empty()) return PTQ_CLOSED;  // closed and drained
    Buf b = q_.front();
    q_.pop_front();
    *out = b.data;
    *out_len = b.len;
    not_full_.notify_one();
    return PTQ_OK;
  }

  void Close() {
    std::lock_guard<std::mutex> g(mu_);
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  void Reopen() {
    std::lock_guard<std::mutex> g(mu_);
    closed_ = false;
  }

  int64_t Size() {
    std::lock_guard<std::mutex> g(mu_);
    return (int64_t)q_.size();
  }

 private:
  template <typename Pred>
  bool Wait(std::unique_lock<std::mutex>& lk, std::condition_variable& cv,
            int64_t timeout_ms, Pred pred) {
    if (timeout_ms < 0) {
      cv.wait(lk, pred);
      return true;
    }
    return cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred);
  }

  const int64_t cap_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  std::deque<Buf> q_;
  bool closed_ = false;
};

std::mutex g_reg_mu;
std::unordered_map<int64_t, Channel*> g_channels;
std::atomic<int64_t> g_next_id{1};

Channel* Get(int64_t h) {
  std::lock_guard<std::mutex> g(g_reg_mu);
  auto it = g_channels.find(h);
  return it == g_channels.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int64_t ptq_chan_create(int64_t capacity) {
  int64_t id = g_next_id.fetch_add(1);
  std::lock_guard<std::mutex> g(g_reg_mu);
  g_channels[id] = new Channel(capacity);
  return id;
}

int ptq_chan_push(int64_t h, const uint8_t* buf, int64_t len,
                  int64_t timeout_ms) {
  Channel* c = Get(h);
  return c ? c->Push(buf, len, timeout_ms) : PTQ_ERR;
}

int ptq_chan_pop(int64_t h, uint8_t** out, int64_t* out_len,
                 int64_t timeout_ms) {
  Channel* c = Get(h);
  return c ? c->Pop(out, out_len, timeout_ms) : PTQ_ERR;
}

void ptq_chan_close(int64_t h) {
  Channel* c = Get(h);
  if (c) c->Close();
}

void ptq_chan_reopen(int64_t h) {
  Channel* c = Get(h);
  if (c) c->Reopen();
}

int64_t ptq_chan_size(int64_t h) {
  Channel* c = Get(h);
  return c ? c->Size() : -1;
}

void ptq_chan_destroy(int64_t h) {
  Channel* c = nullptr;
  {
    std::lock_guard<std::mutex> g(g_reg_mu);
    auto it = g_channels.find(h);
    if (it != g_channels.end()) {
      c = it->second;
      g_channels.erase(it);
    }
  }
  if (c) {
    c->Close();
    delete c;
  }
}

void ptq_buf_free(uint8_t* buf) { free(buf); }

}  // extern "C"
