"""Builds libpaddle_tpu_native.so from the C++ sources with g++.

No pybind11 in this image, so the library exposes a plain C ABI
(src/capi.h) consumed via ctypes. Rebuilds only when a source is newer
than the .so. Importing paddle_tpu.core.native triggers this lazily; the
build is a single g++ invocation (< 10s).
"""
from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src")
_SO = os.path.join(_DIR, "libpaddle_tpu_native.so")
_SOURCES = ["channel.cc", "allocator.cc", "data_feed.cc", "monitor.cc",
            "trace_events.cc", "ragged.cc", "crypto.cc"]
_lock = threading.Lock()


def _stale() -> bool:
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    deps = [os.path.join(_SRC, s) for s in _SOURCES]
    deps.append(os.path.join(_SRC, "capi.h"))
    return any(os.path.getmtime(d) > so_mtime for d in deps)


def build(force: bool = False) -> str:
    """Returns the path to the built shared library."""
    with _lock:
        if not force and not _stale():
            return _SO
        cmd = [
            "g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
            "-Wall", "-o", _SO,
        ] + [os.path.join(_SRC, s) for s in _SOURCES]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                "native runtime build failed (%s):\n%s"
                % (" ".join(cmd), proc.stderr))
        return _SO


if __name__ == "__main__":
    print(build(force=True))
